"""Benchmarks for the five BASELINE.md configs. Prints one JSON line each,
flagship (KMeans Lloyd throughput) first.

Methodology notes (all discovered by measurement on this environment):

- The TPU is reached through a tunnel: ``jax.block_until_ready`` does NOT
  block here, a value fetch costs ~60-120 ms round-trip (RTT), and
  host<->device transfers run at only ~10 MB/s. Timing is therefore done by
  (a) generating/staging all data ON DEVICE outside timed regions (the
  package's dataset generators are jitted, device-output programs),
  (b) putting the repetition loop INSIDE one jitted program
  (``lax.fori_loop`` / the solver's own ``lax.while_loop``) so queued
  dispatch can't fake completion, and (c) fetching one scalar at the end and
  subtracting the separately-measured RTT. Each prior measurement is a
  warm-up, so compile time never lands in a reported number.
- Roofline accounting for the flagship: the fused Lloyd kernel's floor is a
  bare streaming matmul over the same feature-major data, which this script
  MEASURES (``floor_us_per_iter``) instead of trusting a spec-sheet GB/s
  (the measured streaming rate here exceeds the v5e paper number — the
  tunnel hides the actual chip generation). ``kernel_vs_floor`` close to
  1.0 = the full iteration costs little more than just reading the data.

Baselines: scikit-learn on this host's CPU. Where the full-size sklearn run
would take many minutes on the single available core, it runs on a smaller
slice and is scaled linearly (every scaled workload is O(n) in rows);
``baseline_note`` records this. ``vs_baseline`` is whole-system speedup
(mesh throughput / sklearn throughput, or sklearn_time / our_time).

Flagship history (the round-2 regression, explained and erased): round 1
measured 299M samples/sec/chip on a plain XLA step; round 2's "fused" kernel
DROPPED to 204M (2.5% of spec HBM bandwidth) because it (a) hand-scanned
VMEM-sized blocks, serializing HBM reads against compute where XLA's own
tiling pipelines them, and (b) left X row-major with d=50, which TPU tiling
physically pads to 128 lanes — 2.56x the logical traffic on every pass.
The current kernel transposes once to feature-major (padding moves to the
8-sublane dimension) and hands whole shards to XLA (see
models/kmeans.py:lloyd_loop_fused); measured effect: ~4.7B samples/sec/chip,
~930 GB/s effective — above the v5e spec number because the tunnel hides the
actual chip generation, and within 2.4x of this script's own measured
bare-streaming floor.
"""

import json
import os
import tempfile
import time
from functools import partial

import numpy as np

HBM_V5E_SPEC_GBPS = 819.0  # spec-sheet reference point only; see module doc


def _enable_compilation_cache():
    """Persistent XLA compilation cache (verified working on this backend):
    a re-run of the bench — or the driver's run after a warm-up — loads
    compiled programs from disk instead of paying 30-60 s compiles per
    distinct shape. Cache misses behave exactly as before. Routed through
    the ``compilation_cache`` config knob (docs/compile.md), which also
    drops the min-compile-time threshold to 0 — this backend pays ~0.7s
    fixed overhead per tiny program, and a search touches dozens."""
    from dask_ml_tpu.config import set_config

    set_config(
        compilation_cache=os.path.expanduser("~/.cache/dask_ml_tpu_xla"))

_RESULTS = []


def emit(rec: dict) -> None:
    """Print one metric's JSON line and remember it for the final compact
    summary (VERDICT r4 #5: the driver keeps only a 2,000-char tail, which
    truncated mid-record and lost metrics; the LAST line now always carries
    every number)."""
    _RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def emit_summary() -> None:
    """One compact line with every metric's headline numbers, printed LAST
    so the driver's tail capture always contains all of them."""
    print(json.dumps({
        "summary": {
            r["metric"]: {
                "value": r["value"], "unit": r["unit"],
                "vs_baseline": r["vs_baseline"],
            }
            for r in _RESULTS
        }
    }), flush=True)


def _measured_baselines() -> dict:
    """Committed direct sklearn measurements (see baselines.py / VERDICT
    r4 #3). Empty dict when absent — benches then fall back to inline
    mini-runs with explicit extrapolation notes."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {k: v for k, v in data.items()
            if isinstance(v, dict) and "error" not in v}


def _baseline_seconds(name, full_n):
    """(projected_seconds_at_full_n, note) from a committed measurement.
    Direct full-size runs project 1:1; budget-capped runs scale linearly in
    rows WITH the measured size in the note (measured fact, not a guess
    from a hand-picked slice)."""
    rec = _measured_baselines().get(name)
    if not rec or "seconds" not in rec:
        return None, None
    if rec.get("direct_full_size") or rec.get("n") == full_n:
        return float(rec["seconds"]), (
            f"sklearn measured DIRECTLY at full size "
            f"(n={rec['n']}, {rec['how']}; baselines.py)")
    scale = full_n / float(rec["n"])
    return float(rec["seconds"]) * scale, (
        f"sklearn measured at n={rec['n']} (largest fitting the "
        f"baseline budget; {rec['how']}), x{scale:.1f} in rows")


def _baseline_seconds_at(name, n):
    """(projected_seconds_at_exactly_n_rows, note): always row-scales the
    committed measurement to ``n`` — for probe-sized configs (the
    host-streamed benches) whose row count is smaller than the
    measurement's, where :func:`_baseline_seconds`'s direct-full-size
    shortcut would compare a full-size sklearn run against a probe."""
    rec = _measured_baselines().get(name)
    if not rec or "seconds" not in rec:
        return None, None
    scale = n / float(rec["n"])
    return float(rec["seconds"]) * scale, (
        f"sklearn measured at n={rec['n']} ({rec['how']}; baselines.py), "
        f"row-scaled x{scale:.4f} to this probe size")


KM = dict(n=1_000_000, d=50, k=8, iters=1000)
PCA = dict(n=500_000, d=1000, k=100, rank=64, reps=8)
PCA_BP = dict(n=10_000_000, d=1000, k=100, blocks=40)  # BASELINE #2 scale
ADMM = dict(n=10_000_000, d=100, outer=10)
ADMM_BP = dict(n=100_000_000, d=100, outer=10, blocks=40)  # BASELINE #3
INC = dict(n=2_000_000, d=100, block=100_000)
GRID = dict(n=20_000, d=100, points=500, cv=2, sk_points=100)


def fetch(x):
    """Force completion + value transfer (block_until_ready lies here)."""
    import jax

    return np.asarray(jax.tree_util.tree_leaves(x)[0])


def measure(fn, *args, reps=3):
    """Min wall-time of fn(*args) with a forced fetch; call once to warm."""
    fetch(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def measure_rtt():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    return measure(f, jnp.asarray(0.0), reps=8)


def _put_rate(rtt, nbytes=16 << 20):
    """Measured host→device transfer bandwidth (bytes/sec): timed
    ``device_put`` of a contiguous f32 array, completion forced by a value
    fetch (block_until_ready is advisory here — module docstring), RTT
    subtracted. Best of 2. Sizes the host-streamed bench configs to the
    link actually present instead of assuming one."""
    import jax

    a = np.random.RandomState(0).standard_normal(
        nbytes // 4).astype(np.float32)
    ts = []
    for _ in range(2):
        t0 = time.perf_counter()
        x = jax.device_put(a)
        jax.block_until_ready(x)
        np.asarray(x.ravel()[:1])  # one-element completion fetch — a full
        # fetch(x) would time the 16 MB device->host readback too and
        # halve the reported host->device rate
        ts.append(time.perf_counter() - t0)
        del x
    return a.nbytes / max(min(ts) - rtt, 1e-6)


# ---------------------------------------------------------------------------
# config 1: KMeans Lloyd throughput (flagship)
# ---------------------------------------------------------------------------


def bench_kmeans(rtt):
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu import datasets
    from dask_ml_tpu.models import kmeans as core
    from dask_ml_tpu.parallel import mesh as mesh_lib

    n, d, k, iters = KM["n"], KM["d"], KM["k"], KM["iters"]
    mesh = mesh_lib.default_mesh()
    X, _ = datasets.make_blobs(n_samples=n, n_features=d, centers=k,
                               cluster_std=2.0, random_state=0, mesh=mesh)
    w = jnp.ones((n,), jnp.float32)
    key = jax.random.key(0)
    centers0 = core.init_random(X, w, n, k, key)
    tol = jnp.asarray(0.0, jnp.float32)  # run all `iters` iterations

    out = {}
    for dtype_name, Xd in (("float32", X), ("bfloat16", X.astype(jnp.bfloat16))):
        f = partial(core.lloyd_loop_fused, mesh=mesh, max_iter=iters)
        t = max(measure(f, Xd, w, centers0, tol) - rtt, 1e-9)
        out[dtype_name] = n * iters / t / jax.device_count()

    # the single-pass pallas variant at the flagship shape, for the record:
    # XLA's two-pass roofline wins HERE (small k, f32), so auto keeps XLA —
    # but auto DOES select pallas in its measured winning regimes (k=128 /
    # bf16 wide; models/kmeans.py _pallas_auto_wins has the sweep table),
    # demonstrated by the k=128 field below
    fp = partial(core.lloyd_loop_fused, mesh=mesh, max_iter=iters,
                 kernel="pallas")
    t_pallas = max(measure(fp, X, w, centers0, tol) - rtt, 1e-9)
    out["pallas"] = n * iters / t_pallas / jax.device_count()

    # the k=128 regime where the fused single-pass kernel WINS: auto
    # dispatches to pallas there; forced XLA shown for the ratio
    k128, it128 = 128, 300
    c128 = core.init_random(X, w, n, k128, jax.random.key(1))
    for kern in ("auto", "xla"):
        fk = partial(core.lloyd_loop_fused, mesh=mesh, max_iter=it128,
                     kernel=kern)
        t = max(measure(fk, X, w, c128, tol) - rtt, 1e-9)
        out[f"k128_{kern}"] = n * it128 / t / jax.device_count()

    # streaming floor: bare distance matmul + min over the same data,
    # feature-major, same rep count — the kernel's bandwidth floor
    XT = jnp.asarray(np.asarray(X).T.copy())
    C0 = jnp.asarray(np.asarray(centers0))

    @jax.jit
    def floor_loop(XT, C):
        def body(i, carry):
            acc, c = carry
            prod = jax.lax.dot_general(c, XT, (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
            m = prod.min(axis=0).sum()
            return (acc + m, c + m * 1e-30)
        return jax.lax.fori_loop(0, iters, body,
                                 (jnp.asarray(0.0, jnp.float32), C))

    t_floor = (measure(floor_loop, XT, C0) - rtt) / iters
    per_iter = n / out["float32"] / jax.device_count()  # sec/iter (whole mesh)
    gbps = n * d * 4 / jax.device_count() / per_iter / 1e9  # per-chip traffic

    # sklearn Lloyd baseline: committed full-size measurement when present
    # (baselines.py), inline slice run otherwise
    bl = _measured_baselines().get("kmeans_lloyd")
    if bl and "samples_per_sec" in bl:
        sk_rate = float(bl["samples_per_sec"])
        bl_note = (f"sklearn Lloyd measured DIRECTLY at full "
                   f"{bl['n']}x{bl['d']} ({bl['how']}; baselines.py)")
    else:
        from sklearn.cluster import KMeans as SKKMeans

        ns = 200_000
        rng = np.random.RandomState(0)
        Xs = rng.randn(ns, d).astype(np.float32) * 2.0
        init = Xs[rng.choice(ns, k, replace=False)]
        km = SKKMeans(n_clusters=k, init=init, n_init=1, max_iter=20,
                      tol=0.0, algorithm="lloyd")
        t0 = time.perf_counter()
        km.fit(Xs)
        sk_rate = ns * max(int(km.n_iter_), 1) / (time.perf_counter() - t0)
        bl_note = f"sklearn Lloyd on {ns} rows, rate-normalized"

    emit({
        "metric": "kmeans_lloyd_throughput",
        "value": round(out["float32"], 1),
        "unit": "samples/sec/chip",
        # whole-SYSTEM speedup (mesh throughput over the sklearn core), per
        # the module docstring — value stays per-chip, the ratio does not
        "vs_baseline": round(
            out["float32"] * jax.device_count() / sk_rate, 2),
        "dtype": "float32 (f32 accumulation)",
        "bf16_samples_per_sec_per_chip": round(out["bfloat16"], 1),
        "pallas_single_pass_samples_per_sec_per_chip": round(out["pallas"], 1),
        "k128_auto_pallas_samples_per_sec_per_chip":
            round(out["k128_auto"], 1),
        "k128_forced_xla_samples_per_sec_per_chip":
            round(out["k128_xla"], 1),
        "k128_pallas_win": round(out["k128_auto"] / out["k128_xla"], 2),
        "effective_gbps_logical": round(gbps, 1),
        "spec_frac_of_v5e_819gbps": round(gbps / HBM_V5E_SPEC_GBPS, 3),
        "floor_us_per_iter": round(t_floor * 1e6, 1),
        "kernel_vs_floor": round(per_iter / t_floor, 2),
        "baseline_note": bl_note,
    })


# ---------------------------------------------------------------------------
# config 2: PCA n_components=100 on tall-skinny (tsqr + randomized)
# ---------------------------------------------------------------------------


def bench_pca(rtt):
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.ops import linalg
    from dask_ml_tpu.parallel import mesh as mesh_lib

    n, d, k, rank, reps = (PCA["n"], PCA["d"], PCA["k"], PCA["rank"],
                           PCA["reps"])
    mesh = mesh_lib.default_mesh()
    row_sh = mesh_lib.data_sharding(mesh, ndim=2)

    def gen(key):
        ka, kb, kn = jax.random.split(key, 3)
        A = jax.random.normal(ka, (n, rank), jnp.float32)
        B = jax.random.normal(kb, (rank, d), jnp.float32)
        return A @ B + 0.1 * jax.random.normal(kn, (n, d), jnp.float32)

    X = jax.jit(gen, out_shardings=row_sh)(jax.random.key(0))

    @partial(jax.jit, static_argnames=("mesh", "reps"))
    def tsvd_loop(X, *, mesh, reps):
        def body(i, acc):
            Xi = X + acc * 1e-30  # carry-dependence defeats loop hoisting
            _U, S, _Vt = linalg._tsvd_impl(Xi, mesh=mesh)
            return acc + S[0]
        return jax.lax.fori_loop(0, reps, body, jnp.asarray(0.0, jnp.float32))

    @partial(jax.jit, static_argnames=("mesh", "reps"))
    def rand_loop(X, key, *, mesh, reps):
        def body(i, acc):
            Xi = X + acc * 1e-30
            _U, S, _Vt = linalg._svd_compressed_impl(
                Xi, key, k=k, n_power_iter=2, n_oversamples=10)
            return acc + S[0]
        return jax.lax.fori_loop(0, reps, body, jnp.asarray(0.0, jnp.float32))

    t_tsqr = (measure(partial(tsvd_loop, mesh=mesh, reps=reps), X) - rtt) / reps
    t_rand = (measure(partial(rand_loop, mesh=mesh, reps=reps), X,
                      jax.random.key(1)) - rtt) / reps

    sk_scaled, bl_note = _baseline_seconds("pca", n)
    if sk_scaled is None:
        from sklearn.decomposition import PCA as SKPCA

        ns = 50_000
        Xh = np.asarray(X[:ns])
        t0 = time.perf_counter()
        SKPCA(n_components=k, svd_solver="randomized", iterated_power=2,
              random_state=0).fit(Xh)
        sk_scaled = (time.perf_counter() - t0) * n / ns
        bl_note = (f"sklearn randomized PCA on {ns} rows x{n // ns} "
                   "(linear in rows)")

    emit({
        "metric": "pca100_randomized_fit",
        "value": round(t_rand, 4),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t_rand, 1),
        "rows": n, "cols": d, "n_components": k,
        "tsqr_exact_svd_seconds": round(t_tsqr, 4),
        "samples_per_sec_per_chip": round(n / t_rand, 1),
        "baseline_note": bl_note,
    })
    del X


def bench_pca_blueprint(rtt):
    """BASELINE config #2 at blueprint scale: PCA-100 on 1e7×1000 — 40 GB
    of f32, over a single chip's HBM. Staging strategy: STREAMED COVARIANCE
    ACCUMULATION — one lax.scan over 40 row blocks (1 GB each) generated on
    device inside the scan body, accumulating the d×d Gram (4 MB); data is
    never resident. See decomposition/streaming.py."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.decomposition.streaming import (_pca_from_moments,
                                                     streamed_moments)

    n, d, k, n_blocks = (PCA_BP["n"], PCA_BP["d"], PCA_BP["k"],
                         PCA_BP["blocks"])
    rows = n // n_blocks
    scale = jnp.linspace(3.0, 0.3, d)
    key0 = jax.random.key(11)

    def block_fn(b):
        kb = jax.random.fold_in(key0, b)
        X = jax.random.normal(kb, (rows, d), jnp.float32) * scale + 1.0
        return X, jnp.ones((rows,), jnp.float32)

    def run():
        sw, s, G = streamed_moments(block_fn=block_fn, n_blocks=n_blocks)
        return _pca_from_moments(sw, s, G)

    t = measure(run) - rtt

    sk_scaled, bl_note = _baseline_seconds("pca_blueprint", n)
    if sk_scaled is None:
        from sklearn.decomposition import PCA as SKPCA

        ns = 50_000
        rng = np.random.RandomState(0)
        Xh = rng.randn(ns, d).astype(np.float32) * np.asarray(scale) + 1.0
        t0 = time.perf_counter()
        SKPCA(n_components=k, svd_solver="randomized", iterated_power=2,
              random_state=0).fit(Xh)
        sk_scaled = (time.perf_counter() - t0) * n / ns
        bl_note = (f"sklearn randomized PCA on {ns} rows "
                   f"x{n // ns} (linear in rows)")

    emit({
        "metric": "pca100_blueprint_streamed_fit",
        "value": round(t, 3),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t, 1),
        "rows": n, "cols": d, "n_components": k, "blocks": n_blocks,
        "samples_per_sec_per_chip": round(n / t / jax.device_count(), 1),
        "block_source": "device-generated",
        # one Gram pass reads every block once: (d+1) f32s per row
        "effective_gbps": round(n * (d + 1) * 4 / t / 1e9, 2),
        "staging_strategy": "streamed covariance accumulation; 40x1GB "
                            "device-generated blocks scanned through one "
                            "Gram pass, data never resident (40GB > HBM)",
        "baseline_note": bl_note,
    })


def _host_stream_rows(rate, epochs, bytes_per_row, cap_bytes, full_n,
                      n_min, n_blocks):
    """Probe-size a host-streamed config: ~25 s of streaming at the
    measured link rate across all epochs; on fast local links (where
    transfer stops being the bottleneck and the config would balloon
    until CPU compute dominates instead) the stream is capped at
    ``cap_bytes``, and always at the blueprint row count."""
    n_h = int(min(rate * 25.0, cap_bytes) / (epochs * bytes_per_row))
    n_h = max(min(n_h, full_n), n_min)
    return n_h - n_h % n_blocks


def _overlap_runs(run):
    """(t_prefetch, wire_bytes, logical_bytes, t_serial) for a
    host-streamed bench: one warm pass (compiles the per-block programs),
    then the depth-2 and depth-0 schedules.
    ``run(prefetch) -> (seconds, wire_bytes, logical_bytes)`` — wire is
    what actually crossed the link (post precision-policy cast), logical
    what the uncast blocks would have weighed; they differ only under a
    low-precision wire policy (docs/precision.md)."""
    run(2)
    t_pref, wire, logical = run(2)
    t_serial, _, _ = run(0)
    return t_pref, wire, logical, t_serial


def bench_pca_blueprint_host(rtt):
    """The streamed-PCA tier at its REAL bottleneck: blocks live in HOST
    memory and pay the actual host→device transfer, double-buffered
    through ``parallel/stream.py`` (depth 2: block b+1's DMA overlaps
    block b's Gram matmul). Probe-sized to the measured link bandwidth —
    over the tunnel this host streams at ~10 MB/s, so the full 40 GB
    config is transfer-infeasible by construction; effective GB/s IS the
    metric. ``prefetch_disabled_seconds`` is the same run at depth 0
    (strict serial transfer→compute alternation): the gap is what the
    overlap buys."""
    import jax

    from dask_ml_tpu.decomposition.streaming import (_pca_from_moments,
                                                     streamed_moments)
    from dask_ml_tpu.parallel.stream import HostBlockSource

    d, n_blocks = PCA_BP["d"], 8
    rate = _put_rate(rtt)
    bytes_per_row = (d + 1) * 4
    n_h = _host_stream_rows(rate, 1, bytes_per_row, 128e6, PCA_BP["n"],
                            16_000, n_blocks)
    rng = np.random.RandomState(0)
    scale = np.linspace(3.0, 0.3, d).astype(np.float32)
    X = rng.standard_normal((n_h, d)).astype(np.float32) * scale + 1.0
    w = np.ones(n_h, np.float32)

    def run(prefetch):
        src = HostBlockSource((X, w), n_blocks=n_blocks, prefetch=prefetch)
        t0 = time.perf_counter()
        sw, s, G = streamed_moments(block_fn=src, n_blocks=n_blocks)
        out = _pca_from_moments(sw, s, G)
        fetch(out[1])
        return (time.perf_counter() - t0, src.bytes_streamed,
                src.logical_bytes_streamed)

    t_pref, bytes_streamed, logical_bytes, t_serial = _overlap_runs(run)

    sk_scaled, bl_note = _baseline_seconds_at("pca_blueprint", n_h)
    if sk_scaled is None:
        bl_note = "no committed sklearn PCA measurement (baselines.py)"

    emit({
        "metric": "pca100_blueprint_host_streamed_fit",
        "value": round(t_pref, 3),
        "unit": "seconds",
        "vs_baseline": (None if sk_scaled is None
                        else round(sk_scaled / t_pref, 1)),
        "rows": n_h, "cols": d, "n_components": PCA_BP["k"],
        "blocks": n_blocks,
        "block_source": "host-streamed (HostBlockSource, prefetch=2)",
        "effective_gbps": round(bytes_streamed / t_pref / 1e9, 3),
        "effective_gbps_logical": round(logical_bytes / t_pref / 1e9, 3),
        "bytes_streamed": int(bytes_streamed),
        "logical_bytes_streamed": int(logical_bytes),
        "prefetch_disabled_seconds": round(t_serial, 3),
        "prefetch_disabled_gbps": round(bytes_streamed / t_serial / 1e9, 3),
        "overlap_speedup": round(t_serial / t_pref, 2),
        "host_put_rate_gbps": round(rate / 1e9, 3),
        "sizing_note": f"rows probe-sized to ~25s of streaming at the "
                       f"measured {rate / 1e6:.1f} MB/s link "
                       f"(full 1e7-row config = "
                       f"{PCA_BP['n'] * bytes_per_row / 1e9:.0f} GB "
                       "over this link)",
        "baseline_note": bl_note,
    })


# ---------------------------------------------------------------------------
# config 3: LogisticRegression via consensus ADMM
# ---------------------------------------------------------------------------


def bench_admm(rtt):
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu import datasets
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel import mesh as mesh_lib

    n, d, outer = ADMM["n"], ADMM["d"], ADMM["outer"]
    mesh = mesh_lib.default_mesh()
    X, y = datasets.make_classification(
        n_samples=n, n_features=d, n_informative=d, scale=2.0,
        random_state=0, mesh=mesh)
    w = jnp.ones((n,), jnp.float32)
    beta0 = jnp.zeros((d,), jnp.float32)
    mask = jnp.ones((d,), jnp.float32)

    def run():
        return glm_core.admm(
            X, y.astype(jnp.float32), w, beta0, mask, mesh,
            family="logistic", regularizer="l2", lamduh=1.0,
            max_iter=outer, abstol=0.0, reltol=0.0)  # run all outer iters

    t = measure(run) - rtt

    sk_scaled, bl_note = _baseline_seconds("admm", n)
    if sk_scaled is None:
        from sklearn.linear_model import LogisticRegression as SKLR

        ns = 200_000
        Xh, yh = np.asarray(X[:ns]), np.asarray(y[:ns])
        t0 = time.perf_counter()
        SKLR(C=1.0, max_iter=100).fit(Xh, yh)
        sk_scaled = (time.perf_counter() - t0) * n / ns
        bl_note = (f"sklearn lbfgs LogisticRegression on {ns} rows "
                   f"x{n // ns} (linear in rows)")

    emit({
        "metric": "logreg_admm_fit",
        "value": round(t, 3),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t, 1),
        "rows": n, "cols": d, "admm_outer_iters": outer,
        "samples_per_sec_per_chip":
            round(n * outer / t / jax.device_count(), 1),
        "baseline_note": bl_note,
    })
    del X, y


def bench_admm_blueprint(rtt):
    """BASELINE config #3 at blueprint scale: ADMM LogisticRegression on
    1e8×100 — 40 GB of f32, over a single chip's HBM. Staging strategy:
    STREAMED CONSENSUS ADMM — every outer iteration scans 40 row blocks
    (1 GB each) regenerated on device inside the scan, each block resident
    only for its own inner-Newton prox solve (models/glm.py
    admm_streamed)."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models import glm as glm_core

    n, d, outer, n_blocks = (ADMM_BP["n"], ADMM_BP["d"], ADMM_BP["outer"],
                             ADMM_BP["blocks"])
    rows = n // n_blocks
    key0 = jax.random.key(13)
    w_true = jnp.asarray(
        np.random.RandomState(3).randn(d).astype(np.float32))

    def block_fn(b):
        kb = jax.random.fold_in(key0, b)
        kx, kn = jax.random.split(kb)
        X = jax.random.normal(kx, (rows, d), jnp.float32) * 2.0
        eta = X @ w_true + jax.random.normal(kn, (rows,), jnp.float32)
        y = (eta > 0).astype(jnp.float32)
        return X, y, jnp.ones((rows,), jnp.float32)

    def run():
        return glm_core.admm_streamed(
            block_fn, n_blocks, d, float(n), family="logistic",
            regularizer="l2", lamduh=1.0, max_iter=outer,
            abstol=0.0, reltol=0.0)  # run all outer iters

    t = measure(run) - rtt

    sk_scaled, bl_note = _baseline_seconds("admm_blueprint", n)
    if sk_scaled is None:
        from sklearn.linear_model import LogisticRegression as SKLR

        ns = 200_000
        rng = np.random.RandomState(0)
        Xh = rng.randn(ns, d).astype(np.float32) * 2.0
        yh = (Xh @ np.asarray(w_true) + rng.randn(ns) > 0).astype(np.float32)
        t0 = time.perf_counter()
        SKLR(C=1.0, max_iter=100).fit(Xh, yh)
        sk_scaled = (time.perf_counter() - t0) * n / ns
        bl_note = (f"sklearn lbfgs LogisticRegression on {ns} rows "
                   f"x{n // ns} (linear in rows)")

    emit({
        "metric": "logreg_admm_blueprint_streamed_fit",
        "value": round(t, 3),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t, 1),
        "rows": n, "cols": d, "admm_outer_iters": outer, "blocks": n_blocks,
        "samples_per_sec_per_chip":
            round(n * outer / t / jax.device_count(), 1),
        "block_source": "device-generated",
        # every outer iteration re-reads every block: (d+2) f32s per row
        "effective_gbps": round(n * (d + 2) * 4 * outer / t / 1e9, 2),
        "staging_strategy": "streamed consensus ADMM; 40x1GB "
                            "device-generated blocks rescanned per outer "
                            "iteration, one block resident at a time "
                            "(40GB > HBM)",
        "baseline_note": bl_note,
    })


def bench_admm_blueprint_host(rtt):
    """The streamed-ADMM tier at its REAL bottleneck: row blocks live in
    HOST memory (the larger-than-HBM story the device-generated bench
    never exercises — VERDICT r5 "What's weak" #1) and every outer
    iteration re-streams them through the double-buffered pipeline, block
    b+1's async ``device_put`` overlapping block b's inner Newton solve.
    Probe-sized to the measured link bandwidth; ``overlap_speedup`` is
    prefetch=2 vs the strict serial schedule (prefetch=0)."""
    import jax

    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel.stream import HostBlockSource

    d, n_blocks, outer = ADMM_BP["d"], 8, 3
    rate = _put_rate(rtt)
    bytes_per_row = (d + 2) * 4
    n_h = _host_stream_rows(rate, outer, bytes_per_row, 256e6,
                            ADMM_BP["n"], 64_000, n_blocks)
    rng = np.random.RandomState(0)
    w_true = np.random.RandomState(3).randn(d).astype(np.float32)
    X = np.empty((n_h, d), np.float32)
    step = 2_000_000
    for s in range(0, n_h, step):  # chunked gen keeps the f64 temp small
        X[s:s + step] = rng.standard_normal(
            (min(step, n_h - s), d)).astype(np.float32) * 2.0
    y = (X @ w_true + rng.standard_normal(n_h).astype(np.float32)
         > 0).astype(np.float32)
    w = np.ones(n_h, np.float32)

    def run(prefetch):
        src = HostBlockSource((X, y, w), n_blocks=n_blocks,
                              prefetch=prefetch)
        t0 = time.perf_counter()
        z, _ = glm_core.admm_streamed(
            src, n_blocks, d, float(n_h), family="logistic",
            regularizer="l2", lamduh=1.0, max_iter=outer,
            abstol=0.0, reltol=0.0)
        fetch(z)
        return (time.perf_counter() - t0, src.bytes_streamed,
                src.logical_bytes_streamed)

    t_pref, bytes_streamed, logical_bytes, t_serial = _overlap_runs(run)

    sk_scaled, bl_note = _baseline_seconds_at("admm_blueprint", n_h)
    if sk_scaled is None:
        bl_note = "no committed sklearn measurement (baselines.py)"

    emit({
        "metric": "logreg_admm_blueprint_host_streamed_fit",
        "value": round(t_pref, 3),
        "unit": "seconds",
        "vs_baseline": (None if sk_scaled is None
                        else round(sk_scaled / t_pref, 1)),
        "rows": n_h, "cols": d, "admm_outer_iters": outer,
        "blocks": n_blocks,
        "block_source": "host-streamed (HostBlockSource, prefetch=2)",
        "effective_gbps": round(bytes_streamed / t_pref / 1e9, 3),
        "effective_gbps_logical": round(logical_bytes / t_pref / 1e9, 3),
        "bytes_streamed": int(bytes_streamed),
        "logical_bytes_streamed": int(logical_bytes),
        "prefetch_disabled_seconds": round(t_serial, 3),
        "prefetch_disabled_gbps": round(bytes_streamed / t_serial / 1e9, 3),
        "overlap_speedup": round(t_serial / t_pref, 2),
        "host_put_rate_gbps": round(rate / 1e9, 3),
        "sizing_note": f"rows probe-sized to ~25s of streaming at the "
                       f"measured {rate / 1e6:.1f} MB/s link "
                       f"(full 1e8-row config = "
                       f"{ADMM_BP['n'] * bytes_per_row / 1e9:.0f} GB "
                       "per outer iteration over this link)",
        "baseline_note": bl_note,
    })


# ---------------------------------------------------------------------------
# config 4: Incremental streaming partial_fit (fused scan path)
# ---------------------------------------------------------------------------


def bench_incremental(rtt):
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu import datasets
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel import mesh as mesh_lib
    from dask_ml_tpu.wrappers import incremental_scan

    n, d, block = INC["n"], INC["d"], INC["block"]
    mesh = mesh_lib.default_mesh()
    X, y = datasets.make_classification(
        n_samples=n, n_features=d, n_informative=d, scale=2.0,
        random_state=1, mesh=mesh)
    y = y.astype(jnp.float32)

    step, _ = glm_core.get_stream_step(
        family="logistic", regularizer="l2", lamduh=0.01, eta0=0.5,
        fit_intercept=True)
    state0 = (jnp.zeros((d + 1,), jnp.float32), jnp.asarray(0.0, jnp.float32))

    # the fused scan finishes in less than one tunnel RTT, so a single
    # dispatch can't be timed by fetch-minus-RTT (it went negative);
    # queue R independent scans back-to-back and amortize — the device
    # executes them sequentially on one stream, the final fetch syncs all
    R = 10

    def run():
        out = None
        for _ in range(R):
            out = incremental_scan(step, state0, X, y, block_size=block)
        return out

    t = max((measure(run) - rtt) / R, 1e-9)

    sk_scaled, bl_note = _baseline_seconds("incremental", n)
    if sk_scaled is None:
        # sklearn SGDClassifier partial_fit host loop over the same stream
        from sklearn.linear_model import SGDClassifier

        ns = 500_000
        Xh, yh = np.asarray(X[:ns]), np.asarray(y[:ns])
        sk = SGDClassifier(alpha=0.01, random_state=0)
        t0 = time.perf_counter()
        for i in range(0, ns, block):
            sk.partial_fit(Xh[i:i + block], yh[i:i + block],
                           classes=[0.0, 1.0])
        sk_scaled = (time.perf_counter() - t0) * n / ns
        bl_note = (f"sklearn SGDClassifier partial_fit loop on {ns} "
                   f"rows x{n // ns} (linear in rows)")

    emit({
        "metric": "incremental_stream_fit",
        "value": round(t, 4),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t, 1),
        "rows": n, "cols": d, "block_size": block,
        "rows_per_sec_per_chip": round(n / t / jax.device_count(), 1),
        "baseline_note": bl_note,
    })
    del X, y


# ---------------------------------------------------------------------------
# config 5: GridSearchCV 500-point StandardScaler->PCA->KMeans sweep
# ---------------------------------------------------------------------------


def bench_gridsearch(_rtt):
    """The 500-point StandardScaler→PCA→KMeans sweep, swept over the
    JAX-NATIVE pipeline (VERDICT r3 #1: the round-3 bench swept a pure
    sklearn pipeline, so the TPU did nothing). The driver's batched-candidate
    path buckets the 100 (n_clusters, tol) variants per (pca_n, split) into
    ONE compiled program each — trajectory sharing across tol, masked-k
    sharing across n_clusters, bulk scoring — so the whole 1000-cell sweep is
    ~10 group programs + CSE'd prefix fits. Timed twice: the first pass pays
    one-time XLA compiles (5 shapes × ~2 programs), the second is the steady
    state a real sweep runs at; both are reported.
    """
    from sklearn.cluster import KMeans as SKKMeans
    from sklearn.decomposition import PCA as SKPCA
    from sklearn.model_selection import GridSearchCV as SkGridSearchCV
    from sklearn.model_selection import ParameterGrid
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler as SKScaler

    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.preprocessing import StandardScaler

    n, d, cv = GRID["n"], GRID["d"], GRID["cv"]
    rng = np.random.RandomState(0)
    X = (rng.randn(n, d) @ np.diag(np.linspace(2, 0.5, d))).astype(np.float32)

    grid = {
        "pca__n_components": [5, 10, 15, 20, 25],
        "km__n_clusters": list(range(2, 12)),
        "km__tol": list(np.logspace(-6, -2, 10)),
    }  # 5 x 10 x 10 = 500 points
    assert len(ParameterGrid(grid)) == GRID["points"]

    def make_pipe():
        return Pipeline([
            ("scale", StandardScaler()),
            ("pca", PCA(random_state=0)),
            ("km", KMeans(init="random", max_iter=10, random_state=0)),
        ])

    def run_ours():
        # n_jobs=8 on a 1-core host: the workers exist to OVERLAP the
        # ~100ms-RTT device round-trips (group dispatch/fetch, prefix-fit
        # syncs), not for CPU parallelism
        t0 = time.perf_counter()
        ours = GridSearchCV(make_pipe(), grid, cv=cv, refit=False,
                            iid=False, return_train_score=False,
                            n_jobs=8).fit(X)
        return ours, time.perf_counter() - t0

    # persistent-cache accounting: how many compiled programs the cold run
    # loaded vs newly stored — a SECOND process's "cold" run should load
    # nearly everything and land near the warm number
    cache_dir = os.path.expanduser("~/.cache/dask_ml_tpu_xla")

    def _n_cache_files():
        try:
            return len(os.listdir(cache_dir))
        except OSError:
            return 0

    cache_before = _n_cache_files()
    ours, t_cold = run_ours()
    cache_new = _n_cache_files() - cache_before
    assert ours.n_batched_cells_ == GRID["points"] * cv
    # min of two warm runs: the sweep is host-side-driver bound, so a
    # single sample is noisy under transient host/tunnel load
    t_warm = min(run_ours()[1], run_ours()[1])

    # sklearn baseline: the same sweep structure on a candidate subset,
    # scaled (candidates are homogeneous); init='random', n_init=1 matches
    # the jax-native estimator's configuration
    def make_sk_pipe():
        return Pipeline([
            ("scale", SKScaler()),
            ("pca", SKPCA(random_state=0)),
            ("km", SKKMeans(init="random", n_init=1, max_iter=10,
                            random_state=0)),
        ])

    # second-process cold start: a FRESH interpreter (empty jit caches)
    # re-runs the sweep against the persistent compilation cache the cold
    # run just populated — the number a user's next session actually pays
    import subprocess
    import sys as _sys

    child = subprocess.run(
        [_sys.executable, os.path.abspath(__file__), "--grid-child"],
        capture_output=True, text=True, timeout=900)
    try:
        t_second_proc = float(child.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        t_second_proc = None

    bl = _measured_baselines().get("gridsearch")
    if bl and "seconds" in bl and bl.get("direct_full_size"):
        sk_scaled = float(bl["seconds"])
        bl_note = ("sklearn GridSearchCV measured DIRECTLY on the full "
                   f"500-point sweep ({bl['how']}; baselines.py)")
    else:
        sub = {
            "pca__n_components": [5, 10, 15, 20, 25],
            "km__n_clusters": list(range(2, 12)),
            "km__tol": [1e-4, 1e-3],
        }  # 100 points
        n_sub = len(ParameterGrid(sub))
        t0 = time.perf_counter()
        SkGridSearchCV(make_sk_pipe(), sub, cv=cv, refit=False).fit(X)
        sk_scaled = (time.perf_counter() - t0) * GRID["points"] / n_sub
        bl_note = (f"sklearn GridSearchCV on {n_sub} of 500 points "
                   f"x{GRID['points'] // n_sub} (homogeneous grid)")

    emit({
        "metric": "gridsearch_500pt_pipeline_sweep",
        "value": round(t_warm, 2),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t_warm, 2),
        "points": GRID["points"], "cv": cv, "rows": n,
        "cold_seconds_incl_compile": round(t_cold, 2),
        "second_process_cold_seconds": (
            None if t_second_proc is None else round(t_second_proc, 2)),
        "second_process_note": "fresh interpreter vs the persistent "
                               "compile cache, measured while the parent "
                               "still holds the device (tunnel "
                               "contention); standalone `python bench.py "
                               "--grid-child` reruns measure ~9s",
        "xla_cache_programs_stored_by_cold_run": cache_new,
        "xla_cache_programs_preexisting": cache_before,
        "n_shared_fits": int(ours.n_shared_fits_),
        "n_batched_cells": int(ours.n_batched_cells_),
        "cells": GRID["points"] * cv,
        "pipeline": "dask_ml_tpu StandardScaler->PCA->KMeans (jax-native)",
        "baseline_note": bl_note,
    })


# ---------------------------------------------------------------------------
# fused distance-reduction dispatch grid (ISSUE 2): fused vs unfused
# pairwise_distances_argmin_min over (n, m, d) shapes
# ---------------------------------------------------------------------------


def bench_fused(rtt):
    """Fused-vs-unfused ``pairwise_distances_argmin_min`` over an
    (n, m, d) grid — the measurement that populates/validates the fused
    family's auto-dispatch thresholds
    (ops/fused_distance.py::_fused_auto_wins; docs/kernels.md records the
    method). On TPU the grid covers the real consumer shapes: assignment
    k (8), the k-means|| per-round cap (~80), the candidate buffer
    (~337), the spectral landmark count (200/1024), at the KDD feature
    width and a wide-d point. Off-TPU the pallas path runs in INTERPRET
    mode, so the grid shrinks to smoke-scale shapes — the deltas are
    still recorded, and they show unfused winning, which is exactly why
    ``auto`` keeps XLA off-TPU."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.ops import fused_distance as fd
    from dask_ml_tpu.ops.pairwise import pairwise_distances_argmin_min
    from dask_ml_tpu.parallel import mesh as mesh_lib
    from dask_ml_tpu.parallel.sharding import prepare_data

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        grid = [(1_000_000, m, d)
                for m in (8, 80, 337, 1024) for d in (41, 256)]
    else:
        # interpret mode: smoke-scale — records the mechanism + deltas,
        # not a roofline (tier-1 CI prints this table in the kernels job)
        grid = [(4096, 8, 16), (4096, 64, 16), (8192, 128, 32)]

    mesh = mesh_lib.default_mesh()
    rows = []
    for n, m, d in grid:
        key = jax.random.key(hash((n, m, d)) % (2**31))
        kx, ky = jax.random.split(key)
        data = prepare_data(np.asarray(
            jax.random.normal(kx, (n, d), jnp.float32)))
        Y = jax.random.normal(ky, (m, d), jnp.float32)
        t_un = max(measure(partial(pairwise_distances_argmin_min,
                                   kernel="xla"), data.X, Y) - rtt, 1e-9)
        t_f = max(measure(partial(pairwise_distances_argmin_min,
                                  kernel="pallas", mesh=data.mesh),
                          data.X, Y) - rtt, 1e-9)
        rows.append({
            "n": n, "m": m, "d": d,
            "unfused_seconds": round(t_un, 5),
            "fused_seconds": round(t_f, 5),
            "fused_speedup": round(t_un / t_f, 3),
            "winner": "fused" if t_f < t_un else "unfused",
            "auto_picks_fused": bool(
                fd._fused_auto_wins(n, m, d, jnp.float32, mesh)),
        })
        print(json.dumps({"fused_grid_point": rows[-1]}), flush=True)

    best = max(r["fused_speedup"] for r in rows)
    # rule validation is only meaningful against COMPILED kernel timings;
    # interpret-mode smoke deltas are noise at these shapes, so off-TPU
    # the flag is null rather than a standing false
    agree = (all(r["auto_picks_fused"] == (r["winner"] == "fused")
                 for r in rows) if on_tpu else None)
    emit({
        "metric": "fused_argmin_dispatch_grid",
        "value": best,
        "unit": "max fused/unfused speedup over the (n, m, d) grid",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "pallas_mode": "compiled" if on_tpu else "interpret",
        "auto_rule_matches_measured_winners": agree,
        "grid": rows,
        "note": "populates the _fused_auto_wins thresholds "
                "(ops/fused_distance.py); auto keeps the unfused XLA "
                "path off-TPU, where the pallas path only exists in "
                "interpret mode (smoke-scale deltas, not a roofline)",
    })


# ---------------------------------------------------------------------------
# fault-recovery drill (ISSUE 3): clean vs injected-failure runs of the
# host-streamed ADMM tier, with resume — the recovery-overhead numbers the
# CI `faults` job prints
# ---------------------------------------------------------------------------


def _compile_workload():
    """The workload the compile-report drill measures — CI-sized instances
    of the two shapes the ISSUE gates: a 6-candidate x 3-fold KMeans grid
    search whose fold train sizes differ (266 vs 267 rows: the case that
    used to compile the batched program once per fold), and a ragged-tail
    host-streamed ADMM fit (the case that used to be rejected outright).
    Returns observability numbers for the caller to emit."""
    import numpy as np

    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.models import kmeans as km_core
    from dask_ml_tpu.parallel.stream import HostBlockSource

    rng = np.random.RandomState(0)
    X = (rng.randn(400, 12) @ np.diag(np.linspace(2, 0.5, 12))).astype(
        np.float32)
    impl_before = km_core._batched_cells_impl._cache_size()
    t0 = time.perf_counter()
    gs = GridSearchCV(
        KMeans(init="random", max_iter=8, random_state=0),
        {"n_clusters": [2, 3], "tol": [1e-4, 1e-2, 1e-1]},
        cv=3, refit=False, n_jobs=1).fit(X)
    t_search = time.perf_counter() - t0

    n, d, n_blocks = 1003, 6, 8  # ragged: 7 blocks of 126 + a 121-row tail
    Xs = rng.standard_normal((n, d)).astype(np.float32)
    ys = (Xs @ np.random.RandomState(3).randn(d) > 0).astype(np.float32)
    ws = np.ones(n, np.float32)
    t0 = time.perf_counter()
    z, _ = glm_core.admm_streamed(
        HostBlockSource((Xs, ys, ws), n_blocks), n_blocks, d, float(n),
        family="logistic", regularizer="l2", lamduh=1.0, max_iter=4,
        abstol=0.0, reltol=0.0)
    fetch(z)
    return {
        "search_seconds": round(t_search, 3),
        "stream_seconds": round(time.perf_counter() - t0, 3),
        "n_batched_cells": gs.n_batched_cells_,
        "search_shape_buckets": gs.shape_buckets_,
        "batched_program_compiles": (
            km_core._batched_cells_impl._cache_size() - impl_before),
    }


def _compile_child():
    """Fresh-process probe for the cold/warm persistent-cache numbers: the
    same workload with ``compilation_cache`` pointed at argv's dir ('-' =
    no persistent cache), compile stats printed as the LAST line."""
    import sys

    from dask_ml_tpu import config
    from dask_ml_tpu.parallel import shapes

    cache_dir = sys.argv[sys.argv.index("--compile-child") + 1]
    if cache_dir != "-":
        config.set_config(compilation_cache=cache_dir)
    shapes.reset_compile_stats()
    t0 = time.perf_counter()
    out = _compile_workload()
    stats = shapes.compile_stats()
    print(json.dumps({
        "wall_seconds": round(time.perf_counter() - t0, 3),
        "n_compiles": stats["n_compiles"],
        "compile_seconds": round(stats["compile_seconds"], 3),
        "n_traces": stats["n_traces"],
        "shape_buckets": {str(k): v
                          for k, v in stats["shape_buckets"].items()},
        **out,
    }), flush=True)


def _padding_pinned_results() -> dict:
    """Padded-vs-exact pins for the drill: bucket padding must not change
    any result. KMeans on integer-valued data pins labels bitwise against
    a pad_policy=None run; the ragged streamed fit pins (z, x, u) bitwise
    against a manually pre-padded source. Returns flags the caller turns
    into a nonzero exit on divergence."""
    import numpy as np

    from dask_ml_tpu import config
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel.stream import HostBlockSource

    X = np.random.RandomState(0).randint(0, 8, size=(266, 6)).astype(
        np.float32)
    a = KMeans(init="random", n_clusters=3, max_iter=20,
               random_state=0).fit(X)
    with config.config_context(pad_policy=None):
        b = KMeans(init="random", n_clusters=3, max_iter=20,
                   random_state=0).fit(X)
    kmeans_ok = bool(
        np.array_equal(a.labels_, b.labels_)
        and np.allclose(a.inertia_, b.inertia_, rtol=1e-6)
        and a.n_iter_ == b.n_iter_)

    n, d, n_blocks = 1003, 6, 8
    rng = np.random.RandomState(1)
    Xs = rng.standard_normal((n, d)).astype(np.float32)
    ys = (Xs @ np.random.RandomState(3).randn(d) > 0).astype(np.float32)
    ws = np.ones(n, np.float32)
    rows = -(-n // n_blocks)
    pad = rows * n_blocks - n
    Xp = np.concatenate([Xs, np.zeros((pad, d), np.float32)])
    yp = np.concatenate([ys, np.zeros(pad, np.float32)])
    wp = np.concatenate([ws, np.zeros(pad, np.float32)])
    kw = dict(family="logistic", regularizer="l2", lamduh=1.0, max_iter=4,
              abstol=0.0, reltol=0.0, return_state=True)
    _, _, (zm, xm, um), _ = glm_core.admm_streamed(
        HostBlockSource((Xp, yp, wp), n_blocks), n_blocks, d, float(n),
        **kw)
    _, _, (zr, xr, ur), _ = glm_core.admm_streamed(
        HostBlockSource((Xs, ys, ws), n_blocks), n_blocks, d, float(n),
        **kw)
    stream_ok = all(
        np.array_equal(np.asarray(m), np.asarray(r))
        for m, r in ((zm, zr), (xm, xr), (um, ur)))
    return {"kmeans_padded_pinned": kmeans_ok,
            "stream_ragged_pinned": stream_ok}


def bench_compile_report(_rtt):
    """Compile-count observability drill (CI `compile` job; ISSUE 4):

    1. in-process COLD compile census of the gated workload — total
       ``n_compiles``/``compile_seconds`` (jax.monitoring), the shape
       buckets the fold slices shared, and the batched-program compile
       count, which must be bounded by the batch plan's bucket count
       (ONE here: all 3 folds share a train bucket), not candidates x
       folds;
    2. padded-vs-exact pins — exits nonzero if bucket padding changes any
       pinned result (KMeans labels/inertia/n_iter, streamed (z, x, u));
    3. cold-vs-warm persistent-cache drill in fresh subprocesses: the same
       workload against an empty cache dir, then again against the now-
       populated dir — ``compile_seconds`` with and without the
       ``compilation_cache`` knob.
    """
    import subprocess
    import sys

    from dask_ml_tpu.parallel import shapes

    shapes.reset_compile_stats()
    census = _compile_workload()
    stats = shapes.compile_stats()
    pins = _padding_pinned_results()

    cache_dir = tempfile.mkdtemp(prefix="dask_ml_tpu_compile_cache_")
    here = os.path.abspath(__file__)

    def child(arg):
        out = subprocess.run(
            [sys.executable, here, "--compile-child", arg],
            capture_output=True, text=True, timeout=1200)
        if out.returncode != 0:
            raise SystemExit(
                f"compile-report child failed:\n{out.stdout}\n{out.stderr}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = child(cache_dir)   # empty cache: every compile is real + stored
    warm = child(cache_dir)   # second process: loads executables from disk

    n_buckets = len(census["search_shape_buckets"])
    bounded = census["batched_program_compiles"] <= n_buckets
    emit({
        "metric": "compile_report",
        "value": stats["n_compiles"],
        "unit": "XLA compiles for the gated workload (cold, this process)",
        "vs_baseline": None,
        "n_compiles": stats["n_compiles"],
        "compile_seconds": round(stats["compile_seconds"], 3),
        "n_traces": stats["n_traces"],
        "shape_buckets": {str(k): v
                          for k, v in stats["shape_buckets"].items()},
        "search_shape_buckets": census["search_shape_buckets"],
        "batched_program_compiles": census["batched_program_compiles"],
        "batched_compiles_bounded_by_buckets": bounded,
        "n_batched_cells": census["n_batched_cells"],
        **pins,
        "cold": {k: cold[k] for k in ("wall_seconds", "n_compiles",
                                      "compile_seconds")},
        "warm": {k: warm[k] for k in ("wall_seconds", "n_compiles",
                                      "compile_seconds")},
        "warm_compile_speedup": round(
            cold["compile_seconds"] / max(warm["compile_seconds"], 1e-9),
            2),
        "note": "cold/warm are fresh subprocesses sharing one persistent "
                "compilation cache dir (the compilation_cache config "
                "knob); warm's residual compile_seconds is cache "
                "deserialization",
    })
    if not (pins["kmeans_padded_pinned"] and pins["stream_ragged_pinned"]):
        raise SystemExit("compile report: padding changed a pinned result")
    if not bounded:
        raise SystemExit(
            "compile report: batched-program compiles "
            f"({census['batched_program_compiles']}) exceeded the bucket "
            f"count ({n_buckets}) — the compile-once invariant regressed")


def bench_faults(rtt):
    """Deterministic fault-injection drill over a small host-streamed ADMM
    config (CI-sized; the recovery MECHANISMS are scale-independent):

    1. clean run — the baseline wall time;
    2. transient-fault run — injected loader + device_put failures retried
       under a RetryPolicy; must converge to the clean run's exact result,
       and the overhead is retries + backoff;
    3. preempted run — an injected preemption (the SIGTERM path, delivered
       deterministically) drains gracefully to a snapshot, then a resume
       completes; overhead is snapshot + replay of the interrupted epoch.

    ``recovery_overhead`` ratios quantify what a failure costs vs rerunning
    from zero (the reference's only option): resume pays for the snapshot
    and the partial epoch, not the whole fit.
    """
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel.faults import (FaultInjector, Preempted,
                                             RetryPolicy)
    from dask_ml_tpu.parallel.stream import HostBlockSource

    n, d, n_blocks, outer = 65_536, 16, 8, 6
    rng = np.random.RandomState(0)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = np.random.RandomState(3).randn(d).astype(np.float32)
    y = (X @ w_true + rng.standard_normal(n).astype(np.float32)
         > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    kw = dict(family="logistic", regularizer="l2", lamduh=1.0,
              max_iter=outer, abstol=0.0, reltol=0.0)

    def run(source, **extra):
        t0 = time.perf_counter()
        z, _ = glm_core.admm_streamed(source, n_blocks, d, float(n),
                                      **kw, **extra)
        fetch(z)
        return z, time.perf_counter() - t0

    # warm-up compiles, then the clean baseline
    run(HostBlockSource((X, y, w), n_blocks))
    z_clean, t_clean = run(HostBlockSource((X, y, w), n_blocks))

    # transient faults: 2 loader failures + 1 transfer failure, retried
    policy = RetryPolicy(max_retries=3, base_delay=0.01)
    inj = (FaultInjector().fail_load(3, times=2).fail_transfer(5, times=1))
    src_f = HostBlockSource((X, y, w), n_blocks, retry_policy=policy,
                            fault_injector=inj)
    z_retry, t_retry = run(src_f)
    retry_identical = bool(np.array_equal(np.asarray(z_retry),
                                          np.asarray(z_clean)))

    # preemption mid-run, then resume from the snapshot
    ckpt = os.path.join(tempfile.mkdtemp(prefix="dask_ml_tpu_faults_"),
                        "admm.ckpt")
    inj_p = FaultInjector().preempt_at(block=n_blocks // 2,
                                       epoch=outer // 2)
    t0 = time.perf_counter()
    try:
        run(HostBlockSource((X, y, w), n_blocks, fault_injector=inj_p),
            checkpoint_path=ckpt)
        t_interrupted = None  # pragma: no cover - preemption must fire
    except Preempted:
        t_interrupted = time.perf_counter() - t0
    z_resumed, t_resume = run(HostBlockSource((X, y, w), n_blocks),
                              checkpoint_path=ckpt)
    resume_identical = bool(np.array_equal(np.asarray(z_resumed),
                                           np.asarray(z_clean)))

    emit({
        "metric": "fault_recovery_drill",
        "value": round((t_retry + t_resume) / (2 * t_clean), 3),
        "unit": "mean recovery overhead vs clean (1.0 = free)",
        "vs_baseline": None,
        "rows": n, "cols": d, "blocks": n_blocks, "admm_outer_iters": outer,
        "clean_seconds": round(t_clean, 3),
        "transient_fault_seconds": round(t_retry, 3),
        "transient_overhead": round(t_retry / t_clean, 3),
        "transient_identical_result": retry_identical,
        "retry_stats": policy.stats(),
        "injected": dict(inj.injected),
        "preempted_partial_seconds": (None if t_interrupted is None
                                      else round(t_interrupted, 3)),
        "resume_seconds": round(t_resume, 3),
        "preempt_plus_resume_overhead": round(
            ((t_interrupted or 0.0) + t_resume) / t_clean, 3),
        "resume_identical_result": resume_identical,
        "note": "overheads on this CPU mesh are upper bounds: compute per "
                "block is tiny, so snapshot/backoff costs are maximally "
                "visible; at blueprint scale they amortize against real "
                "block solves",
    })
    if not (retry_identical and resume_identical):  # defense in depth: the
        raise SystemExit("fault drill: recovered results diverged")  # CI job fails loudly


# ---------------------------------------------------------------------------
# elastic kill-one-host drill (ISSUE 8): 2 REAL OS processes sharing a
# filesystem workdir, one killed mid-epoch (os._exit — no drain, no
# snapshot, heartbeats just stop), the survivor rebalancing and finishing
# with a bit-identical trajectory. The numbers committed as
# ELASTIC_r01.json and gated by the CI `faults` job
# (`bench.py --faults --elastic`, nonzero exit on divergence).
# ---------------------------------------------------------------------------

#: one problem shape shared by the parent baselines and the workers — the
#: workers REGENERATE the data from the seed (each host of a real fleet
#: loads its own blocks; nothing is shipped)
_ELASTIC = dict(n=65_536, d=16, n_blocks=8, outer=4, seed=11,
                heartbeat=4.0)


def _elastic_problem():
    p = _ELASTIC
    rng = np.random.RandomState(0)
    X = rng.standard_normal((p["n"], p["d"])).astype(np.float32)
    w_true = np.random.RandomState(3).randn(p["d"]).astype(np.float32)
    y = (X @ w_true + rng.standard_normal(p["n"]).astype(np.float32)
         > 0).astype(np.float32)
    return X, y, np.ones(p["n"], np.float32)


def _elastic_fit(source, elastic=None, **extra):
    from dask_ml_tpu.models import glm as glm_core

    p = _ELASTIC
    z, _, (z2, x, u), _ = glm_core.admm_streamed(
        source, p["n_blocks"], p["d"], float(p["n"]),
        family="logistic", regularizer="l2", lamduh=1.0,
        max_iter=p["outer"], abstol=0.0, reltol=0.0, return_state=True,
        elastic=elastic, **extra)
    return np.asarray(z), np.asarray(x), np.asarray(u)


def _elastic_worker():
    """One host of the drill fleet: ``bench.py --elastic-worker RANK
    WORKDIR MODE``. MODE 'kill' arms an injected host death on rank 1 —
    after publishing its first block of epoch 1 the process ``os._exit``s
    (the faithful stand-in for kill -9 / machine loss: no drain, no
    tombstone, heartbeats just stop). Survivors print the final state as
    hex (bit-exact transport) plus their per-host stream stats."""
    import sys

    from dask_ml_tpu.parallel.elastic import (BlockPlan, ElasticRun,
                                              SimulatedHostDeath)
    from dask_ml_tpu.parallel.faults import FaultInjector
    from dask_ml_tpu.parallel.stream import HostBlockSource

    _enable_compilation_cache()
    i = sys.argv.index("--elastic-worker")
    rank, workdir, mode = (int(sys.argv[i + 1]), sys.argv[i + 2],
                           sys.argv[i + 3])
    p = _ELASTIC
    X, y, w = _elastic_problem()
    inj = None
    if mode == "kill" and rank == 1:
        order = BlockPlan(p["n_blocks"], seed=p["seed"]).epoch_order(1)
        shard1 = BlockPlan.shard(order, 1, [0, 1])
        inj = FaultInjector().die_at(block=shard1[0], epoch=1)
    run = ElasticRun(workdir, rank=rank, world=2, shuffle_seed=p["seed"],
                     heartbeat_timeout=p["heartbeat"],
                     fault_injector=inj)
    src = HostBlockSource((X, y, w), p["n_blocks"], host_rank=rank)
    t0 = time.perf_counter()
    try:
        z, x, u = _elastic_fit(src, elastic=run)
    except SimulatedHostDeath:
        os._exit(17)  # kill -9 semantics: no cleanup, no goodbye
    elapsed = time.perf_counter() - t0
    print("Z " + z.tobytes().hex(), flush=True)
    print("X " + x.tobytes().hex(), flush=True)
    print("U " + u.tobytes().hex(), flush=True)
    print("STATS " + json.dumps({
        "rank": rank, "seconds": round(elapsed, 3),
        "bytes_streamed": src.bytes_streamed,
        "logical_bytes_streamed": src.logical_bytes_streamed,
        "hosts_lost": run.hosts_lost,
        "blocks_rebalanced": run.blocks_rebalanced,
    }), flush=True)


def bench_elastic(rtt):
    """The kill-one-host recovery drill (docs/robustness.md "Elastic
    epochs"):

    1. single-host baselines — the non-elastic streamed ADMM and the
       elastic world=1 run must already be bit-identical (the data plane
       adds a disk round-trip per block, not arithmetic);
    2. a 2-process CLEAN elastic run — both hosts finish, both derive the
       baseline's exact (z, x, u) (deterministic consensus: no collective
       exists to disagree through);
    3. the KILL run — rank 1 os._exits after one block of epoch 1; rank 0
       detects the silence via the heartbeat timeout, re-deals the
       orphaned blocks to itself, and finishes all epochs. Gate: the
       survivor's (z, x, u) is bit-identical to the uninterrupted
       single-host baseline.

    ``recovery_overhead`` = kill-run wall / clean-2-process wall. On this
    drill it is dominated by the DETECTION LATENCY (the heartbeat
    timeout) plus the re-dealt blocks' compute — the failure-free path
    pays nothing (no barriers were added; coordination is arithmetic)."""
    import subprocess
    import sys

    from dask_ml_tpu.parallel.elastic import ElasticRun
    from dask_ml_tpu.parallel.stream import HostBlockSource

    p = _ELASTIC
    X, y, w = _elastic_problem()

    # 1. single-host baselines: non-elastic vs elastic world=1
    z_clean, x_clean, u_clean = _elastic_fit(
        HostBlockSource((X, y, w), p["n_blocks"]))
    t0 = time.perf_counter()
    z_clean, x_clean, u_clean = _elastic_fit(
        HostBlockSource((X, y, w), p["n_blocks"]))  # warm timing
    t_single = time.perf_counter() - t0
    wd1 = tempfile.mkdtemp(prefix="dask_ml_tpu_elastic_w1_")
    z_e1, x_e1, u_e1 = _elastic_fit(
        HostBlockSource((X, y, w), p["n_blocks"]),
        elastic=ElasticRun(wd1, rank=0, world=1, shuffle_seed=p["seed"]))
    world1_identical = bool(
        np.array_equal(z_e1, z_clean) and np.array_equal(x_e1, x_clean)
        and np.array_equal(u_e1, u_clean))

    def fleet(mode):
        workdir = tempfile.mkdtemp(prefix=f"dask_ml_tpu_elastic_{mode}_")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        t0 = time.perf_counter()
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--elastic-worker", str(r), workdir, mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
            for r in (0, 1)]
        outs = [pr.communicate(timeout=900)[0] for pr in procs]
        wall = time.perf_counter() - t0
        return procs, outs, wall

    def parse(out):
        state, stats = {}, None
        for line in out.splitlines():
            for tag in ("Z", "X", "U"):
                if line.startswith(tag + " "):
                    state[tag] = np.frombuffer(
                        bytes.fromhex(line.split()[1]), np.float32)
            if line.startswith("STATS "):
                stats = json.loads(line[len("STATS "):])
        return state, stats

    def identical(state):
        # a worker that died mid-report leaves a partial state dict —
        # that must FAIL the gate, not crash the drill before it emits
        if not all(tag in state for tag in ("Z", "X", "U")):
            return False
        return bool(
            np.array_equal(state["Z"], z_clean)
            and np.array_equal(state["X"], x_clean.ravel())
            and np.array_equal(state["U"], u_clean.ravel()))

    # 2. clean 2-process run: both hosts finish with the baseline's bytes
    procs, outs, t_clean2 = fleet("clean")
    clean_ok = all(pr.returncode == 0 for pr in procs)
    clean_states = [parse(out) for out in outs]
    clean_identical = all(identical(st) for st, _ in clean_states)

    # 3. the kill run: rank 1 dies mid-epoch, rank 0 must finish alone
    procs, outs, t_kill = fleet("kill")
    kill_rcs = [pr.returncode for pr in procs]
    surv_state, surv_stats = parse(outs[0])
    kill_ok = kill_rcs[0] == 0 and kill_rcs[1] == 17
    kill_identical = identical(surv_state)

    per_host_gbps = {
        f"host{st['rank']}": round(
            st["bytes_streamed"] / st["seconds"] / 1e9, 3)
        for _, st in clean_states if st is not None}
    gates = {
        "world1_bit_identical": world1_identical,
        "clean_2proc_exit_ok": clean_ok,
        "clean_2proc_bit_identical": clean_identical,
        "kill_exit_codes_ok": kill_ok,
        "survivor_bit_identical": kill_identical,
        "survivor_observed_loss_and_rebalanced": bool(
            surv_stats and surv_stats["hosts_lost"] == 1
            and surv_stats["blocks_rebalanced"] >= 1),
    }
    rec = {
        "metric": "elastic_kill_one_host_drill",
        "value": round(t_kill / max(t_clean2, 1e-9), 3),
        "unit": "recovery overhead vs clean 2-process run (1.0 = free)",
        "vs_baseline": None,
        "rows": p["n"], "cols": p["d"], "blocks": p["n_blocks"],
        "admm_outer_iters": p["outer"], "shuffle_seed": p["seed"],
        "heartbeat_timeout_seconds": p["heartbeat"],
        "single_host_seconds": round(t_single, 3),
        "clean_2proc_seconds": round(t_clean2, 3),
        "kill_2proc_seconds": round(t_kill, 3),
        "gates": gates,
        "per_host_effective_gbps_clean": per_host_gbps,
        "survivor_stats": surv_stats,
        "survivor_effective_gbps": (
            None if not surv_stats else round(
                surv_stats["bytes_streamed"] / surv_stats["seconds"] / 1e9,
                3)),
        "note": "2-process wall includes per-worker process start + "
                "compile (persistent cache warm); recovery overhead is "
                "dominated by the heartbeat detection latency plus the "
                "re-dealt blocks — the failure-free path adds no "
                "barriers. Workers exchange NOTHING but the shared "
                "workdir: kill -9 is survivable because per-block "
                "results are published atomically as they complete.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ELASTIC_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "elastic drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# ASHA on the elastic data plane (ISSUE 19): successive halving must find
# the synchronous grid search's optimum at <= 1/5 its fit-epoch budget,
# promote without recompiling after each bracket's first rung, resume a
# killed search from the journal bit-identically, and survive a
# kill-one-host drill mid-bracket with zero dropped candidates — the
# numbers committed as SEARCH_r01.json and run by the CI `search` job
# ---------------------------------------------------------------------------

_ASHA = {
    # KDD-character scaled binary problem: 23 imbalanced clusters with
    # per-feature scale spread (the _load_kdd stand-in's shape), labeled
    # dominant-attack-cluster vs rest. ASHA_N scales the drill for CI.
    "n": int(os.environ.get("ASHA_N", 200_000)),
    "d": 20,
    "n_blocks": 8,
    "max_epochs": 16,
    "eta": 4,
    "heartbeat": float(os.environ.get("ASHA_HEARTBEAT", 5.0)),
    "grid": {"C": [1e-3, 1e-2, 1e-1, 1.0],
             "solver_kwargs": [{"eta0": 0.05}, {"eta0": 0.2},
                               {"eta0": 0.5}, {"eta0": 1.0}]},
}


def _asha_problem():
    n, d = _ASHA["n"], _ASHA["d"]
    rng = np.random.RandomState(99)
    n_clusters = 23
    centers = rng.randn(n_clusters, d) * np.exp(rng.randn(1, d))
    logits = -0.45 * np.arange(n_clusters)
    p = np.exp(logits) / np.exp(logits).sum()
    ids = rng.choice(n_clusters, size=n, p=p)
    X = (centers[ids] + 0.3 * rng.randn(n, d)).astype(np.float32)
    y = (ids == 0).astype(np.int64)  # the smurf-like dominant class
    return X, y


def _asha_search(elastic=None, checkpoint=None, sync=False):
    """The drill's searcher. ``sync=True`` degenerates the bracket to a
    single rung with EVERY candidate trained to max_epochs — the honest
    synchronous grid reference on the identical data plane, split, and
    scoring (n_initial_epochs = max_epochs means no promotion ever
    happens)."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import SuccessiveHalvingSearchCV

    p = _ASHA
    return SuccessiveHalvingSearchCV(
        LogisticRegression(solver="gradient_descent"), p["grid"],
        n_initial_parameters="grid",
        n_initial_epochs=p["max_epochs"] if sync else 1,
        aggressiveness=p["eta"], max_epochs=p["max_epochs"],
        n_blocks=p["n_blocks"], random_state=0, shuffle_seed=0,
        elastic=elastic, checkpoint=checkpoint)


def _asha_worker():
    """One host of the search fleet: ``bench.py --asha-worker RANK
    WORKDIR MODE``. MODE 'kill' arms an injected host death on rank 1:
    after publishing its first candidate of the second rung (uid 1001)
    the process ``os._exit``s — kill -9 semantics mid-bracket. The
    survivor prints every candidate's final score as hex (bit-exact
    transport) plus the winning parameters."""
    import sys

    from dask_ml_tpu.parallel.elastic import (ElasticRun,
                                              SimulatedHostDeath)
    from dask_ml_tpu.parallel.faults import FaultInjector

    _enable_compilation_cache()
    i = sys.argv.index("--asha-worker")
    rank, workdir, mode = (int(sys.argv[i + 1]), sys.argv[i + 2],
                           sys.argv[i + 3])
    inj = None
    if mode == "kill" and rank == 1:
        # rung 1 (uid 1001) holds 4 alive candidates; rank 1 owns the
        # upper shard {2, 3} — die right after publishing candidate 2
        inj = FaultInjector().die_at(block=2, epoch=1001)
    run = ElasticRun(workdir, rank=rank, world=2,
                     heartbeat_timeout=_ASHA["heartbeat"],
                     poll_interval=0.05, fault_injector=inj)
    X, y = _asha_problem()
    sh = _asha_search(elastic=run)
    t0 = time.perf_counter()
    try:
        sh.fit(X, y)
    except SimulatedHostDeath:
        os._exit(17)
    elapsed = time.perf_counter() - t0
    scores = np.asarray(sh.cv_results_["test_score"], np.float64)
    print("SCORES " + scores.tobytes().hex(), flush=True)
    print("BEST " + json.dumps(sh.best_params_, sort_keys=True),
          flush=True)
    print("STATS " + json.dumps({
        "rank": rank, "seconds": round(elapsed, 3),
        "hosts_lost": run.hosts_lost,
        "blocks_rebalanced": sh.n_blocks_rebalanced_,
        "blocks_speculated": sh.n_blocks_speculated_,
        "budget_fit_epochs": sh.budget_spent_,
    }), flush=True)


def bench_asha(_rtt):
    """The asynchronous-search drill (docs/search.md):

    1. ASHA vs the synchronous grid — same estimator, grid, data plane,
       split, and scoring; the sync run is the same searcher degenerated
       to one full-budget rung. Gates: identical winning parameters at
       <= 1/5 the fit-epoch budget.
    2. compile discipline — zero fresh heavy compiles after the
       bracket's first rung (promotions shrink the batched program's
       alive-MASK, never a shape).
    3. journal resume — truncate the search's journal mid-bracket,
       refit, and every score byte and the winner must reproduce.
    4. kill-one-host — a 2-process fleet; rank 1 dies mid-bracket after
       publishing one rung-1 candidate. Gates: the survivor scores ALL
       candidates (zero dropped), bit-identical to the single-host run.
    """
    import subprocess
    import sys

    X, y = _asha_problem()
    p = _ASHA

    # 1. synchronous grid reference, then ASHA on the same plane
    t0 = time.perf_counter()
    sync = _asha_search(sync=True).fit(X, y)
    t_sync = time.perf_counter() - t0
    t0 = time.perf_counter()
    asha = _asha_search().fit(X, y)
    t_asha = time.perf_counter() - t0
    budget_ratio = asha.budget_spent_ / max(sync.budget_spent_, 1)
    found_optimum = asha.best_params_ == sync.best_params_

    # 2. compile gate: every post-rung-0 rung compiled nothing
    late = [r["n_compiles"] for r in asha.rung_compile_stats_
            if r["rung"] > 0]
    compile_ok = len(late) > 0 and sum(late) == 0

    # 3. journal resume, truncated mid-bracket
    from dask_ml_tpu.checkpoint import CellJournal

    wd = tempfile.mkdtemp(prefix="dask_ml_tpu_asha_ck_")
    ck = os.path.join(wd, "asha.journal")
    a = _asha_search(checkpoint=ck).fit(X, y)
    full = list(CellJournal(ck).load().items())
    ck2 = os.path.join(wd, "resume.journal")
    j2 = CellJournal(ck2)
    for k, v in full[:len(full) * 6 // 10]:
        j2.append(k, v)
    b = _asha_search(checkpoint=ck2).fit(X, y)
    resume_identical = bool(
        np.array_equal(np.asarray(a.cv_results_["test_score"]),
                       np.asarray(b.cv_results_["test_score"]))
        and a.best_params_ == b.best_params_
        and b.n_resumed_rungs_ > 0)

    # 4. the 2-process kill drill
    ref_scores = np.asarray(asha.cv_results_["test_score"], np.float64)

    def fleet(mode):
        workdir = tempfile.mkdtemp(prefix=f"dask_ml_tpu_asha_{mode}_")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        t0 = time.perf_counter()
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--asha-worker", str(r), workdir, mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
            for r in (0, 1)]
        outs = [pr.communicate(timeout=900)[0] for pr in procs]
        return procs, outs, time.perf_counter() - t0

    def parse(out):
        scores, best, stats = None, None, None
        for line in out.splitlines():
            if line.startswith("SCORES "):
                scores = np.frombuffer(
                    bytes.fromhex(line.split()[1]), np.float64)
            elif line.startswith("BEST "):
                best = json.loads(line[len("BEST "):])
            elif line.startswith("STATS "):
                stats = json.loads(line[len("STATS "):])
        return scores, best, stats

    procs, outs, t_kill = fleet("kill")
    kill_rcs = [pr.returncode for pr in procs]
    surv_scores, surv_best, surv_stats = parse(outs[0])
    kill_ok = kill_rcs[0] == 0 and kill_rcs[1] == 17
    zero_dropped = bool(
        surv_scores is not None and len(surv_scores) == len(ref_scores)
        and np.isfinite(surv_scores).all())
    kill_identical = bool(
        surv_scores is not None
        and np.array_equal(surv_scores, ref_scores)
        and surv_best == json.loads(
            json.dumps(asha.best_params_, sort_keys=True)))

    gates = {
        "asha_finds_grid_optimum": bool(found_optimum),
        "budget_ratio_le_one_fifth": bool(budget_ratio <= 0.2),
        "zero_compiles_after_rung0": bool(compile_ok),
        "journal_resume_bit_identical": resume_identical,
        "kill_exit_codes_ok": bool(kill_ok),
        "kill_zero_dropped_candidates": zero_dropped,
        "survivor_bit_identical": kill_identical,
        "survivor_observed_loss_and_rebalanced": bool(
            surv_stats and surv_stats["hosts_lost"] == 1
            and surv_stats["blocks_rebalanced"] >= 1),
    }
    rec = {
        "metric": "asha_vs_synchronous_grid",
        "value": round(budget_ratio, 4),
        "unit": "fit-epoch budget vs synchronous grid (gate: <= 0.2)",
        "vs_baseline": None,
        "rows": p["n"], "cols": p["d"], "blocks": p["n_blocks"],
        "n_candidates": sync.metadata_["n_models"],
        "max_epochs": p["max_epochs"], "aggressiveness": p["eta"],
        "rung_table": asha.rung_table_,
        "asha_fit_epochs": asha.budget_spent_,
        "sync_fit_epochs": sync.budget_spent_,
        "asha_best_params": json.loads(
            json.dumps(asha.best_params_, sort_keys=True)),
        "sync_best_params": json.loads(
            json.dumps(sync.best_params_, sort_keys=True)),
        "asha_best_score": round(asha.best_score_, 6),
        "sync_best_score": round(sync.best_score_, 6),
        "asha_seconds": round(t_asha, 3),
        "sync_seconds": round(t_sync, 3),
        "kill_2proc_seconds": round(t_kill, 3),
        "rung_compile_stats": asha.rung_compile_stats_,
        "survivor_stats": surv_stats,
        "heartbeat_timeout_seconds": p["heartbeat"],
        "gates": gates,
        "note": "sync reference = the same searcher degenerated to one "
                "full-budget rung (identical split, blocks, scoring); "
                "the budget ratio counts logical fit-epochs, so it is "
                "hardware-independent. The kill drill murders rank 1 "
                "after it publishes one rung-1 candidate; candidate "
                "rungs are pure functions of journaled state + seeded "
                "epoch orders, so the survivor's recomputation is "
                "byte-identical and no candidate is dropped.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "SEARCH_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "asha drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# mixed-precision f32-vs-bf16 grid (ISSUE 5): wire bytes, effective GB/s,
# end-to-end fit time, and accuracy deltas for the streamed tier + every
# solver family — the numbers committed as PRECISION_r01.json and printed
# by the CI `precision` job (nonzero exit if any accuracy gate fails)
# ---------------------------------------------------------------------------


def bench_precision(rtt):
    """The f32-vs-bf16 precision grid (docs/precision.md):

    1. streamed ADMM + streamed-PCA moments at the tier's REAL bottleneck
       (host-resident blocks through ``HostBlockSource``), run under the
       f32 null policy and the bf16 wire policy — wire bytes vs logical
       bytes (their ratio is the policy's transfer win; the acceptance
       gate is >= 1.8x), effective GB/s on BOTH accountings, end-to-end
       seconds, and the result's relative delta vs the f32 run;
    2. in-memory solver accuracy gates — L-BFGS/Newton coefficients,
       KMeans inertia, randomized-SVD singular values — each pinned
       against its f32 baseline with the tolerances tabulated in
       docs/precision.md.

    Exits nonzero if any wire-reduction or accuracy gate fails. On this
    CPU CI mesh the bf16 matmuls are emulated (slower than f32 — the
    speed column only means something on TPU, where bf16 is the MXU's
    native path); the wire-byte and accuracy columns are
    backend-independent, which is why the gate runs everywhere."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu import config
    from dask_ml_tpu.decomposition.streaming import (_pca_from_moments,
                                                     streamed_moments)
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel import precision as px
    from dask_ml_tpu.parallel.stream import HostBlockSource

    COEF_RTOL, VAR_RTOL, INERTIA_RTOL = 5e-2, 2e-2, 1e-2
    rng = np.random.RandomState(0)
    gates = {}

    def rel(a, b):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12))

    # -- streamed ADMM at the wire -----------------------------------------
    n, d, n_blocks, outer = 65_536, 100, 8, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = np.random.RandomState(3).randn(d).astype(np.float32)
    y = (X @ w_true + rng.standard_normal(n).astype(np.float32)
         > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    admm_kw = dict(family="logistic", regularizer="l2", lamduh=1.0,
                   max_iter=outer, abstol=0.0, reltol=0.0)

    def run_admm(policy):
        with config.config_context(precision=policy):
            src = HostBlockSource((X, y, w), n_blocks)
        t0 = time.perf_counter()
        z, _ = glm_core.admm_streamed(src, n_blocks, d, float(n), **admm_kw)
        fetch(z)
        return (np.asarray(z), time.perf_counter() - t0,
                src.bytes_streamed, src.logical_bytes_streamed)

    run_admm(None)  # warm-up compiles
    z32, t32, wire32, logical32 = run_admm(None)
    run_admm(px.BF16)
    z16, t16, wire16, logical16 = run_admm(px.BF16)
    admm_wire_reduction = logical16 / wire16
    admm_delta = rel(z16, z32)
    gates["admm_wire_reduction_ge_1.8"] = bool(admm_wire_reduction >= 1.8)
    gates["admm_coef_delta_le_tol"] = bool(admm_delta <= COEF_RTOL)

    # -- streamed PCA moments at the wire ----------------------------------
    np_, dp, pblocks, kp = 131_072, 256, 8, 16
    scale = np.linspace(3.0, 0.3, dp).astype(np.float32)
    Xp = rng.standard_normal((np_, dp)).astype(np.float32) * scale + 1.0
    wp = np.ones(np_, np.float32)

    def run_pca(policy):
        with config.config_context(precision=policy):
            src = HostBlockSource((Xp, wp), pblocks)
        t0 = time.perf_counter()
        sw, s, G = streamed_moments(block_fn=src, n_blocks=pblocks)
        _mean, evals, _comps = _pca_from_moments(sw, s, G)
        fetch(evals)
        return (np.asarray(evals[:kp]), time.perf_counter() - t0,
                src.bytes_streamed, src.logical_bytes_streamed)

    run_pca(None)  # warm-up compiles
    ev32, pt32, pwire32, plogical32 = run_pca(None)
    run_pca(px.BF16)
    ev16, pt16, pwire16, plogical16 = run_pca(px.BF16)
    pca_wire_reduction = plogical16 / pwire16
    pca_delta = rel(ev16, ev32)
    gates["pca_wire_reduction_ge_1.8"] = bool(pca_wire_reduction >= 1.8)
    gates["pca_variance_delta_le_tol"] = bool(pca_delta <= VAR_RTOL)

    # -- in-memory solver gates --------------------------------------------
    ns, ds = 4096, 32
    Xs = rng.standard_normal((ns, ds)).astype(np.float32)
    ys = (Xs @ np.random.RandomState(1).randn(ds) > 0).astype(np.float32)
    ws = jnp.ones((ns,), jnp.float32)
    beta0 = jnp.zeros((ds,), jnp.float32)
    mask = jnp.ones((ds,), jnp.float32)
    solver_rows = {}
    for name, fn in (("lbfgs", glm_core.lbfgs), ("newton", glm_core.newton)):
        b32, it32 = fn(jnp.asarray(Xs), jnp.asarray(ys), ws, beta0, mask,
                       family="logistic", regularizer="l2", lamduh=1.0,
                       max_iter=100)
        b16, it16 = fn(jnp.asarray(Xs, jnp.bfloat16), jnp.asarray(ys), ws,
                       beta0, mask, family="logistic", regularizer="l2",
                       lamduh=1.0, max_iter=100)
        delta = rel(b16, b32)
        solver_rows[name] = {
            "coef_rel_delta": round(delta, 5),
            "n_iter_f32": int(it32), "n_iter_bf16": int(it16),
        }
        gates[f"{name}_coef_delta_le_tol"] = bool(delta <= COEF_RTOL)

    from dask_ml_tpu.cluster import KMeans

    centers = (rng.standard_normal((8, 16)) * 6).astype(np.float32)
    Xk = np.concatenate([
        c + rng.standard_normal((2048, 16)).astype(np.float32)
        for c in centers])
    # the f32 baseline is PINNED to the null policy: on TPU the default
    # "auto" resolves to BF16, and an unpinned baseline would stage bf16
    # itself — making the gate compare bf16 against bf16
    with config.config_context(precision=None):
        km32 = KMeans(n_clusters=8, init="random", random_state=0,
                      max_iter=50).fit(Xk)
    with config.config_context(precision="bf16"):
        km16 = KMeans(n_clusters=8, init="random", random_state=0,
                      max_iter=50).fit(Xk)
    inertia_delta = abs(float(km16.inertia_) - float(km32.inertia_)) \
        / float(km32.inertia_)
    gates["kmeans_inertia_delta_le_tol"] = bool(
        inertia_delta <= INERTIA_RTOL)

    from dask_ml_tpu.ops import linalg
    from dask_ml_tpu.parallel.sharding import prepare_data

    A = rng.standard_normal((8192, 16)).astype(np.float32)
    B = rng.standard_normal((16, 64)).astype(np.float32)
    Xr = A @ B + 0.05 * rng.standard_normal((8192, 64)).astype(np.float32)
    with config.config_context(precision=None):  # stage f32 on any backend
        data = prepare_data(Xr)
    _, S32, _ = linalg.svd_compressed(data.X, 12, 2, weights=data.weights,
                                      compute_dtype=None)
    _, S16, _ = linalg.svd_compressed(data.X, 12, 2, weights=data.weights,
                                      compute_dtype=jnp.bfloat16)
    sketch_delta = rel(S16, S32)
    gates["sketch_singular_values_delta_le_tol"] = bool(
        sketch_delta <= VAR_RTOL)

    emit({
        "metric": "precision_grid",
        "value": round(min(admm_wire_reduction, pca_wire_reduction), 3),
        "unit": "min wire-byte reduction (logical/wire) on the streamed "
                "ADMM/PCA paths under the bf16 policy",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "tolerances": {"coef_rtol": COEF_RTOL, "var_rtol": VAR_RTOL,
                       "inertia_rtol": INERTIA_RTOL},
        "admm_streamed": {
            "rows": n, "cols": d, "blocks": n_blocks, "outer_iters": outer,
            "f32": {"seconds": round(t32, 3), "wire_bytes": int(wire32),
                    "logical_bytes": int(logical32),
                    "wire_gbps": round(wire32 / t32 / 1e9, 4)},
            "bf16": {"seconds": round(t16, 3), "wire_bytes": int(wire16),
                     "logical_bytes": int(logical16),
                     "wire_gbps": round(wire16 / t16 / 1e9, 4),
                     "logical_gbps": round(logical16 / t16 / 1e9, 4)},
            "wire_reduction": round(admm_wire_reduction, 3),
            "coef_rel_delta": round(admm_delta, 5),
        },
        "pca_streamed_moments": {
            "rows": np_, "cols": dp, "blocks": pblocks,
            "f32": {"seconds": round(pt32, 3), "wire_bytes": int(pwire32),
                    "logical_bytes": int(plogical32),
                    "wire_gbps": round(pwire32 / pt32 / 1e9, 4)},
            "bf16": {"seconds": round(pt16, 3), "wire_bytes": int(pwire16),
                     "logical_bytes": int(plogical16),
                     "wire_gbps": round(pwire16 / pt16 / 1e9, 4),
                     "logical_gbps": round(plogical16 / pt16 / 1e9, 4)},
            "wire_reduction": round(pca_wire_reduction, 3),
            "explained_variance_rel_delta": round(pca_delta, 5),
        },
        "solvers": solver_rows,
        "kmeans_inertia_rel_delta": round(inertia_delta, 6),
        "kmeans_n_iter": [int(km32.n_iter_), int(km16.n_iter_)],
        "sketch_singular_values_rel_delta": round(sketch_delta, 5),
        "note": "wire/accuracy columns are backend-independent; the "
                "seconds columns only mean speed on TPU (CPU emulates "
                "bf16 matmuls). PRECISION_r01.json commits this record.",
    })
    if not all(gates.values()):
        raise SystemExit(
            "precision grid: failed gates: "
            + ", ".join(k for k, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# bounded-Lloyd drill (ISSUE 6): exactness gate vs the oracle loops +
# measured iteration speedup / pruned fraction, committed as
# BOUNDS_r01.json — the CI `kernels` job runs this and exits nonzero if
# the bounded path diverges from the oracle
# ---------------------------------------------------------------------------


def _bounds_synth(n, d, key_seed=99):
    """KDD-character synthetic at a chosen n (the bench_kdd stand-in's
    recipe: 23 imbalanced clusters, per-feature scale spread) sharded over
    the default mesh."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.default_mesh()
    row_sh = mesh_lib.data_sharding(mesh, ndim=2)
    kt = 23

    def gen(key):
        kc, ks, kp, ki, kn = jax.random.split(key, 5)
        centers = jax.random.normal(kc, (kt, d)) * \
            jnp.exp(jax.random.normal(ks, (1, d)) * 1.5)
        logits = -0.45 * jnp.arange(kt, dtype=jnp.float32)
        ids = jax.random.categorical(ki, logits, shape=(n,))
        noise = jax.random.normal(kn, (n, d), jnp.float32)
        return centers[ids] + noise * 0.3 * jnp.exp(
            jax.random.normal(kp, (1, d)) * 0.5)

    X = jax.jit(gen, out_shardings=row_sh)(jax.random.key(key_seed))
    jax.block_until_ready(X)
    return X, mesh


def bench_bounds(_rtt):
    """Bounded-Lloyd exactness + speedup drill (docs/kernels.md,
    "Bound-based pruning"):

    1. **Exactness gates** — bounded vs oracle (``lloyd_loop_fused``) on
       KDD-shaped synthetic data: bit-identical centers, identical
       labels, identical re-evaluated inertia, identical stopping — for
       ``kernel='xla'`` at pin scale and interpret-mode pallas at smoke
       scale. Any divergence exits nonzero.
    2. **Iteration speedup** — full-loop wall times at ``BOUNDS_N`` rows
       (env-overridable; tol=0 so the loop runs a fixed iteration count)
       plus a STEADY-STATE comparison: both loops restarted from the
       converged centers, where the bounds are saturated and the bounded
       loop skips ~all distance work — the regime the optimization buys.
    3. **Pruned fraction** — per-iteration ``rows_skipped / n`` from the
       bounded carry; gated ``> 0.5`` by the late iterations.
    4. **Compile-count gate** — a second bounded fit at the same shapes
       must add ZERO compiles (the bound path is one program, not a
       recompile per iteration).

    The record is committed as BOUNDS_r01.json.
    """
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models import kmeans as core
    from dask_ml_tpu.parallel.shapes import track_compiles
    from dask_ml_tpu.parallel.sharding import prepare_data

    gates = {}
    k, d, max_iter = 8, 41, 24
    tol0 = jnp.asarray(0.0, jnp.float32)

    # -- 1. exactness pins -------------------------------------------------
    def pin(n, kernel):
        X, mesh = _bounds_synth(n, d)
        data = prepare_data(np.asarray(X))
        c0 = core.init_random(data.X, data.weights, data.n, k,
                              jax.random.key(0))
        tol = jnp.asarray(1e-4, jnp.float32)
        of = core.lloyd_loop_fused(data.X, data.weights, c0, tol,
                                   mesh=data.mesh, max_iter=max_iter,
                                   kernel="xla")
        ob = core.lloyd_loop_bounded(data.X, data.weights, c0, tol,
                                     mesh=data.mesh, max_iter=max_iter,
                                     kernel=kernel)
        centers_ok = bool(
            (np.asarray(of[0]) == np.asarray(ob[0])).all())
        labels_ok = bool((np.asarray(core.predict_labels(data.X, of[0]))
                          == np.asarray(ob[4])).all())
        inertia_ok = bool(
            float(core.compute_inertia(data.X, data.weights, of[0]))
            == float(core.compute_inertia(data.X, data.weights, ob[0])))
        # the bounded loop's own RETURNED inertia (its jitted
        # final-assignment epilogue) must agree with an independent
        # recompute on its centers — compute_inertia is a different
        # expression, so this is a tight-tolerance consistency gate, not
        # a bit pin; it catches an epilogue regression (e.g. the
        # eager-reduction drift _bounded_final_assign exists to prevent)
        # that the centers-level bit pins above are blind to
        recomputed = float(core.compute_inertia(data.X, data.weights,
                                                ob[0]))
        ret_inertia_ok = bool(
            abs(float(ob[1]) - recomputed) <= 1e-6 * max(recomputed, 1.0))
        iters_ok = int(of[2]) == int(ob[2])
        return (centers_ok and labels_ok and inertia_ok and iters_ok
                and ret_inertia_ok)

    n_pin = int(os.environ.get("BOUNDS_PIN_N", 200_000))
    gates["bounded_xla_bit_identical"] = pin(n_pin, "xla")
    # interpret-mode pallas is slow on CPU — smoke scale keeps the CI job
    # honest about the kernel path without a multi-minute pin
    gates["bounded_pallas_bit_identical"] = pin(
        int(os.environ.get("BOUNDS_PALLAS_N", 20_000)), "pallas")

    # -- 2+3. measured speedup + pruned fraction ---------------------------
    n_big = int(os.environ.get("BOUNDS_N", 2_000_000))
    X, mesh = _bounds_synth(n_big, d)
    data = prepare_data(np.asarray(X))
    c0 = core.init_random(data.X, data.weights, data.n, k,
                          jax.random.key(1))

    def t_full(c_init, iters):
        return measure(partial(core.lloyd_loop_fused, mesh=data.mesh,
                               max_iter=iters, kernel="xla"),
                       data.X, data.weights, c_init, tol0, reps=2)

    def t_bound(c_init, iters):
        return measure(partial(core.lloyd_loop_bounded, mesh=data.mesh,
                               max_iter=iters, kernel="xla"),
                       data.X, data.weights, c_init, tol0, reps=2)

    t_oracle = t_full(c0, max_iter)
    t_bounded = t_bound(c0, max_iter)
    out = core.lloyd_loop_bounded(data.X, data.weights, c0, tol0,
                                  mesh=data.mesh, max_iter=max_iter,
                                  kernel="xla")
    n_iter = int(out[2])
    pruned = [round(float(s) / data.n, 4)
              for s in np.asarray(out[5]["rows_skipped"])[:n_iter]]
    held = [round(float(s) / data.n, 4)
            for s in np.asarray(out[5]["bounds_held"])[:n_iter]]
    late = pruned[-max(2, len(pruned) // 4):]
    gates["late_pruned_fraction_gt_0.5"] = bool(
        min(late) > 0.5) if late else False

    # steady state: restart both loops from the converged centers — the
    # bounds saturate after the first iteration and the remaining ones
    # skip ~all distance work
    c_conv = out[0]
    tail_iters = 8
    t_tail_oracle = t_full(c_conv, tail_iters)
    t_tail_bounded = t_bound(c_conv, tail_iters)

    # -- 4. compile-count gate ---------------------------------------------
    with track_compiles() as tc:
        core.lloyd_loop_bounded(data.X, data.weights, c0, tol0,
                                mesh=data.mesh, max_iter=max_iter,
                                kernel="xla")
    gates["bounded_refit_zero_compiles"] = int(tc["n_compiles"]) == 0

    rec = {
        "metric": "bounded_lloyd",
        "value": round(t_tail_oracle / max(t_tail_bounded, 1e-9), 3),
        "unit": "steady-state Lloyd-iteration speedup (oracle/bounded, "
                "bounds saturated)",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "rows": n_big, "cols": d, "n_clusters": k, "max_iter": max_iter,
        "full_loop_seconds": {"oracle": round(t_oracle, 3),
                              "bounded": round(t_bounded, 3),
                              "speedup": round(
                                  t_oracle / max(t_bounded, 1e-9), 3)},
        "steady_state_seconds": {
            "iters": tail_iters,
            "oracle": round(t_tail_oracle, 3),
            "bounded": round(t_tail_bounded, 3),
            "speedup": round(t_tail_oracle / max(t_tail_bounded, 1e-9), 3)},
        "lloyd_pruned_fraction": pruned,
        "lloyd_bound_held_fraction": held,
        "pin_rows": n_pin,
        "note": "exactness gates compare against the lloyd_loop_fused "
                "oracle (bit-identical centers / labels / inertia / "
                "stopping); pruned fraction is distance work actually "
                "avoided (block granularity, ops/fused_distance.py "
                "row_need contract), bound_held the row-level bound hit "
                "rate. Off-TPU the speedups measure the XLA block-skip "
                "lowering only.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BOUNDS_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "bounded lloyd drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# two-level mesh scale-out drill (ISSUE 10): flat vs (pod, chip) on the
# 8-device CPU mesh — trajectory pins per solver family, the cross-pod
# ("DCN-modeled") logical-byte reduction gate, the compile-once gate, and
# the telemetry-mirror exactness pin. Committed as MULTICHIP_r06.json.
# ---------------------------------------------------------------------------


def _multichip_child():
    """Re-exec target: the drill needs >= 8 devices; when the parent
    process has fewer (the TPU deployment has 1 local chip), the drill
    runs in a subprocess on a forced 8-device CPU mesh — same pattern as
    __graft_entry__.dryrun_multichip."""
    import subprocess
    import sys

    env = dict(os.environ)
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    env["JAX_PLATFORMS"] = "cpu"
    env["_DASK_ML_TPU_MULTICHIP_CHILD"] = "1"
    # forward the drill-selection and DCN-model flags so the child runs
    # the same variant the parent was asked for (--model-axis, --dcn-*)
    extra = [a for a in sys.argv[1:]
             if a == "--model-axis" or a.startswith("--dcn-")]
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multichip", *extra],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    # the child emitted the records and its own summary; exit with its
    # status so the parent never appends an empty duplicate summary
    raise SystemExit(proc.returncode)


def _multichip_dryrun_smoke() -> dict:
    """The driver's entry-point smoke (the r05 record), upgraded per the
    satellite: besides {rc, ok, tail} it now records n_devices, the mesh
    shapes exercised, per-axis collective bytes/calls (parsed from the
    dryrun's LEDGER line), and wall time — so MULTICHIP trajectory files
    stay comparable across PRs even when only the dryrun runs."""
    import subprocess
    import sys

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         "import __graft_entry__ as g; g.dryrun_multichip(8); "
         "print('dryrun_multichip subprocess: ok')"],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip(),
             "_DASK_ML_TPU_DRYRUN_CHILD": "1"},
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True, text=True, timeout=900)
    wall = time.perf_counter() - t0
    out = (proc.stdout or "") + (proc.stderr or "")
    ledger_lines = [ln for ln in out.splitlines()
                    if ln.startswith("LEDGER ")]
    per_axis = json.loads(ledger_lines[-1][len("LEDGER "):]) \
        if ledger_lines else None
    return {
        "n_devices": 8,
        "mesh_shapes": {"flat": [8], "hierarchical": [2, 4]},
        "rc": proc.returncode,
        "ok": proc.returncode == 0,
        "wall_seconds": round(wall, 2),
        "per_axis_collectives": per_axis,
        "tail": out[-600:],
    }


def _dcn_knobs():
    """Wall-clock DCN model knobs (PR 16 satellite): per-hop one-way
    latency (us) and per-link bandwidth (GB/s). Argv wins over env over
    defaults; the defaults are public TPU-v4-pod-interconnect figures
    (~50 us cross-pod hop, ~25 GB/s per DCN link)."""
    import sys

    lat = float(os.environ.get("DCN_LATENCY_US", 50.0))
    bw = float(os.environ.get("DCN_GBPS", 25.0))
    for a in sys.argv[1:]:
        if a.startswith("--dcn-latency-us="):
            lat = float(a.split("=", 1)[1])
        elif a.startswith("--dcn-gbps="):
            bw = float(a.split("=", 1)[1])
    return lat, bw


def _dcn_seconds(snap, axis, n_hops, latency_us, gbps):
    """Modeled DCN wall seconds for one ledger snapshot: every collective
    call on ``axis`` pays ``n_hops`` ring hops of per-hop latency, and the
    axis's logical combining bytes drain once through the DCN bandwidth.
    Flat meshes are charged on the ``data`` axis (topology-oblivious
    routing exposes every ring hop to DCN, ``n_hops = N-1``); hierarchical
    meshes only on the ``pod`` axis (``n_hops = n_pods-1``; the chip axis
    rides ICI and is free at this model's resolution). The degenerate
    ``(1, c)`` mesh has zero DCN hops and sleeps zero seconds."""
    if n_hops <= 0:
        return 0.0
    calls = sum(c for key, c in snap["calls"].items()
                if key.startswith(axis + "/"))
    nbytes = snap["bytes"].get(axis, 0)
    return calls * n_hops * latency_us * 1e-6 + nbytes / (gbps * 1e9)


def bench_multichip(_rtt):
    """Hierarchical scale-out drill (docs/scale-out.md):

    1. **Dryrun smoke** — the entry-point SPMD check, now recording mesh
       shape + per-axis collective bytes/calls + wall time (satellite).
    2. **Trajectory pins** — every hpsum solver family (Lloyd fused +
       bounded, k-means|| init, binary ADMM (z, x, u), tsqr Q/R) run flat
       vs ``(4, 2)`` vs ``(2, 4)`` vs the degenerate ``(1, 8)`` on the
       same 8 devices: degenerate must be BIT-identical to flat (tsqr,
       whose hierarchical lowering restructures even at n_pods=1, is
       pinned close instead), real splits pinned Neumaier-close at
       rtol 2e-5 (re-association of <= 8 f32 partials; see
       tests/test_hierarchy.py for the argument).
    3. **Traffic gate** — per-trace ledger: flat ``data``-axis combining
       bytes (all DCN-exposed under topology-oblivious routing) over the
       hierarchical ``pod``-axis bytes must be >= chips_per_pod for the
       M-step and z-consensus reductions — the analytic factor
       (N-1)/(n_pods-1).
    4. **Compile gate** — a repeat fit under the active hierarchical mesh
       adds ZERO compiles (and zero ledger growth — recording is
       per-trace).
    5. **Telemetry mirror** — ``collective.bytes{axis=}`` /
       ``collective.calls{axis=,op=}`` counters exactly equal the ledger.

    Committed as MULTICHIP_r06.json; nonzero exit on any gate failure.
    """
    import jax

    if len(jax.devices()) < 8 and not os.environ.get(
            "_DASK_ML_TPU_MULTICHIP_CHILD"):
        _multichip_child()
        return

    import jax.numpy as jnp

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.models import kmeans as km
    from dask_ml_tpu.ops import linalg
    from dask_ml_tpu.parallel import hierarchy as hier
    from dask_ml_tpu.parallel import mesh as mesh_lib
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.shapes import track_compiles
    from dask_ml_tpu.parallel.sharding import prepare_data

    f32 = jnp.float32
    n = int(os.environ.get("MULTICHIP_N", 65536))
    d, k = 24, 8
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    c0 = jnp.asarray(X[:k])
    tol0 = jnp.asarray(0.0, f32)
    lloyd_iters, admm_iters = 8, 4

    # the drill's meshes all use the SAME first 8 devices (a >8-device
    # host would otherwise fail the fixed-shape hierarchical reshapes)
    devs = jax.devices()[:8]
    meshes = {
        "flat": mesh_lib.make_mesh(devices=devs),
        "hier42": hier.make_hierarchical_mesh(4, 2, devices=devs),
        "hier24": hier.make_hierarchical_mesh(2, 4, devices=devs),
        "hier18": hier.make_hierarchical_mesh(1, 8, devices=devs),
    }

    dcn_lat_us, dcn_gbps = _dcn_knobs()

    def run_families(mesh):
        hier.reset_ledger()
        t0 = time.perf_counter()
        with mesh_lib.use_mesh(mesh):
            data = prepare_data(X, y=y)
            lf = km.lloyd_loop_fused(data.X, data.weights, c0, tol0,
                                     mesh=mesh, max_iter=lloyd_iters)
            lb = km.lloyd_loop_bounded(data.X, data.weights, c0, tol0,
                                       mesh=mesh, max_iter=lloyd_iters)
            ci = km.init_scalable(data.X, data.weights, data.n, k,
                                  jax.random.key(0), mesh=mesh)
            z, _, st, _ = glm_core.admm(
                data.X, data.y, data.weights, jnp.zeros((d,), f32),
                jnp.ones((d,), f32), mesh, family="logistic", lamduh=0.5,
                max_iter=admm_iters, abstol=0.0, reltol=0.0,
                return_state=True)
            Q, R = linalg.tsqr(data.X, mesh=mesh, weights=data.weights)
            outs = {
                "lloyd_centers": np.asarray(lf[0]),
                "lloyd_inertia": float(lf[1]),
                "lloyd_niter": int(lf[2]),
                "bounded_centers": np.asarray(lb[0]),
                "bounded_labels": np.asarray(lb[4]),
                "init_centers": np.asarray(ci),
                "admm_z": np.asarray(z),
                "admm_x": np.asarray(st[1]),
                "admm_u": np.asarray(st[2]),
                "tsqr_Q": np.asarray(Q),
                "tsqr_R": np.asarray(R),
            }
        snap = hier.ledger_snapshot()
        # wall-clock DCN latency injection (PR 16 satellite): turn the
        # ledger's logical bytes + call counts into modeled cross-pod
        # seconds and SLEEP them inside the timed window, so the committed
        # record carries measured seconds, not just bytes
        if mesh_lib.is_hierarchical(mesh):
            axis, hops = hier.POD_AXIS, int(mesh.shape[hier.POD_AXIS]) - 1
        else:
            axis, hops = hier.DATA_AXIS, len(devs) - 1
        modeled = _dcn_seconds(snap, axis, hops, dcn_lat_us, dcn_gbps)
        s0 = time.perf_counter()
        if modeled > 0:
            time.sleep(modeled)
        slept = time.perf_counter() - s0
        wall = time.perf_counter() - t0
        return outs, snap, {"wall_seconds": wall,
                            "dcn_modeled_seconds": modeled,
                            "dcn_slept_seconds": slept}

    outs, snaps, walls = {}, {}, {}
    for name, m in meshes.items():
        outs[name], snaps[name], walls[name] = run_families(m)

    gates, deltas = {}, {}

    # -- 2. trajectory pins ------------------------------------------------
    bit_keys = ["lloyd_centers", "bounded_centers", "bounded_labels",
                "init_centers", "admm_z", "admm_x", "admm_u"]
    gates["degenerate_bit_identical"] = all(
        np.array_equal(outs["flat"][kk], outs["hier18"][kk])
        for kk in bit_keys) and (
            outs["flat"]["lloyd_niter"] == outs["hier18"]["lloyd_niter"])

    def close(a, b, rtol=2e-5, atol=1e-5):
        return bool(np.allclose(a, b, rtol=rtol, atol=atol))

    # tsqr's hierarchical path changes the LOWERING even at n_pods=1
    # (explicit shard_map Gram instead of GSPMD), so the degenerate case
    # is pinned close rather than bit-identical — as a drill gate, not
    # just a test (tests/test_hierarchy.py carries the argument)
    gates["degenerate_tsqr_close"] = (
        close(outs["flat"]["tsqr_Q"], outs["hier18"]["tsqr_Q"])
        and close(outs["flat"]["tsqr_R"], outs["hier18"]["tsqr_R"],
                  atol=1e-4))

    for mode in ("hier42", "hier24"):
        ok = outs["flat"]["lloyd_niter"] == outs[mode]["lloyd_niter"]
        ok &= np.array_equal(outs["flat"]["bounded_labels"],
                             outs[mode]["bounded_labels"])
        dd = {}
        for kk in ("lloyd_centers", "bounded_centers", "init_centers",
                   "admm_z", "admm_x", "admm_u", "tsqr_Q", "tsqr_R"):
            delta = float(np.max(np.abs(
                np.asarray(outs["flat"][kk], np.float64)
                - np.asarray(outs[mode][kk], np.float64))))
            dd[kk] = delta
            ok &= close(outs["flat"][kk], outs[mode][kk],
                        atol=1e-4 if kk == "tsqr_R" else 1e-5)
        ok &= close(outs["flat"]["lloyd_inertia"],
                    outs[mode]["lloyd_inertia"], atol=1e-2)
        deltas[mode] = dd
        gates[f"trajectories_pinned_{mode}"] = bool(ok)

    # -- 3. cross-pod ("DCN-modeled") byte-reduction gate ------------------
    traffic = {}
    for mode, cpp in (("hier42", 2), ("hier24", 4)):
        rec = {}
        for op in ("kmeans.mstep", "glm.admm.consensus"):
            flat_b = snaps["flat"]["ops"][op]["data"]
            pod_b = snaps[mode]["ops"][op]["pod"]
            rec[op] = {
                "flat_dcn_modeled_bytes": flat_b,
                "hier_pod_bytes": pod_b,
                "reduction_factor": round(flat_b / max(pod_b, 1), 3),
                "required_factor": cpp,
            }
            gates[f"dcn_bytes_{op}_{mode}"] = flat_b >= cpp * pod_b
        traffic[mode] = rec

    # -- 3b. wall-clock DCN injection gates (PR 16 satellite) --------------
    # the injected component is the only wall-clock term the topology
    # changes (compute is identical work on the same 8 devices), so the
    # win gate compares modeled DCN seconds; the measured gate proves the
    # injection really slept them (slept >= modeled, perf_counter-timed)
    for mode in meshes:
        gates[f"dcn_injection_measured_{mode}"] = (
            walls[mode]["dcn_slept_seconds"] + 1e-9
            >= walls[mode]["dcn_modeled_seconds"])
    for mode in ("hier42", "hier24", "hier18"):
        gates[f"dcn_wall_win_{mode}"] = (
            walls[mode]["dcn_modeled_seconds"]
            <= walls["flat"]["dcn_modeled_seconds"])

    # -- 4. compile-once + zero ledger growth under the hier mesh ----------
    m = meshes["hier42"]
    with mesh_lib.use_mesh(m):
        data = prepare_data(X, y=y)
        hier.reset_ledger()
        with track_compiles() as tc:
            km.lloyd_loop_fused(data.X, data.weights, c0, tol0, mesh=m,
                                max_iter=lloyd_iters)
            glm_core.admm(data.X, data.y, data.weights,
                          jnp.zeros((d,), f32), jnp.ones((d,), f32), m,
                          family="logistic", lamduh=0.5,
                          max_iter=admm_iters, abstol=0.0, reltol=0.0)
    gates["zero_steady_state_compiles"] = int(tc["n_compiles"]) == 0
    gates["zero_steady_state_ledger_growth"] = (
        hier.ledger_snapshot()["bytes"] == {})

    # -- 5. telemetry mirror exactness -------------------------------------
    hier.reset_ledger()
    telemetry.reset_telemetry()
    n2 = n + 8  # fresh shape => fresh trace under the warm caches
    X2 = rng.randn(n2, d).astype(np.float32)
    with config_lib.config_context(telemetry=True):
        with mesh_lib.use_mesh(meshes["hier24"]):
            d2 = prepare_data(X2)
            km.lloyd_loop_fused(d2.X, d2.weights, c0, tol0,
                                mesh=meshes["hier24"],
                                max_iter=lloyd_iters)
    snap = hier.ledger_snapshot()
    counters = telemetry.metrics().snapshot()["counters"]
    mirror_ok = bool(snap["bytes"]) and all(
        counters.get(f"collective.bytes{{axis={ax}}}") == b
        for ax, b in snap["bytes"].items()) and all(
        counters.get("collective.calls{axis=%s,op=%s}"
                     % tuple(key.split("/", 1))) == c
        for key, c in snap["calls"].items())
    gates["telemetry_mirror_exact"] = mirror_ok

    dryrun = _multichip_dryrun_smoke()
    gates["dryrun_ok"] = bool(dryrun["ok"])

    rec = {
        "metric": "multichip_hierarchical",
        "value": traffic["hier24"]["glm.admm.consensus"][
            "reduction_factor"],
        "unit": "flat-DCN-modeled / hierarchical cross-pod logical bytes "
                "(z-consensus, (2,4) mesh)",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "n_devices": 8,
        "rows": n, "cols": d, "n_clusters": k,
        "lloyd_iters": lloyd_iters, "admm_iters": admm_iters,
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "mesh_shapes": {name: list(m.shape.values())
                        for name, m in meshes.items()},
        "wall_seconds": {name: round(w["wall_seconds"], 3)
                         for name, w in walls.items()},
        "dcn_injection": {
            "latency_us": dcn_lat_us, "gbps": dcn_gbps,
            "per_mode": {name: {kk: round(v, 6) for kk, v in w.items()}
                         for name, w in walls.items()}},
        "per_axis_bytes": {name: s["bytes"]
                           for name, s in snaps.items()},
        "per_axis_calls": {name: s["calls"]
                           for name, s in snaps.items()},
        "per_op_bytes": {name: s["ops"] for name, s in snaps.items()},
        "dcn_reduction": traffic,
        "max_abs_trajectory_delta": deltas,
        "dryrun": dryrun,
        "note": "ledger records logical combining bytes per TRACE of each "
                "collective site ((s-1)*B per reduction group per axis; "
                "docs/scale-out.md); flat bytes are DCN-exposed under "
                "topology-oblivious routing, so reduction_factor = "
                "(N-1)/(n_pods-1) >= chips_per_pod analytically and the "
                "measured ledger must reproduce it exactly. Trajectory "
                "pins: degenerate (1,8) bit-identical to flat; real pod "
                "splits Neumaier-close (rtol 2e-5) per "
                "tests/test_hierarchy.py's re-association argument.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "MULTICHIP_r06.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "multichip hierarchical drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# model-axis scale-out drill (PR 16): the third ("model") mesh axis —
# feature-sharded GLM / PCA / Lloyd vs the flat replicated oracle, the
# model-ledger exactness pins, the (2,4,1)-degenerate bit-identity gate,
# the compile-once gate, and the d=2^17 capacity fit that replicated f32
# state provably cannot hold per-chip. Committed as MODELAXIS_r01.json.
# ---------------------------------------------------------------------------


def _sign_align(ref, other):
    """Principal axes are sign-ambiguous across lowerings; align each row
    of ``other`` to ``ref`` by the sign of their inner product."""
    s = np.sign(np.sum(np.asarray(ref, np.float64)
                       * np.asarray(other, np.float64),
                       axis=1, keepdims=True))
    s[s == 0] = 1.0
    return other * s.astype(other.dtype)


def _populate_decisions():
    """Measured autotuner seed (PR 16 satellite): time the hand-tuned
    dispatch alternatives on THIS backend and persist the verdicts into
    the decision cache next to parallel/decisions.py. The hand-written
    inequalities stay in the code as the cold-start fallback; entries are
    backend-tagged and narrowly ranged (about +/-50% around the measured
    point) so they only apply near what was actually measured — in
    particular the rule-pin test points fall through to the fallback."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models import kmeans as km
    from dask_ml_tpu.ops import sparse as sparse_ops
    from dask_ml_tpu.parallel import decisions
    from dask_ml_tpu.parallel import mesh as mesh_lib
    from dask_ml_tpu.parallel.sharding import prepare_data

    backend = jax.default_backend()
    rng = np.random.RandomState(7)

    def best_of(fn, reps=3):
        fn()  # warm the compile cache; time steady-state dispatches only
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return min(ts)

    measured = {}

    # -- sparse SpMM: Pallas blocked-ELL (interpret off-TPU) vs XLA
    #    segment-sum, at a mid-size (rows, slots) both kernels support
    n_s, k_s, d_s = 4096, 16, 512
    A = sparse_ops.SparseRows(
        jnp.asarray(rng.randn(n_s, k_s).astype(np.float32)),
        jnp.asarray(rng.randint(0, d_s, (n_s, k_s)).astype(np.int32)), d_s)
    v = jnp.asarray(rng.randn(d_s).astype(np.float32))
    t_x = best_of(lambda: jax.block_until_ready(
        sparse_ops.matvec(A, v, kernel="xla")))
    t_p = best_of(lambda: jax.block_until_ready(
        sparse_ops.matvec(A, v, kernel="pallas")))
    decisions.record(
        "sparse.spmv.pallas",
        {"n": [n_s // 2, n_s * 2], "k": [k_s // 2, k_s * 2],
         "dtype": "float32"},
        bool(t_p < t_x),
        measured={"xla_s": round(t_x, 6), "pallas_s": round(t_p, 6),
                  "pallas_speedup": round(t_x / t_p, 3)},
        backend=backend)
    measured["sparse.spmv.pallas"] = {"xla_s": t_x, "pallas_s": t_p}

    # -- Lloyd kernels on the current mesh: pallas vs XLA, and
    #    bounded (Hamerly-style pruning) vs fused full assignment
    mesh = mesh_lib.default_mesh()
    f32 = jnp.float32
    n_k, k_k, d_k = 2048, 128, 64
    Xk = rng.randn(n_k, d_k).astype(np.float32)
    with mesh_lib.use_mesh(mesh):
        dk = prepare_data(Xk)
        c0 = jnp.asarray(Xk[:k_k])
        tol0 = jnp.asarray(0.0, f32)
        t_x = best_of(lambda: jax.block_until_ready(km.lloyd_loop_fused(
            dk.X, dk.weights, c0, tol0, mesh=mesh, max_iter=2,
            kernel="xla")[0]))
        t_p = best_of(lambda: jax.block_until_ready(km.lloyd_loop_fused(
            dk.X, dk.weights, c0, tol0, mesh=mesh, max_iter=2,
            kernel="pallas")[0]))
    decisions.record(
        "kmeans.lloyd.pallas",
        {"k": [64, 256], "d": [32, 128], "dtype": "float32"},
        bool(t_p < t_x),
        measured={"xla_s": round(t_x, 6), "pallas_s": round(t_p, 6),
                  "pallas_speedup": round(t_x / t_p, 3)},
        backend=backend)
    measured["kmeans.lloyd.pallas"] = {"xla_s": t_x, "pallas_s": t_p}

    n_b, k_b, d_b = 32768, 8, 24
    Xb = rng.randn(n_b, d_b).astype(np.float32)
    with mesh_lib.use_mesh(mesh):
        db = prepare_data(Xb)
        cb = jnp.asarray(Xb[:k_b])
        t_f = best_of(lambda: jax.block_until_ready(km.lloyd_loop_fused(
            db.X, db.weights, cb, tol0, mesh=mesh, max_iter=8,
            kernel="xla")[0]))
        t_b = best_of(lambda: jax.block_until_ready(km.lloyd_loop_bounded(
            db.X, db.weights, cb, tol0, mesh=mesh, max_iter=8)[0]))
    decisions.record(
        "kmeans.lloyd.bounded",
        {"n": [24000, 44000], "k": [6, 12], "d": [16, 32]},
        bool(t_b < t_f),
        measured={"fused_s": round(t_f, 6), "bounded_s": round(t_b, 6),
                  "bounded_speedup": round(t_f / t_b, 3)},
        backend=backend)
    measured["kmeans.lloyd.bounded"] = {"fused_s": t_f, "bounded_s": t_b}

    path = decisions.save()
    return {"path": path, "backend": backend,
            "n_entries": len(decisions.entries()),
            "measured": {r: {kk: round(v, 6) for kk, v in t.items()}
                         for r, t in measured.items()}}


def bench_modelaxis(_rtt):
    """Model-axis ("tensor-parallel") scale-out drill (docs/scale-out.md,
    "The model axis"):

    1. **Families** — LogisticRegression (newton + lbfgs, the plain-jit
       GSPMD solvers), randomized PCA, and the feature-parallel fused
       Lloyd loop run on flat, ``(2,4)``, ``(2,2,2)``, ``(1,2,4)`` and an
       EXPLICIT ``(2,4,1)`` mesh over the same 8 devices.
    2. **Oracle pins** — every model-sharded fit is Neumaier-close to the
       flat replicated oracle; ``(2,4,1)`` (size-1 model axis, handled by
       the collective family's identity guards) is BIT-identical to
       ``(2,4)``; ``make_hierarchical_mesh(..., model_parallel=1)``
       structurally degenerates to the plain 2-axis mesh.
    3. **Ledger gates** — feature-axis collectives (coef gathers, gradient
       reduce-scatters, score/x2/shift psums) land on the ``model`` ledger
       axis ONLY, with analytically exact bytes; the sample-axis M-step
       stays on chip/pod; flat / 2-axis / size-1 meshes record ZERO model
       traffic.
    4. **Compile gate** — repeat fits under the 3-axis mesh add zero
       compiles and zero ledger growth (recording is per-trace).
    5. **Capacity** — LogisticRegression(lbfgs) + randomized PCA fit at
       ``d = MODELAXIS_D`` (default 2^17 = 131072), where the replicated
       f32 Gram/Hessian (d^2 * 4 = 68.7 GB) provably cannot fit in one
       chip's 16 GiB HBM — only O(d) sharded state ever materializes.

    With ``DECISIONS_WRITE=1`` it also runs the measured autotuner seed
    (``_populate_decisions``) and persists the decision cache. Committed
    as MODELAXIS_r01.json; nonzero exit on any gate failure.
    """
    import jax

    if len(jax.devices()) < 8 and not os.environ.get(
            "_DASK_ML_TPU_MULTICHIP_CHILD"):
        _multichip_child()
        return

    import jax.numpy as jnp

    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.models import kmeans as km
    from dask_ml_tpu.parallel import hierarchy as hier
    from dask_ml_tpu.parallel import mesh as mesh_lib
    from dask_ml_tpu.parallel.shapes import track_compiles
    from dask_ml_tpu.parallel.sharding import prepare_data

    f32 = jnp.float32
    n = int(os.environ.get("MODELAXIS_SMALL_N", 8192))
    d, k, k_pca = 24, 8, 4
    lloyd_iters = 8
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    yc = (X[:, 0] + 0.25 * X[:, 1] > 0).astype(np.float32)
    c0 = jnp.asarray(X[:k])
    tol0 = jnp.asarray(0.0, f32)

    devs = jax.devices()[:8]
    meshes = {
        "flat": mesh_lib.make_mesh(devices=devs),
        "hier24": hier.make_hierarchical_mesh(2, 4, devices=devs),
        "h222": hier.make_hierarchical_mesh(2, 2, devices=devs,
                                            model_parallel=2),
        "h124": hier.make_hierarchical_mesh(1, 2, devices=devs,
                                            model_parallel=4),
        # EXPLICIT size-1 model axis: not the structural degeneracy
        # (make_hierarchical_mesh returns a 2-axis mesh at m=1) but the
        # collective family's identity-guard path — must be bit-identical
        "h241": jax.sharding.Mesh(
            np.asarray(devs).reshape(2, 4, 1),
            (hier.POD_AXIS, hier.CHIP_AXIS, hier.MODEL_AXIS)),
    }
    model_modes = ("h222", "h124")

    def run_families(mesh, model):
        hier.reset_ledger()
        t0 = time.perf_counter()
        with mesh_lib.use_mesh(mesh):
            lr_n = LogisticRegression(solver="newton", max_iter=20).fit(
                X, yc)
            lr_l = LogisticRegression(solver="lbfgs", max_iter=50).fit(
                X, yc)
            pca = PCA(n_components=k_pca, svd_solver="randomized",
                      iterated_power=2, random_state=0).fit(X)
            data = prepare_data(X, mesh=mesh, shard_features=model)
            lf = km.lloyd_loop_fused(data.X, data.weights, c0, tol0,
                                     mesh=mesh, max_iter=lloyd_iters,
                                     shard_features=model)
            outs = {
                "lr_newton_coef": np.asarray(lr_n.coef_),
                "lr_newton_intercept": np.asarray(lr_n.intercept_),
                "lr_lbfgs_coef": np.asarray(lr_l.coef_),
                "pca_components": np.asarray(pca.components_),
                "pca_ev": np.asarray(pca.explained_variance_),
                "lloyd_centers": np.asarray(lf[0]),
                "lloyd_inertia": float(lf[1]),
                "lloyd_niter": int(lf[2]),
            }
        wall = time.perf_counter() - t0
        return outs, hier.ledger_snapshot(), wall

    outs, snaps, walls = {}, {}, {}
    for name, m in meshes.items():
        outs[name], snaps[name], walls[name] = run_families(
            m, name in model_modes)

    gates = {}

    # -- 2. oracle pins ----------------------------------------------------
    gates["model1_structural_degeneracy"] = (
        hier.make_hierarchical_mesh(2, 4, devices=devs,
                                    model_parallel=1).axis_names
        == (hier.POD_AXIS, hier.CHIP_AXIS))
    bit_keys = [kk for kk in outs["flat"]
                if kk not in ("lloyd_inertia", "lloyd_niter")]
    gates["size1_model_axis_bit_identical"] = all(
        np.array_equal(outs["hier24"][kk], outs["h241"][kk])
        for kk in bit_keys) and (
            outs["hier24"]["lloyd_niter"] == outs["h241"]["lloyd_niter"])

    deltas = {}
    for mode in model_modes:
        dd, ok = {}, True
        for kk in ("lr_newton_coef", "lr_newton_intercept",
                   "lr_lbfgs_coef", "lloyd_centers"):
            delta = float(np.max(np.abs(
                np.asarray(outs["flat"][kk], np.float64)
                - np.asarray(outs[mode][kk], np.float64))))
            dd[kk] = delta
            ok &= bool(np.allclose(outs["flat"][kk], outs[mode][kk],
                                   rtol=5e-3, atol=1e-4))
        comp = _sign_align(outs["flat"]["pca_components"],
                           outs[mode]["pca_components"])
        dd["pca_components"] = float(np.max(np.abs(
            outs["flat"]["pca_components"] - comp)))
        ok &= bool(np.allclose(outs["flat"]["pca_components"], comp,
                               rtol=5e-3, atol=5e-4))
        ok &= bool(np.allclose(outs["flat"]["pca_ev"], outs[mode]["pca_ev"],
                               rtol=5e-3, atol=1e-5))
        ok &= outs["flat"]["lloyd_niter"] == outs[mode]["lloyd_niter"]
        ok &= bool(np.allclose(outs["flat"]["lloyd_inertia"],
                               outs[mode]["lloyd_inertia"], rtol=1e-4))
        deltas[mode] = dd
        gates[f"oracle_close_{mode}"] = bool(ok)

    # satellite (a): the plain-jit GSPMD solver families stay pinned
    # flat-vs-(pod,chip) too (no model axis involved)
    ok = all(np.allclose(outs["flat"][kk], outs["hier24"][kk],
                         rtol=5e-3, atol=1e-4)
             for kk in ("lr_newton_coef", "lr_lbfgs_coef", "lloyd_centers"))
    gates["gspmd_hier_close"] = bool(ok)

    # -- 3. model-axis ledger exactness ------------------------------------
    # the glm.pullback seam only fires on the ADMM path (excluded from
    # tensor-parallel); its byte exactness is pinned directly in
    # tests/test_model_axis.py instead
    MODEL_OPS = ("glm.matvec", "glm.gram.gather",
                 "pca.colgather", "pca.components.gather",
                 "kmeans.scores", "kmeans.x2", "kmeans.shift")
    ledger = {}
    for mode in model_modes:
        m_ = mesh_lib.n_model_shards(meshes[mode])
        n_pods = int(meshes[mode].shape[hier.POD_AXIS])
        cpp = int(meshes[mode].shape[hier.CHIP_AXIS])
        shards = n_pods * cpp
        ops = snaps[mode]["ops"]
        calls = snaps[mode]["calls"]
        # GLM pads d+1 (intercept) to the model-axis bucket; PCA requires
        # even division (d % m == 0) and stays unpadded; rows divide the
        # data shards exactly at these sizes
        d_glm = -(-(d + 1) // m_) * m_
        # randomized sketch rank is bucketed to a 32-multiple, clipped to
        # min(n, d) (decomposition/pca.py)
        k_fit = min(-(-k_pca // 32) * 32, min(n, d))
        unit = {
            "glm.matvec": n * 4,
            "glm.gram.gather": d_glm * d_glm * 4,
            "pca.colgather": n * d * 4,
            "pca.components.gather": k_fit * d * 4,
            "kmeans.scores": k * n * 4,
            "kmeans.x2": n * 4,
            "kmeans.shift": shards * 4,
        }
        rec = {}
        ok_axes = all(op in ops and set(ops[op]) == {hier.MODEL_AXIS}
                      for op in MODEL_OPS)
        ok_exact = ok_axes and all(
            ops[op][hier.MODEL_AXIS]
            == calls[f"{hier.MODEL_AXIS}/{op}"] * (m_ - 1) * unit[op]
            for op in MODEL_OPS)
        # the sample-axis M-step stays on the hierarchical (chip, pod)
        # path, scaled by the m model replicas of each data group
        mstep_unit = (k * (d // m_) + k + 1) * 4
        n_traces = calls.get(f"{hier.CHIP_AXIS}/kmeans.mstep", 0) // 3
        ok_mstep = (
            set(snaps[mode]["ops"].get("kmeans.mstep", {}))
            <= {hier.CHIP_AXIS, hier.POD_AXIS}
            and ops["kmeans.mstep"][hier.CHIP_AXIS]
            == m_ * n_pods * (cpp - 1) * mstep_unit * n_traces
            and ops["kmeans.mstep"].get(hier.POD_AXIS, 0)
            == m_ * (n_pods - 1) * mstep_unit * n_traces)
        rec["model_bytes"] = {op: ops[op][hier.MODEL_AXIS]
                              for op in MODEL_OPS if op in ops}
        rec["mstep_bytes"] = dict(ops.get("kmeans.mstep", {}))
        ledger[mode] = rec
        gates[f"model_ops_model_axis_only_{mode}"] = bool(ok_axes)
        gates[f"model_ledger_exact_{mode}"] = bool(ok_exact)
        gates[f"mstep_hier_axes_exact_{mode}"] = bool(ok_mstep)

    for mode in ("flat", "hier24", "h241"):
        snap = snaps[mode]
        gates[f"zero_model_traffic_{mode}"] = (
            hier.MODEL_AXIS not in snap["bytes"]
            and not any(op in snap["ops"] for op in MODEL_OPS))

    # -- 4. compile-once + zero ledger growth under the 3-axis mesh --------
    mh = meshes["h222"]
    with mesh_lib.use_mesh(mh):
        hier.reset_ledger()
        with track_compiles() as tc:
            LogisticRegression(solver="lbfgs", max_iter=50).fit(X, yc)
            data = prepare_data(X, mesh=mh, shard_features=True)
            km.lloyd_loop_fused(data.X, data.weights, c0, tol0, mesh=mh,
                                max_iter=lloyd_iters, shard_features=True)
    gates["zero_steady_state_compiles"] = int(tc["n_compiles"]) == 0
    gates["zero_steady_state_ledger_growth"] = (
        hier.ledger_snapshot()["bytes"] == {})

    # -- 5. capacity: d = 2^17 feature-sharded fits ------------------------
    full_d = 1 << 17
    d_cap = int(os.environ.get("MODELAXIS_D", full_d))
    n_cap = int(os.environ.get("MODELAXIS_N", 1024))
    hbm = 16 * (1 << 30)  # one TPU v4 chip's HBM
    Xc = np.random.default_rng(1).standard_normal(
        (n_cap, d_cap), dtype=np.float32)
    yc_cap = (Xc[:, 0] > 0).astype(np.float32)
    t0 = time.perf_counter()
    with mesh_lib.use_mesh(mh):
        lr_cap = LogisticRegression(solver="lbfgs", max_iter=10).fit(
            Xc, yc_cap)
        cap_lr_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        pca_cap = PCA(n_components=k_pca, svd_solver="randomized",
                      iterated_power=1, random_state=0).fit(Xc)
        cap_pca_wall = time.perf_counter() - t0
    gates["capacity_lr_finite"] = bool(
        np.isfinite(np.asarray(lr_cap.coef_)).all()
        and np.abs(np.asarray(lr_cap.coef_)).max() > 0)
    gates["capacity_pca_finite"] = bool(
        np.isfinite(np.asarray(pca_cap.components_)).all()
        and np.all(np.asarray(pca_cap.explained_variance_) > 0))
    # the capacity CLAIM is analytic and pinned at full d: a replicated
    # f32 Gram/Hessian at d=2^17 is 68.7 GB — over 4x one chip's HBM —
    # while the model-sharded path only materializes O(d) state
    gates["capacity_replicated_infeasible_full_d"] = (
        full_d * full_d * 4 > hbm)
    capacity = {
        "run_n": n_cap, "run_d": d_cap, "full_scale": d_cap == full_d,
        "per_chip_hbm_bytes": hbm,
        "replicated_gram_bytes_full_d": full_d * full_d * 4,
        "replicated_gram_bytes_run_d": d_cap * d_cap * 4,
        "sharded_X_bytes_per_chip": n_cap * d_cap * 4 // 8,
        "coef_bytes": d_cap * 4,
        "lr_wall_seconds": round(cap_lr_wall, 3),
        "pca_wall_seconds": round(cap_pca_wall, 3),
    }

    # -- 6. measured autotuner seed (DECISIONS_WRITE=1 only) ---------------
    decisions_info = None
    if os.environ.get("DECISIONS_WRITE"):
        decisions_info = _populate_decisions()

    rec = {
        "metric": "modelaxis_tensor_parallel",
        "value": capacity["replicated_gram_bytes_full_d"] / hbm,
        "unit": "replicated f32 Gram bytes at d=2^17 over one chip's HBM "
                "(the infeasibility factor the model axis removes)",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "n_devices": 8,
        "rows": n, "cols": d, "n_clusters": k, "pca_components": k_pca,
        "lloyd_iters": lloyd_iters,
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "mesh_shapes": {name: list(m.shape.values())
                        for name, m in meshes.items()},
        "wall_seconds": {name: round(w, 3) for name, w in walls.items()},
        "per_axis_bytes": {name: s["bytes"] for name, s in snaps.items()},
        "per_axis_calls": {name: s["calls"] for name, s in snaps.items()},
        "per_op_bytes": {name: s["ops"] for name, s in snaps.items()},
        "model_ledger": ledger,
        "max_abs_oracle_delta": deltas,
        "capacity": capacity,
        "decisions": decisions_info,
        "note": "feature-axis collectives (coef/component gathers, "
                "gradient reduce-scatters, score/x2/shift psums) are "
                "metered on the 'model' ledger axis only — one group per "
                "data-mesh coordinate, (m-1)*B logical combining bytes "
                "per group per trace — while sample-axis reductions stay "
                "on the hierarchical (chip, pod) path with the m-replica "
                "multiplier (docs/scale-out.md, 'The model axis'). The "
                "(2,4,1) mesh exercises the size-1 identity guards; the "
                "structural degeneracy (model_parallel=1 returns the "
                "2-axis mesh) is pinned in tests/test_model_axis.py.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "MODELAXIS_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "model-axis drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# unified-telemetry drill (ISSUE 7): spans + metrics + Perfetto export over
# a streamed ADMM fit and a bucketed K-fold search, with the three
# acceptance gates — the numbers committed as TELEMETRY_r01.json and
# printed by the CI `telemetry` job (nonzero exit on any gate failure)
# ---------------------------------------------------------------------------


def bench_telemetry(_rtt):
    """Telemetry drill (docs/observability.md):

    1. streamed host-block ADMM fit, telemetry OFF (the fit wall time the
       disabled-overhead gate is measured against);
    2. the same fit, telemetry ON, with injected transient faults under a
       RetryPolicy — collects the span tree, pins every registry mirror
       against its legacy surface (stream bytes wire+logical, blocks,
       queue-depth bounds, retry counters) — the telemetry_report()
       single-source acceptance criterion;
    3. a bucketed K-fold grid search, telemetry ON — search-cell spans +
       shape-bucket/compile counters ride the same report;
    4. ``export_chrome_trace`` of everything recorded.

    Gates (nonzero exit on failure):
    (a) disabled-mode overhead < 1% of fit wall time — the per-call cost
        of the disabled span/metric fast path is microbenchmarked and
        multiplied by the enabled run's actual event count (the honest
        estimate: the instrumentation cannot be compiled out, so the gate
        prices every call site the fit actually hit);
    (b) the span tree covers >= 90% of the enabled fit's wall time (sum
        of root-span durations vs the measured fit time);
    (c) the exported Chrome trace parses, is non-empty, and its span
        hierarchy survives (every parent_span_id resolves).
    """
    import jax

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.faults import FaultInjector, RetryPolicy
    from dask_ml_tpu.parallel.stream import HostBlockSource

    n, d, n_blocks, outer = 65_536, 16, 8, 6
    rng = np.random.RandomState(0)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w_true = np.random.RandomState(3).randn(d).astype(np.float32)
    y = (X @ w_true + rng.standard_normal(n).astype(np.float32)
         > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    kw = dict(family="logistic", regularizer="l2", lamduh=1.0,
              max_iter=outer, abstol=0.0, reltol=0.0)

    def run(**src_kw):
        src = HostBlockSource((X, y, w), n_blocks, **src_kw)
        t0 = time.perf_counter()
        z, _ = glm_core.admm_streamed(src, n_blocks, d, float(n), **kw)
        fetch(z)
        return src, time.perf_counter() - t0

    run()  # warm: compiles
    # disabled-mode fit wall time: best of 3 — the fastest baseline is the
    # least-noise estimate AND the one the overhead ratio is hardest
    # against
    t_off = min(run()[1] for _ in range(3))

    # -- enabled fit with injected faults: span tree + mirror pins --------
    policy = RetryPolicy(max_retries=3, base_delay=0.01)
    inj = FaultInjector().fail_load(3, times=2).fail_transfer(5, times=1)
    with config_lib.config_context(telemetry=True):
        telemetry.reset_telemetry(ring_capacity=65_536)
        src_on, t_on = run(retry_policy=policy, fault_injector=inj)
        fit_spans = telemetry.spans()
        counters = telemetry.metrics().snapshot()["counters"]
        gauges = telemetry.metrics().snapshot()["gauges"]

        mirrors_exact = (
            counters.get("stream.bytes_streamed")
            == src_on.bytes_streamed
            and counters.get("stream.logical_bytes_streamed")
            == src_on.logical_bytes_streamed
            and counters.get("stream.blocks_started")
            == src_on.blocks_started
            and counters.get("faults.retries{kind=block-load}", 0)
            == policy.by_kind.get("block-load", 0)
            and counters.get("faults.retries{kind=device-put}", 0)
            == policy.by_kind.get("device-put", 0)
        )
        qd = gauges.get("stream.queue_depth", {})
        queue_depth_bounded = (qd.get("n_samples", 0) > 0
                               and 0 <= qd.get("min", -1)
                               and qd.get("max", 99) <= src_on.prefetch)

        roots = [r for r in fit_spans if r["parent"] is None]
        coverage = sum(r["dur"] for r in roots) / max(t_on, 1e-9)

        # -- bucketed K-fold search rides the same report -----------------
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.model_selection import GridSearchCV

        Xs = rng.standard_normal((6_000, 8)).astype(np.float32)
        GridSearchCV(
            KMeans(init="random", max_iter=5, random_state=0),
            {"n_clusters": [2, 3, 4]}, cv=3, refit=False, iid=False,
        ).fit(Xs)
        report = telemetry.telemetry_report()
        n_cells = sum(1 for r in telemetry.spans()
                      if r["name"] == "search.cell")

        # -- export + parse gate ------------------------------------------
        trace_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "TELEMETRY_trace_r01.json")
        telemetry.export_chrome_trace(trace_path)
    with open(trace_path) as f:
        payload = json.load(f)
    xs = [e for e in payload.get("traceEvents", []) if e.get("ph") == "X"]
    ids = {e["args"]["span_id"] for e in xs}
    parents = {e["args"]["parent_span_id"] for e in xs
               if "parent_span_id" in e["args"]}
    trace_ok = bool(xs) and parents <= ids

    # -- disabled-overhead gate: microbenchmark the fast path x the event
    # count the enabled fit actually generated ---------------------------
    reps = 100_000
    t0 = time.perf_counter()
    for _ in range(reps):
        with telemetry.span("bench.noop"):
            pass
    span_cost = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        telemetry.counter("bench.noop").inc()
    metric_cost = (time.perf_counter() - t0) / reps
    n_fit_spans = len(fit_spans)
    # metric-helper hits during the fit: 3 counter mirrors per started
    # block + 1 queue-depth sample per take + retry-path increments
    n_fit_metrics = (3 * src_on.blocks_started + n_blocks * outer
                     + 3 * policy.retries)
    disabled_cost = span_cost * n_fit_spans + metric_cost * n_fit_metrics
    disabled_overhead = disabled_cost / max(t_off, 1e-9)

    gates = {
        "disabled_overhead_under_1pct": disabled_overhead < 0.01,
        "span_coverage_over_90pct": coverage >= 0.90,
        "chrome_trace_parses_nonempty": trace_ok,
        "mirrors_equal_legacy_surfaces": bool(mirrors_exact),
        "queue_depth_gauge_bounded": bool(queue_depth_bounded),
    }
    rec = {
        "metric": "telemetry_drill",
        "value": round(coverage, 4),
        "unit": "span-tree coverage of fit wall time (gate >= 0.90)",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "rows": n, "cols": d, "blocks": n_blocks,
        "admm_outer_iters": outer,
        "fit_seconds_telemetry_off": round(t_off, 3),
        "fit_seconds_telemetry_on": round(t_on, 3),
        "enabled_overhead": round(t_on / max(t_off, 1e-9) - 1.0, 4),
        "disabled_span_cost_ns": round(span_cost * 1e9, 1),
        "disabled_metric_cost_ns": round(metric_cost * 1e9, 1),
        "disabled_overhead_estimate": round(disabled_overhead, 6),
        "n_spans_fit": n_fit_spans,
        "n_search_cell_spans": n_cells,
        "retry_stats": policy.stats(),
        "queue_depth": qd,
        "span_summary": report["spans"]["by_name"],
        "counters": counters,
        "compile": {k: report["compile"][k]
                    for k in ("n_compiles", "compile_seconds", "n_traces")},
        "n_trace_events": len(xs),
        "note": "disabled overhead is per-call microbenchmark x the "
                "enabled run's event count (the instrumentation cannot "
                "be compiled out, so this prices every call site the fit "
                "hit); enabled_overhead compares one-shot wall times, is "
                "noise-dominated on this CPU mesh, and the enabled run "
                "additionally pays the injected faults' retry backoff "
                "plus the root span's completion barrier",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "TELEMETRY_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "telemetry drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


def bench_serving(_rtt):
    """Online-serving drill (docs/serving.md): a closed-loop load
    generator against the continuously-batched :class:`ServingLoop`.

    1. fit three families (KMeans k=16, logistic GLM, PCA) at 4096 x 32
       and register them on one loop;
    2. ``warmup()`` pre-compiles every (model, method, bucket) program;
    3. identity phase: served results pinned bit-for-bit against the
       direct predict paths across ragged sizes straddling every bucket
       boundary (incl. n=1 and n < the smallest bucket);
    4. steady state: C closed-loop clients x R requests each, mixed
       sizes/models/methods from a seeded trace, telemetry ON — wrapped
       in ``track_compiles`` for the zero-recompile gate; client-side
       latencies give p50/p99, and the loop's own
       ``serving.request_seconds`` histogram percentiles (satellite:
       Histogram.percentiles) are recorded next to them;
    5. baseline: the SAME trace served one-dispatch-per-request through
       the (warm) direct predict paths, telemetry OFF — the per-request
       dispatch floor continuous batching must beat.

    Gates (nonzero exit on failure):
    (a) served == direct bit-for-bit on every identity pin;
    (b) ZERO compiles during steady-state traffic after warmup;
    (c) sustained QPS >= ``SERVING_MIN_SPEEDUP`` (default 2.0) x the
        one-dispatch-per-request baseline on the same mesh;
    (d) p99 latency within budget vs the committed SERVING_r01.json
        (10x headroom + a 500 ms floor — cross-machine noise tolerance;
        skipped when no artifact is committed yet).

    CI runs this scaled down via SERVING_CLIENTS/SERVING_REQS; the
    committed artifact is generated at the defaults.
    """
    import threading

    import jax

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.serving import (
        ModelRegistry,
        ServingLoop,
        serving_buckets,
    )
    from dask_ml_tpu.parallel.shapes import track_compiles

    n_fit, d = 4096, 32
    # batching depth scales with CONCURRENCY (requests coalesce per
    # (model, method) key): 32 closed-loop clients over 4 keys gives
    # ~8-deep batches on this mesh. CI shortens the run via SERVING_REQS
    # but keeps the client count — depth, not duration, drives the gate.
    clients = int(os.environ.get("SERVING_CLIENTS", "32"))
    reqs_per_client = int(os.environ.get("SERVING_REQS", "32"))
    min_speedup = float(os.environ.get("SERVING_MIN_SPEEDUP", "2.0"))
    max_batch_rows = 1024

    rng = np.random.RandomState(0)
    X = rng.standard_normal((n_fit, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.int32)

    km = KMeans(n_clusters=16, random_state=0, max_iter=10).fit(X)
    lr = LogisticRegression(max_iter=30).fit(X, y)
    pca = PCA(n_components=8, random_state=0).fit(X)
    direct = {
        ("kmeans", "predict"): km.predict,
        ("logistic", "predict"): lr.predict,
        ("logistic", "predict_proba"): lr.predict_proba,
        ("pca", "transform"): pca.transform,
    }
    registry = ModelRegistry()
    registry.register("kmeans", km)
    registry.register("logistic", lr)
    registry.register("pca", pca)

    # seeded request trace shared by the serving and baseline phases:
    # small-skewed mixed sizes over all four (model, method) families
    keys = sorted(direct)
    size_choices = [1, 2, 4, 8, 16, 24, 32, 48, 64, 96, 128]
    trng = np.random.RandomState(42)
    trace = []
    for _ in range(clients):
        rows = []
        for _ in range(reqs_per_client):
            key = keys[trng.randint(len(keys))]
            size = int(size_choices[trng.randint(len(size_choices))])
            rows.append((key, int(trng.randint(0, n_fit - size)), size))
        trace.append(rows)
    total_requests = clients * reqs_per_client

    identity_sizes = [1, 3, 31, 32, 33, 64, 100, 255, 256, 257, 500, 1000]
    identity_failures = []
    with config_lib.config_context(telemetry=True):
        telemetry.reset_telemetry(ring_capacity=65_536)
        loop = ServingLoop(registry, max_batch_rows=max_batch_rows).start()
        warm = loop.warmup()
        buckets = serving_buckets(loop.policy, max_batch_rows,
                                  align=loop._align)

        # -- identity gate (also warms the direct-path buckets) -----------
        for (name, method), fn in direct.items():
            for nreq in identity_sizes:
                served = loop.submit(
                    name, X[:nreq], method=method).result(300)
                if not np.array_equal(served, fn(X[:nreq])):
                    identity_failures.append((name, method, nreq))
        # warm the direct path over every trace size so the baseline
        # phase measures dispatch, not compiles
        for sz in sorted({s for rows in trace for (_, _, s) in rows}):
            for fn in direct.values():
                fn(X[:sz])

        # -- steady-state closed-loop load --------------------------------
        lat: list = []
        lat_lock = threading.Lock()
        start_evt = threading.Event()

        def client(rows):
            mine = []
            start_evt.wait()
            for key, off, size in rows:
                name, method = key
                t0 = time.perf_counter()
                loop.submit(
                    name, X[off:off + size], method=method).result(300)
                mine.append(time.perf_counter() - t0)
            with lat_lock:
                lat.extend(mine)

        threads = [threading.Thread(target=client, args=(rows,))
                   for rows in trace]
        for t in threads:
            t.start()
        batches_before = loop.n_batches
        rows_before = loop.rows_served
        with track_compiles() as steady:
            t0 = time.perf_counter()
            start_evt.set()
            for t in threads:
                t.join()
            serve_elapsed = time.perf_counter() - t0
        n_batches = loop.n_batches - batches_before
        rows_served = loop.rows_served - rows_before
        loop.stop()
        report = telemetry.telemetry_report()

    # -- one-dispatch-per-request baseline (telemetry OFF: the baseline
    # does not pay the serving path's observability) ----------------------
    t0 = time.perf_counter()
    for rows in trace:
        for key, off, size in rows:
            direct[key](X[off:off + size])
    base_elapsed = time.perf_counter() - t0

    qps_serving = total_requests / serve_elapsed
    qps_direct = total_requests / base_elapsed
    speedup = qps_serving / qps_direct
    p50_ms, p99_ms = (
        float(v) * 1e3 for v in np.percentile(lat, [50, 99]))

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "SERVING_r01.json")
    committed_p99 = None
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                committed_p99 = json.load(f).get("p99_ms")
        except Exception:
            committed_p99 = None
    p99_budget_ms = (max(10.0 * committed_p99, 500.0)
                     if committed_p99 is not None else None)

    gates = {
        "served_bit_identical_to_direct": not identity_failures,
        "zero_recompiles_steady_state": steady["n_compiles"] == 0,
        "qps_speedup_vs_per_request_dispatch":
            speedup >= min_speedup,
        "p99_within_committed_budget":
            p99_budget_ms is None or p99_ms <= p99_budget_ms,
    }
    hists = report["metrics"]["histograms"]
    rec = {
        "metric": "serving_drill",
        "value": round(speedup, 3),
        "unit": f"sustained QPS vs one-dispatch-per-request "
                f"(gate >= {min_speedup})",
        "vs_baseline": round(speedup, 3),
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "fit_rows": n_fit, "cols": d,
        "clients": clients, "reqs_per_client": reqs_per_client,
        "total_requests": total_requests,
        "request_size_mix": size_choices,
        "serving_buckets": buckets,
        "warmup": warm,
        "steady_state_compiles": steady["n_compiles"],
        "qps_serving": round(qps_serving, 1),
        "qps_direct": round(qps_direct, 1),
        "p50_ms": round(p50_ms, 3),
        "p99_ms": round(p99_ms, 3),
        "p99_budget_ms": p99_budget_ms,
        "n_batches": n_batches,
        "requests_per_batch": round(total_requests / max(n_batches, 1), 2),
        "rows_per_batch": round(rows_served / max(n_batches, 1), 1),
        "identity_failures": identity_failures,
        "request_seconds_histograms": {
            k: {q: hists[k][q] for q in ("count", "p50", "p90", "p99")}
            for k in sorted(hists) if k.startswith("serving.request_seconds")
        },
        "queue_depth": report["metrics"]["gauges"].get(
            "serving.queue_depth"),
        "batch_occupancy": report["metrics"]["gauges"].get(
            "serving.batch_occupancy"),
        "note": "closed-loop clients (each waits for its result before "
                "the next submit); baseline replays the identical seeded "
                "trace through the warm direct predict paths one dispatch "
                "per request, single-threaded (the repo caps concurrent "
                "device dispatch at 1 on the cpu backend). The speedup is "
                "continuous batching amortizing per-dispatch overhead — "
                "the serving run additionally pays telemetry, the "
                "baseline does not.",
    }
    emit(rec)
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "serving drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


def bench_fleet(_rtt):
    """Serving-fleet drill (docs/serving.md, "The serving fleet"): the
    closed-loop load generator against a replicated, health-checked,
    SLO-routed :class:`ServingFleet` — with a mid-run hot-swap, a mid-run
    replica kill, an injected over-capacity burst, and a graceful drain,
    all in ONE run (ROADMAP item 2's kill-drill gate).

    Phases:
    1. fit four families, register on a fleet of ``FLEET_REPLICAS``
       replicas over disjoint device subsets, ``warmup()`` everywhere;
    2. identity phase: fleet results pinned bit-for-bit against the
       direct paths across ragged sizes (whichever replica answers), and
       a wire-protocol client round-trip pinned the same way;
    3. steady state: C closed-loop clients x R mixed-priority requests
       (1/3 high-priority with a deadline, 1/3 deadline-only, 1/3
       best-effort) from a seeded trace. Mid-run, a coordinator
       (a) HOT-SWAPS the logistic model to a differently-regularized
       refit — new version pre-warmed, then atomically installed — and
       (b) KILLS one replica via ``FaultInjector.kill_replica`` once a
       third of traffic has completed / half completed respectively;
    4. over-capacity burst: ``FLEET_BURST`` requests whose deadline is
       already past — every one must shed with ``DeadlineExceeded``,
       and ONLY those may shed;
    5. drain: ``GracefulDrain.request()`` (the deterministic SIGTERM) —
       every surviving replica flushes and stops, later submits are
       rejected.

    Gates (nonzero exit on failure):
    (a) >= 3 replicas (2 allowed only under the CI scale-down env);
    (b) every steady-state result bit-identical to the direct path —
        for the swapped model, to the OLD or NEW version's direct path,
        with BOTH versions observed;
    (c) replica kill delivered exactly once, the fleet ends the run with
        exactly one replica down, and ZERO requests dropped (every
        future resolved: a result or the burst's DeadlineExceeded);
    (d) p99 latency of non-shed traffic within the SLO
        (``FLEET_P99_SLO_MS``, default 5000) and within 10x the
        committed FLEET_r01.json p99 (500 ms floor) when one exists;
    (e) shed count EXACTLY equals the injected burst (fleet counter and
        telemetry mirror agree);
    (f) drain leaves every surviving replica stopped with an empty
        queue and post-drain submits rejected.
    """
    import threading

    import jax

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.faults import FaultInjector, GracefulDrain
    from dask_ml_tpu.parallel.fleet import (
        FleetClient,
        FleetServer,
        ServingFleet,
    )
    from dask_ml_tpu.parallel.serving import (
        DeadlineExceeded,
        ServingClosed,
    )

    n_fit, d = 4096, 32
    replicas = int(os.environ.get("FLEET_REPLICAS", "3"))
    clients = int(os.environ.get("FLEET_CLIENTS", "24"))
    reqs_per_client = int(os.environ.get("FLEET_REQS", "24"))
    burst = int(os.environ.get("FLEET_BURST", "40"))
    slo_budget_s = float(os.environ.get("FLEET_SLO_S", "30.0"))
    p99_slo_ms = float(os.environ.get("FLEET_P99_SLO_MS", "5000.0"))
    max_batch_rows = 1024

    rng = np.random.RandomState(0)
    X = rng.standard_normal((n_fit, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.int32)

    km = KMeans(n_clusters=16, random_state=0, max_iter=10).fit(X)
    lr = LogisticRegression(max_iter=30).fit(X, y)
    lr2 = LogisticRegression(max_iter=60, C=0.3).fit(X, y)  # the swap-in
    pca = PCA(n_components=8, random_state=0).fit(X)
    direct = {
        ("kmeans", "predict"): km.predict,
        ("logistic", "predict"): lr.predict,
        ("logistic", "predict_proba"): lr.predict_proba,
        ("pca", "transform"): pca.transform,
    }
    direct_new = {
        ("logistic", "predict"): lr2.predict,
        ("logistic", "predict_proba"): lr2.predict_proba,
    }

    keys = sorted(direct)
    size_choices = [1, 2, 4, 8, 16, 24, 32, 48, 64, 96, 128]
    trng = np.random.RandomState(42)
    trace = []
    for c in range(clients):
        rows = []
        for r in range(reqs_per_client):
            key = keys[trng.randint(len(keys))]
            size = int(size_choices[trng.randint(len(size_choices))])
            # mixed priorities: 1/3 high-priority + deadline, 1/3
            # deadline-only, 1/3 best-effort
            tier = (c * reqs_per_client + r) % 3
            rows.append((key, int(trng.randint(0, n_fit - size)), size,
                         tier))
        trace.append(rows)
    total_requests = clients * reqs_per_client

    fi = FaultInjector()
    drain = GracefulDrain()
    identity_failures = []
    wire_failures = []
    swap_info = {}
    kill_info = {}

    with config_lib.config_context(telemetry=True):
        telemetry.reset_telemetry(ring_capacity=65_536)
        fleet = ServingFleet(
            n_replicas=replicas, max_batch_rows=max_batch_rows,
            fault_injector=fi, drain=drain,
            heartbeat_interval_s=0.02).start()
        fleet.register("kmeans", km)
        fleet.register("logistic", lr)
        fleet.register("pca", pca)
        warm = fleet.warmup()

        # -- identity gate: fleet + wire vs the direct paths --------------
        for (name, method), fn in direct.items():
            for nreq in (1, 3, 32, 33, 100, 255, 256, 500):
                served = fleet.submit(
                    name, X[:nreq], method=method).result(300)
                if not np.array_equal(served, fn(X[:nreq])):
                    identity_failures.append((name, method, nreq))
        server = FleetServer(fleet).start()
        with FleetClient(server.address) as cli:
            for (name, method), fn in direct.items():
                for nreq in (1, 33, 200):
                    out = cli.call(name, X[:nreq], method=method,
                                   timeout=300)
                    if not np.array_equal(out, fn(X[:nreq])):
                        wire_failures.append((name, method, nreq))
        server.stop()

        # -- steady state: mixed-priority closed loop + mid-run events ----
        completed = [0]
        clock = threading.Lock()
        lat: list = []
        outcomes: list = []  # (key, off, size, ndarray result)
        errors: list = []
        start_evt = threading.Event()

        def client(rows):
            mine_lat, mine_out = [], []
            start_evt.wait()
            for key, off, size, tier in rows:
                name, method = key
                kw = {}
                if tier == 0:
                    kw = {"priority": 5, "deadline": slo_budget_s}
                elif tier == 1:
                    kw = {"deadline": slo_budget_s}
                t0 = time.perf_counter()
                try:
                    out = fleet.submit(
                        name, X[off:off + size], method=method,
                        **kw).result(300)
                except Exception as e:  # noqa: BLE001 — gate on these
                    errors.append((key, off, size, repr(e)))
                    continue
                mine_lat.append(time.perf_counter() - t0)
                mine_out.append((key, off, size, out))
                with clock:
                    completed[0] += 1
            with clock:
                lat.extend(mine_lat)
                outcomes.extend(mine_out)

        def coordinator():
            # hot-swap at ~1/3 of traffic
            while completed[0] < total_requests // 3:
                time.sleep(0.002)
            t0 = time.perf_counter()
            new_version = fleet.swap("logistic", lr2)
            swap_info.update(
                version=new_version,
                at_completed=completed[0],
                swap_seconds=round(time.perf_counter() - t0, 4))
            # replica kill at ~1/2: arm the injector for the busiest
            # live replica's NEXT batch
            while completed[0] < total_requests // 2:
                time.sleep(0.002)
            victim = max(
                (r for r in fleet._replicas if not r.dead
                 and r.loop.alive()),
                key=lambda r: r.loop.n_batches)
            fi.kill_replica(victim.name,
                            after_batches=victim.loop.n_batches)
            kill_info.update(victim=victim.name,
                             at_completed=completed[0])

        threads = [threading.Thread(target=client, args=(rows,))
                   for rows in trace]
        coord = threading.Thread(target=coordinator)
        for t in threads:
            t.start()
        coord.start()
        t0 = time.perf_counter()
        start_evt.set()
        for t in threads:
            t.join()
        serve_elapsed = time.perf_counter() - t0
        coord.join(30)

        # wait out the monitor's death detection
        deadline_t = time.monotonic() + 10.0
        while fleet.replicas_up() > replicas - 1 \
                and time.monotonic() < deadline_t:
            time.sleep(0.02)
        kill_info.update(replicas_up_after=fleet.replicas_up(),
                         injected=fi.injected["replica_kill"],
                         deaths=fleet.n_replica_deaths,
                         reroutes=fleet.n_reroutes)

        # -- over-capacity burst: every request past-deadline, all shed --
        shed_before = fleet.n_shed
        burst_shed = 0
        for _ in range(burst):
            try:
                fleet.submit("kmeans", X[:8], deadline=-1.0)
            except DeadlineExceeded:
                burst_shed += 1
        shed_total = fleet.n_shed

        # -- graceful drain: flush, stop, reject ---------------------------
        drain.request()
        deadline_t = time.monotonic() + 15.0
        survivors = [r for r in fleet._replicas if not r.dead]
        while time.monotonic() < deadline_t and not all(
                r.loop.stopped for r in survivors):
            time.sleep(0.02)
        drain_stopped = all(r.loop.stopped for r in survivors)
        drain_queues_empty = all(
            r.loop.queue_depth() == 0 for r in survivors)
        try:
            fleet.submit("kmeans", X[:8])
            drain_rejects = False
        except ServingClosed:  # ServingStopped is a subclass
            drain_rejects = True
        fleet_stats = fleet.stats()
        fleet.stop()
        report = telemetry.telemetry_report()

    # -- verification ------------------------------------------------------
    n_old = n_new = n_mismatch = 0
    direct_cache: dict = {}
    for key, off, size, out in outcomes:
        ck = (key, off, size)
        if ck not in direct_cache:
            old = direct[key](X[off:off + size])
            new = (direct_new[key](X[off:off + size])
                   if key in direct_new else None)
            direct_cache[ck] = (old, new)
        old, new = direct_cache[ck]
        if np.array_equal(out, old):
            n_old += 1
        elif new is not None and np.array_equal(out, new):
            n_new += 1
        else:
            n_mismatch += 1
    resolved = len(outcomes)
    dropped = total_requests - resolved - len(errors)

    qps = resolved / serve_elapsed
    p50_ms, p99_ms = (float(v) * 1e3
                      for v in np.percentile(lat, [50, 99]))
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "FLEET_r01.json")
    committed_p99 = None
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                committed_p99 = json.load(f).get("p99_ms")
        except Exception:
            committed_p99 = None
    p99_budget_ms = p99_slo_ms
    if committed_p99 is not None:
        p99_budget_ms = min(p99_budget_ms,
                            max(10.0 * committed_p99, 500.0))

    counters = report["metrics"]["counters"]
    mirror_shed = sum(v for k, v in counters.items()
                      if k.startswith("fleet.shed"))
    scaled_down = "FLEET_REPLICAS" in os.environ
    gates = {
        "fleet_of_three_replicas":
            replicas >= (2 if scaled_down else 3),
        "served_bit_identical_to_direct":
            not identity_failures and not wire_failures
            and n_mismatch == 0,
        "hot_swap_no_request_lost":
            bool(swap_info.get("version")) and n_old > 0 and n_new > 0
            and not errors,
        "replica_kill_failover":
            kill_info.get("injected") == 1
            and kill_info.get("deaths") == 1
            and kill_info.get("replicas_up_after") == replicas - 1,
        "zero_dropped_requests":
            dropped == 0 and not errors,
        "p99_within_slo": p99_ms <= p99_budget_ms,
        "shed_exactly_the_burst":
            burst_shed == burst
            and shed_total - shed_before == burst
            and mirror_shed == shed_total,
        "drain_flushes_and_rejects":
            drain_stopped and drain_queues_empty and drain_rejects,
    }
    rec = {
        "metric": "fleet_drill",
        "value": round(qps, 1),
        "unit": "sustained QPS across the fleet (mixed-priority, with "
                "mid-run swap + kill)",
        "vs_baseline": None,  # robustness drill: the gates ARE the result
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "replicas": replicas,
        "devices_per_replica": [
            int(np.prod(list(r.mesh.shape.values())))
            for r in fleet._replicas],
        "clients": clients, "reqs_per_client": reqs_per_client,
        "total_requests": total_requests,
        "resolved": resolved, "dropped": dropped,
        "errors": errors[:10],
        "warmup": warm,
        "qps": round(qps, 1),
        "p50_ms": round(p50_ms, 3),
        "p99_ms": round(p99_ms, 3),
        "p99_budget_ms": p99_budget_ms,
        "slo_budget_s": slo_budget_s,
        "results_old_version": n_old,
        "results_new_version": n_new,
        "results_mismatched": n_mismatch,
        "swap": swap_info,
        "kill": kill_info,
        "burst_injected": burst,
        "burst_shed": burst_shed,
        "shed_total": shed_total,
        "telemetry_shed_mirror": mirror_shed,
        "spillovers": fleet_stats["spillovers"],
        "reroutes": fleet_stats["reroutes"],
        "per_replica_batches": {
            name: r["batches"]
            for name, r in fleet_stats["replicas"].items()},
        "identity_failures": identity_failures,
        "wire_failures": wire_failures,
        "replica_up_gauge": report["metrics"]["gauges"].get(
            "fleet.replica_up"),
        "note": "closed-loop mixed-priority clients; the logistic model "
                "hot-swaps to a differently-regularized refit at ~1/3 of "
                "traffic (old/new version counts prove both served), one "
                "replica is killed via FaultInjector at ~1/2, the burst "
                "arrives past-deadline so it must shed EXACTLY, and the "
                "run ends in a GracefulDrain. Scaled down in CI via "
                "FLEET_REPLICAS/FLEET_CLIENTS/FLEET_REQS.",
    }
    emit(rec)
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "fleet drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


def bench_fleet_proc(_rtt):
    """Process-isolation kill drill (ISSUE 15; docs/serving.md, "The
    process-isolated fleet"): the kill drill graduates from simulated
    thread death to ``kill -9`` of a live replica OS PROCESS under
    traffic, plus a hedging A/B under a real injected straggler.

    Phases:
    1. fit three families once; they ship to every replica process via
       the registry snapshot;
    2. hedging A/B: two fleets of ``FLEETPROC_REPLICAS`` replica
       processes, replica slot 0 carrying a REAL wall-clock straggle
       plan (``FaultInjector.straggle_replica``: sleep
       ``FLEETPROC_STRAGGLE_S`` every ``FLEETPROC_STRAGGLE_EVERY``-th
       batch). Identical seeded closed-loop traffic with hedging OFF
       then ON — the measured p99 must improve;
    3. kill -9: a fresh fleet (telemetry on), closed-loop traffic;
       at ~1/3 of traffic the coordinator sends REAL ``SIGKILL`` to a
       replica process. The router must replay its in-flight requests on
       survivors (idempotent by request id), respawn the slot — snapshot
       load + warmup through the exact serving staging path BEFORE
       rejoining rotation — and finish the run;
    4. drain: SIGTERM to every replica; graceful exit 0 everywhere
       (except the SIGKILLed incarnation, whose -9 is itself a gate).

    Gates (nonzero exit on failure): the kill was a real SIGKILL of a
    real OS process; ZERO dropped requests and ZERO double-resolutions
    (every future resolved exactly once — ``n_results`` equals resolved
    count); every result — including replayed and hedged ones —
    bit-identical to the direct path; the respawned replica rejoins with
    zero steady-state compiles; hedged p99 < unhedged p99 under the
    straggler (which must be visible unhedged); hedge/respawn/death
    telemetry mirrors exact; and the fleet module is pickle-free
    (``grep -r pickle dask_ml_tpu/parallel/fleet.py`` comes back
    empty). Committed as FLEET_r02.json; the CI ``chaos`` job runs this
    scaled to 2 replica processes.
    """
    import signal as signal_mod
    import threading

    import jax

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.procfleet import ProcessFleet

    n_fit, d = 4096, 32
    replicas = int(os.environ.get("FLEETPROC_REPLICAS", "3"))
    clients = int(os.environ.get("FLEETPROC_CLIENTS", "8"))
    reqs_per_client = int(os.environ.get("FLEETPROC_REQS", "24"))
    straggle_s = float(os.environ.get("FLEETPROC_STRAGGLE_S", "0.25"))
    straggle_every = int(os.environ.get("FLEETPROC_STRAGGLE_EVERY", "3"))
    max_batch_rows = 1024

    rng = np.random.RandomState(0)
    X = rng.standard_normal((n_fit, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.int32)
    km = KMeans(n_clusters=16, random_state=0, max_iter=10).fit(X)
    lr = LogisticRegression(max_iter=30).fit(X, y)
    pca = PCA(n_components=8, random_state=0).fit(X)
    direct = {
        ("kmeans", "predict"): km.predict,
        ("logistic", "predict_proba"): lr.predict_proba,
        ("pca", "transform"): pca.transform,
    }
    keys = sorted(direct)
    size_choices = [1, 2, 4, 8, 16, 24, 32, 48, 64, 96, 128]
    trng = np.random.RandomState(42)
    trace = []
    for c in range(clients):
        rows = []
        for r in range(reqs_per_client):
            key = keys[trng.randint(len(keys))]
            size = int(size_choices[trng.randint(len(size_choices))])
            rows.append((key, int(trng.randint(0, n_fit - size)), size))
        trace.append(rows)
    total_requests = clients * reqs_per_client

    def build(name, *, hedge, straggle=None):
        fleet = ProcessFleet(
            n_replicas=replicas, max_batch_rows=max_batch_rows,
            hedge=hedge, hedge_min_s=0.02, request_timeout_s=300.0,
            straggle=straggle, name=name)
        fleet.register("kmeans", km)
        fleet.register("logistic", lr)
        fleet.register("pca", pca)
        return fleet.start()

    def closed_loop(fleet, on_complete=None):
        """Run the seeded trace; returns (latencies, outcomes, errors,
        wall)."""
        lat: list = []
        outcomes: list = []
        errors: list = []
        lock = threading.Lock()
        done = [0]
        start_evt = threading.Event()

        def client(rows):
            mine_lat, mine_out = [], []
            start_evt.wait()
            for key, off, size in rows:
                name, method = key
                t0 = time.perf_counter()
                try:
                    out = fleet.submit(
                        name, X[off:off + size], method=method).result(300)
                except Exception as e:  # noqa: BLE001 — gate on these
                    errors.append((key, off, size, repr(e)))
                    continue
                mine_lat.append(time.perf_counter() - t0)
                mine_out.append((key, off, size, out))
                with lock:
                    done[0] += 1
                if on_complete is not None:
                    on_complete(done[0])
            with lock:
                lat.extend(mine_lat)
                outcomes.extend(mine_out)

        threads = [threading.Thread(target=client, args=(rows,))
                   for rows in trace]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        start_evt.set()
        for t in threads:
            t.join()
        return lat, outcomes, errors, time.perf_counter() - t0

    def verify(outcomes):
        bad = 0
        cache: dict = {}
        for key, off, size, out in outcomes:
            ck = (key, off, size)
            if ck not in cache:
                cache[ck] = direct[key](X[off:off + size])
            if not np.array_equal(out, cache[ck]):
                bad += 1
        return bad

    # -- phase 2: hedging A/B under a real straggler ----------------------
    hedge_ab = {}
    for hedge in (False, True):
        fleet = build(f"pf-h{int(hedge)}", hedge=hedge,
                      straggle={0: (straggle_s, straggle_every)})
        try:
            lat, outcomes, errors, wall = closed_loop(fleet)
            stats = fleet.stats()
        finally:
            fleet.stop()
        p50, p99 = (float(v) * 1e3 for v in np.percentile(lat, [50, 99]))
        hedge_ab["hedged" if hedge else "unhedged"] = {
            "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
            "qps": round(len(lat) / wall, 1),
            "resolved": len(lat), "errors": errors[:5],
            "mismatches": verify(outcomes),
            "hedged": stats["hedged"], "hedge_wins": stats["hedge_wins"],
            "reroutes": stats["reroutes"],
        }
    p99_unhedged = hedge_ab["unhedged"]["p99_ms"]
    p99_hedged = hedge_ab["hedged"]["p99_ms"]

    # -- phase 3: kill -9 of a live replica process under traffic ---------
    kill_info: dict = {}
    with config_lib.config_context(telemetry=True):
        telemetry.reset_telemetry(ring_capacity=65_536)
        fleet = build("pf-kill", hedge=True)
        try:
            pids_before = {rep.name: rep.pid for rep in fleet._procs}
            victim = fleet._procs[0]
            old_pid, old_proc = victim.pid, victim.proc
            killed = threading.Event()
            kill_lock = threading.Lock()

            def maybe_kill(done_count):
                # atomic test-and-set: exactly ONE client thread delivers
                # the kill, and a pid already reaped by the respawner
                # must not blow up that client's trace
                if done_count < total_requests // 3:
                    return
                with kill_lock:
                    if killed.is_set():
                        return
                    killed.set()
                try:
                    os.kill(old_pid, signal_mod.SIGKILL)
                except ProcessLookupError:
                    pass
                kill_info["at_completed"] = done_count

            results_before = fleet.n_results
            lat, outcomes, errors, wall = closed_loop(
                fleet, on_complete=maybe_kill)
            resolved = len(outcomes)
            first_resolutions = fleet.n_results - results_before
            old_proc.wait(60)
            # wait out the respawn, then prove steady-state is compile-free
            deadline_t = time.monotonic() + 300.0
            while (fleet.replicas_up() < replicas
                   or fleet.n_respawns < 1) \
                    and time.monotonic() < deadline_t:
                time.sleep(0.05)
            post_outcomes = []
            for i in range(10 * replicas):
                out = fleet.call("kmeans", X[i:i + 16], timeout=300)
                post_outcomes.append((("kmeans", "predict"), i, 16, out))
            remote = fleet.remote_stats()
            stats = fleet.stats()
            kill_info.update(
                victim=victim.name, old_pid=old_pid,
                old_exit=old_proc.returncode, new_pid=victim.pid,
                deaths=stats["replica_deaths"],
                respawns=stats["respawns"],
                reroutes=stats["reroutes"],
                replicas_up_after=fleet.replicas_up())
        finally:
            fleet.stop()
        exit_codes = {rep.name: rep.proc.returncode
                      for rep in fleet._procs}
        report = telemetry.telemetry_report()

    counters = report["metrics"]["counters"]

    def mirror(prefix):
        return sum(v for k, v in counters.items()
                   if k == prefix or k.startswith(prefix + "{"))

    steady_compiles = {name: st.get("steady_compiles")
                       for name, st in remote.items()}
    fleet_src = open(os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "dask_ml_tpu", "parallel", "fleet.py")).read()
    dropped = total_requests - resolved - len(errors)
    p50, p99 = (float(v) * 1e3 for v in np.percentile(lat, [50, 99]))
    gates = {
        "replicas_are_processes":
            len(set(pids_before.values())) == replicas
            and os.getpid() not in pids_before.values(),
        "kill_was_real_sigkill":
            kill_info.get("old_exit") == -signal_mod.SIGKILL,
        "zero_dropped_requests":
            dropped == 0 and not errors,
        "zero_double_resolutions":
            first_resolutions == resolved,
        "replayed_results_bit_identical":
            verify(outcomes) == 0 and verify(post_outcomes) == 0
            and hedge_ab["unhedged"]["mismatches"] == 0
            and hedge_ab["hedged"]["mismatches"] == 0,
        "respawn_rejoined_rotation":
            kill_info.get("respawns") == 1
            and kill_info.get("replicas_up_after") == replicas
            and kill_info.get("new_pid") != kill_info.get("old_pid"),
        "respawn_zero_steady_compiles":
            len(steady_compiles) == replicas
            and all(v == 0 for v in steady_compiles.values()),
        "hedging_improves_p99":
            hedge_ab["hedged"]["hedged"] >= 1
            and p99_hedged < p99_unhedged,
        "straggler_visible_unhedged":
            p99_unhedged >= straggle_s * 1e3 * 0.8,
        "telemetry_mirrors_exact":
            mirror("fleet.respawns") == kill_info.get("respawns")
            and mirror("fleet.replica_deaths") == kill_info.get("deaths")
            and mirror("fleet.reroutes") == kill_info.get("reroutes"),
        "graceful_drain_exit_codes":
            all(rc == 0 for rc in exit_codes.values()),
        "fleet_module_pickle_free": "pickle" not in fleet_src,
    }
    rec = {
        "metric": "fleet_proc_drill",
        "value": round(resolved / wall, 1),
        "unit": "sustained QPS across replica PROCESSES (with mid-run "
                "kill -9 + respawn)",
        "vs_baseline": None,  # robustness drill: the gates ARE the result
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "replicas": replicas,
        "clients": clients, "reqs_per_client": reqs_per_client,
        "total_requests": total_requests,
        "resolved": resolved, "dropped": dropped,
        "first_resolutions": first_resolutions,
        "errors": errors[:10],
        "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
        "hedging_ab": hedge_ab,
        "straggle": {"seconds": straggle_s, "every": straggle_every,
                     "replica_slot": 0},
        "kill": kill_info,
        "steady_compiles_after_respawn": steady_compiles,
        "exit_codes_after_drain": exit_codes,
        "telemetry_mirrors": {
            "fleet.respawns": mirror("fleet.respawns"),
            "fleet.replica_deaths": mirror("fleet.replica_deaths"),
            "fleet.reroutes": mirror("fleet.reroutes"),
            "serving.hedged": mirror("serving.hedged"),
            "serving.hedge_wins": mirror("serving.hedge_wins"),
        },
        "note": "replica processes spawned via the ReplicaHost "
                "entrypoint (registry snapshot + warmup before "
                "rotation); slot-0 straggle is a REAL wall-clock sleep "
                "every Nth batch; the kill is os.kill(SIGKILL) of a "
                "live replica pid mid-traffic. Scaled down in CI via "
                "FLEETPROC_REPLICAS/FLEETPROC_CLIENTS/FLEETPROC_REQS.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "FLEET_r02.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "fleet-proc drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


def bench_fleet_machines(_rtt):
    """Cross-machine fleet drill (ISSUE 18; docs/serving.md, "The
    multi-machine fleet"): two isolated "machines" (separate workdirs +
    their own OS processes on loopback TCP), content-addressed snapshot
    distribution with per-machine chunk caches, the SLO autoscaler's
    closed loop, and machine loss under traffic.

    Phases:
    1. fit three families; a 2-machine fleet comes up (one replica per
       machine via capacity-weighted placement), each machine cold-
       fetching the FULL registry snapshot chunk-by-chunk;
    2. burst: seeded closed-loop traffic from ``FLEETMACH_CLIENTS``
       clients sustains queue depth over the SLO bound — the autoscaler
       (breach hysteresis + cooldown) must call ``scale_up(1)``; the new
       replica lands on a machine whose chunk cache is already warm, so
       the link carries ZERO snapshot bytes;
    3. quiet: traffic stops; every signal sits under ``clear_fraction``
       of its bound for ``quiet_ticks`` — the autoscaler must DRAIN the
       extra slot (tombstone + exit 0, not a kill, no death counter);
    4. machine loss: fresh closed-loop traffic; at ~1/3 of it the armed
       ``FaultInjector.kill_machine`` plan SIGKILLs every replica on
       machine m1 at once. The router must detect the MACHINE death
       (all its heartbeats stop together), replay in-flight requests on
       survivors, and respawn the lost slots on the surviving machine —
       re-shipping only missing chunks (zero, its cache is warm);
    5. steady state: the rejoined fleet serves with zero steady-state
       compiles, bit-identical to the direct path.

    Gates (nonzero exit on failure): >= 2 isolated machines; burst
    scaled up; scale-up re-shipped less than a full snapshot; quiet
    drained back down; ZERO dropped and ZERO double-resolved requests
    through the machine loss; machine death detected + counted; the
    lost slots respawned on the survivor with a delta-only (empty)
    re-ship; zero steady-state compiles after rejoin; every result
    bit-identical; autoscaler/fleet telemetry mirrors exact. Committed
    as FLEET_r03.json; the CI ``chaos`` job runs this scaled down.
    """
    import shutil
    import signal as signal_mod
    import threading

    import jax

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.autoscaler import SLO, Autoscaler
    from dask_ml_tpu.parallel.faults import FaultInjector
    from dask_ml_tpu.parallel.launcher import MachineSpec
    from dask_ml_tpu.parallel.procfleet import ProcessFleet

    n_fit, d = 4096, 32
    replicas = int(os.environ.get("FLEETMACH_REPLICAS", "2"))
    clients = int(os.environ.get("FLEETMACH_CLIENTS", "8"))
    reqs_per_client = int(os.environ.get("FLEETMACH_REQS", "24"))
    chunk_bytes = int(os.environ.get("FLEETMACH_CHUNK_BYTES", "4096"))

    rng = np.random.RandomState(0)
    X = rng.standard_normal((n_fit, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.int32)
    km = KMeans(n_clusters=16, random_state=0, max_iter=10).fit(X)
    lr = LogisticRegression(max_iter=30).fit(X, y)
    pca = PCA(n_components=8, random_state=0).fit(X)
    direct = {
        ("kmeans", "predict"): km.predict,
        ("logistic", "predict_proba"): lr.predict_proba,
        ("pca", "transform"): pca.transform,
    }
    keys = sorted(direct)
    size_choices = [1, 2, 4, 8, 16, 24, 32, 48, 64, 96, 128]
    trng = np.random.RandomState(42)

    def make_trace():
        trace = []
        for _c in range(clients):
            rows = []
            for _r in range(reqs_per_client):
                key = keys[trng.randint(len(keys))]
                size = int(size_choices[trng.randint(len(size_choices))])
                rows.append((key, int(trng.randint(0, n_fit - size)), size))
            trace.append(rows)
        return trace

    total_requests = clients * reqs_per_client

    def closed_loop(fleet, trace):
        lat: list = []
        outcomes: list = []
        errors: list = []
        lock = threading.Lock()
        start_evt = threading.Event()

        def client(rows):
            mine_lat, mine_out = [], []
            start_evt.wait()
            for key, off, size in rows:
                name, method = key
                t0 = time.perf_counter()
                try:
                    out = fleet.submit(
                        name, X[off:off + size], method=method).result(300)
                except Exception as e:  # noqa: BLE001 — gate on these
                    errors.append((key, off, size, repr(e)))
                    continue
                mine_lat.append(time.perf_counter() - t0)
                mine_out.append((key, off, size, out))
            with lock:
                lat.extend(mine_lat)
                outcomes.extend(mine_out)

        threads = [threading.Thread(target=client, args=(rows,))
                   for rows in trace]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        start_evt.set()
        for t in threads:
            t.join()
        return lat, outcomes, errors, time.perf_counter() - t0

    def verify(outcomes):
        bad = 0
        cache: dict = {}
        for key, off, size, out in outcomes:
            ck = (key, off, size)
            if ck not in cache:
                cache[ck] = direct[key](X[off:off + size])
            if not np.array_equal(out, cache[ck]):
                bad += 1
        return bad

    def fetch_stats(fleet):
        return {name: st["snapshot_fetch"]
                for name, st in fleet.stats()["replicas"].items()
                if st["snapshot_fetch"] is not None}

    base = tempfile.mkdtemp(prefix="fleetmach-")
    inj = FaultInjector()
    machines = [MachineSpec(name="m0", workdir=os.path.join(base, "m0")),
                MachineSpec(name="m1", workdir=os.path.join(base, "m1"))]
    slo = SLO(target_p99_s=float("inf"), max_queue_depth=3.0,
              max_shed_per_s=0.0)
    scale_info: dict = {}
    kill_info: dict = {}
    mismatches = 0
    try:
        with config_lib.config_context(telemetry=True):
            telemetry.reset_telemetry(ring_capacity=65_536)
            fleet = ProcessFleet(
                n_replicas=replicas, max_batch_rows=1024,
                request_timeout_s=300.0, name="pm",
                machines=machines, fault_injector=inj,
                snapshot_chunk_bytes=chunk_bytes)
            fleet.register("kmeans", km)
            fleet.register("logistic", lr)
            fleet.register("pca", pca)
            fleet.start()
            scaler = Autoscaler(
                fleet, slo, min_replicas=replicas,
                max_replicas=replicas + 1, interval_s=0.1,
                breach_ticks=2, quiet_ticks=5,
                scale_up_cooldown_s=1.0, scale_down_cooldown_s=2.0)
            try:
                # -- phase 1: cold distribution -------------------------
                initial_fetch = fetch_stats(fleet)
                full_bytes = max(
                    fs["bytes_total"] for fs in initial_fetch.values())
                initial_placement = {
                    m: row["replicas"] for m, row in
                    fleet.stats()["machines"].items()}

                # -- phase 2: burst -> autoscaler scale-up --------------
                scaler.start()
                lat_b, out_b, err_b, wall_b = closed_loop(
                    fleet, make_trace())
                # keep the pressure on until the scaler fires: at CI
                # scale one trace drains faster than breach_ticks
                # consecutive ticks can accumulate, so re-burst the same
                # seeded trace (verify() stays exact) until scale-up
                deadline_t = time.monotonic() + 60.0
                while scaler.n_scale_ups < 1 \
                        and time.monotonic() < deadline_t:
                    lb, ob, eb, wb = closed_loop(fleet, make_trace())
                    lat_b += lb
                    out_b += ob
                    err_b += eb
                    wall_b += wb
                scaled_fetch = {
                    name: fs for name, fs in fetch_stats(fleet).items()
                    if name not in initial_fetch}
                scale_info["scale_ups"] = scaler.n_scale_ups
                scale_info["replicas_after_burst"] = fleet.replicas_up()
                scale_info["new_replica_fetch"] = scaled_fetch

                # -- phase 3: quiet -> autoscaler drain -----------------
                deadline_t = time.monotonic() + 60.0
                while (scaler.n_scale_downs < 1
                       or fleet.stats()["drains"] < 1) \
                        and time.monotonic() < deadline_t:
                    time.sleep(0.05)
                scale_info["scale_downs"] = scaler.n_scale_downs
                scale_info["replicas_after_quiet"] = fleet.replicas_up()
                scale_info["decisions"] = [
                    {k: v for k, v in d.items() if k != "signals"}
                    for d in list(scaler.decisions)]
            finally:
                scaler.stop()

            # -- phase 4: machine loss mid-traffic ----------------------
            deaths_before = fleet.n_replica_deaths
            results_before = fleet.n_results
            inj.kill_machine(
                "m1", after_results=results_before + total_requests // 3)
            lat_k, out_k, err_k, wall_k = closed_loop(fleet, make_trace())
            resolved = len(out_k)
            first_resolutions = fleet.n_results - results_before
            deadline_t = time.monotonic() + 300.0
            while (fleet.replicas_up() < replicas
                   or fleet.n_respawns < 1) \
                    and time.monotonic() < deadline_t:
                time.sleep(0.05)

            # -- phase 5: steady state after rejoin ---------------------
            post_outcomes = []
            for i in range(10 * replicas):
                out = fleet.call("kmeans", X[i:i + 16], timeout=300)
                post_outcomes.append((("kmeans", "predict"), i, 16, out))
            remote = fleet.remote_stats()
            stats = fleet.stats()
            mismatches = (verify(out_b) + verify(out_k)
                          + verify(post_outcomes))
            live_rows = {
                name: row for name, row in stats["replicas"].items()
                if not row["dead"] and not row["retired"]}
            respawned = {name: row for name, row in live_rows.items()
                         if row["gen"] > 1}
            kill_info.update(
                machine="m1",
                machine_deaths=stats["machine_deaths"],
                deaths=stats["replica_deaths"] - deaths_before,
                respawns=stats["respawns"],
                m1_down=stats["machines"]["m1"]["down"],
                survivor_placement={
                    name: row["machine"]
                    for name, row in live_rows.items()},
                respawn_fetch={
                    name: row["snapshot_fetch"]
                    for name, row in respawned.items()})
            fleet.stop()
            exit_codes = {rep.name: rep.proc.returncode
                          for rep in fleet._procs if rep.proc is not None}
            report = telemetry.telemetry_report()
            scaler_stats = scaler.stats()
    finally:
        shutil.rmtree(base, ignore_errors=True)

    counters = report["metrics"]["counters"]

    def mirror(prefix):
        return sum(v for k, v in counters.items()
                   if k == prefix or k.startswith(prefix + "{"))

    steady_compiles = {name: st.get("steady_compiles")
                       for name, st in remote.items()}
    dropped = total_requests - resolved - len(err_k)
    p50, p99 = (float(v) * 1e3 for v in np.percentile(lat_k, [50, 99]))
    new_fetch = list(scale_info.get("new_replica_fetch", {}).values())
    respawn_fetch = list(kill_info.get("respawn_fetch", {}).values())
    gates = {
        "two_isolated_machines":
            len(initial_placement) == 2
            and all(len(reps) >= 1 for reps in initial_placement.values()),
        "initial_ship_full_per_machine":
            len(initial_fetch) == replicas
            and all(fs["bytes_fetched"] == fs["bytes_total"] == full_bytes
                    and fs["chunks_total"] >= 2
                    for fs in initial_fetch.values()),
        "burst_scaled_up":
            scale_info.get("scale_ups", 0) >= 1
            and scale_info.get("replicas_after_burst", 0) == replicas + 1,
        "scale_up_delta_only_reship":
            len(new_fetch) == 1
            and new_fetch[0]["bytes_fetched"] < full_bytes
            and new_fetch[0]["chunks_cached"] > 0,
        "quiet_drained_back_down":
            scale_info.get("scale_downs", 0) >= 1
            and scale_info.get("replicas_after_quiet", 0) == replicas,
        "machine_loss_zero_dropped":
            dropped == 0 and not err_k and not err_b,
        "machine_loss_zero_double_resolved":
            first_resolutions == resolved,
        "machine_death_detected":
            kill_info.get("machine_deaths") == 1
            and kill_info.get("m1_down") is True
            and inj.injected["machine_kill"] == 1,
        "respawn_on_survivor_delta_reship":
            len(respawn_fetch) >= 1
            and set(kill_info.get("survivor_placement", {}).values())
            == {"m0"}
            and all(fs["bytes_fetched"] < full_bytes
                    for fs in respawn_fetch),
        "zero_steady_compiles_after_rejoin":
            len(steady_compiles) >= replicas
            and all(v == 0 for v in steady_compiles.values()),
        "results_bit_identical": mismatches == 0,
        "graceful_exit_codes_after_stop":
            all(rc == 0 for rc in exit_codes.values()),
        "telemetry_mirrors_exact":
            mirror("fleet.machine_deaths")
            == kill_info.get("machine_deaths")
            and mirror("fleet.scale_ups") == scale_info.get("scale_ups")
            and mirror("fleet.drains") >= scale_info.get("scale_downs", 1)
            and mirror("autoscaler.scale_ups") == scaler_stats["scale_ups"]
            and mirror("autoscaler.scale_downs")
            == scaler_stats["scale_downs"],
    }
    rec = {
        "metric": "fleet_machines_drill",
        "value": round(resolved / wall_k, 1),
        "unit": "sustained QPS across MACHINES (with mid-run machine "
                "loss + respawn-elsewhere)",
        "vs_baseline": None,  # robustness drill: the gates ARE the result
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "machines": 2, "replicas": replicas,
        "clients": clients, "reqs_per_client": reqs_per_client,
        "total_requests": total_requests,
        "resolved": resolved, "dropped": dropped,
        "first_resolutions": first_resolutions,
        "errors": (err_b + err_k)[:10],
        "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
        "burst": {"qps": round(len(lat_b) / wall_b, 1),
                  "resolved": len(lat_b)},
        "snapshot": {"chunk_bytes": chunk_bytes,
                     "full_bytes": full_bytes,
                     "initial_fetch": initial_fetch},
        "autoscaler": {**scaler_stats, "slo": {
            "max_queue_depth": slo.max_queue_depth,
            "max_shed_per_s": slo.max_shed_per_s}},
        "scaling": scale_info,
        "kill": kill_info,
        "steady_compiles_after_rejoin": steady_compiles,
        "exit_codes_after_stop": exit_codes,
        "telemetry_mirrors": {
            "fleet.machine_deaths": mirror("fleet.machine_deaths"),
            "fleet.scale_ups": mirror("fleet.scale_ups"),
            "fleet.drains": mirror("fleet.drains"),
            "fleet.respawns": mirror("fleet.respawns"),
            "autoscaler.scale_ups": mirror("autoscaler.scale_ups"),
            "autoscaler.scale_downs": mirror("autoscaler.scale_downs"),
            "autoscaler.breaches": mirror("autoscaler.breaches"),
            "snapshot.bytes_fetched": mirror("snapshot.bytes_fetched"),
        },
        "note": "each 'machine' is an isolated workdir + its own OS "
                "processes on loopback TCP — every seam (placement, "
                "chunked snapshot distribution, machine-death "
                "detection, replay, respawn-elsewhere) is the real "
                "code path; only the physical box is shared. The kill "
                "is an armed kill_machine plan SIGKILLing every m1 "
                "replica at once mid-traffic. Scaled down in CI via "
                "FLEETMACH_CLIENTS/FLEETMACH_REQS.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "FLEET_r03.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "fleet-machines drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


# ---------------------------------------------------------------------------
# KDD-Cup'99 harness (the reference's flagship real-data benchmark,
# benchmarks/k_means_kdd.py:95-125: KMeans(n_clusters=8,
# oversampling_factor=2, random_state=0) on ~4.9M x 41)
# ---------------------------------------------------------------------------


def _load_kdd():
    """The real KDD-Cup'99 numeric matrix when a local sklearn cache exists;
    otherwise a synthetic stand-in with the dataset's shape and character
    (4,898,431 x 41; heavily imbalanced cluster structure — smurf/neptune/
    normal dominate the real data — and per-feature scales spanning orders
    of magnitude). Returns ``(X_device, source_str)``.

    This environment has no network egress, so the download path cannot
    run; the loader still tries the cache first so the harness uses real
    data wherever it is available."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.parallel import mesh as mesh_lib

    n, d = 4_898_431, 41
    try:
        from sklearn.datasets import fetch_kddcup99

        bunch = fetch_kddcup99(percent10=False, download_if_missing=False)
        import pandas as pd

        # bunch.data is an OBJECT array (3 of the 41 columns are
        # categorical bytes); coerce per column and keep the fully
        # numeric ones, like the reference's parquet preprocessing
        df = pd.DataFrame(bunch.data).apply(
            lambda col: pd.to_numeric(col, errors="coerce"))
        df = df.dropna(axis="columns")
        if df.shape[1] == 0:
            raise ValueError("no numeric KDD columns")
        X = df.to_numpy(np.float32)
        Xd = jax.device_put(
            X, mesh_lib.data_sharding(mesh_lib.default_mesh(), ndim=2))
        return Xd, f"real KDD-Cup'99 ({X.shape[0]}x{X.shape[1]})"
    except Exception:
        pass

    mesh = mesh_lib.default_mesh()
    row_sh = mesh_lib.data_sharding(mesh, ndim=2)
    n_clusters_true = 23  # attack types in the real labels

    def gen(key):
        kc, ks, kp, ki, kn = jax.random.split(key, 5)
        centers = jax.random.normal(kc, (n_clusters_true, d)) * \
            jnp.exp(jax.random.normal(ks, (1, d)) * 1.5)  # scale spread
        # heavy imbalance: geometric-ish cluster mass like the real data
        logits = -0.45 * jnp.arange(n_clusters_true, dtype=jnp.float32)
        ids = jax.random.categorical(ki, logits, shape=(n,))
        noise = jax.random.normal(kn, (n, d), jnp.float32)
        return centers[ids] + noise * 0.3 * jnp.exp(
            jax.random.normal(kp, (1, d)) * 0.5)

    X = jax.jit(gen, out_shardings=row_sh)(jax.random.key(99))
    return X, ("synthetic stand-in, 4898431x41 (no network egress in this "
               "environment; the loader uses the real sklearn "
               "fetch_kddcup99 cache when present)")


def bench_wire(_rtt):
    """Zero-copy wire drill (ISSUE 20; docs/serving.md, "The wire"):
    the shared-memory ring transport vs the TCP loopback wire, the
    crc32c integrity tier, and the adaptive micro-batching window.

    Phases:
    1. identity: one FleetServer fronting real kmeans/logistic/pca
       models, one shm-negotiated client and one TCP-pinned client —
       every family, ragged sizes, results bit-identical to each other
       and to the direct path;
    2. zero-copy pin: direct ring endpoints under BOTH checksums — the
       decoded request array's buffer pointer lies INSIDE the shared
       segment (and a defensive copy does not);
    3. throughput: closed-loop echo traffic (wire cost dominant) —
       this PR's data plane (shm ring + crc32c tier) against the wire
       it replaced (framed TCP loopback + whole-frame sha256, the
       seed's DMLTWIRE2 semantics), with a same-checksum TCP row so
       the json decomposes transport vs integrity-tier wins; telemetry
       on — ``wire.bytes{transport=}`` mirrors must see both
       transports and ``wire.hash_seconds{algo=}`` both digests;
    4. adaptive window: one open-loop mixed trace (idle singles, a
       steady stream, back-to-back bursts) against fixed window=0,
       fixed window=max, and "adaptive" — adaptive must batch like
       neither extreme: far fewer batches than window=0, far lower
       latency than window=max, and window=0's latency when idle;
    5. kill -9 over shm: a 2-process ProcessFleet whose replica links
       negotiated shm, SIGKILL of a live replica mid-traffic — zero
       dropped requests, all results bit-identical, and ZERO shm
       segments left in /dev/shm after stop;
    6. fuzz: frame bit-flip/truncation sweeps and torn ring records
       (status, length, payload) under BOTH checksums — every
       corruption caught, none silent.

    Gates (nonzero exit on failure): identity across transports;
    decode-side zero-copy by buffer-pointer identity; same-machine
    data-plane QPS >= 2x the seed wire (or p99 >= 2x lower);
    adaptive beats window=0 on batch count AND window=max on latency
    on the same trace; the kill -9 drops zero requests and leaks zero
    segments; fuzz fully caught; telemetry mirrors present. Committed
    as WIRE_r01.json; the CI ``chaos`` job runs this scaled down.
    """
    import signal as signal_mod
    import threading
    from concurrent.futures import Future

    import jax

    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import framing, telemetry
    from dask_ml_tpu.parallel import shm as shm_lib
    from dask_ml_tpu.parallel.fleet import FleetClient, FleetServer
    from dask_ml_tpu.parallel.procfleet import ProcessFleet
    from dask_ml_tpu.parallel.serving import ModelRegistry, ServingLoop

    qps_clients = int(os.environ.get("WIRE_QPS_CLIENTS", "4"))
    qps_reqs = int(os.environ.get("WIRE_QPS_REQS", "50"))
    qps_rows = int(os.environ.get("WIRE_QPS_ROWS", "8192"))
    steady_n = int(os.environ.get("WIRE_STEADY", "150"))
    burst_n = int(os.environ.get("WIRE_BURST", "30"))
    idle_n = int(os.environ.get("WIRE_IDLE", "12"))
    kill_clients = int(os.environ.get("WIRE_KILL_CLIENTS", "6"))
    kill_reqs = int(os.environ.get("WIRE_KILL_REQS", "18"))
    replicas = int(os.environ.get("WIRE_REPLICAS", "2"))

    n_fit, d = 2048, 16
    rng = np.random.RandomState(0)
    X = rng.standard_normal((n_fit, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.int32)
    km = KMeans(n_clusters=8, random_state=0, max_iter=8).fit(X)
    lr = LogisticRegression(max_iter=20).fit(X, y)
    pca = PCA(n_components=4, random_state=0).fit(X)
    direct = {
        ("kmeans", "predict"): km.predict,
        ("logistic", "predict"): lr.predict,
        ("logistic", "predict_proba"): lr.predict_proba,
        ("pca", "transform"): pca.transform,
    }
    ragged = (1, 3, 31, 32, 33, 64, 100, 128)

    # -- phase 1: shm bit-identical to TCP for every family ---------------
    reg = ModelRegistry()
    reg.register("kmeans", km)
    reg.register("logistic", lr)
    reg.register("pca", pca)
    identity_mismatches = 0
    shm_negotiated = False
    with ServingLoop(reg, max_batch_rows=256) as lp:
        server = FleetServer(lp).start()
        try:
            with FleetClient(server.address) as cs, \
                    FleetClient(server.address, shm=False) as ct:
                shm_negotiated = (cs._shm is not None and ct._shm is None
                                  and server.n_shm_conns == 1)
                for (name, method), fn in sorted(direct.items()):
                    for n in ragged:
                        ref = np.asarray(fn(X[:n]))
                        a = cs.call(name, X[:n], method=method, timeout=120)
                        b = ct.call(name, X[:n], method=method, timeout=120)
                        if not (np.array_equal(a, ref)
                                and np.array_equal(b, ref)):
                            identity_mismatches += 1
        finally:
            server.stop()

    # -- phase 2: decode-side zero-copy by buffer-pointer identity --------
    zero_copy = {}
    for checksum in framing.CHECKSUMS:
        cli = shm_lib.ShmClient(ring_bytes=1 << 20, checksum=checksum)
        srv = shm_lib.ShmServer(cli.segment)
        try:
            payload = np.arange(4096, dtype=np.float32).reshape(64, 64)
            cli.send({"op": "submit", "id": "zc"}, [payload])
            ctrl, arrays, tok = srv.recv(timeout=10.0)
            seg = np.frombuffer(srv._shm.buf, dtype=np.uint8)
            lo = seg.__array_interface__["data"][0]
            hi = lo + seg.nbytes
            addr = arrays[0].__array_interface__["data"][0]
            copy_addr = np.array(arrays[0]).__array_interface__["data"][0]
            zero_copy[checksum] = bool(
                lo <= addr < hi and addr + arrays[0].nbytes <= hi
                and not (lo <= copy_addr < hi)
                and np.array_equal(arrays[0], payload))
            del arrays, seg
            srv.release(tok)
        finally:
            srv.close()
            cli.close(unlink=True)

    # -- phase 3: QPS/p99, shm vs TCP loopback, echo server ---------------
    class _EchoFleet:
        def submit(self, model, Xa, method="predict", priority=0,
                   deadline=None):
            fut = Future()
            fut.set_result(np.asarray(Xa))
            return fut

    payload = rng.standard_normal((qps_rows, 32)).astype(np.float32)
    loadgen = {}
    # process-wide, NOT config_context: the wire.* mirrors fire in client
    # and server worker threads, and config_context is thread-local
    config_lib.set_config(telemetry=True)
    try:
        telemetry.reset_telemetry()
        telemetry.metrics().reset()
        # the QPS gate measures THE PR'S CLAIM: the new same-machine
        # data plane (shm ring + crc32c integrity tier) against the wire
        # every co-located link paid before it — framed TCP loopback
        # with whole-frame sha256 (the seed's DMLTWIRE2 semantics).
        # tcp_crc32c is reported alongside so the json decomposes the
        # win into its transport and integrity-tier parts.
        configs = (("tcp_seed", False, "sha256"),
                   ("tcp_crc32c", False, "crc32c"),
                   ("shm", True, "crc32c"))
        for label, use_shm, wire_checksum in configs:
            old_checksum = framing.WIRE_CHECKSUM
            framing.WIRE_CHECKSUM = wire_checksum
            try:
                echo_server = FleetServer(_EchoFleet(),
                                          shm=use_shm).start()
                lat: list = []
                lock = threading.Lock()
                start_evt = threading.Event()

                def client():
                    cli = FleetClient(echo_server.address, shm=use_shm)
                    try:
                        cli.call("echo", payload, timeout=120)  # warm
                        mine = []
                        start_evt.wait()
                        for _ in range(qps_reqs):
                            t0 = time.perf_counter()
                            cli.call("echo", payload, timeout=120)
                            mine.append(time.perf_counter() - t0)
                        with lock:
                            lat.extend(mine)
                    finally:
                        cli.close()

                threads = [threading.Thread(target=client)
                           for _ in range(qps_clients)]
                for t in threads:
                    t.start()
                time.sleep(0.3)  # everyone connected + warmed
                t0 = time.perf_counter()
                start_evt.set()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                echo_server.stop()
                p50, p99 = (float(v) * 1e3
                            for v in np.percentile(lat, [50, 99]))
                loadgen[label] = {
                    "qps": round(len(lat) / wall, 1),
                    "p50_ms": round(p50, 4), "p99_ms": round(p99, 4),
                    "requests": len(lat),
                    "payload_bytes": int(payload.nbytes),
                    "checksum": wire_checksum,
                }
            finally:
                framing.WIRE_CHECKSUM = old_checksum
        counters = telemetry.metrics().snapshot()["counters"]
        hists = telemetry.metrics().snapshot()["histograms"]
    finally:
        config_lib.set_config(telemetry=False)
    wire_bytes = {
        t: sum(v for k, v in counters.items()
               if k == f"wire.bytes{{transport={t}}}")
        for t in ("shm", "tcp")
    }
    hash_algos = sorted({k for k in hists
                         if k.startswith("wire.hash_seconds")})
    qps_ratio = (loadgen["shm"]["qps"]
                 / max(loadgen["tcp_seed"]["qps"], 1e-9))
    p99_ratio = (loadgen["tcp_seed"]["p99_ms"]
                 / max(loadgen["shm"]["p99_ms"], 1e-9))

    # -- phase 4: adaptive window vs both fixed extremes ------------------
    class _CostModel:
        """Flat per-batch cost: the dispatch-overhead regime where
        batching wins and the window controller has something to
        trade."""

        n_features_in_ = 8

        def predict(self, Xa):
            time.sleep(3e-04)
            return np.asarray(Xa)[:, 0]

    trace = []  # (t_offset_s, segment)
    t = 0.0
    for _ in range(idle_n):  # idle singles: latency must not pay a window
        trace.append((t, "idle"))
        t += 0.025
    t += 0.05
    for _ in range(steady_n):  # steady stream: occupancy must widen
        trace.append((t, "steady"))
        t += 4e-04
    t += 0.05
    for _ in range(3):  # bursts: both batching modes handle these
        for _ in range(burst_n):
            trace.append((t, "burst"))
            t += 1e-05
        t += 0.04

    def run_trace(window_cfg):
        reg2 = ModelRegistry()
        reg2.register("cost", _CostModel())
        lp = ServingLoop(reg2, max_batch_rows=256,
                         coalesce_window_s=window_cfg)
        lp.start()
        rows = rng.standard_normal((4, 8)).astype(np.float32)
        lp.submit("cost", rows).result(30)  # warm
        results = []
        lock = threading.Lock()
        t_start = time.perf_counter()
        pending = []
        for t_off, seg in trace:
            now = time.perf_counter() - t_start
            if t_off > now:
                time.sleep(t_off - now)
            t0 = time.perf_counter()
            fut = lp.submit("cost", rows)

            def done(f, t0=t0, seg=seg):
                dt = time.perf_counter() - t0
                with lock:
                    results.append((seg, dt))

            fut.add_done_callback(done)
            pending.append(fut)
        for fut in pending:
            fut.result(60)
        stats = lp.stats()
        lp.stop()
        by_seg: dict = {}
        for seg, dt in results:
            by_seg.setdefault(seg, []).append(dt)
        out = {"batches": int(stats["batches"]) - 1,  # minus the warm-up
               "mean_ms": round(float(np.mean(
                   [dt for _, dt in results])) * 1e3, 3)}
        for seg, vals in sorted(by_seg.items()):
            p50, p99 = (float(v) * 1e3
                        for v in np.percentile(vals, [50, 99]))
            out[f"{seg}_p50_ms"] = round(p50, 3)
            out[f"{seg}_p99_ms"] = round(p99, 3)
        return out

    config_lib.set_config(telemetry=True)
    try:
        telemetry.reset_telemetry()
        telemetry.metrics().reset()
        adapt = {"adaptive": run_trace("adaptive")}
        snap = telemetry.metrics().snapshot()
        window_gauge = snap["gauges"].get("serving.window_s")
        occupancy_hist = "serving.occupancy" in snap["histograms"]
    finally:
        config_lib.set_config(telemetry=False)
    adapt["fixed_zero"] = run_trace(0.0)
    adapt["fixed_max"] = run_trace(0.010)

    # -- phase 5: kill -9 over shm, zero drops, zero leaked segments ------
    segments_before = shm_lib.list_segments()
    fleet = ProcessFleet(n_replicas=replicas, max_batch_rows=256,
                         request_timeout_s=300.0, name="wire-kill")
    fleet.register("kmeans", km)
    kill_info: dict = {}
    try:
        fleet.start()
        links_shm = [rep.client._shm is not None for rep in fleet._procs]
        segments_during = len(shm_lib.list_segments())
        total = kill_clients * kill_reqs
        victim = fleet._procs[0]
        old_pid, old_proc = victim.pid, victim.proc
        killed = threading.Event()
        kill_lock = threading.Lock()
        done_box = [0]
        outcomes: list = []
        errors: list = []
        lock = threading.Lock()

        def kclient(cid):
            crng = np.random.RandomState(100 + cid)
            for _ in range(kill_reqs):
                off = int(crng.randint(0, n_fit - 128))
                n = int(crng.randint(1, 128))
                try:
                    out = fleet.submit(
                        "kmeans", X[off:off + n]).result(300)
                except Exception as e:  # noqa: BLE001 — gate on these
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    outcomes.append((off, n, out))
                    done_box[0] += 1
                    hit = done_box[0] >= total // 3
                if hit:
                    with kill_lock:
                        if killed.is_set():
                            continue
                        killed.set()
                    try:
                        os.kill(old_pid, signal_mod.SIGKILL)
                    except ProcessLookupError:
                        pass

        threads = [threading.Thread(target=kclient, args=(c,))
                   for c in range(kill_clients)]
        for t_ in threads:
            t_.start()
        for t_ in threads:
            t_.join()
        old_proc.wait(60)
        kill_mismatches = sum(
            0 if np.array_equal(out, km.predict(X[off:off + n])) else 1
            for off, n, out in outcomes)
        kill_info = {
            "links_negotiated_shm": all(links_shm),
            "segments_during": segments_during,
            "old_exit": old_proc.returncode,
            "resolved": len(outcomes), "total": total,
            "errors": errors[:5], "mismatches": kill_mismatches,
        }
    finally:
        fleet.stop()
    time.sleep(0.2)
    segments_after = [s for s in shm_lib.list_segments()
                      if s not in segments_before]

    # -- phase 6: fuzz both transports, both checksums --------------------
    fuzz = {"checked": 0, "caught": 0}
    blob = framing.encode_payload({"op": "submit", "id": "f"},
                                  [np.arange(64, dtype=np.float32)])
    for checksum in framing.CHECKSUMS:
        frame = framing.encode_frame(blob, magic=framing.WIRE_MAGIC,
                                     checksum=checksum)
        flips = range(len(framing.WIRE_MAGIC) + 8, len(frame), 7)
        cuts = range(0, len(frame), 13)
        for i in flips:
            mutant = bytearray(frame)
            mutant[i] ^= 0xFF
            fuzz["checked"] += 1
            try:
                framing.decode_frame(bytes(mutant),
                                     magic=framing.WIRE_MAGIC,
                                     checksum=checksum)
            except framing.FrameError:
                fuzz["caught"] += 1
        for cut in cuts:
            fuzz["checked"] += 1
            try:
                framing.decode_frame(frame[:cut],
                                     magic=framing.WIRE_MAGIC,
                                     checksum=checksum)
            except framing.FrameError:
                fuzz["caught"] += 1
        for tear in ("status", "length", "payload"):
            cli = shm_lib.ShmClient(ring_bytes=1 << 16, checksum=checksum)
            srv = shm_lib.ShmServer(cli.segment)
            try:
                cli.send({"op": "x"}, [np.zeros(64, np.float32)])
                base = srv._reader._data
                if tear == "status":
                    import struct as struct_mod
                    struct_mod.pack_into(">I", cli._shm.buf, base, 0xBAD)
                elif tear == "length":
                    import struct as struct_mod
                    struct_mod.pack_into(">I", cli._shm.buf, base + 4,
                                         0x7FFFFFFF)
                else:
                    off = base + 8 + framing.digest_length(checksum) + 5
                    cli._shm.buf[off] ^= 0xFF
                fuzz["checked"] += 1
                try:
                    srv.recv(timeout=1.0)
                except framing.FrameCorruptError:
                    fuzz["caught"] += 1
            finally:
                srv.close()
                cli.close(unlink=True)

    gates = {
        "identity_shm_equals_tcp_and_direct":
            shm_negotiated and identity_mismatches == 0,
        "decode_zero_copy_pointer_identity":
            all(zero_copy.get(c) for c in framing.CHECKSUMS),
        "shm_2x_qps_or_2x_p99":
            qps_ratio >= 2.0 or p99_ratio >= 2.0,
        "adaptive_beats_fixed_zero_on_batches":
            adapt["adaptive"]["batches"]
            <= 0.6 * adapt["fixed_zero"]["batches"],
        "adaptive_beats_fixed_max_on_latency":
            adapt["adaptive"]["idle_p50_ms"]
            <= 0.6 * adapt["fixed_max"]["idle_p50_ms"]
            and adapt["adaptive"]["mean_ms"]
            <= adapt["fixed_max"]["mean_ms"],
        "adaptive_latency_bounded":
            adapt["adaptive"]["mean_ms"]
            <= max(3.0 * adapt["fixed_zero"]["mean_ms"], 15.0),
        "kill9_was_real_and_zero_drops_over_shm":
            kill_info.get("links_negotiated_shm") is True
            and kill_info.get("old_exit") == -signal_mod.SIGKILL
            and kill_info.get("resolved") == kill_info.get("total")
            and not kill_info.get("errors")
            and kill_info.get("mismatches") == 0,
        "zero_segment_leaks":
            kill_info.get("segments_during", 0) >= replicas
            and not segments_after,
        "fuzz_all_caught": fuzz["checked"] > 0
            and fuzz["caught"] == fuzz["checked"],
        "telemetry_wire_mirrors":
            wire_bytes["shm"] > 0 and wire_bytes["tcp"] > 0
            and any("crc32c" in k for k in hash_algos)
            and window_gauge is not None and occupancy_hist,
    }
    rec = {
        "metric": "wire_drill",
        "value": round(qps_ratio, 2),
        "unit": "data-plane QPS ratio vs seed wire (TCP + sha256), "
                "same-machine echo, equal clients",
        "vs_baseline": round(qps_ratio, 2),
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "identity": {"mismatches": identity_mismatches,
                     "families": len(direct), "ragged_sizes": list(ragged),
                     "shm_negotiated": shm_negotiated},
        "zero_copy": zero_copy,
        "loadgen": loadgen,
        "qps_ratio": round(qps_ratio, 2),
        "p99_ratio": round(p99_ratio, 2),
        "wire_bytes": wire_bytes,
        "hash_algos_observed": hash_algos,
        "adaptive_window": adapt,
        "kill": kill_info,
        "segments_leaked": segments_after,
        "fuzz": fuzz,
        "note": "echo server makes wire cost dominant for the QPS "
                "gate; baseline is the pre-PR wire (framed TCP "
                "loopback + whole-frame sha256); the adaptive trace "
                "is open-loop (idle singles / "
                "steady stream / bursts) against fixed window=0 and "
                "fixed window=max on the same arrivals. Scaled down in "
                "CI via WIRE_QPS_CLIENTS/WIRE_QPS_REQS/WIRE_STEADY/"
                "WIRE_KILL_CLIENTS/WIRE_REPLICAS.",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "WIRE_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "wire drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


def bench_kdd(_rtt):
    from dask_ml_tpu.cluster import KMeans

    X, source = _load_kdd()
    import jax

    jax.block_until_ready(X)
    n = int(X.shape[0])

    def one_fit():
        km = KMeans(n_clusters=8, oversampling_factor=2, random_state=0)
        t0 = time.perf_counter()
        km.fit(X)
        return km, time.perf_counter() - t0

    _, t_cold = one_fit()  # includes one-time XLA compiles at this shape
    km, t1 = one_fit()
    km2, t2 = one_fit()  # min of two: the host link's throughput wobbles
    if t2 < t1:
        km, t = km2, t2
    else:
        t = t1

    # bounded-Lloyd pruning observability at the flagship shape: one
    # algorithm='bounded' fit (bit-identical results, pinned elsewhere)
    # recording the per-iteration pruned fraction next to the PR-2
    # roofline keys (full grid + exactness gates: bench.py --bounds)
    def bounded_fit():
        kb = KMeans(n_clusters=8, oversampling_factor=2, random_state=0,
                    algorithm="bounded")
        t0 = time.perf_counter()
        kb.fit(X)
        return kb, time.perf_counter() - t0

    bounded_fit()  # warm (compile)
    km_b, t_bounded = bounded_fit()

    bl = _measured_baselines().get("kdd")
    if bl and "seconds" in bl:
        vs = round(float(bl["seconds"]) / t, 1)
        bl_note = (f"sklearn KMeans full KDD fit measured DIRECTLY: "
                   f"{bl['seconds']:.1f}s, n_iter={bl.get('n_iter')}, "
                   f"inertia={bl.get('inertia'):.4g} ({bl['how']}; "
                   "baselines.py; reference harness logs wall-time only, "
                   "benchmarks/k_means_kdd.py:108-125)")
    else:
        vs = None
        bl_note = ("reference harness logs wall-time only "
                   "(benchmarks/k_means_kdd.py:108-125); no committed "
                   "number to compare against")

    # k-means|| init roofline: the four sub-phases as separate programs
    # (models/kmeans.py measure_init_phases) — attributes the ~60% of the
    # warm fit the fused init program spends (VERDICT r5 "What's weak" #2),
    # now with logical bytes-moved and effective HBM GB/s next to each wall
    # time so the BENCH trajectory tracks the roofline across PRs (the
    # stable keys: init_phase_seconds / init_phase_bytes_moved /
    # init_phase_effective_gbps / init_fused_dispatch)
    from dask_ml_tpu.models.kmeans import measure_init_phases
    from dask_ml_tpu.parallel.sharding import prepare_data
    from dask_ml_tpu.utils.validation import check_random_state

    data = prepare_data(X)
    init_phases = measure_init_phases(
        data.X, data.weights, 8, check_random_state(0),
        oversampling_factor=2, mesh=data.mesh)

    phases = getattr(km, "fit_phase_seconds_", {})
    emit({
        "metric": "kmeans_kdd_fit",
        "value": round(t, 2),
        "unit": "seconds",
        "vs_baseline": vs,
        "rows": n, "cols": int(X.shape[1]),
        "n_clusters": 8, "oversampling_factor": 2,
        "cold_seconds_incl_compile": round(t_cold, 2),
        "init_seconds": round(float(phases.get("init", 0.0)), 2),
        "init_phase_seconds": {k_: round(float(v), 3)
                               for k_, v in init_phases["seconds"].items()},
        "init_phase_bytes_moved": {
            k_: int(v) for k_, v in init_phases["bytes_moved"].items()},
        "init_phase_effective_gbps": {
            k_: round(float(v), 2)
            for k_, v in init_phases["effective_gbps"].items()},
        "init_fused_dispatch": init_phases["fused"],
        # per-mesh-axis collective accounting — present only under a
        # hierarchical (pod, chip) mesh (docs/scale-out.md); stable keys
        # next to the per-device streaming roofline above
        **({"init_phase_bytes_by_axis":
                init_phases["bytes_moved_by_axis"],
            "init_phase_effective_gbps_by_axis": {
                p: {ax: round(float(v), 4) for ax, v in axes.items()}
                for p, axes in
                init_phases["effective_gbps_by_axis"].items()}}
           if "bytes_moved_by_axis" in init_phases else {}),
        "init_round_skip_ratio": round(
            float(init_phases["round_skip_ratio"]), 4),
        "lloyd_seconds": round(float(phases.get("lloyd", 0.0)), 2),
        # bounded-Lloyd pruning next to the roofline keys (ISSUE 6): the
        # algorithm='bounded' fit at the same flagship shape
        "bounded_fit_seconds": round(t_bounded, 2),
        "lloyd_pruned_fraction": [
            round(f, 4)
            for f in km_b.lloyd_pruning_["pruned_fraction_per_iter"]],
        "lloyd_rows_skipped": km_b.lloyd_pruning_["rows_skipped"],
        "lloyd_distances_avoided": km_b.lloyd_pruning_["distances_avoided"],
        "n_iter": int(km.n_iter_),
        "inertia": float(km.inertia_),
        "samples_per_sec_per_chip": round(n / t / jax.device_count(), 1),
        "data_source": source,
        "baseline_note": bl_note,
    })


# ---------------------------------------------------------------------------
# SpectralClustering at scale (VERDICT r4 #6: the Nyström path is built for
# 1e6+-row inputs; this pins its wall-time and the no-host-copy staging)
# ---------------------------------------------------------------------------


def bench_spectral(rtt):
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu import datasets
    from dask_ml_tpu.cluster import SpectralClustering
    from dask_ml_tpu.parallel import mesh as mesh_lib

    n, d, l, k = 1_000_000, 50, 200, 8
    mesh = mesh_lib.default_mesh()
    X, _ = datasets.make_blobs(n_samples=n, n_features=d, centers=k,
                               cluster_std=1.0, random_state=0, mesh=mesh)
    X = (X - X.mean(0)) / jnp.maximum(X.std(0), 1e-6)
    jax.block_until_ready(X)

    def one_fit():
        sc = SpectralClustering(n_clusters=k, n_components=l, gamma=None,
                                random_state=0,
                                kmeans_params={"init": "random"})
        t0 = time.perf_counter()
        sc.fit(X)  # device input: staged once, no host round-trip of X
        return time.perf_counter() - t0

    t_cold = one_fit()
    t = one_fit()

    # sklearn baseline: the SAME approximation (Nystroem landmarks +
    # KMeans on the feature map) — exact sklearn SpectralClustering is
    # O(n²) memory (8 TB affinity at 1e6 rows) and structurally infeasible;
    # the approximate pipeline is the honest CPU comparison (VERDICT r5
    # "What's missing" #2: this metric was the last vs_baseline: null)
    sk_scaled, bl_note = _baseline_seconds("spectral", n)
    if sk_scaled is None:
        from sklearn.cluster import KMeans as SKKMeans
        from sklearn.kernel_approximation import Nystroem

        ns = 50_000
        Xh = np.asarray(X[:ns])
        t0 = time.perf_counter()
        F = Nystroem(n_components=l, random_state=0).fit_transform(Xh)
        SKKMeans(n_clusters=k, n_init=1, random_state=0).fit(F)
        sk_scaled = (time.perf_counter() - t0) * n / ns
        bl_note = (f"sklearn Nystroem({l}) + KMeans({k}) on {ns} rows "
                   f"x{n // ns} (linear in rows)")

    emit({
        "metric": "spectral_nystrom_1e6_fit",
        "value": round(t, 2),
        "unit": "seconds",
        "vs_baseline": round(sk_scaled / t, 1),
        "rows": n, "cols": d, "n_components": l, "n_clusters": k,
        "cold_seconds_incl_compile": round(t_cold, 2),
        "rows_per_sec_per_chip": round(n / t / jax.device_count(), 1),
        "baseline_note": bl_note + "; exact sklearn SpectralClustering is "
                         "O(n^2) memory (8 TB affinity at 1e6 rows), so "
                         "the baseline is the same Nystroem approximation "
                         "(the reference publishes plots only, "
                         "docs/source/clustering.rst:50-53)",
    })


# ---------------------------------------------------------------------------
# sparse-tier drill (ISSUE 13): the 1e7 x 1e5, 0.1%-dense LogisticRegression
# + grid-search problem dense staging cannot represent, plus the wire,
# bit-identity, compile-once, and dense-unchanged gates — committed as
# SPARSE_r01.json and run scaled-down by the CI `sparse` job (nonzero exit
# on any gate)
# ---------------------------------------------------------------------------


def bench_sparse(_rtt):
    """Sparse execution tier (docs/sparse.md). Five gate families:

    1. the flagship problem — LogisticRegression fit at (SPARSE_N x
       SPARSE_D, SPARSE_DENSITY dense): streamed proximal-SGD over
       generator blocks (the dataset never materializes AT ALL) and an
       in-memory L-BFGS fit of the staged container (10 GB where dense
       f32 would be 4 TB) — both must beat chance on held-out rows;
    2. the sparse wire: indices+values blocks through HostBlockSource
       must beat the DENSE BF16 wire by >= 50x at the bench density
       (logical vs wire bytes, measured on the real stream);
    3. sparse-vs-dense coef BIT-identity at a small dense-feasible size
       (one Newton step, power-of-two n, integer data — the regime where
       every quantity is exactly representable; see docs/sparse.md);
    4. compile-once across mixed sparse batch sizes within one
       (rows, nnz) bucket — fits and a repeat grid search add ZERO
       compiles;
    5. dense-path bit-unchanged: the GLM contraction seams produce
       byte-identical results for dense inputs.
    """
    import jax.numpy as jnp
    import scipy.sparse as scipy_sparse

    from dask_ml_tpu.datasets import make_sparse_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.ops import sparse as sparse_ops
    from dask_ml_tpu.parallel import shapes
    from dask_ml_tpu.parallel.stream import HostBlockSource, prefetched_scan

    gates = {}
    N = int(os.environ.get("SPARSE_N", 10_000_000))
    D = int(os.environ.get("SPARSE_D", 100_000))
    DENSITY = float(os.environ.get("SPARSE_DENSITY", 0.001))
    SEARCH_N = int(os.environ.get("SPARSE_SEARCH_N", 500_000))
    MAX_ITER = int(os.environ.get("SPARSE_MAX_ITER", 3))
    B = int(os.environ.get("SPARSE_BLOCKS", 64))
    k = max(1, round(DENSITY * D))

    # the impossibility statement is about the FLAGSHIP problem shape,
    # independent of any CI scaling of this run
    try:
        host_ram = (os.sysconf("SC_PHYS_PAGES")
                    * os.sysconf("SC_PAGE_SIZE"))
    except (ValueError, OSError, AttributeError):  # non-POSIX fallback
        host_ram = 0
    mem_kb = host_ram // 1024
    flagship_dense_gb = 1e7 * 1e5 * 4 / 1e9
    gates["dense_impossible_on_host"] = (
        flagship_dense_gb * 1e9 > host_ram)

    blocks = make_sparse_classification(N, D, DENSITY, random_state=42,
                                        n_blocks=B)

    # -- 1a. streamed SGD: the dataset never materializes ------------------
    src = HostBlockSource(loader=blocks, n_blocks=B, storage_dtype=None)
    _, apply_one = glm_core.get_stream_step(family="logistic",
                                            regularizer="l2", lamduh=1e-4,
                                            eta0=0.5, fit_intercept=True)

    def sgd_step(carry, b, blk):
        X_b, y_b, w_b = blk
        return apply_one(carry, X_b, y_b, w_b), None

    state0 = (jnp.zeros((D + 1,), jnp.float32), jnp.asarray(0.0,
                                                            jnp.float32))
    t0 = time.perf_counter()
    state, _ = prefetched_scan(sgd_step, state0, src)
    fetch(state[0])
    sgd_s = time.perf_counter() - t0
    beta_sgd = np.asarray(state[0])

    # wire accounting: MEASURED on the stream that just trained — the
    # gate divides what dense bf16 would have moved by what the source
    # actually streamed (X + labels + weights; the analytic X-only figure
    # rows*k*8 is emitted alongside for the docs, but gating on it would
    # pass regardless of what the implementation really moved)
    wire = src.bytes_streamed
    logical = src.logical_bytes_streamed
    rows_streamed = N
    dense_bf16_wire = rows_streamed * D * 2  # what dense bf16 would move
    wire_win_vs_bf16 = dense_bf16_wire / max(wire, 1)
    gates["wire_ge_50x_vs_dense_bf16"] = wire_win_vs_bf16 >= 50.0
    gates["logical_counts_dense_equivalent"] = (
        logical >= rows_streamed * D * 4)

    # held-out-ish accuracy of the streamed model (block 0, first rows;
    # SGD saw each row once — the gate is beats-chance, not convergence)
    Xe, ye, we = blocks(0)
    m = min(65_536, Xe.values.shape[0])
    Ae = sparse_ops.SparseRows(jnp.asarray(Xe.values[:m]),
                               jnp.asarray(Xe.cols[:m]), D)
    eta = np.asarray(sparse_ops.matvec(
        sparse_ops.add_intercept_ell(Ae),
        jnp.asarray(beta_sgd), kernel="xla"))
    acc_sgd = float(((eta > 0) == (ye[:m] > 0.5)).mean())
    gates["streamed_sgd_beats_chance"] = acc_sgd > 0.55

    emit({
        "metric": "sparse_streamed_sgd", "value": round(acc_sgd, 4),
        "unit": "accuracy@1epoch",
        "vs_baseline": f"chance 0.5; {N}x{D} @ {DENSITY} never resident",
        "seconds": round(sgd_s, 2),
        "wire_bytes": int(wire), "logical_bytes": int(logical),
        "logical_over_wire": round(logical / max(wire, 1), 1),
        "wire_win_vs_dense_bf16": round(wire_win_vs_bf16, 1),
        "effective_wire_gbps": round(wire / sgd_s / 1e9, 3),
    })

    # -- 1b. in-memory L-BFGS fit of the staged container ------------------
    vals = np.empty((N, blocks.k), np.float32)
    cols = np.empty((N, blocks.k), np.int32)
    y_all = np.empty(N, np.float32)
    for b in range(B):
        Xb, yb, _ = blocks(b)
        s = b * blocks.block_rows
        e = s + yb.shape[0]
        vals[s:e] = Xb.values
        cols[s:e] = Xb.cols
        y_all[s:e] = yb
    X_host = sparse_ops.SparseRows(vals, cols, D)
    est = LogisticRegression(solver="lbfgs", max_iter=MAX_ITER)
    t0 = time.perf_counter()
    est.fit(X_host, y_all)
    fit_s = time.perf_counter() - t0
    m2 = min(262_144, N)
    t0 = time.perf_counter()
    acc_fit = float(est.score(X_host[:m2], y_all[:m2]))
    score_s = time.perf_counter() - t0
    gates["big_fit_beats_chance"] = acc_fit > 0.55
    emit({
        "metric": "sparse_big_fit", "value": round(acc_fit, 4),
        "unit": f"accuracy (train sample, {MAX_ITER} lbfgs iters)",
        "vs_baseline": (
            f"dense f32 staging of the flagship shape = "
            f"{flagship_dense_gb:.0f} GB vs host RAM "
            f"{mem_kb / 1e6:.0f} GB: impossible; sparse container = "
            f"{(vals.nbytes + cols.nbytes) / 1e9:.1f} GB"),
        "fit_seconds": round(fit_s, 2), "score_seconds": round(score_s, 2),
        "n": N, "d": D, "density": DENSITY, "nnz_per_row": blocks.k,
        "n_iter": int(est.n_iter_),
    })

    # -- 3. bit-identity pin at a small dense-feasible size ----------------
    rngp = np.random.RandomState(5)
    np_, dp = 256, 32
    dpin = (rngp.randint(-3, 4, (np_, dp))
            * (rngp.uniform(size=(np_, dp)) < 0.3)).astype(np.float32)
    ypin = (dpin @ rngp.standard_normal(dp).astype(np.float32)
            > 0).astype(np.int32)
    ed = LogisticRegression(solver="newton", max_iter=1).fit(dpin, ypin)
    es = LogisticRegression(solver="newton", max_iter=1).fit(
        scipy_sparse.csr_matrix(dpin), ypin)
    gates["coef_bit_identity_small"] = (
        np.array_equal(np.asarray(ed.coef_), np.asarray(es.coef_))
        and float(ed.intercept_) == float(es.intercept_))

    # -- 5. dense path bit-unchanged ---------------------------------------
    from dask_ml_tpu.models.glm import (_data_matvec, _data_pullback,
                                        _weighted_gram)
    from dask_ml_tpu.parallel import precision as px

    Xdn = jnp.asarray(rngp.standard_normal((256, 24)).astype(np.float32))
    vdn = jnp.asarray(rngp.standard_normal(24).astype(np.float32))
    rdn = jnp.asarray(rngp.standard_normal(256).astype(np.float32))
    hdn = jnp.asarray(rngp.uniform(size=256).astype(np.float32))
    acc_dt = px.state_dtype(Xdn.dtype)
    gates["dense_seams_bit_unchanged"] = (
        np.array_equal(np.asarray(_data_matvec(Xdn, vdn)),
                       np.asarray(px.pmatmul(Xdn, vdn, accum=acc_dt)))
        and np.array_equal(
            np.asarray(_data_pullback(Xdn, rdn)),
            np.asarray(px.pdot(Xdn, rdn, (((0,), (0,)), ((), ())),
                               accum=acc_dt)))
        and np.array_equal(
            np.asarray(_weighted_gram(Xdn, hdn)),
            np.asarray(px.pdot(Xdn, (hdn[:, None] * Xdn).astype(Xdn.dtype),
                               (((0,), (0,)), ((), ())), accum=acc_dt))))

    # -- 4. grid search over sparse cells + compile-once gates -------------
    ns = min(SEARCH_N, N)
    coo_rows = np.repeat(np.arange(ns, dtype=np.int64), blocks.k)
    csr = scipy_sparse.coo_matrix(
        (vals[:ns].ravel(), (coo_rows, cols[:ns].ravel().astype(np.int64))),
        shape=(ns, D)).tocsr()
    del coo_rows
    grid = {"C": [0.1, 1.0, 10.0]}
    t0 = time.perf_counter()
    gs = GridSearchCV(LogisticRegression(solver="lbfgs",
                                         max_iter=MAX_ITER),
                      grid, cv=2, refit=False, iid=False,
                      return_train_score=False)
    gs.fit(csr, y_all[:ns])
    search_s = time.perf_counter() - t0
    # a second search whose fold sizes land in the same (rows, nnz)
    # buckets — the PR-4 batched-cells discipline extended to sparse —
    # must add ZERO compiles
    shift = max(8, ns // 512)
    with shapes.track_compiles() as tc:
        gs2 = GridSearchCV(LogisticRegression(solver="lbfgs",
                                              max_iter=MAX_ITER),
                           grid, cv=2, refit=False, iid=False,
                           return_train_score=False)
        gs2.fit(csr[:ns - shift], y_all[:ns - shift])
    gates["grid_repeat_zero_compiles"] = tc["n_compiles"] == 0
    # mixed single fits within one bucket: zero compiles after ONE warm
    # fit of the single-fit program (the searches above warmed only the
    # batched-group program — a different executable)
    LogisticRegression(solver="lbfgs", max_iter=MAX_ITER).fit(
        csr[:ns - shift], y_all[:ns - shift])
    with shapes.track_compiles() as tf:
        for n3 in (ns - 2 * shift, ns - 3 * shift):
            LogisticRegression(solver="lbfgs", max_iter=MAX_ITER).fit(
                csr[:n3], y_all[:n3])
    gates["mixed_sizes_zero_compiles"] = tf["n_compiles"] == 0
    emit({
        "metric": "sparse_grid_search",
        "value": round(float(gs.best_score_), 4), "unit": "cv accuracy",
        "vs_baseline": f"{len(grid['C'])} C values x 2 folds at "
                       f"{ns}x{D} sparse cells",
        "seconds": round(search_s, 2),
        "best_params": {kk: float(vv) for kk, vv in
                        gs.best_params_.items()},
        "repeat_search_compiles": tc["n_compiles"],
        "mixed_fit_compiles": tf["n_compiles"],
    })

    rec = {
        "metric": "sparse_gates", "value": float(all(gates.values())),
        "unit": "all_gates_pass",
        "vs_baseline": "SPARSE_r01.json commits this record",
        "gates": {kk: bool(vv) for kk, vv in gates.items()},
        "config": {"n": N, "d": D, "density": DENSITY, "blocks": B,
                   "search_n": ns, "max_iter": MAX_ITER,
                   "nnz_per_row": blocks.k,
                   "host_ram_gb": round(mem_kb / 1e6, 1)},
        "wire": {"bytes": int(wire), "logical_bytes": int(logical),
                 "win_vs_dense_bf16": round(wire_win_vs_bf16, 1)},
        "accuracy": {"streamed_sgd": round(acc_sgd, 4),
                     "lbfgs_fit": round(acc_fit, 4)},
        "seconds": {"streamed_epoch": round(sgd_s, 2),
                    "lbfgs_fit": round(fit_s, 2),
                    "grid_search": round(search_s, 2)},
    }
    emit(rec)
    if os.environ.get("SPARSE_COMMIT", "0") == "1":
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "SPARSE_r01.json")
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    if not all(gates.values()):
        raise SystemExit("sparse drill: failed gates: "
                         + ", ".join(kk for kk, vv in gates.items()
                                     if not vv))


# ---------------------------------------------------------------------------
# sketched-assignment drill (ISSUE 17): learned fast-transform centers +
# Nyström kernel k-means, with the gated-quality contract — speedup AND
# inertia-ratio/ARI-vs-exact gates, committed as SKETCH_r01.json
# ---------------------------------------------------------------------------


def bench_sketch(_rtt):
    """Sketched k-means drill (docs/kernels.md, "Sketched assignment"):

    1. **Assignment-phase speedup** at n x d x k the exact fused kernel is
       strong at: exact ``fused_argmin_min`` per iteration vs the sketched
       path, both measured as the jitted programs production runs (the
       Lloyd loop and ``predict_labels_sketched`` are jitted; eager
       dispatch overhead is not the thing being bought). The sketched
       side is staged the way the estimator stages it: the (d, p) support
       slice is materialized ONCE at fit time, per-batch staging is one
       affine matmul ``X @ Wp - off`` (no centered temporary, no row-norm
       pass — labels are invariant to the per-row |x - mu|^2 constant),
       amortized over 10 Lloyd iterations. Gate: amortized speedup >= 3x.
    2. **Quality vs exact** on the KDD-character synthetic (same recipe as
       the bounded-Lloyd drill): a full ``algorithm='sketched'`` fit vs
       the exact fit from the same seed. Gates: inertia ratio <= 1.05 and
       ARI >= 0.9 — approximation is allowed to move labels, but only
       within the committed quality envelope.
    3. **Kernel k-means beats dense Lloyd where convexity is the wall**:
       the XOR problem (four gaussian blobs at (+-2, +-2), class =
       sign(x1*x2)) — no convex partition separates the classes, so dense
       KMeans must FAIL (ARI < 0.5 control) while a degree-2 polynomial
       kernel exposes the x1*x2 monomial and Nystrom KernelKMeans must
       recover the partition (ARI >= 0.9), with predict(train) ==
       labels_ exactly.
    4. **Compile-once**: a repeat sketched predict at a warmed shape adds
       ZERO compiles.
    5. **Serving**: a registered sketched model served through the batch
       loop returns labels bit-equal to the direct predict path.

    With ``DECISIONS_WRITE=1`` the measured exact-vs-sketched verdict is
    persisted as decision rule ``kmeans.sketched.assign`` (the hand
    inequality in ``models.kmeans.sketched_assign_wins`` stays as the
    cold-start fallback). All sizes env-scalable: SKETCH_N/SKETCH_D
    (speedup grid), SKETCH_QN/SKETCH_QD (quality problem), SKETCH_KN
    (kernel XOR problem).
    """
    import jax
    import jax.numpy as jnp
    from sklearn.metrics import adjusted_rand_score

    from dask_ml_tpu.cluster import KernelKMeans, KMeans
    from dask_ml_tpu.models import kmeans as core
    from dask_ml_tpu.ops import fast_transform as ftm
    from dask_ml_tpu.ops.fused_distance import (
        fused_argmin_min,
        fused_argmin_min_sketched,
    )
    from dask_ml_tpu.parallel.serving import ModelRegistry, ServingLoop
    from dask_ml_tpu.parallel.shapes import track_compiles

    gates = {}

    # -- 1. assignment-phase speedup ---------------------------------------
    n = int(os.environ.get("SKETCH_N", 262144))
    d = int(os.environ.get("SKETCH_D", 512))
    k, p, n_sweeps = 23, 32, 16
    X, mesh = _bounds_synth(n, d, key_seed=17)
    c0 = jnp.take(X, jnp.arange(k) * (n // k), axis=0)
    mu = jnp.mean(X, axis=0)
    ft, support, vals, _ = ftm.palm4msa_fit(
        c0 - mu[None, :], p, n_iter=n_sweeps)

    # Fit-time staging, exactly as k_means._finish_sketched sets it up:
    # the (d, p) support slice is materialized ONCE; the per-batch work
    # is the affine map below, and the label-only path skips the
    # |x - mu|^2 row pass entirely (argmin is invariant to a per-row
    # constant — models.kmeans._predict_sketched_fast).
    Wp = jax.jit(ftm.support_matrix)(ft, support)
    off = mu @ Wp
    zero = jnp.zeros((n,), jnp.float32)

    exact_j = jax.jit(lambda Xs: fused_argmin_min(Xs, c0, mesh=mesh)[0])
    stage_j = jax.jit(
        lambda Xs: Xs @ Wp.astype(Xs.dtype) - off[None, :].astype(Xs.dtype))
    sketch_j = jax.jit(
        lambda Zs: fused_argmin_min_sketched(Zs, vals, x2=zero)[0])
    Zp = stage_j(X)
    t_exact = measure(lambda: exact_j(X), reps=3)
    t_stage = measure(lambda: stage_j(X), reps=3)
    t_sketch = measure(lambda: sketch_j(Zp), reps=3)
    speedup_iter = t_exact / max(t_sketch, 1e-9)
    amort_iters = 10
    speedup_amort = t_exact / max(t_sketch + t_stage / amort_iters, 1e-9)
    gates["assign_speedup_amortized_ge_3x"] = bool(speedup_amort >= 3.0)

    # -- 2. quality envelope vs the exact fit ------------------------------
    qn = int(os.environ.get("SKETCH_QN", 65536))
    qd = int(os.environ.get("SKETCH_QD", 41))
    Xq = np.asarray(_bounds_synth(qn, qd)[0])
    t0 = time.perf_counter()
    exact = KMeans(n_clusters=23, random_state=11, max_iter=100).fit(Xq)
    t_exact_fit = time.perf_counter() - t0
    t0 = time.perf_counter()
    sk = KMeans(n_clusters=23, random_state=11, max_iter=100,
                algorithm="sketched", sketch_cols=36,
                sketch_iters=16).fit(Xq)
    t_sketch_fit = time.perf_counter() - t0
    ratio = float(sk.inertia_) / max(float(exact.inertia_), 1e-12)
    ari = float(adjusted_rand_score(exact.labels_, sk.labels_))
    gates["inertia_ratio_le_1.05"] = bool(ratio <= 1.05)
    gates["ari_vs_exact_ge_0.9"] = bool(ari >= 0.9)

    # -- 3. kernel k-means where dense Lloyd structurally fails ------------
    # XOR: four gaussian blobs at (+-2, +-2), class = sign(x1*x2). No
    # convex partition separates the classes, so dense Lloyd sits near
    # ARI 0; the degree-2 polynomial kernel's feature map contains the
    # x1*x2 monomial, which separates them linearly.
    kn = int(os.environ.get("SKETCH_KN", 4096))
    rng = np.random.RandomState(0)
    signs = rng.randint(0, 2, (kn, 2)) * 2 - 1
    Xr = (signs * 2.0 + rng.randn(kn, 2) * 0.6).astype(np.float32)
    y_xor = (signs[:, 0] * signs[:, 1] > 0).astype(np.int32)
    ari_dense = float(adjusted_rand_score(
        y_xor, KMeans(n_clusters=2, random_state=3).fit(Xr).labels_))
    kk = KernelKMeans(n_clusters=2, n_components=min(128, kn // 4),
                      affinity="polynomial", degree=2, coef0=1.0,
                      gamma=0.5, random_state=5).fit(Xr)
    ari_kernel = float(adjusted_rand_score(y_xor, kk.labels_))
    gates["dense_lloyd_fails_xor"] = bool(ari_dense < 0.5)
    gates["kernel_kmeans_xor_ari_ge_0.9"] = bool(ari_kernel >= 0.9)
    gates["kernel_predict_matches_labels"] = bool(
        np.array_equal(kk.predict(Xr), kk.labels_))

    # -- 4. compile-once + 5. serving bit-identity -------------------------
    probe = Xq[:2048]
    lab_direct = sk.predict(probe)  # warms the predict shape bucket
    with track_compiles() as tc:
        lab_direct = sk.predict(probe)
    gates["zero_steady_state_compiles"] = int(tc["n_compiles"]) == 0
    reg = ModelRegistry()
    reg.register("sketched", sk)
    with ServingLoop(reg, max_batch_rows=2048) as lp:
        lp.submit("sketched", probe).result(600)  # warm serving buckets
        with track_compiles() as tcs:
            served = lp.submit("sketched", probe).result(600)
    gates["serving_bit_equal"] = bool(np.array_equal(served, lab_direct))
    gates["serving_zero_compiles"] = int(tcs["n_compiles"]) == 0

    # -- measured autotuner verdict (DECISIONS_WRITE=1 only) ---------------
    decisions_info = None
    if os.environ.get("DECISIONS_WRITE"):
        from dask_ml_tpu.parallel import decisions

        decisions.record(
            "kmeans.sketched.assign",
            {"n": [n // 2, n * 2], "k": [k // 2, k * 2],
             "d": [d // 2, d * 2], "p": [p // 2, p * 2]},
            bool(speedup_amort > 1.0),
            measured={"exact_s": round(t_exact, 6),
                      "sketch_s": round(t_sketch, 6),
                      "stage_s": round(t_stage, 6),
                      "amortized_speedup": round(speedup_amort, 3)},
            backend=jax.default_backend())
        path = decisions.save()
        decisions_info = {"path": path,
                          "n_entries": len(decisions.entries())}

    rec = {
        "metric": "sketched_kmeans",
        "value": round(speedup_amort, 3),
        "unit": "assignment-phase speedup vs exact fused Lloyd "
                f"(staging amortized over {amort_iters} iters)",
        "vs_baseline": None,
        "backend": jax.default_backend(),
        "all_gates_pass": all(gates.values()),
        "gates": gates,
        "speedup": {"rows": n, "cols": d, "n_clusters": k, "p": p,
                    "exact_assign_s": round(t_exact, 4),
                    "sketch_assign_s": round(t_sketch, 4),
                    "stage_s": round(t_stage, 4),
                    "per_iter_speedup": round(speedup_iter, 3),
                    "amortized_speedup": round(speedup_amort, 3)},
        "quality": {"rows": qn, "cols": qd, "n_clusters": 23,
                    "sketch_cols": 36, "sketch_iters": 16,
                    "inertia_ratio_vs_exact": round(ratio, 6),
                    "ari_vs_exact": round(ari, 4),
                    "exact_fit_s": round(t_exact_fit, 3),
                    "sketched_fit_s": round(t_sketch_fit, 3)},
        "kernel_kmeans": {"rows": kn, "problem": "xor",
                          "affinity": "polynomial(degree=2)",
                          "landmarks": int(min(128, kn // 4)),
                          "ari_dense_control": round(ari_dense, 4),
                          "ari_kernel": round(ari_kernel, 4)},
        "decisions": decisions_info,
        "note": "quality gates are the contract change this drill "
                "commits: sketched assignment is NOT bit-identical to "
                "exact Lloyd — it is allowed to trade labels for speed "
                "only inside the inertia-ratio/ARI envelope above. "
                "Off-TPU the speedup measures the XLA lowering of both "
                "paths on the 8-device host mesh; the structured-matmul "
                "epilogue's Pallas lowering is pinned by interpret-mode "
                "parity tests (tests/test_fast_transform.py).",
    }
    emit(rec)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "SKETCH_r01.json")
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    if not all(gates.values()):
        raise SystemExit(
            "sketched drill: failed gates: "
            + ", ".join(g for g, v in gates.items() if not v))


def main():
    _enable_compilation_cache()
    rtt = measure_rtt()
    bench_kmeans(rtt)
    bench_pca(rtt)
    bench_pca_blueprint(rtt)
    bench_pca_blueprint_host(rtt)
    bench_admm(rtt)
    bench_admm_blueprint(rtt)
    bench_admm_blueprint_host(rtt)
    bench_incremental(rtt)
    bench_gridsearch(rtt)
    bench_spectral(rtt)
    bench_fused(rtt)
    bench_kdd(rtt)
    emit_summary()


def _grid_child():
    """Fresh-process sweep for the second-process-cold measurement: same
    data, grid, and pipeline as bench_gridsearch; prints seconds last."""
    import numpy as np
    from sklearn.pipeline import Pipeline

    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.preprocessing import StandardScaler

    _enable_compilation_cache()
    n, d, cv = GRID["n"], GRID["d"], GRID["cv"]
    rng = np.random.RandomState(0)
    X = (rng.randn(n, d) @ np.diag(np.linspace(2, 0.5, d))).astype(np.float32)
    grid = {
        "pca__n_components": [5, 10, 15, 20, 25],
        "km__n_clusters": list(range(2, 12)),
        "km__tol": list(np.logspace(-6, -2, 10)),
    }
    pipe = Pipeline([
        ("scale", StandardScaler()),
        ("pca", PCA(random_state=0)),
        ("km", KMeans(init="random", max_iter=10, random_state=0)),
    ])
    t0 = time.perf_counter()
    GridSearchCV(pipe, grid, cv=cv, refit=False, iid=False,
                 return_train_score=False, n_jobs=8).fit(X)
    print(time.perf_counter() - t0)


if __name__ == "__main__":
    import sys

    if "--kdd" in sys.argv:
        _enable_compilation_cache()
        bench_kdd(measure_rtt())
        emit_summary()
    elif "--host-stream" in sys.argv:
        # just the two host-streamed >HBM configs (ISSUE 1)
        _enable_compilation_cache()
        rtt = measure_rtt()
        bench_pca_blueprint_host(rtt)
        bench_admm_blueprint_host(rtt)
        emit_summary()
    elif "--spectral" in sys.argv:
        _enable_compilation_cache()
        bench_spectral(measure_rtt())
        emit_summary()
    elif "--fused" in sys.argv:
        # fused-vs-unfused dispatch grid only (ISSUE 2); CI's kernels job
        # runs this to print the deltas in the workflow log
        _enable_compilation_cache()
        bench_fused(measure_rtt())
        emit_summary()
    elif "--elastic-worker" in sys.argv:
        _elastic_worker()
    elif "--asha-worker" in sys.argv:
        _asha_worker()
    elif "--asha" in sys.argv:
        # asynchronous Hyperband/ASHA drill (ISSUE 19); CI's search job
        # runs this scaled via ASHA_N — grid-optimum-at-1/5-budget,
        # compile, resume, and kill-one-host gates, nonzero exit on any
        # failure (committed as SEARCH_r01.json)
        _enable_compilation_cache()
        bench_asha(measure_rtt())
        emit_summary()
    elif "--faults" in sys.argv:
        # fault-recovery drill (ISSUE 3); CI's faults job runs this to
        # print the clean-vs-injected recovery-overhead deltas. With
        # --elastic it also runs the 2-process kill-one-host drill
        # (ISSUE 8) — nonzero exit on trajectory divergence
        _enable_compilation_cache()
        rtt = measure_rtt()
        bench_faults(rtt)
        if "--elastic" in sys.argv:
            bench_elastic(rtt)
        emit_summary()
    elif "--bounds" in sys.argv:
        # bounded-Lloyd drill (ISSUE 6); CI's kernels job runs this:
        # bit-identical-vs-oracle gates + measured iteration speedup +
        # pruned-fraction trajectory, nonzero exit on any gate failure
        # (committed as BOUNDS_r01.json)
        _enable_compilation_cache()
        bench_bounds(measure_rtt())
        emit_summary()
    elif "--precision" in sys.argv:
        # f32-vs-bf16 precision grid (ISSUE 5); CI's precision job runs
        # this: wire-byte reduction + accuracy gates, nonzero exit on any
        # gate failure (committed as PRECISION_r01.json)
        _enable_compilation_cache()
        bench_precision(measure_rtt())
        emit_summary()
    elif "--fleet-proc" in sys.argv:
        # process-isolation kill drill (ISSUE 15); CI's chaos job runs
        # this scaled to 2 replica processes: kill -9 of a live replica
        # OS process under traffic, replay/respawn/zero-drop gates, the
        # hedging A/B under a real straggler, and the pickle-free wire
        # pin — nonzero exit on any gate (committed as FLEET_r02.json)
        _enable_compilation_cache()
        bench_fleet_proc(measure_rtt())
        emit_summary()
    elif "--wire" in sys.argv:
        # zero-copy wire drill (ISSUE 20); CI's chaos job runs this
        # scaled down: shm-vs-TCP identity + zero-copy pointer pin +
        # QPS/p99 gate + adaptive-window A/B/C + kill -9 over shm +
        # both-checksum fuzz — nonzero exit on any gate (committed as
        # WIRE_r01.json)
        _enable_compilation_cache()
        bench_wire(measure_rtt())
        emit_summary()
    elif "--fleet-machines" in sys.argv:
        # cross-machine fleet drill (ISSUE 18); CI's chaos job runs this
        # scaled down: 2 isolated "machines" on loopback, content-
        # addressed snapshot distribution, autoscaler burst/quiet loop,
        # and machine loss under traffic with replay + respawn-elsewhere
        # — nonzero exit on any gate (committed as FLEET_r03.json)
        _enable_compilation_cache()
        bench_fleet_machines(measure_rtt())
        emit_summary()
    elif "--serving" in sys.argv:
        # online-serving drill (ISSUE 9); CI's serving job runs this
        # scaled down: identity + zero-recompile + QPS-speedup + p99
        # gates, nonzero exit on any gate failure (committed as
        # SERVING_r01.json). With --fleet it instead runs the serving-
        # FLEET kill drill (ISSUE 14): replica sharding + SLO routing +
        # mid-run hot-swap + replica kill + exact-shed burst + drain,
        # committed as FLEET_r01.json
        _enable_compilation_cache()
        if "--fleet" in sys.argv:
            bench_fleet(measure_rtt())
        else:
            bench_serving(measure_rtt())
        emit_summary()
    elif "--sketch" in sys.argv:
        # sketched-assignment drill (ISSUE 17); CI's sketch job runs this
        # scaled down (SKETCH_N/SKETCH_QN/... env): amortized assignment
        # speedup vs exact fused Lloyd, the inertia-ratio/ARI-vs-exact
        # quality envelope, the kernel-k-means nonlinear-boundary gate
        # with its dense-Lloyd-fails control, compile-once, and the
        # serving bit-identity drill — nonzero exit on any gate failure
        # (committed as SKETCH_r01.json); with DECISIONS_WRITE=1 it also
        # persists the measured kmeans.sketched.assign verdict
        _enable_compilation_cache()
        bench_sketch(measure_rtt())
        emit_summary()
    elif "--sparse" in sys.argv:
        # sparse-tier drill (ISSUE 13); CI's sparse job runs this scaled
        # down (SPARSE_N/SPARSE_D/... env): flagship streamed+in-memory
        # fits at a density dense staging cannot represent, the >= 50x
        # wire gate vs dense bf16, the small-size coef bit-identity pin,
        # compile-once across mixed (rows, nnz)-bucketed sparse batches,
        # and the dense-path bit-unchanged pins — nonzero exit on any
        # gate (committed as SPARSE_r01.json)
        _enable_compilation_cache()
        bench_sparse(measure_rtt())
        emit_summary()
    elif "--multichip" in sys.argv:
        # two-level mesh scale-out drill (ISSUE 10); CI's multichip job
        # runs this on the 8-device CPU mesh: flat-vs-hierarchical
        # trajectory pins, the cross-pod logical-byte reduction gate
        # (>= chips_per_pod x), the wall-clock DCN injection gates,
        # compile-once + telemetry-mirror gates, nonzero exit on any
        # failure (committed as MULTICHIP_r06.json). With --model-axis it
        # instead runs the third-axis tensor-parallel drill (PR 16):
        # feature-sharded GLM/PCA/Lloyd vs the flat oracle, model-ledger
        # exactness, the (2,4,1) bit-identity gate, and the d=2^17
        # capacity fit (committed as MODELAXIS_r01.json); with
        # DECISIONS_WRITE=1 it also persists the measured autotuner seed
        _enable_compilation_cache()
        if "--model-axis" in sys.argv:
            bench_modelaxis(measure_rtt())
        else:
            bench_multichip(measure_rtt())
        emit_summary()
    elif "--telemetry" in sys.argv:
        # unified-telemetry drill (ISSUE 7); CI's telemetry job runs this:
        # disabled-overhead, span-coverage, and trace-export gates plus
        # the mirror-exactness pins, nonzero exit on any gate failure
        # (committed as TELEMETRY_r01.json)
        _enable_compilation_cache()
        bench_telemetry(measure_rtt())
        emit_summary()
    elif "--compile-child" in sys.argv:
        _compile_child()
    elif "--compile-report" in sys.argv:
        # compile-count observability drill (ISSUE 4); CI's compile job
        # runs this: compile census + padded-vs-exact pins (nonzero exit on
        # divergence) + the cold-vs-warm persistent-cache numbers. The
        # process-global persistent cache stays OFF here so the census
        # counts real compiles; the child runs own the cache knob.
        bench_compile_report(measure_rtt())
        emit_summary()
    elif "--grid-child" in sys.argv:
        _grid_child()
    else:
        main()
