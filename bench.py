"""Headline benchmark: KMeans Lloyd-iteration throughput (samples/sec/chip).

Mirrors the reference's flagship benchmark workload — KMeans on a large blob
dataset (reference: benchmarks/k_means_kdd.py runs k=8 over ~4.9M×41;
BASELINE.md config #1 is make_blobs 1e6×50, k=8). We time a fixed number of
Lloyd iterations of the jitted SPMD loop on the accelerator and compare
against scikit-learn's Lloyd on the host CPU (the reference's own qualitative
baseline is "2-3x over scikit-learn", cluster/k_means.py:117-121).

Prints exactly one JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

import json
import time

import numpy as np

N_SAMPLES = 1_000_000
N_FEATURES = 50
N_CLUSTERS = 8
N_ITER = 20
SK_SAMPLES = 200_000  # sklearn baseline runs a smaller slice, scaled by work


def bench_tpu():
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu import datasets
    from dask_ml_tpu.models import kmeans as core
    from dask_ml_tpu.parallel.sharding import prepare_data

    X, _ = datasets.make_blobs(
        n_samples=N_SAMPLES, n_features=N_FEATURES, centers=N_CLUSTERS,
        cluster_std=2.0, random_state=0,
    )
    data = prepare_data(np.asarray(X))
    key = jax.random.key(0)
    centers0 = core.init_random(data.X, data.weights, data.n, N_CLUSTERS, key)
    tol = jnp.asarray(0.0, jnp.float32)

    # compile + warm up the single-program Lloyd loop
    out = core.lloyd_loop(data.X, data.weights, centers0, tol, N_ITER)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    centers, inertia, n_iter, _ = core.lloyd_loop(
        data.X, data.weights, centers0, tol, N_ITER
    )
    jax.block_until_ready(centers)
    dt = time.perf_counter() - t0
    iters = max(int(n_iter), 1)
    mesh_rate = N_SAMPLES * iters / dt  # whole-mesh samples/sec
    return mesh_rate, mesh_rate / jax.device_count(), float(inertia)


def bench_sklearn_baseline():
    from sklearn.cluster import KMeans as SKKMeans

    rng = np.random.RandomState(0)
    X = rng.randn(SK_SAMPLES, N_FEATURES).astype(np.float32) * 2.0
    init = X[rng.choice(SK_SAMPLES, N_CLUSTERS, replace=False)]
    km = SKKMeans(
        n_clusters=N_CLUSTERS, init=init, n_init=1, max_iter=N_ITER,
        tol=0.0, algorithm="lloyd",
    )
    t0 = time.perf_counter()
    km.fit(X)
    dt = time.perf_counter() - t0
    iters = max(int(km.n_iter_), 1)
    return SK_SAMPLES * iters / dt


def main():
    mesh_rate, per_chip, _ = bench_tpu()
    sk_throughput = bench_sklearn_baseline()
    print(
        json.dumps(
            {
                "metric": "kmeans_lloyd_throughput",
                "value": round(per_chip, 1),
                "unit": "samples/sec/chip",
                # whole-system vs whole-baseline speedup (not per-chip), so
                # the ratio keeps its meaning across mesh sizes
                "vs_baseline": round(mesh_rate / sk_throughput, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
