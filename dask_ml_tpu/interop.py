"""Ecosystem hand-off: export sharded device data and fitted models to host.

The reference's ecosystem bridges are one-line re-exports of external
runtimes colocated with the dask cluster (reference: xgboost.py:1-7
``dask-xgboost``'s rabit trainer, tensorflow.py:1-5
``dask-tensorflow``'s cluster bootstrap, joblib.py:1 the distributed joblib
backend). Those runtimes are out of scope for a TPU framework — capability
parity per SURVEY §2.9 (last row) is a *clean export of sharded arrays to
host NumPy plus an interop shim*, which is this module:

- :func:`to_numpy` — any ``jax.Array`` (sharded or not) or
  :class:`~dask_ml_tpu.parallel.sharding.DeviceData` → host ndarray, with
  padding rows dropped. This is the input side of an XGBoost/TF/torch
  hand-off: train the tree/neural model on the exported features.
- :func:`to_torch` — zero-copy(ish) bridge to a CPU torch tensor via
  dlpack when torch is importable.
- :func:`export_learned_attrs` — fitted-estimator learned state
  (trailing-underscore attributes) as a plain ``{name: ndarray}`` dict, the
  serialization-friendly form for serving stacks.

The thin ``dask_ml_tpu.xgboost`` / ``dask_ml_tpu.tensorflow`` /
``dask_ml_tpu.joblib`` modules re-export these under the reference's module
names and document the per-ecosystem recipe.
"""

from __future__ import annotations

import numpy as np

__all__ = ["to_numpy", "to_torch", "export_learned_attrs"]


def to_numpy(x, n_valid=None):
    """Gather a (possibly sharded, possibly padded) array to host NumPy.

    Accepts a ``jax.Array``, ndarray, or a ``DeviceData`` (in which case the
    padding rows are dropped automatically; for raw arrays pass ``n_valid``
    to drop them explicitly)."""
    from dask_ml_tpu.parallel.sharding import DeviceData

    if isinstance(x, DeviceData):
        return np.asarray(x.X)[: x.n]
    out = np.asarray(x)
    if n_valid is not None:
        out = out[:n_valid]
    return out


def to_torch(x, n_valid=None):
    """Export to a CPU torch tensor (the torch side of an XGBoost/TF-style
    hand-off). Imports torch lazily; raises ImportError with the recipe when
    unavailable."""
    try:
        import torch
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "to_torch requires torch; install it or use to_numpy() and "
            "torch.from_numpy() on your side"
        ) from e
    # copy: jax gives read-only buffers, torch wants writable memory
    return torch.from_numpy(np.array(to_numpy(x, n_valid), copy=True))


def export_learned_attrs(estimator) -> dict:
    """Fitted state (``*_`` attributes) as plain host arrays — the hand-off
    form for foreign serving/training stacks (the same attribute set
    ``copy_learned_attributes`` propagates, reference: _utils.py:1-5)."""
    out = {}
    for name, value in vars(estimator).items():
        if name.endswith("_") and not name.startswith("_"):
            try:
                out[name] = np.asarray(value)
            except Exception:
                out[name] = value
    return out
