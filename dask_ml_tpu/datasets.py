"""Sharded synthetic-data generators.

Parity with the reference's chunked generators (reference: datasets.py —
``make_counts:22``, ``make_blobs:70``, ``make_regression:189``,
``make_classification:313``). The reference builds per-block delayed tasks
with shared centers/coefs; here each generator is a single jitted XLA program
whose output is laid out directly with sample-axis sharding over the mesh
(``out_shardings=P('data', None)``), so large datasets materialize shard-wise
on the devices without a host round-trip.

Like the reference, only the sample axis is partitioned
(reference: datasets.py:12-19 ``_check_axis_partitioning``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.utils.validation import check_random_state


def _out_shardings(mesh, n_samples: int, n_per_row_outputs: int, n_repl: int):
    """Sample-axis sharding for row-aligned outputs when evenly divisible,
    else no constraint (estimators reshard+pad via prepare_data anyway)."""
    if n_samples % mesh_lib.n_data_shards(mesh) == 0:
        row2 = mesh_lib.data_sharding(mesh, ndim=2)
        row1 = mesh_lib.data_sharding(mesh, ndim=1)
        repl = mesh_lib.replicated_sharding(mesh)
        return tuple([row2] + [row1] * (n_per_row_outputs - 1) + [repl] * n_repl)
    return None


def make_blobs(
    n_samples: int = 100,
    n_features: int = 2,
    centers: Union[int, np.ndarray, None] = None,
    cluster_std: float = 1.0,
    center_box: tuple = (-10.0, 10.0),
    shuffle: bool = True,
    random_state=None,
    mesh=None,
    return_centers: bool = False,
):
    """Isotropic Gaussian blobs for clustering (reference: datasets.py:70-186).

    Cluster assignment is drawn i.i.d. per row, so the output is exchangeable
    and needs no separate shuffle pass (the ``shuffle`` flag is accepted for
    API parity).
    """
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    ck, lk, nk = jax.random.split(key, 3)
    if centers is None:
        centers = 3
    if isinstance(centers, (int, np.integer)):
        n_centers = int(centers)
        centers_arr = jax.random.uniform(
            ck, (n_centers, n_features), minval=center_box[0],
            maxval=center_box[1], dtype=jnp.float32,
        )
    else:
        centers_arr = jnp.asarray(centers, dtype=jnp.float32)
        n_centers = centers_arr.shape[0]

    def gen(centers_arr, lk, nk):
        labels = jax.random.randint(lk, (n_samples,), 0, n_centers)
        noise = jax.random.normal(nk, (n_samples, n_features), dtype=jnp.float32)
        X = centers_arr[labels] + cluster_std * noise
        return X, labels

    out_sh = _out_shardings(mesh, n_samples, 2, 0)
    f = jax.jit(gen, out_shardings=out_sh) if out_sh else jax.jit(gen)
    X, y = f(centers_arr, lk, nk)
    if return_centers:
        return X, y, centers_arr
    return X, y


def make_regression(
    n_samples: int = 100,
    n_features: int = 100,
    n_informative: int = 10,
    n_targets: int = 1,
    bias: float = 0.0,
    effective_rank: Optional[int] = None,
    tail_strength: float = 0.5,
    noise: float = 0.0,
    shuffle: bool = True,
    coef: bool = False,
    random_state=None,
    mesh=None,
):
    """Random regression problem (reference: datasets.py:189-310).

    ``effective_rank`` produces an approximately-low-rank design with a
    bell-shaped singular profile, like sklearn's ``make_low_rank_matrix``
    (which the reference delegates to) — but built distributed: the left
    singular basis is a sharded Gaussian orthonormalized by this package's
    OWN tall-skinny QR (one shard-local QR + one replicated combine), so
    the (n, d) design never leaves the mesh.
    """
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    xk, ik, ck2, nk = jax.random.split(key, 4)
    tshape = (n_features,) if n_targets == 1 else (n_features, n_targets)
    informative = jax.random.permutation(ik, n_features)[:n_informative]
    cvals = 100.0 * jax.random.uniform(
        ck2, (n_informative,) + tshape[1:], dtype=jnp.float32
    )
    ground_truth = jnp.zeros(tshape, dtype=jnp.float32).at[informative].set(cvals)

    def low_rank_design(k):
        """sklearn ``make_low_rank_matrix`` semantics, mesh-resident:
        ``X = (Q · s) @ Vᵀ`` with Q an (n, r) orthonormal basis from the
        package's distributed tsqr, V an (d, r) replicated orthonormal
        basis, and s the bell-curve + heavy-tail singular profile."""
        from dask_ml_tpu.ops.linalg import tsqr

        r = min(n_samples, n_features)
        gk, vk = jax.random.split(k)
        row_sh = mesh_lib.data_sharding(mesh, ndim=2)
        G = jax.jit(
            lambda kk: jax.random.normal(kk, (n_samples, r), jnp.float32),
            out_shardings=row_sh if mesh_lib.n_data_shards(mesh) > 1 else None,
        )(gk)
        Q, _ = tsqr(G, mesh=mesh)
        V, _ = jnp.linalg.qr(
            jax.random.normal(vk, (n_features, r), jnp.float32))
        sind = jnp.arange(r, dtype=jnp.float32) / effective_rank
        s = ((1.0 - tail_strength) * jnp.exp(-(sind ** 2))
             + tail_strength * jnp.exp(-0.1 * sind))
        return jax.jit(
            lambda Q, s, V: (Q * s) @ V.T,
            out_shardings=row_sh if mesh_lib.n_data_shards(mesh) > 1 else None,
        )(Q, s, V)

    def gen(ground_truth, xk, nk, X=None):
        if X is None:
            X = jax.random.normal(xk, (n_samples, n_features),
                                  dtype=jnp.float32)
        y = X @ ground_truth + bias
        if noise > 0.0:
            y = y + noise * jax.random.normal(nk, y.shape, dtype=jnp.float32)
        return X, y

    out_sh = _out_shardings(mesh, n_samples, 1, 0)
    if out_sh:
        row_y = mesh_lib.data_sharding(mesh, ndim=1 if n_targets == 1 else 2)
        out_sh = (out_sh[0], row_y)
        f = jax.jit(gen, out_shardings=out_sh)
    else:
        f = jax.jit(gen)
    Xlr = low_rank_design(xk) if effective_rank is not None else None
    X, y = f(ground_truth, xk, nk, Xlr) if Xlr is not None \
        else f(ground_truth, xk, nk)
    if coef:
        return X, y, ground_truth
    return X, y


def make_classification(
    n_samples: int = 100,
    n_features: int = 20,
    n_informative: int = 2,
    scale: float = 1.0,
    random_state=None,
    mesh=None,
    return_coef: bool = False,
):
    """Binary classification through a logistic link
    (reference: datasets.py:313-338 — the reference is also binary-only and
    uses exactly this Gaussian-design + Bernoulli(sigmoid) construction)."""
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    xk, ik, bk, uk = jax.random.split(key, 4)
    informative = jax.random.permutation(ik, n_features)[:n_informative]
    beta_full = (jax.random.uniform(bk, (n_features,), dtype=jnp.float32) - 1.0) * scale
    beta = jnp.zeros(n_features, dtype=jnp.float32).at[informative].set(
        beta_full[informative]
    )

    def gen(beta, xk, uk):
        X = jax.random.normal(xk, (n_samples, n_features), dtype=jnp.float32)
        z0 = X @ beta
        y = (jax.random.uniform(uk, (n_samples,)) < jax.nn.sigmoid(z0)).astype(
            jnp.int32
        )
        return X, y

    out_sh = _out_shardings(mesh, n_samples, 2, 0)
    f = jax.jit(gen, out_shardings=out_sh) if out_sh else jax.jit(gen)
    X, y = f(beta, xk, uk)
    if return_coef:
        return X, y, beta
    return X, y


class SparseClassificationBlocks:
    """Block-wise view of a :func:`make_sparse_classification` problem:
    calling ``loader(b)`` materializes ONLY block ``b`` as
    ``(SparseRows, y, w)`` host arrays — the 1e7 x 1e5 bench problem
    streams through this without the full dataset (let alone its 4 TB
    dense form) ever existing on the host at once.

    Deterministic by construction: content derives from fixed-size row
    CHUNKS, each seeded ``np.random.default_rng([seed, 1, chunk_id])``
    (numpy's counter-based bit generators are platform- and
    process-stable), and a block assembles the chunks its row range
    covers. Row ``i`` is therefore the same whatever the blocking — any
    process, any ``n_blocks``, any day regenerates it bit-identically,
    which is what lets the elastic data plane re-deal a lost host's
    blocks to survivors and lets a scaled-down CI drill slice the exact
    rows the full bench run used. Compatible with
    ``HostBlockSource(loader=blocks, n_blocks=blocks.n_blocks)``: every
    block shares the same ELL width ``k`` (fixed nonzeros per row), so
    the consuming per-block program compiles once.
    """

    #: rows per seeding chunk — the blocking-independent generation unit
    CHUNK = 4096

    def __init__(self, n_samples, n_features, k, coef, seed, n_blocks):
        self.n_samples = int(n_samples)
        self.n_features = int(n_features)
        self.k = int(k)
        self.coef = coef
        self.seed = int(seed)
        self.n_blocks = int(n_blocks)
        self.block_rows = -(-self.n_samples // self.n_blocks)

    def _chunk(self, cid: int):
        """One seeding chunk: (cols, vals, y) for rows
        ``[cid*CHUNK, min((cid+1)*CHUNK, n))``."""
        rows = min(self.CHUNK, self.n_samples - cid * self.CHUNK)
        rng = np.random.default_rng([self.seed, 1, int(cid)])
        cols = rng.integers(0, self.n_features, size=(rows, self.k),
                            dtype=np.int32)
        vals = rng.standard_normal((rows, self.k), dtype=np.float32)
        eta = (vals * self.coef[cols]).sum(axis=1)
        y = (rng.random(rows) < 1.0 / (1.0 + np.exp(-eta))).astype(
            np.float32)
        return cols, vals, y

    def __call__(self, b: int):
        from dask_ml_tpu.ops.sparse import SparseRows

        if not 0 <= b < self.n_blocks:
            raise IndexError(f"block {b} out of range [0, {self.n_blocks})")
        start = b * self.block_rows
        stop = min(start + self.block_rows, self.n_samples)
        parts = []
        for cid in range(start // self.CHUNK, -(-stop // self.CHUNK)):
            c0 = cid * self.CHUNK
            cols, vals, y = self._chunk(cid)
            lo = max(start - c0, 0)
            hi = min(stop - c0, cols.shape[0])
            parts.append((cols[lo:hi], vals[lo:hi], y[lo:hi]))
        cols = np.concatenate([p[0] for p in parts])
        vals = np.concatenate([p[1] for p in parts])
        y = np.concatenate([p[2] for p in parts])
        w = np.ones(cols.shape[0], np.float32)
        return SparseRows(vals, cols, self.n_features), y, w


def make_sparse_classification(
    n_samples: int = 100,
    n_features: int = 1000,
    density: float = 0.01,
    n_informative: Optional[int] = None,
    random_state: int = 0,
    n_blocks: Optional[int] = None,
    return_coef: bool = False,
):
    """Binary classification with a SPARSE design: each row holds exactly
    ``k = round(density * n_features)`` nonzeros (uniform column draws,
    N(0,1) values — duplicates legal and summing, the container's
    semantics), labels from a logistic link over a dense coefficient
    vector with ``n_informative`` (default d/10) nonzero entries.

    Returns ``(X, y)`` with ``X`` a HOST
    :class:`~dask_ml_tpu.ops.sparse.SparseRows` (stage it through any
    sparse-capable estimator, or ``ops.sparse.to_dense`` it for small
    oracles). With ``n_blocks=`` the data is NOT materialized: a
    :class:`SparseClassificationBlocks` loader is returned instead, each
    block regenerated on demand from counter-based seeds — deterministic
    across processes, so the >HBM/elastic tiers can stream it
    (docs/sparse.md). ``random_state`` must be an integer seed for that
    same reason (cross-process determinism leaves no room for ambient
    RandomState objects)."""
    if not isinstance(random_state, (int, np.integer)):
        raise TypeError(
            "make_sparse_classification requires an INTEGER random_state: "
            "blocks regenerate from counter-based seeds so any process "
            "can rebuild any block bit-identically")
    seed = int(random_state)
    d = int(n_features)
    k = max(1, int(round(float(density) * d)))
    if n_informative is None:
        n_informative = max(1, d // 10)
    rng = np.random.default_rng([seed, 0])
    idx = rng.choice(d, size=min(int(n_informative), d), replace=False)
    coef = np.zeros(d, np.float32)
    coef[idx] = rng.standard_normal(len(idx), dtype=np.float32)
    blocks = SparseClassificationBlocks(n_samples, d, k, coef, seed,
                                        n_blocks or 1)
    if n_blocks is not None:
        return (blocks, coef) if return_coef else blocks
    X, y, _ = blocks(0)
    if return_coef:
        return X, y, coef
    return X, y


def make_counts(
    n_samples: int = 1000,
    n_features: int = 100,
    n_informative: int = 2,
    scale: float = 1.0,
    random_state=None,
    mesh=None,
):
    """Poisson count data for GLM modelling (reference: datasets.py:22-67):
    ``y ~ Poisson(exp(X[:, idx] @ beta[idx]))``."""
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    xk, ik, bk, pk = jax.random.split(key, 4)
    informative = jax.random.permutation(ik, n_features)[:n_informative]
    beta_full = (jax.random.uniform(bk, (n_features,), dtype=jnp.float32) - 1.0) * scale
    beta = jnp.zeros(n_features, dtype=jnp.float32).at[informative].set(
        beta_full[informative]
    )

    def gen(beta, xk, pk):
        X = jax.random.normal(xk, (n_samples, n_features), dtype=jnp.float32)
        rate = jnp.exp(X @ beta)
        y = jax.random.poisson(pk, rate).astype(jnp.int32)
        return X, y

    out_sh = _out_shardings(mesh, n_samples, 2, 0)
    f = jax.jit(gen, out_shardings=out_sh) if out_sh else jax.jit(gen)
    return f(beta, xk, pk)
