"""Sharded synthetic-data generators.

Parity with the reference's chunked generators (reference: datasets.py —
``make_counts:22``, ``make_blobs:70``, ``make_regression:189``,
``make_classification:313``). The reference builds per-block delayed tasks
with shared centers/coefs; here each generator is a single jitted XLA program
whose output is laid out directly with sample-axis sharding over the mesh
(``out_shardings=P('data', None)``), so large datasets materialize shard-wise
on the devices without a host round-trip.

Like the reference, only the sample axis is partitioned
(reference: datasets.py:12-19 ``_check_axis_partitioning``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.utils.validation import check_random_state


def _out_shardings(mesh, n_samples: int, n_per_row_outputs: int, n_repl: int):
    """Sample-axis sharding for row-aligned outputs when evenly divisible,
    else no constraint (estimators reshard+pad via prepare_data anyway)."""
    if n_samples % mesh_lib.n_data_shards(mesh) == 0:
        row2 = mesh_lib.data_sharding(mesh, ndim=2)
        row1 = mesh_lib.data_sharding(mesh, ndim=1)
        repl = mesh_lib.replicated_sharding(mesh)
        return tuple([row2] + [row1] * (n_per_row_outputs - 1) + [repl] * n_repl)
    return None


def make_blobs(
    n_samples: int = 100,
    n_features: int = 2,
    centers: Union[int, np.ndarray, None] = None,
    cluster_std: float = 1.0,
    center_box: tuple = (-10.0, 10.0),
    shuffle: bool = True,
    random_state=None,
    mesh=None,
    return_centers: bool = False,
):
    """Isotropic Gaussian blobs for clustering (reference: datasets.py:70-186).

    Cluster assignment is drawn i.i.d. per row, so the output is exchangeable
    and needs no separate shuffle pass (the ``shuffle`` flag is accepted for
    API parity).
    """
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    ck, lk, nk = jax.random.split(key, 3)
    if centers is None:
        centers = 3
    if isinstance(centers, (int, np.integer)):
        n_centers = int(centers)
        centers_arr = jax.random.uniform(
            ck, (n_centers, n_features), minval=center_box[0],
            maxval=center_box[1], dtype=jnp.float32,
        )
    else:
        centers_arr = jnp.asarray(centers, dtype=jnp.float32)
        n_centers = centers_arr.shape[0]

    def gen(centers_arr, lk, nk):
        labels = jax.random.randint(lk, (n_samples,), 0, n_centers)
        noise = jax.random.normal(nk, (n_samples, n_features), dtype=jnp.float32)
        X = centers_arr[labels] + cluster_std * noise
        return X, labels

    out_sh = _out_shardings(mesh, n_samples, 2, 0)
    f = jax.jit(gen, out_shardings=out_sh) if out_sh else jax.jit(gen)
    X, y = f(centers_arr, lk, nk)
    if return_centers:
        return X, y, centers_arr
    return X, y


def make_regression(
    n_samples: int = 100,
    n_features: int = 100,
    n_informative: int = 10,
    n_targets: int = 1,
    bias: float = 0.0,
    effective_rank: Optional[int] = None,
    tail_strength: float = 0.5,
    noise: float = 0.0,
    shuffle: bool = True,
    coef: bool = False,
    random_state=None,
    mesh=None,
):
    """Random regression problem (reference: datasets.py:189-310).

    ``effective_rank`` produces an approximately-low-rank design with a
    bell-shaped singular profile, like sklearn's ``make_low_rank_matrix``
    (which the reference delegates to) — but built distributed: the left
    singular basis is a sharded Gaussian orthonormalized by this package's
    OWN tall-skinny QR (one shard-local QR + one replicated combine), so
    the (n, d) design never leaves the mesh.
    """
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    xk, ik, ck2, nk = jax.random.split(key, 4)
    tshape = (n_features,) if n_targets == 1 else (n_features, n_targets)
    informative = jax.random.permutation(ik, n_features)[:n_informative]
    cvals = 100.0 * jax.random.uniform(
        ck2, (n_informative,) + tshape[1:], dtype=jnp.float32
    )
    ground_truth = jnp.zeros(tshape, dtype=jnp.float32).at[informative].set(cvals)

    def low_rank_design(k):
        """sklearn ``make_low_rank_matrix`` semantics, mesh-resident:
        ``X = (Q · s) @ Vᵀ`` with Q an (n, r) orthonormal basis from the
        package's distributed tsqr, V an (d, r) replicated orthonormal
        basis, and s the bell-curve + heavy-tail singular profile."""
        from dask_ml_tpu.ops.linalg import tsqr

        r = min(n_samples, n_features)
        gk, vk = jax.random.split(k)
        row_sh = mesh_lib.data_sharding(mesh, ndim=2)
        G = jax.jit(
            lambda kk: jax.random.normal(kk, (n_samples, r), jnp.float32),
            out_shardings=row_sh if mesh_lib.n_data_shards(mesh) > 1 else None,
        )(gk)
        Q, _ = tsqr(G, mesh=mesh)
        V, _ = jnp.linalg.qr(
            jax.random.normal(vk, (n_features, r), jnp.float32))
        sind = jnp.arange(r, dtype=jnp.float32) / effective_rank
        s = ((1.0 - tail_strength) * jnp.exp(-(sind ** 2))
             + tail_strength * jnp.exp(-0.1 * sind))
        return jax.jit(
            lambda Q, s, V: (Q * s) @ V.T,
            out_shardings=row_sh if mesh_lib.n_data_shards(mesh) > 1 else None,
        )(Q, s, V)

    def gen(ground_truth, xk, nk, X=None):
        if X is None:
            X = jax.random.normal(xk, (n_samples, n_features),
                                  dtype=jnp.float32)
        y = X @ ground_truth + bias
        if noise > 0.0:
            y = y + noise * jax.random.normal(nk, y.shape, dtype=jnp.float32)
        return X, y

    out_sh = _out_shardings(mesh, n_samples, 1, 0)
    if out_sh:
        row_y = mesh_lib.data_sharding(mesh, ndim=1 if n_targets == 1 else 2)
        out_sh = (out_sh[0], row_y)
        f = jax.jit(gen, out_shardings=out_sh)
    else:
        f = jax.jit(gen)
    Xlr = low_rank_design(xk) if effective_rank is not None else None
    X, y = f(ground_truth, xk, nk, Xlr) if Xlr is not None \
        else f(ground_truth, xk, nk)
    if coef:
        return X, y, ground_truth
    return X, y


def make_classification(
    n_samples: int = 100,
    n_features: int = 20,
    n_informative: int = 2,
    scale: float = 1.0,
    random_state=None,
    mesh=None,
    return_coef: bool = False,
):
    """Binary classification through a logistic link
    (reference: datasets.py:313-338 — the reference is also binary-only and
    uses exactly this Gaussian-design + Bernoulli(sigmoid) construction)."""
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    xk, ik, bk, uk = jax.random.split(key, 4)
    informative = jax.random.permutation(ik, n_features)[:n_informative]
    beta_full = (jax.random.uniform(bk, (n_features,), dtype=jnp.float32) - 1.0) * scale
    beta = jnp.zeros(n_features, dtype=jnp.float32).at[informative].set(
        beta_full[informative]
    )

    def gen(beta, xk, uk):
        X = jax.random.normal(xk, (n_samples, n_features), dtype=jnp.float32)
        z0 = X @ beta
        y = (jax.random.uniform(uk, (n_samples,)) < jax.nn.sigmoid(z0)).astype(
            jnp.int32
        )
        return X, y

    out_sh = _out_shardings(mesh, n_samples, 2, 0)
    f = jax.jit(gen, out_shardings=out_sh) if out_sh else jax.jit(gen)
    X, y = f(beta, xk, uk)
    if return_coef:
        return X, y, beta
    return X, y


def make_counts(
    n_samples: int = 1000,
    n_features: int = 100,
    n_informative: int = 2,
    scale: float = 1.0,
    random_state=None,
    mesh=None,
):
    """Poisson count data for GLM modelling (reference: datasets.py:22-67):
    ``y ~ Poisson(exp(X[:, idx] @ beta[idx]))``."""
    mesh = mesh or mesh_lib.default_mesh()
    key = check_random_state(random_state)
    xk, ik, bk, pk = jax.random.split(key, 4)
    informative = jax.random.permutation(ik, n_features)[:n_informative]
    beta_full = (jax.random.uniform(bk, (n_features,), dtype=jnp.float32) - 1.0) * scale
    beta = jnp.zeros(n_features, dtype=jnp.float32).at[informative].set(
        beta_full[informative]
    )

    def gen(beta, xk, pk):
        X = jax.random.normal(xk, (n_samples, n_features), dtype=jnp.float32)
        rate = jnp.exp(X @ beta)
        y = jax.random.poisson(pk, rate).astype(jnp.int32)
        return X, y

    out_sh = _out_shardings(mesh, n_samples, 2, 0)
    f = jax.jit(gen, out_shardings=out_sh) if out_sh else jax.jit(gen)
    return f(beta, xk, pk)
