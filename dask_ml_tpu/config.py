"""Global + scoped execution configuration.

The reference has no global config object — its knobs are scattered over
estimator params, ``scheduler=``/``n_jobs=`` kwargs, ``compute=`` flags, and
dask's own ``dask.config`` scoping (SURVEY §5.6). The TPU rebuild gets one
small sklearn-style config: process-wide :func:`set_config`, scoped
:func:`config_context` (thread-local, nestable), read by the staging layer.

Knobs (all also overridable per-call at the API they configure):

- ``dtype`` — default staging dtype for ``X`` (e.g. ``jnp.bfloat16`` to run
  every fit in bf16 on the MXU without touching estimator code). ``None``
  keeps the input dtype as validated by ``check_array`` (float32 policy).
  Thread-local under :func:`config_context`.
- ``mesh`` — the mesh fits run on: ``set_config(mesh=...)`` sets the
  process-wide default (consulted by ``default_mesh()``), and
  ``config_context(mesh=...)`` scopes it via
  :func:`dask_ml_tpu.parallel.mesh.use_mesh`. Mesh scoping is deliberately
  PROCESS-VISIBLE, not thread-local: the search driver's worker threads
  must resolve the same mesh as the thread that opened the scope.
- ``device_outputs`` — when True, transform-like outputs of the jax-native
  estimators (scaler/PCA transforms, predictions) are returned as device
  arrays instead of host numpy. The default (False) preserves the sklearn
  contract; the search driver enables it around all-jax-native pipelines so
  stage outputs flow device→device between pipeline steps — over a slow
  host link every needless fetch is ~RTT + bytes/bandwidth, and a CV sweep
  does thousands of them. ``np.asarray`` on a returned device array still
  works everywhere. Thread-local under :func:`config_context`.
- ``pad_policy`` — sample-axis shape bucketing for the staging layer
  (:mod:`dask_ml_tpu.parallel.shapes`): ``"auto"`` (default) buckets every
  staged sample count into a small set of padded sizes so nearby ``n``
  share one compiled program per algorithm (rows past the true count carry
  weight 0 and are inert); ``None`` disables bucketing (exact mesh-multiple
  padding); a :class:`~dask_ml_tpu.parallel.shapes.PadPolicy` customizes
  the waste cap / smallest bucket. Thread-local under
  :func:`config_context`.
- ``precision`` — the mixed-precision execution policy
  (:mod:`dask_ml_tpu.parallel.precision`): ``"auto"`` (default) runs bf16
  wire + compute with f32 accumulation on TPU and plain f32 everywhere
  else; ``None``/``"f32"`` forces f32; ``"bf16"`` forces the bf16 policy
  on any backend; a :class:`~dask_ml_tpu.parallel.precision.PrecisionPolicy`
  customizes storage/compute/accumulation dtypes and per-op overrides.
  The policy acts at staging (``prepare_data`` storage dtype), on the
  streamed tier's wire (``HostBlockSource`` casts blocks host-side before
  ``device_put``), and on the PCA sketch dtype; solver state always stays
  ≥ f32 (``precision.state_dtype``). Thread-local under
  :func:`config_context`; see ``docs/precision.md``. An explicit ``dtype``
  knob (above) wins over the policy's storage dtype where both are set.
- ``telemetry`` — the unified observability subsystem
  (:mod:`dask_ml_tpu.parallel.telemetry`): ``True`` records hierarchical
  spans into the ring buffer and mirrors every instrumented counter into
  the metrics registry; ``False`` (default) keeps all instrumented call
  sites on a measured near-no-op path (no recorder growth, shared null
  span/metric objects). Thread-local under :func:`config_context`; see
  ``docs/observability.md``.
- ``compilation_cache`` — directory for XLA's PERSISTENT compilation cache
  (``set_config(compilation_cache="~/.cache/...")``): repeat invocations
  load compiled programs from disk and start warm. Process-wide only
  (it configures jax globally), so :func:`config_context` rejects it —
  see ``docs/compile.md`` for the cold-vs-warm numbers.

(Feature-axis sharding is NOT a config knob: staging layout changes the
shape of fitted state, so only estimators written for it — the GLMs —
enable it, automatically, on meshes with a ``model`` axis.)
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

_DEFAULTS: dict[str, Any] = {
    "dtype": None,
    "mesh": None,
    "device_outputs": False,
    "pad_policy": "auto",
    "precision": "auto",
    "telemetry": False,
    "compilation_cache": None,
}


def maybe_host(x, trusted: bool = True):
    """Return ``x`` as host numpy unless ``device_outputs`` is enabled.

    The one call every estimator's transform/predict tail goes through:
    by default it materializes to numpy (sklearn contract); inside a
    ``config_context(device_outputs=True)`` scope the device array passes
    through untouched, so pipeline stages chain device→device with no
    host round-trip. Pass-through outputs are marked TRUSTED in the active
    staging scope (they derive from inputs the producing estimator already
    validated), so the next stage's ``check_array`` can skip the NaN-scan
    sync without weakening validation of genuinely user-supplied arrays.

    ``trusted=False`` is for producers that can MANUFACTURE non-finite
    values from finite input (e.g. PCA whitening divides by a variance
    that may be zero): their outputs keep the downstream NaN scan so the
    search's error semantics match the host path.
    """
    if get_config()["device_outputs"]:
        if trusted:
            from dask_ml_tpu.parallel.sharding import _current_memo

            memo = _current_memo()
            if memo is not None:
                memo.trust(x)
        return x
    import numpy as np

    return np.asarray(x)

_global_config = dict(_DEFAULTS)
_local = threading.local()


def _stack() -> list:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def get_config() -> dict:
    """The effective configuration: process-wide settings overlaid by every
    active :func:`config_context` scope on this thread (innermost wins)."""
    cfg = dict(_global_config)
    for layer in _stack():
        cfg.update(layer)
    return cfg


def _validate_options(names) -> None:
    for k in names:
        if k not in _DEFAULTS:
            raise KeyError(
                f"unknown config option {k!r}; valid: {sorted(_DEFAULTS)}"
            )


def get_option(name: str):
    _validate_options([name])
    return get_config()[name]


def _get_one(name: str):
    """Single-key read without building the merged dict — the hot-path
    accessor behind ``telemetry.enabled()``, which instrumented call sites
    hit on every span/metric even with the knob off. Innermost scope wins,
    same as :func:`get_config`."""
    for layer in reversed(_stack()):
        if name in layer:
            return layer[name]
    return _global_config[name]


def set_config(**options) -> None:
    """Set process-wide defaults (``set_config(dtype=jnp.bfloat16)``).

    ``compilation_cache=dir`` additionally points XLA's persistent
    compilation cache at ``dir`` (``None`` turns it back off) — the knob is
    applied immediately, not just recorded."""
    _validate_options(options)
    if "compilation_cache" in options:
        # apply BEFORE recording: if the dir is unwritable the exception
        # propagates with the config still reporting the previous state,
        # never claiming a cache jax does not have
        from dask_ml_tpu.parallel.shapes import enable_persistent_cache

        enable_persistent_cache(options["compilation_cache"])
    _global_config.update(options)


def reset_config() -> None:
    """Restore the built-in defaults (mainly for tests). Like
    :func:`set_config`, the ``compilation_cache`` knob is APPLIED, not just
    recorded: a configured persistent cache is switched back off, so the
    config dict never claims None while jax still writes to a cache dir."""
    had_cache = _global_config.get("compilation_cache") is not None
    _global_config.clear()
    _global_config.update(_DEFAULTS)
    if had_cache:
        from dask_ml_tpu.parallel.shapes import enable_persistent_cache

        enable_persistent_cache(None)


@contextlib.contextmanager
def config_context(**options):
    """Scoped, nestable override — the dask.config-style scoping the
    reference leans on, without a global dict of strings. ``dtype`` (and
    future value-knobs) are thread-local; ``mesh=`` pushes onto the parallel
    layer's process-visible mesh stack (see the module docstring for why)
    so ``default_mesh()`` resolves to it inside the scope — including from
    search worker threads.

    ``mesh=None`` inside a scope is rejected: popping back to "no mesh"
    cannot be expressed on the process-visible mesh stack, and silently
    letting ``get_config()`` claim None while staging still used the
    enclosing mesh would lie. Clear the process default with
    ``set_config(mesh=None)`` instead.
    """
    _validate_options(options)
    if "compilation_cache" in options:
        raise ValueError(
            "compilation_cache is process-wide (it configures jax "
            "globally); use set_config(compilation_cache=...) instead of "
            "config_context"
        )
    if "mesh" in options and options["mesh"] is None:
        raise ValueError(
            "config_context(mesh=None) cannot clear an enclosing mesh "
            "scope; use set_config(mesh=None) to clear the process-wide "
            "default, or pass an explicit Mesh"
        )
    mesh: Optional[Any] = options.get("mesh")
    stack = _stack()
    stack.append(dict(options))
    try:
        if mesh is not None:
            from dask_ml_tpu.parallel.mesh import use_mesh

            with use_mesh(mesh):
                yield
        else:
            yield
    finally:
        stack.pop()
