"""Preprocessing: scalers and transformers as sharded column reductions,
plus pandas-tier categorical encoders
(reference: preprocessing/data.py, preprocessing/label.py)."""

from dask_ml_tpu.preprocessing.data import (  # noqa: F401
    Categorizer,
    DummyEncoder,
    MinMaxScaler,
    OneHotEncoder,
    OrdinalEncoder,
    QuantileTransformer,
    RobustScaler,
    StandardScaler,
)
from dask_ml_tpu.preprocessing.label import LabelEncoder  # noqa: F401

__all__ = [
    "StandardScaler",
    "MinMaxScaler",
    "RobustScaler",
    "QuantileTransformer",
    "Categorizer",
    "DummyEncoder",
    "OneHotEncoder",
    "OrdinalEncoder",
    "LabelEncoder",
]
