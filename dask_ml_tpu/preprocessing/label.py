"""LabelEncoder (reference: preprocessing/label.py:12-57).

Label vocabularies are host metadata (they can be strings), so fit runs
``np.unique`` on host; numeric transforms could ride the device via
``jnp.searchsorted`` but per-element label lookups are never the bottleneck —
keeping this host-side mirrors the reference's per-block
``np.searchsorted`` tasks without the task overhead."""

from __future__ import annotations

import numpy as np
import sklearn.preprocessing as sklabel
from sklearn.utils.validation import check_is_fitted


class LabelEncoder(sklabel.LabelEncoder):
    __doc__ = sklabel.LabelEncoder.__doc__

    def fit(self, y):
        self.classes_ = np.unique(np.asarray(y))
        return self

    def fit_transform(self, y):
        return self.fit(y).transform(y)

    def transform(self, y):
        check_is_fitted(self, "classes_")
        y = np.asarray(y)
        diff = np.setdiff1d(y, self.classes_)
        if diff.size:
            raise ValueError(f"y contains previously unseen labels: {diff}")
        return np.searchsorted(self.classes_, y)

    def inverse_transform(self, y):
        check_is_fitted(self, "classes_")
        return self.classes_[np.asarray(y)]
