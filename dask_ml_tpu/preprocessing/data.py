"""Scalers and transformers.

Two tiers, mirroring the reference's split:

- **Device tier** (StandardScaler, MinMaxScaler, RobustScaler,
  QuantileTransformer): fit is one jitted reduction over the sharded sample
  axis (column means/vars/extrema/percentiles — each a psum/all-reduce over
  the mesh), transform is a sharded elementwise program. The reference
  expresses the same reductions as lazy dask column ops + one ``compute``
  (reference: preprocessing/data.py:28-66 StandardScaler, :69-126
  MinMaxScaler, :128-157 RobustScaler, :160-246 QuantileTransformer).
  Improvement over the reference: percentiles here are exact (global
  distributed sort under XLA) where dask's ``da.percentile`` is a chunkwise
  approximation — the reference's QuantileTransformer docstring even warns
  about it (data.py:161-163).
- **Pandas tier** (Categorizer, DummyEncoder, OrdinalEncoder): categorical
  bookkeeping on host DataFrames, exactly as in the reference
  (data.py:249-403, :405-644, :647-800) — these are metadata transforms, not
  device compute.

Like the reference, the scalers subclass their sklearn counterparts to
inherit the constructor/params surface and docs (reference: data.py:24-26).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import sklearn.preprocessing as skdata
from pandas.api.types import CategoricalDtype
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.utils.validation import check_is_fitted

from dask_ml_tpu.config import maybe_host
from dask_ml_tpu.parallel.sharding import prepare_data, shard_rows, unpad_rows
from dask_ml_tpu.utils.validation import check_array

BOUNDS_THRESHOLD = 1e-7

# canonical home is the utils layer, as in the reference (imported from
# dask_ml.utils at data.py:18); re-exported here for backward compat
from dask_ml_tpu.utils._utils import handle_zeros_in_scale  # noqa: E402,F401


@jax.jit
def _standardize(X, mean, scale):
    return (X - mean) / scale


@jax.jit
def _mean_var(X, w):
    sw = jnp.maximum(w.sum(), 1.0)
    mean = (w[:, None] * X).sum(0) / sw
    var = (w[:, None] * (X - mean) ** 2).sum(0) / sw
    # scale in the SAME program (handle_zeros_in_scale semantics:
    # constant features divide by 1) — an eager sqrt/where would add
    # two more tiny programs, each ~0.7s of fixed compile cost on a
    # tunneled backend, to every cold search
    scale = jnp.sqrt(jnp.where(var == 0.0, 1.0, var))
    return mean, var, scale


@jax.jit
def _min_max(X, w):
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    valid = (w > 0)[:, None]
    mn = jnp.min(jnp.where(valid, X, big), axis=0)
    mx = jnp.max(jnp.where(valid, X, -big), axis=0)
    return mn, mx


def _valid_rows(data):
    """The unpadded sharded view, for order-statistics reductions where
    zero-padding would pollute the result."""
    return data.X[: data.n]


@jax.jit
def _sparse_mean_var(A, w):
    """Sparse column mean/variance from the nnz moments
    (``ops.sparse.column_mean_var`` — the stable two-pass form; the
    one-pass E[x^2]-mean^2 identity cancels in f32 for large-mean
    columns), O(nnz) where the dense reduction is O(n*d). Same
    handle-zeros-in-scale rule as the dense program."""
    from dask_ml_tpu.ops import sparse as sparse_ops

    mean, var, _ = sparse_ops.column_mean_var(A, w)
    scale = jnp.sqrt(jnp.where(var == 0.0, 1.0, var))
    return mean, var, scale


class StandardScaler(skdata.StandardScaler):
    __doc__ = skdata.StandardScaler.__doc__

    def fit(self, X, y=None):
        from dask_ml_tpu.config import get_config
        from dask_ml_tpu.ops import sparse as sparse_ops
        from dask_ml_tpu.parallel.sharding import is_sparse_input

        self._reset()
        if is_sparse_input(X):
            # sparse tier (docs/sparse.md): centering would densify (every
            # zero becomes -mean), so it is rejected exactly like sklearn
            # rejects it; the variance comes from the nnz moments
            if self.with_mean:
                raise ValueError(
                    "Cannot center sparse data (with_mean=True would "
                    "densify every zero to -mean); construct "
                    "StandardScaler(with_mean=False) for sparse inputs")
            X = check_array(X, accept_sparse=True)
            data = prepare_data(X)
            if bool(sparse_ops.has_duplicate_slots(data.X)):
                raise ValueError(
                    "this sparse container stores some column twice in "
                    "one row (duplicate slots sum in the linear "
                    "contractions, but per-column VARIANCE cannot be "
                    "computed slot-wise over them); re-canonicalize "
                    "through scipy first: csr.sum_duplicates()")
            mean, var, scale = _sparse_mean_var(data.X, data.weights)
            if not get_config()["device_outputs"]:
                var, scale = np.asarray(var), np.asarray(scale)
            self.mean_ = None
            if self.with_std:
                self.var_ = var
                self.scale_ = scale
            else:
                self.var_ = None
                self.scale_ = None
            self.n_samples_seen_ = data.n
            return self
        X = check_array(X)
        data = prepare_data(X)
        mean, var, scale = _mean_var(data.X, data.weights)
        if not get_config()["device_outputs"]:
            # host attrs; device_outputs keeps them as device arrays
            # (np.asarray on access still works). Either way the scale's
            # handle-zeros rule matches handle_zeros_in_scale's
            # divide-by-1-for-constant-features.
            mean, var, scale = (np.asarray(mean), np.asarray(var),
                                np.asarray(scale))
        # sklearn's attribute contract: disabled statistics are None, not
        # absent.
        self.mean_ = mean if self.with_mean else None
        if self.with_std:
            self.var_ = var
            self.scale_ = scale
        else:
            self.var_ = None
            self.scale_ = None
        self.n_samples_seen_ = data.n
        return self

    def partial_fit(self, X, y=None):
        raise NotImplementedError(
            "partial_fit is unsupported, as in the reference "
            "(preprocessing/data.py:51-52)"
        )

    def transform(self, X, y=None, copy=None):
        from dask_ml_tpu.ops import sparse as sparse_ops
        from dask_ml_tpu.parallel.sharding import is_sparse_input

        check_is_fitted(self, "n_samples_seen_")
        if is_sparse_input(X):
            if self.with_mean:
                raise ValueError(
                    "Cannot center sparse data; this scaler was "
                    "constructed with with_mean=True")
            X = check_array(X, accept_sparse=True)
            Xs, n = shard_rows(X)
            if self.with_std:
                Xs = sparse_ops.scale_columns(
                    Xs, jnp.asarray(self.scale_, jnp.float32))
            # stays SPARSE: the sharded container feeds the GLM/search
            # tier directly — the one-hot -> scale -> fit pipeline never
            # materializes dense (docs/sparse.md)
            return unpad_rows(Xs, n)
        X = check_array(X)
        Xs, n = shard_rows(X)
        if self.with_mean and self.with_std:
            # fused single dispatch for the common case (a CV sweep calls
            # this hundreds of times; per-op dispatch latency adds up on a
            # high-RTT host link)
            Xs = _standardize(Xs, jnp.asarray(self.mean_, Xs.dtype),
                              jnp.asarray(self.scale_, Xs.dtype))
        else:
            if self.with_mean:
                Xs = Xs - jnp.asarray(self.mean_, Xs.dtype)
            if self.with_std:
                Xs = Xs / jnp.asarray(self.scale_, Xs.dtype)
        return maybe_host(unpad_rows(Xs, n))

    def inverse_transform(self, X, copy=None):
        check_is_fitted(self, "n_samples_seen_")
        X = check_array(X)
        Xs, n = shard_rows(X)
        if self.with_std:
            Xs = Xs * jnp.asarray(self.scale_, Xs.dtype)
        if self.with_mean:
            Xs = Xs + jnp.asarray(self.mean_, Xs.dtype)
        return maybe_host(unpad_rows(Xs, n))


class MinMaxScaler(skdata.MinMaxScaler):
    __doc__ = skdata.MinMaxScaler.__doc__

    def fit(self, X, y=None):
        self._reset()
        if self.feature_range[0] >= self.feature_range[1]:
            raise ValueError(
                "Minimum of desired feature range must be smaller than maximum."
            )
        X = check_array(X)
        data = prepare_data(X)
        lo, hi = self.feature_range
        data_min, data_max = (np.asarray(a)
                              for a in _min_max(data.X, data.weights))
        data_range = data_max - data_min
        scale = (hi - lo) / handle_zeros_in_scale(data_range)
        self.data_min_ = data_min
        self.data_max_ = data_max
        self.data_range_ = data_range
        self.scale_ = scale
        self.min_ = lo - data_min * scale
        self.n_samples_seen_ = data.n
        return self

    def partial_fit(self, X, y=None):
        raise NotImplementedError(
            "partial_fit is unsupported, as in the reference "
            "(preprocessing/data.py:100-101)"
        )

    def transform(self, X, y=None, copy=None):
        check_is_fitted(self, "scale_")
        X = check_array(X)
        Xs, n = shard_rows(X)
        out = Xs * jnp.asarray(self.scale_, Xs.dtype) + jnp.asarray(
            self.min_, Xs.dtype)
        if getattr(self, "clip", False):
            lo, hi = self.feature_range
            out = jnp.clip(out, lo, hi)
        return maybe_host(unpad_rows(out, n))

    def inverse_transform(self, X, y=None, copy=None):
        check_is_fitted(self, "scale_")
        X = check_array(X)
        Xs, n = shard_rows(X)
        out = (Xs - jnp.asarray(self.min_, Xs.dtype)) / jnp.asarray(
            self.scale_, Xs.dtype)
        return maybe_host(unpad_rows(out, n))


class RobustScaler(skdata.RobustScaler):
    __doc__ = skdata.RobustScaler.__doc__

    def fit(self, X, y=None):
        q_min, q_max = self.quantile_range
        if not 0 <= q_min <= q_max <= 100:
            raise ValueError(
                f"Invalid quantile range: {self.quantile_range}"
            )
        X = check_array(X)
        data = prepare_data(X)
        # Exact distributed percentiles over the valid rows (the reference
        # uses dask's approximate ``da.percentile``, data.py:151).
        qs = jnp.percentile(
            _valid_rows(data), jnp.asarray([q_min, 50.0, q_max]), axis=0)
        qs = np.asarray(qs)
        if self.with_centering:
            self.center_ = qs[1]
        else:
            self.center_ = None
        if self.with_scaling:
            self.scale_ = handle_zeros_in_scale(qs[2] - qs[0])
        else:
            self.scale_ = None
        return self

    def transform(self, X):
        check_is_fitted(self, "scale_")
        X = check_array(X)
        Xs, n = shard_rows(X)
        if self.with_centering:
            Xs = Xs - jnp.asarray(self.center_, Xs.dtype)
        if self.with_scaling:
            Xs = Xs / jnp.asarray(self.scale_, Xs.dtype)
        return maybe_host(unpad_rows(Xs, n))

    def inverse_transform(self, X):
        check_is_fitted(self, "scale_")
        X = check_array(X)
        Xs, n = shard_rows(X)
        if self.with_scaling:
            Xs = Xs * jnp.asarray(self.scale_, Xs.dtype)
        if self.with_centering:
            Xs = Xs + jnp.asarray(self.center_, Xs.dtype)
        return maybe_host(unpad_rows(Xs, n))


# ---------------------------------------------------------------------------
# QuantileTransformer
# ---------------------------------------------------------------------------


def _qt_transform_cols(X, quantiles, references, inverse: bool,
                       normal: bool):
    """Per-column monotone interpolation, vmapped over the feature axis
    (the reference's ``_transform_col`` column loop, data.py:193-246)."""

    def fwd_col(x, q):
        # sklearn's two-sided interpolation trick for repeated values
        # (cited in the reference at data.py:228-233).
        a = jnp.interp(x, q, references)
        b = jnp.interp(-x, -q[::-1], -references[::-1])
        out = 0.5 * (a - b)
        # Bound overrides match modern sklearn exactly: uniform mode uses
        # EXACT equality with the extreme quantiles, normal mode strict
        # thresholds; upper applied first, lower last (so a constant feature
        # maps to 0 in uniform mode and to ppf(0.5)=0 in normal mode).
        if normal:
            out = jnp.where(x + BOUNDS_THRESHOLD > q[-1], 1.0, out)
            out = jnp.where(x - BOUNDS_THRESHOLD < q[0], 0.0, out)
            out = jax.scipy.stats.norm.ppf(out)
            clip_min = float(jax.scipy.stats.norm.ppf(
                BOUNDS_THRESHOLD - np.spacing(1)))
            clip_max = float(jax.scipy.stats.norm.ppf(
                1 - (BOUNDS_THRESHOLD - np.spacing(1))))
            out = jnp.clip(out, clip_min, clip_max)
        else:
            out = jnp.where(x == q[-1], 1.0, out)
            out = jnp.where(x == q[0], 0.0, out)
        return out

    def inv_col(x, q):
        if normal:
            x = jax.scipy.stats.norm.cdf(x)
            out = jnp.interp(x, references, q)
            out = jnp.where(x + BOUNDS_THRESHOLD > 1.0, q[-1], out)
            out = jnp.where(x - BOUNDS_THRESHOLD < 0.0, q[0], out)
        else:
            out = jnp.interp(x, references, q)
            out = jnp.where(x == 1.0, q[-1], out)
            out = jnp.where(x == 0.0, q[0], out)
        return out

    col = inv_col if inverse else fwd_col
    return jax.vmap(col, in_axes=(1, 1), out_axes=1)(X, quantiles)


class QuantileTransformer(skdata.QuantileTransformer):
    """Transforms features using quantile information.

    Unlike the reference — whose quantiles are dask's chunkwise
    approximations (reference: data.py:160-163 notes the difference from
    sklearn) — the quantiles here are exact: a distributed sort/percentile
    over the sharded sample axis. The scikit-learn docstring follows.
    """

    __doc__ += "\n".join(skdata.QuantileTransformer.__doc__.split("\n")[1:])

    def fit(self, X, y=None):
        if self.output_distribution not in ("uniform", "normal"):
            raise ValueError(
                f"'output_distribution' has to be either 'normal' or "
                f"'uniform'. Got '{self.output_distribution}' instead."
            )
        if int(self.n_quantiles) < 1:
            raise ValueError(
                f"n_quantiles must be at least 1, got {self.n_quantiles}"
            )
        X = check_array(X)
        data = prepare_data(X)
        n_quantiles = min(int(self.n_quantiles), data.n)
        self.n_quantiles_ = n_quantiles
        self.references_ = np.linspace(0, 1, n_quantiles, endpoint=True)
        qs = jnp.percentile(
            _valid_rows(data),
            jnp.asarray(self.references_ * 100.0, jnp.float32), axis=0)
        self.quantiles_ = np.asarray(qs)
        return self

    def _transform_inner(self, X, inverse: bool):
        check_is_fitted(self, "quantiles_")
        X = check_array(X)
        Xs, n = shard_rows(X)
        out = _qt_transform_cols(
            Xs, jnp.asarray(self.quantiles_, Xs.dtype),
            jnp.asarray(self.references_, Xs.dtype),
            inverse=inverse, normal=self.output_distribution == "normal")
        return maybe_host(unpad_rows(out, n))

    def transform(self, X):
        return self._transform_inner(X, inverse=False)

    def inverse_transform(self, X):
        return self._transform_inner(X, inverse=True)


# ---------------------------------------------------------------------------
# Pandas-tier categorical encoders (reference: data.py:249-800) — host-side
# metadata transforms, deliberately not device code (same in the reference).
# ---------------------------------------------------------------------------


class Categorizer(BaseEstimator, TransformerMixin):
    """Convert columns of a DataFrame to categorical dtype
    (reference: preprocessing/data.py:249-403; same attributes)."""

    def __init__(self, categories=None, columns=None):
        self.categories = categories
        self.columns = columns

    def _check_array(self, X):
        if not isinstance(X, pd.DataFrame):
            raise TypeError(
                f"Expected a pandas DataFrame, got {type(X)} instead"
            )
        return X

    def fit(self, X, y=None):
        X = self._check_array(X)
        if self.categories is not None:
            columns = pd.Index(self.categories)
            categories = dict(self.categories)
        else:
            if self.columns is None:
                try:
                    columns = X.select_dtypes(
                        include=["object", "str", "category"]).columns
                except TypeError:
                    # pandas < 3 maps "str" to the rejected numpy str_
                    # dtype (the dedicated str dtype doesn't exist yet);
                    # object covers strings there
                    columns = X.select_dtypes(
                        include=["object", "category"]).columns
            else:
                columns = pd.Index(self.columns)
            categories = {}
            for name in columns:
                col = X[name]
                if not isinstance(col.dtype, CategoricalDtype):
                    col = col.astype("category")
                categories[name] = col.dtype
        self.columns_ = columns
        self.categories_ = categories
        return self

    def transform(self, X, y=None):
        check_is_fitted(self, "categories_")
        X = self._check_array(X).copy()
        for k, dtype in self.categories_.items():
            if not isinstance(dtype, CategoricalDtype):
                dtype = CategoricalDtype(*dtype)
            X[k] = X[k].astype(dtype)
        return X


class DummyEncoder(BaseEstimator, TransformerMixin):
    """One-hot encode categorical DataFrame columns
    (reference: preprocessing/data.py:405-644; same attributes incl. the
    per-column block slices used by inverse_transform)."""

    def __init__(self, columns=None, drop_first=False):
        self.columns = columns
        self.drop_first = drop_first

    def fit(self, X, y=None):
        self.columns_ = X.columns
        columns = self.columns
        if columns is None:
            columns = X.select_dtypes(include=["category"]).columns
        else:
            for column in columns:
                if not isinstance(X[column].dtype, CategoricalDtype):
                    raise ValueError(f"Column {column!r} must be categorical")
            columns = pd.Index(columns)
        self.categorical_columns_ = columns
        self.non_categorical_columns_ = X.columns.drop(columns)
        self.dtypes_ = {col: X[col].dtype for col in columns}

        left = len(self.non_categorical_columns_)
        self.categorical_blocks_ = {}
        for col in columns:
            right = left + len(X[col].cat.categories)
            if self.drop_first:
                right -= 1
            self.categorical_blocks_[col], left = slice(left, right), right
        self.transformed_columns_ = pd.get_dummies(
            X.iloc[:1], columns=list(columns),
            drop_first=self.drop_first).columns
        return self

    def transform(self, X, y=None):
        check_is_fitted(self, "columns_")
        if not isinstance(X, pd.DataFrame):
            raise TypeError(f"Unexpected type {type(X)}")
        if not X.columns.equals(self.columns_):
            raise ValueError(
                f"Columns of 'X' do not match the training columns. "
                f"Got {X.columns!r}, expected {self.columns_!r}"
            )
        # Restrict encoding to the fitted column subset so the block slices
        # recorded in fit stay aligned even when other categorical columns
        # exist — and coerce every categorical column to the dtype recorded
        # at fit: independently-categorized chunks would otherwise emit a
        # different dummy-column count and silently shift all later columns
        # (values outside the fitted categories become NaN → all-zero rows,
        # column layout intact).
        X = X.copy()
        for col in self.categorical_columns_:  # (not assign(**...): column
            X[col] = X[col].astype(self.dtypes_[col])  # labels may be ints)
        return pd.get_dummies(X, columns=list(self.categorical_columns_),
                              drop_first=self.drop_first)

    def inverse_transform(self, X):
        check_is_fitted(self, "columns_")
        if isinstance(X, np.ndarray):
            X = pd.DataFrame(X, columns=self.transformed_columns_)
        non_cat = X[list(self.non_categorical_columns_)]
        cats = {}
        for col in self.categorical_columns_:
            dtype = self.dtypes_[col]
            block = X.iloc[:, self.categorical_blocks_[col]]
            codes = np.asarray(block).argmax(axis=1)
            if self.drop_first:
                # All-zero rows are the dropped first category (code 0);
                # otherwise shift by one.
                any_set = np.asarray(block).sum(axis=1) > 0
                codes = np.where(any_set, codes + 1, 0)
            cats[col] = pd.Categorical.from_codes(
                codes, dtype.categories, ordered=dtype.ordered)
        out = non_cat.assign(**cats)
        return out[list(self.columns_)]


class OneHotEncoder(BaseEstimator, TransformerMixin):
    """One-hot encode categorical feature columns — emitting the SHARDED
    SPARSE container directly (docs/sparse.md).

    The dense one-hot of d_in categorical columns with C total categories
    is an (n, C) matrix that is exactly d_in/C dense (~0.1% at CTR-style
    cardinalities) — the canonical way the "impossible dense" sparse GLM
    inputs arise. ``transform`` therefore emits a host
    :class:`~dask_ml_tpu.ops.sparse.SparseRows` in blocked-ELL layout with
    k = d_in slots per row (every row has EXACTLY one nonzero per input
    column — the ELL layout's best case, zero slot waste before
    bucketing): the GLMs, the sparse ``StandardScaler`` and the search
    driver consume it natively, so the one-hot -> (scale) -> fit pipeline
    never materializes a dense row. ``sparse_output=False`` returns the
    dense numpy one-hot for small/debug use.

    ``handle_unknown='ignore'`` maps unseen categories to an inert slot
    (value 0 — the row simply lacks that column's indicator, exactly like
    sklearn's all-zero block); ``'error'`` (default) raises.
    """

    def __init__(self, categories="auto", dtype=np.float32,
                 handle_unknown="error", sparse_output=True):
        self.categories = categories
        self.dtype = dtype
        self.handle_unknown = handle_unknown
        self.sparse_output = sparse_output

    def _check_X(self, X):
        if hasattr(X, "iloc"):
            X = X.values
        X = np.asarray(X)
        if X.ndim != 2:
            raise ValueError(
                f"Expected 2D array of categorical columns, got {X.ndim}D")
        return X

    def fit(self, X, y=None):
        if self.handle_unknown not in ("error", "ignore"):
            raise ValueError(
                f"handle_unknown must be 'error' or 'ignore', got "
                f"{self.handle_unknown!r}")
        X = self._check_X(X)
        n_cols = X.shape[1]
        # isinstance first: an ndarray `categories` would broadcast the
        # == "auto" comparison elementwise and raise on truth-testing
        if isinstance(self.categories, str) and self.categories == "auto":
            cats = [np.unique(X[:, j]) for j in range(n_cols)]
        else:
            if len(self.categories) != n_cols:
                raise ValueError(
                    f"categories has {len(self.categories)} entries for "
                    f"{n_cols} columns")
            cats = [np.asarray(c) for c in self.categories]
        self.categories_ = cats
        self.n_features_in_ = n_cols
        # column j's indicators occupy feature ids offset_[j] ..
        # offset_[j] + len(cats[j]) - 1 in the encoded space
        sizes = np.array([len(c) for c in cats], dtype=np.int64)
        self._offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
        self._n_out = int(sizes.sum())
        self._sorters = [np.argsort(c, kind="stable") for c in cats]
        return self

    def _column_codes(self, col, j):
        """Codes of one raw column against the fitted categories; -1 marks
        unknown (inert slot under handle_unknown='ignore')."""
        cat, sorter = self.categories_[j], self._sorters[j]
        pos = np.searchsorted(cat, col, sorter=sorter)
        pos = np.clip(pos, 0, len(cat) - 1)
        code = sorter[pos]
        found = cat[code] == col
        if not found.all():
            if self.handle_unknown == "error":
                bad = np.unique(np.asarray(col)[~found])[:5]
                raise ValueError(
                    f"Found unknown categories {bad.tolist()} in column "
                    f"{j} during transform")
            code = np.where(found, code, -1)
        return code

    def transform(self, X, y=None):
        check_is_fitted(self, "categories_")
        X = self._check_X(X)
        if X.shape[1] != self.n_features_in_:
            raise ValueError(
                f"X has {X.shape[1]} features, but OneHotEncoder was "
                f"fitted with {self.n_features_in_}")
        from dask_ml_tpu.ops.sparse import SparseRows

        n, k = X.shape
        values = np.ones((n, k), np.dtype(self.dtype))
        cols = np.zeros((n, k), np.int32)
        for j in range(k):
            code = self._column_codes(X[:, j], j)
            known = code >= 0
            cols[:, j] = np.where(known, self._offsets[j] + code, 0)
            if not known.all():
                values[:, j] = np.where(known, values[:, j], 0)
        out = SparseRows(values, cols, self._n_out)
        if self.sparse_output:
            return out
        dense = np.zeros((n, self._n_out), values.dtype)
        np.add.at(dense, (np.arange(n)[:, None], cols), values)
        return dense

    def get_feature_names_out(self, input_features=None):
        names = []
        for j, cat in enumerate(self.categories_):
            base = (input_features[j] if input_features is not None
                    else f"x{j}")
            names.extend(f"{base}_{c}" for c in cat)
        return np.asarray(names, dtype=object)


class OrdinalEncoder(BaseEstimator, TransformerMixin):
    """Integer-encode categorical DataFrame columns
    (reference: preprocessing/data.py:647-800)."""

    def __init__(self, columns=None):
        self.columns = columns

    def fit(self, X, y=None):
        self.columns_ = X.columns
        columns = self.columns
        if columns is None:
            columns = X.select_dtypes(include=["category"]).columns
        else:
            for column in columns:
                if not isinstance(X[column].dtype, CategoricalDtype):
                    raise ValueError(f"Column {column!r} must be categorical")
            columns = pd.Index(columns)
        self.categorical_columns_ = columns
        self.non_categorical_columns_ = X.columns.drop(columns)
        self.dtypes_ = {col: X[col].dtype for col in columns}
        return self

    def transform(self, X, y=None):
        check_is_fitted(self, "columns_")
        if not isinstance(X, pd.DataFrame):
            raise TypeError(f"Unexpected type {type(X)}")
        if not X.columns.equals(self.columns_):
            raise ValueError(
                f"Columns of 'X' do not match the training columns. "
                f"Got {X.columns!r}, expected {self.columns_!r}"
            )
        X = X.copy()
        for col in self.categorical_columns_:
            # codes against the FITTED category set: an independently
            # categorized chunk would otherwise produce different codes for
            # the same values (unseen values become -1, pandas' NaN code)
            X[col] = X[col].astype(self.dtypes_[col]).cat.codes
        return X

    def inverse_transform(self, X):
        check_is_fitted(self, "columns_")
        if isinstance(X, np.ndarray):
            X = pd.DataFrame(X, columns=self.columns_)
        X = X.copy()
        for col in self.categorical_columns_:
            dtype = self.dtypes_[col]
            X[col] = pd.Categorical.from_codes(
                np.asarray(X[col], dtype=int), dtype.categories,
                ordered=dtype.ordered)
        return X
