"""Deprecated Partial Perceptron wrapper (reference: perceptron.py:7-9)."""

from __future__ import annotations

from sklearn.linear_model import Perceptron as _Perceptron

from dask_ml_tpu._partial import _BigPartialFitMixin, _copy_partial_doc


@_copy_partial_doc
class PartialPerceptron(_BigPartialFitMixin, _Perceptron):
    _init_kwargs = ["classes"]
    _fit_kwargs = []
