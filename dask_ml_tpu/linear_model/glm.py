"""sklearn-facade GLM estimators over the native solver suite.

The reference wraps external dask-glm solvers in sklearn-style estimators
(reference: linear_model/glm.py:86-325). Same facade here — identical
constructor surface including the ignored-for-compat params, the same
``lamduh = 1/C`` hyperparameter mapping, and the same solver-specific kwarg
pruning (reference: glm.py:114-139) — but the solvers are the jitted SPMD
programs in :mod:`dask_ml_tpu.models.glm`.

Deliberate deviations, documented:

- the intercept is NOT penalized (dask-glm penalizes the appended intercept
  column; unpenalized matches sklearn and the differential test oracle);
- ``LinearRegression.score`` returns R² as its docstring promises (the
  reference's *code* returns MSE — glm.py:270-290 — a known bug we do not
  reproduce).
"""

from __future__ import annotations

import logging

import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator

from dask_ml_tpu.metrics import accuracy_score, r2_score
from dask_ml_tpu.models import glm as core
from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.parallel.sharding import prepare_data, shard_rows, unpad_rows
from dask_ml_tpu.utils.validation import check_array

logger = logging.getLogger(__name__)


def add_intercept(X):
    """Append a ones column (reference: dask-glm ``add_intercept``, used at
    glm.py:165-169). Feature axis is replicated, so sharding is preserved."""
    ones = jnp.ones((X.shape[0], 1), dtype=X.dtype)
    return jnp.concatenate([X, ones], axis=1)


class _GLM(BaseEstimator):
    """Shared GLM facade (reference: linear_model/glm.py:86-177)."""

    family = None  # set by subclasses: 'logistic' | 'normal' | 'poisson'

    def __init__(self, penalty="l2", dual=False, tol=1e-4, C=1.0,
                 fit_intercept=True, intercept_scaling=1.0, class_weight=None,
                 random_state=None, solver="admm", multiclass="ovr",
                 verbose=0, warm_start=False, n_jobs=1, max_iter=100,
                 solver_kwargs=None):
        self.penalty = penalty
        self.dual = dual
        self.tol = tol
        self.C = C
        self.fit_intercept = fit_intercept
        self.intercept_scaling = intercept_scaling
        self.class_weight = class_weight
        self.random_state = random_state
        self.solver = solver
        self.multiclass = multiclass
        self.verbose = verbose
        self.warm_start = warm_start
        self.n_jobs = n_jobs
        self.max_iter = max_iter
        self.solver_kwargs = solver_kwargs

    def _get_solver_kwargs(self):
        """``lamduh = 1/C`` mapping + per-solver pruning
        (reference: glm.py:114-139)."""
        if self.solver not in core.SOLVERS:
            raise ValueError(
                f"'solver' must be {set(core.SOLVERS)}. "
                f"Got '{self.solver}' instead"
            )
        kwargs = {
            "max_iter": self.max_iter,
            "family": self.family,
            "tol": self.tol,
            "regularizer": self.penalty,
            "lamduh": 1.0 / self.C,
        }
        if self.solver in ("gradient_descent", "newton"):
            # These solve the unregularized problem, as in the reference
            # (glm.py:120-122 pops regularizer/lamduh).
            kwargs["lamduh"] = 0.0
            kwargs["regularizer"] = "l2"
        if self.solver == "admm":
            kwargs.pop("tol")  # uses reltol / abstol instead (glm.py:124-126)
        if self.solver_kwargs:
            kwargs.update(self.solver_kwargs)
        return kwargs

    def _encode_y(self, y):
        """Hook for family-specific target validation/encoding."""
        return np.asarray(y)

    def fit(self, X, y=None, sample_weight=None):
        X = check_array(X)
        y = self._encode_y(y)
        mesh = mesh_lib.default_mesh()
        data = prepare_data(X, y=y, sample_weight=sample_weight, mesh=mesh,
                            y_dtype=jnp.float32)
        Xd = add_intercept(data.X) if self.fit_intercept else data.X
        d = int(Xd.shape[1])
        # Penalty mask: exclude the intercept column from regularization.
        mask = np.ones(d, dtype=np.float32)
        if self.fit_intercept:
            mask[-1] = 0.0
        beta0 = jnp.zeros((d,), Xd.dtype)
        kwargs = self._get_solver_kwargs()
        beta, n_iter = core.solve(
            self.solver, Xd, data.y, data.weights, beta0,
            jnp.asarray(mask), mesh=mesh, **kwargs,
        )
        self._coef = np.asarray(beta)
        self.n_iter_ = int(n_iter)
        if self.fit_intercept:
            self.coef_ = self._coef[:-1]
            self.intercept_ = self._coef[-1]
        else:
            self.coef_ = self._coef
        return self

    def _decision_function(self, X):
        """Linear predictor on sharded rows, gathered back to host."""
        X = check_array(X)
        Xs, n = shard_rows(X)
        Xs = add_intercept(Xs) if self.fit_intercept else Xs
        eta = Xs @ jnp.asarray(self._coef, Xs.dtype)
        return np.asarray(unpad_rows(eta, n))


class LogisticRegression(_GLM):
    """Logistic regression (reference: linear_model/glm.py:180-232)."""

    family = "logistic"

    def _encode_y(self, y):
        # The logistic loss needs y ∈ {0, 1}; arbitrary binary labels are
        # encoded like sklearn does (classes_ + positional remap). The
        # reference would silently diverge on e.g. {1, 2} labels — dask-glm
        # feeds y straight into the loss — which we do not reproduce.
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        if len(self.classes_) != 2:
            raise ValueError(
                f"LogisticRegression requires exactly 2 classes, got "
                f"{len(self.classes_)}: {self.classes_!r}"
            )
        return (y == self.classes_[1]).astype(np.float32)

    def decision_function(self, X):
        return self._decision_function(X)

    def predict_proba(self, X):
        # 1-D probability of the positive class, like the reference
        # (glm.py:203-215 returns sigmoid(X·coef), not an (n, 2) matrix).
        from scipy.special import expit

        return expit(self._decision_function(X))

    def predict(self, X):
        mask = self.predict_proba(X) > 0.5
        if hasattr(self, "classes_"):
            return self.classes_[mask.astype(np.int64)]
        return mask

    def score(self, X, y):
        return accuracy_score(np.asarray(y), self.predict(X))


class LinearRegression(_GLM):
    """Linear (Normal-family) regression (reference: glm.py:235-290)."""

    family = "normal"

    def predict(self, X):
        return self._decision_function(X)

    def score(self, X, y):
        return r2_score(np.asarray(y), self.predict(X))


class PoissonRegression(_GLM):
    """Poisson count regression (reference: glm.py:293-325)."""

    family = "poisson"

    def _encode_y(self, y):
        y = np.asarray(y)
        if np.any(y < 0):
            raise ValueError("Poisson regression requires y >= 0")
        return y

    def predict(self, X):
        return np.exp(self._decision_function(X))

    def get_deviance(self, X, y):
        y = np.asarray(y, dtype=np.float64)
        mu = np.asarray(self.predict(X), dtype=np.float64)
        # 2·Σ [y·log(y/mu) − (y − mu)], with the y=0 limit handled
        # (dask-glm ``poisson_deviance`` semantics, used at glm.py:325).
        with np.errstate(divide="ignore", invalid="ignore"):
            term = np.where(y > 0, y * np.log(y / mu), 0.0)
        return float(2.0 * np.sum(term - (y - mu)))
