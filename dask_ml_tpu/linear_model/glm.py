"""sklearn-facade GLM estimators over the native solver suite.

The reference wraps external dask-glm solvers in sklearn-style estimators
(reference: linear_model/glm.py:86-325). Same facade here — identical
constructor surface including the ignored-for-compat params, the same
``lamduh = 1/C`` hyperparameter mapping, and the same solver-specific kwarg
pruning (reference: glm.py:114-139) — but the solvers are the jitted SPMD
programs in :mod:`dask_ml_tpu.models.glm`.

Deliberate deviations, documented:

- the intercept is NOT penalized (dask-glm penalizes the appended intercept
  column; unpenalized matches sklearn and the differential test oracle);
- ``LinearRegression.score`` returns R² as its docstring promises (the
  reference's *code* returns MSE — glm.py:270-290 — a known bug we do not
  reproduce).
"""

from __future__ import annotations

import contextlib
import logging
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator

from dask_ml_tpu.metrics import accuracy_score, r2_score
from dask_ml_tpu.models import glm as core
from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.parallel import precision as precision_lib
from dask_ml_tpu.parallel.sharding import prepare_data, shard_rows, unpad_rows
from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.utils.validation import check_array

logger = logging.getLogger(__name__)


def add_intercept(X):
    """Append a ones column (reference: dask-glm ``add_intercept``, used at
    glm.py:165-169). Feature axis is replicated, so sharding is preserved.
    Sparse containers (docs/sparse.md) append the intercept as one extra
    nonzero slot per row (column index ``d``, value 1) — same linear map,
    same in-trace fusion, dispatched by input type."""
    from dask_ml_tpu.ops import sparse as sparse_ops

    if isinstance(X, sparse_ops.SparseRows):
        return sparse_ops.add_intercept_ell(X)
    ones = jnp.ones((X.shape[0], 1), dtype=X.dtype)
    return jnp.concatenate([X, ones], axis=1)


def _intercept_block(blk):
    """Block-tuple intercept append for host-streamed fits. Module-level so
    the streamed solver's per-block program (which keys its compile cache
    on the transform's identity) compiles once across estimator fits."""
    X_b, y_b, w_b = blk
    return add_intercept(X_b), y_b, w_b


@partial(jax.jit, static_argnames=("intercept",))
def eta_program(Xs, coef, *, intercept: bool):
    """The WHOLE linear predictor as one jitted program over staged rows:
    in-trace intercept append plus the precision-aware contraction
    (operands feed the MXU in the data's wire dtype, accumulation forced
    f32 — for f32 data this is the plain ``X @ coef`` it replaces).

    One program per (bucket, d, coef-shape): both the direct
    ``_decision_function`` path and the serving loop's batch runners
    (:mod:`dask_ml_tpu.parallel.serving`) route through it, which is what
    makes served results structurally bit-identical to direct calls —
    same executable, row-independent math, different padding only.
    """
    if intercept:
        Xs = add_intercept(Xs)
    ct = coef.T if coef.ndim == 2 else coef
    from dask_ml_tpu.ops import sparse as sparse_ops

    if isinstance(Xs, sparse_ops.SparseRows):
        return (sparse_ops.matmat(Xs, ct) if ct.ndim == 2
                else sparse_ops.matvec(Xs, ct))
    return precision_lib.pmatmul(Xs, ct)


def proba_from_eta(eta: np.ndarray, multiclass: str) -> np.ndarray:
    """Host epilogue mapping a fetched linear predictor to probabilities —
    rowwise, so serving can apply it to a padded batch and slice after.
    Binary: 1-D sigmoid of the positive-class score (reference glm.py:
    203-215 semantics). Multiclass: softmax over joint logits for
    'multinomial', per-class sigmoids normalized per row for 'ovr'."""
    from scipy.special import expit

    if eta.ndim == 2 and multiclass == "multinomial":
        z = np.exp(eta - eta.max(axis=1, keepdims=True))
        return z / z.sum(axis=1, keepdims=True)
    scores = expit(eta)
    if scores.ndim == 2:
        denom = np.maximum(scores.sum(axis=1, keepdims=True), 1e-30)
        return scores / denom
    return scores


def labels_from_proba(proba: np.ndarray, classes) -> np.ndarray:
    """Host epilogue mapping probabilities to class labels (rowwise)."""
    if proba.ndim == 2:
        return np.asarray(classes)[np.argmax(proba, axis=1)]
    mask = proba > 0.5
    if classes is not None:
        return np.asarray(classes)[mask.astype(np.int64)]
    return mask


class _GLM(BaseEstimator):
    """Shared GLM facade (reference: linear_model/glm.py:86-177)."""

    family = None  # set by subclasses: 'logistic' | 'normal' | 'poisson'

    #: solvers that optimize the UNREGULARIZED objective, as in the
    #: reference (glm.py:120-122 pops regularizer/lamduh) — the single
    #: definition every path (batch fit, streaming, batched search) reads
    _UNREGULARIZED_SOLVERS = ("gradient_descent", "newton")

    def __init__(self, penalty="l2", dual=False, tol=1e-4, C=1.0,
                 fit_intercept=True, intercept_scaling=1.0, class_weight=None,
                 random_state=None, solver="admm", multiclass="ovr",
                 verbose=0, warm_start=False, n_jobs=1, max_iter=100,
                 solver_kwargs=None, checkpoint=None, checkpoint_every=50):
        self.penalty = penalty
        self.dual = dual
        self.tol = tol
        self.C = C
        self.fit_intercept = fit_intercept
        self.intercept_scaling = intercept_scaling
        self.class_weight = class_weight
        self.random_state = random_state
        self.solver = solver
        self.multiclass = multiclass
        self.verbose = verbose
        self.warm_start = warm_start
        self.n_jobs = n_jobs
        self.max_iter = max_iter
        self.solver_kwargs = solver_kwargs
        # checkpoint: snapshot path PREFIX making fit() resumable in chunks
        # of checkpoint_every device iterations; each distinct fit problem
        # writes its own fingerprint-suffixed snapshot file (SURVEY §5.4;
        # see dask_ml_tpu.checkpoint.solve_checkpointed)
        self.checkpoint = checkpoint
        self.checkpoint_every = checkpoint_every

    def _get_solver_kwargs(self):
        """``lamduh = 1/C`` mapping + per-solver pruning
        (reference: glm.py:114-139)."""
        if self.solver not in core.SOLVERS:
            raise ValueError(
                f"'solver' must be {set(core.SOLVERS)}. "
                f"Got '{self.solver}' instead"
            )
        kwargs = {
            "max_iter": self.max_iter,
            "family": self.family,
            "tol": self.tol,
            "regularizer": self.penalty,
            "lamduh": 1.0 / self.C,
        }
        if self.solver in self._UNREGULARIZED_SOLVERS:
            # These solve the unregularized problem, as in the reference
            # (glm.py:120-122 pops regularizer/lamduh).
            kwargs["lamduh"] = 0.0
            kwargs["regularizer"] = "l2"
        if self.solver == "admm":
            kwargs.pop("tol")  # uses reltol / abstol instead (glm.py:124-126)
        if self.solver_kwargs:
            kwargs.update(self.solver_kwargs)
        return kwargs

    def _encode_y(self, y):
        """Hook for family-specific target validation/encoding."""
        return np.asarray(y)

    def fit(self, X, y=None, sample_weight=None):
        self._pf_state = None  # batch fit discards any streaming state
        self._pf_classes = None
        X = check_array(X, accept_sparse=True)
        y = self._encode_y(y)
        mesh = mesh_lib.default_mesh()
        from dask_ml_tpu.parallel.sharding import is_sparse_input

        sparse_in = is_sparse_input(X)
        # Feature-axis tensor parallelism (SURVEY §2.9): on a 2-D
        # ('data', 'model') mesh the jit-compiled solvers shard X over BOTH
        # axes — XLA partitions the O(n·d²) Hessian/Gram matmuls and their
        # (d, d) outputs over the model axis, inserting the d-axis psums
        # itself. ADMM is excluded: its shard_map program keeps per-shard
        # d-vectors, a layout that is data-parallel by construction.
        # Sparse inputs are excluded too: the sparse tier is
        # sample-parallel (the container shards rows like dense data; the
        # coefficient axis replicates).
        tensor_parallel = (
            mesh_lib.n_model_shards(mesh) > 1 and self.solver != "admm"
            and not sparse_in
        )
        if tensor_parallel:
            # the intercept joins as a TRUE column (before feature padding)
            # inside prepare_data, keeping the staging memo keyed on the
            # caller's X so search cells share one staged copy per CV slice
            data = prepare_data(X, y=y, sample_weight=sample_weight,
                                mesh=mesh, y_dtype=jnp.float32,
                                shard_features=True,
                                append_ones=self.fit_intercept)
            Xd = data.X
            d = int(Xd.shape[1])  # padded width
            d_true = data.n_features
            n_feat = d_true - 1 if self.fit_intercept else d_true
            # penalize only the real feature columns (not intercept, not
            # zero padding — padded coords stay 0 under the ridge/prox)
            mask = np.zeros(d, dtype=np.float32)
            mask[:n_feat] = 1.0
        else:
            data = prepare_data(X, y=y, sample_weight=sample_weight,
                                mesh=mesh, y_dtype=jnp.float32)
            Xd = add_intercept(data.X) if self.fit_intercept else data.X
            d = d_true = int(Xd.shape[1])
            # Penalty mask: exclude the intercept column from regularization.
            mask = np.ones(d, dtype=np.float32)
            if self.fit_intercept:
                mask[-1] = 0.0
        beta0 = jnp.zeros((d,), Xd.dtype)
        kwargs = self._get_solver_kwargs()

        def solve_one(y_dev):
            if self.checkpoint:
                from dask_ml_tpu.checkpoint import (problem_fingerprint,
                                                    solve_checkpointed)

                ck_kwargs = dict(kwargs)
                ck_max_iter = ck_kwargs.pop("max_iter")
                # ``checkpoint`` is a PATH PREFIX: each distinct fit problem
                # (data content + hyperparameters — including each OVR
                # class's targets) snapshots to its own fingerprint-suffixed
                # file, so a second fit on different data — e.g. a
                # checkpointed estimator inside a CV search, where every
                # (candidate, split) cell stages a different slice — resumes
                # ITS OWN snapshot instead of erroring on a fingerprint
                # mismatch (ADVICE r3).
                # max_iter stays OUT of the fingerprint (as in
                # solve_checkpointed itself): re-fitting with a larger
                # budget must resume the same snapshot, not start a new one
                fp = problem_fingerprint(
                    self.solver, Xd, y_dev, data.weights, beta0,
                    jnp.asarray(mask), **ck_kwargs)
                ck_path = f"{self.checkpoint}.{fp[:16]}"
                # migration: a snapshot written AT the bare configured path
                # (pre-suffix versions) whose stored fingerprint matches this
                # problem keeps being used — an interrupted long fit must not
                # silently restart from zero after an upgrade. The loaded
                # snapshot is passed through so the (possibly large) carry
                # is not deserialized a second time inside solve_checkpointed.
                preloaded = None
                if not os.path.exists(ck_path) and os.path.isfile(
                        self.checkpoint):
                    from dask_ml_tpu.checkpoint import load_pytree

                    bare = load_pytree(self.checkpoint)
                    if bare is not None and bare[1].get("fingerprint") == fp:
                        ck_path = self.checkpoint
                        preloaded = bare
                return solve_checkpointed(
                    self.solver, Xd, y_dev, data.weights, beta0,
                    jnp.asarray(mask), mesh, path=ck_path,
                    chunk_iters=int(self.checkpoint_every),
                    max_iter=ck_max_iter, fingerprint=fp,
                    preloaded_snapshot=preloaded, **ck_kwargs,
                )
            return core.solve(
                self.solver, Xd, y_dev, data.weights, beta0,
                jnp.asarray(mask), mesh=mesh, **kwargs,
            )

        from dask_ml_tpu.ops import sparse as sparse_ops
        from dask_ml_tpu.parallel import hierarchy as hier

        with telemetry.span(f"glm-{self.solver}", logger=logger), \
                (sparse_ops.metered(mesh) if sparse_in
                 else contextlib.nullcontext()), \
                (hier.model_metered(mesh) if tensor_parallel
                 else contextlib.nullcontext()):
            # the metered scopes make the cross-shard collectives record
            # per-axis bytes into the hierarchy ledger AT TRACE TIME —
            # sparse contractions (pullback/Gram reductions) under
            # sparse_ops.metered; feature-sharded fits' GSPMD-implicit
            # model-axis collectives (matvec/pullback/Gram seams) under
            # hier.model_metered. Cache hits record nothing, preserving
            # the per-trace semantics docs/scale-out.md pins (zero steady
            # state compiles <=> zero ledger growth)
            results = [solve_one(y_dev) for y_dev in self._solve_targets(data)]
        betas = [np.asarray(b)[:d_true] for b, _ in results]  # drop padding
        self.n_iter_ = int(max(int(n) for _, n in results))
        self._finalize_coef(betas)
        return self

    def _solve_targets(self, data):
        """Device target vectors, one solver run each. The base GLM solves a
        single problem; multiclass OVR (LogisticRegression) overrides."""
        return [data.y]

    def _finalize_coef(self, betas):
        self._coef = betas[0]
        if self.fit_intercept:
            self.coef_ = self._coef[:-1]
            self.intercept_ = self._coef[-1]
        else:
            self.coef_ = self._coef

    def _decision_function(self, X):
        """Linear predictor on sharded rows, gathered back to host.
        ``_coef`` is 1-D for a single problem, (n_classes, width) for OVR —
        the latter yields an (n, n_classes) score matrix, like sklearn.

        Staged on the precision wire into the active shape bucket and run
        through the shared :func:`eta_program`, then sliced HOST-side: a
        repeat predict whose n lands in a warm bucket compiles NOTHING
        (the per-request contract the serving loop builds on; pinned by
        ``tests/test_serving.py::test_direct_predict_zero_compiles``)."""
        X = check_array(X, accept_sparse=True)
        Xs, n = shard_rows(X, dtype=precision_lib.staging_wire_dtype())
        eta = eta_program(Xs, jnp.asarray(self._coef, jnp.float32),
                          intercept=bool(self.fit_intercept))
        return np.asarray(eta)[:n]

    # -- larger-than-HBM block streaming ----------------------------------

    def fit_blocks(self, block_fn, n_blocks, n_samples, n_features,
                   classes=None, sw_total=None, elastic=None):
        """Fit from streamed row blocks — data larger than device memory.

        ``block_fn(b) -> (X_b, y_b, w_b)`` is a TRACED function producing
        block ``b`` on device (regenerate from a seed, or slice a resident
        array), or a :class:`dask_ml_tpu.parallel.stream.HostBlockSource`
        streaming real host-resident blocks through the double-buffered
        transfer pipeline: either way one block is resident at a time
        inside the solver (models/glm.py ``admm_streamed``), and the two
        modes take the same trajectory (shared per-block programs).
        ``y_b`` must already be numeric
        — {0,1} for logistic (pass ``classes`` to fix ``classes_``), raw
        targets for linear/poisson. Requires ``solver='admm'``, the
        streamed consensus solver; blocks must NOT include an intercept
        column (it is appended in-trace when ``fit_intercept``).

        ``sw_total`` is the total sample weight over ALL blocks; it
        defaults to ``n_samples``, which is only correct for UNIT weights —
        pass it explicitly when block weights are not all 1 (the solver
        normalizes its objective by 1/SW, so a wrong total mis-scales the
        effective regularization).

        The blueprint-scale bench fits 1e8×100 (40 GB of f32) this way on
        one 16 GB chip.

        With ``checkpoint=`` (HostBlockSource mode only) the streamed fit
        is preemption-safe: snapshots every ``checkpoint_every`` blocks, a
        SIGTERM/SIGINT drains gracefully (raising
        :class:`~dask_ml_tpu.parallel.faults.Preempted` after saving), and
        re-calling ``fit_blocks`` with the same path resumes from the last
        complete block with a bit-identical trajectory. Pair the source
        with a :class:`~dask_ml_tpu.parallel.faults.RetryPolicy` to also
        survive transient loader/transfer failures (docs/robustness.md).

        ``elastic`` (an :class:`~dask_ml_tpu.parallel.elastic.ElasticRun`,
        HostBlockSource mode only) spans the fit over a fleet of
        processes with seeded epoch shuffling and survivor rebalancing
        on host loss — every participating process calls ``fit_blocks``
        with its own source over the SAME global block space and the
        shared run; results are bit-identical to the single-host fit
        (docs/robustness.md "Elastic epochs").
        """
        if self.solver != "admm":
            raise ValueError(
                "fit_blocks streams through consensus ADMM; construct the "
                "estimator with solver='admm'"
            )
        self._pf_state = None  # block fit discards any streaming state
        self._pf_classes = None
        kwargs = self._get_solver_kwargs()
        kwargs.pop("family", None)
        d = int(n_features) + (1 if self.fit_intercept else 0)
        mask = np.ones(d, dtype=np.float32)
        if self.fit_intercept:
            mask[-1] = 0.0

        from dask_ml_tpu.parallel.stream import HostBlockSource

        # checkpoint: the streamed solver's preemption-safe snapshot path
        # (SIGTERM-driven graceful drain + resume from the last complete
        # block; docs/robustness.md). checkpoint_every is re-used as the
        # snapshot interval in BLOCKS here (it counts device iterations in
        # the in-memory fit() path — both mean "work between snapshots").
        ck = {}
        if self.checkpoint:
            if not isinstance(block_fn, HostBlockSource):
                raise ValueError(
                    "checkpoint= on fit_blocks requires a HostBlockSource "
                    "block source (a traced block_fn runs each epoch as one "
                    "compiled program; chunk it via models.glm.admm_streamed"
                    "'s state/return_state carry instead)"
                )
            ck = dict(checkpoint_path=f"{self.checkpoint}.stream",
                      checkpoint_every=int(self.checkpoint_every))

        if not self.fit_intercept:
            wrapped = block_fn
        elif isinstance(block_fn, HostBlockSource):
            # the intercept append rides INSIDE the per-block compiled
            # program (stable module-level transform identity keeps the
            # compile cache warm across fits)
            wrapped = block_fn.with_transform(_intercept_block)
        else:
            def wrapped(b):
                X_b, y_b, w_b = block_fn(b)
                return add_intercept(X_b), y_b, w_b

        try:
            with telemetry.span("glm-admm-streamed", logger=logger,
                    blocks=int(n_blocks)):
                beta, n_iter = core.admm_streamed(
                    wrapped, int(n_blocks), d,
                    float(n_samples if sw_total is None else sw_total),
                    jnp.asarray(mask), family=self.family, elastic=elastic,
                    **ck, **kwargs)
        finally:
            if wrapped is not block_fn and isinstance(wrapped,
                                                      HostBlockSource):
                # surface transfer accounting on the CALLER's source (the
                # intercept wrap is a stats-reset copy)
                block_fn.bytes_streamed += wrapped.bytes_streamed
                block_fn.logical_bytes_streamed += \
                    wrapped.logical_bytes_streamed
                block_fn.blocks_started += wrapped.blocks_started
        self.n_iter_ = int(n_iter)
        self._finalize_coef([np.asarray(beta)])
        if classes is not None:
            self.classes_ = np.asarray(classes)
        elif self.family == "logistic":
            self.classes_ = np.array([0, 1])
        return self

    # -- streaming / incremental training --------------------------------
    #
    # The reference reaches streaming GLMs through the deprecated Partial*
    # wrappers + the Incremental chain (reference: _partial.py:104-182,
    # stochastic_gradient.py:7-15). Here the estimator itself implements
    # partial_fit (one proximal-SGD step per block), and exposes the
    # functional hooks Incremental uses to fuse the whole block chain into a
    # single lax.scan (wrappers.incremental_scan).

    def _encode_y_partial(self, y, classes=None):
        return self._encode_y(y)

    def _sgd_config(self):
        sk = dict(self.solver_kwargs or {})
        regularizer, lamduh = self.penalty, 1.0 / self.C
        if self.solver in self._UNREGULARIZED_SOLVERS:
            # these solvers optimize the unregularized objective in fit()
            # (reference: glm.py:120-122); streaming must match, or
            # fit/partial_fit on the same estimator solve different problems
            regularizer, lamduh = "l2", 0.0
        return dict(
            family=self.family,
            regularizer=regularizer,
            lamduh=lamduh,
            eta0=float(sk.get("eta0", 0.1)),
            power_t=float(sk.get("power_t", 0.5)),
            fit_intercept=bool(self.fit_intercept),
        )

    def _pf_width(self, n_features: int) -> int:
        return n_features + 1 if self.fit_intercept else n_features

    def _pf_coef_shape(self, width: int) -> tuple:
        """Streaming-state coefficient shape: (width,) for a single
        problem; LogisticRegression widens to (width, K) for softmax
        streaming."""
        return (width,)

    def _pf_state_device(self, n_features: int):
        state = getattr(self, "_pf_state", None)
        if state is None:
            width = self._pf_width(n_features)
            shape = self._pf_coef_shape(width)
            coef = getattr(self, "_coef", None)
            if coef is not None:
                # warm-start streaming from a batch-fitted solution, the
                # sklearn partial_fit contract (continue, don't reset);
                # multiclass _coef is stored (K, width) — the stream state
                # carries its transpose
                if len(shape) == 1 and coef.shape == shape:
                    return (jnp.asarray(coef, jnp.float32),
                            jnp.asarray(0.0, jnp.float32))
                if len(shape) == 2 and coef.shape == (shape[1], shape[0]):
                    return (jnp.asarray(coef.T, jnp.float32),
                            jnp.asarray(0.0, jnp.float32))
            return (jnp.zeros(shape, jnp.float32),
                    jnp.asarray(0.0, jnp.float32))
        beta, t = state
        if beta.shape != self._pf_coef_shape(self._pf_width(n_features)):
            raise ValueError(
                f"partial_fit block has {n_features} features but the "
                f"running state was built for coefficient shape "
                f"{beta.shape}"
            )
        return jnp.asarray(beta, jnp.float32), jnp.asarray(t, jnp.float32)

    def _store_pf_state(self, state):
        beta = np.asarray(state[0])
        self._pf_state = (beta, float(state[1]))
        if beta.ndim == 2:
            self._coef = beta.T  # (K, width), the OVR/multinomial layout
            if self.fit_intercept:
                self.coef_ = self._coef[:, :-1]
                self.intercept_ = self._coef[:, -1]
            else:
                self.coef_ = self._coef
        else:
            self._coef = beta
            if self.fit_intercept:
                self.coef_ = beta[:-1]
                self.intercept_ = beta[-1]
            else:
                self.coef_ = beta
        self.n_iter_ = int(float(state[1]))

    def partial_fit(self, X, y=None, classes=None, sample_weight=None):
        """One proximal-SGD step on this block; resumable across calls."""
        X = check_array(X, accept_sparse=True)
        y_enc = self._encode_y_partial(y, classes)
        state = self._pf_state_device(int(X.shape[1]))
        _, apply_one = core.get_stream_step(**self._sgd_config())
        data = prepare_data(X, y=y_enc, sample_weight=sample_weight,
                            y_dtype=jnp.float32)
        state = apply_one(state, data.X, data.y, data.weights)
        self._store_pf_state(state)
        return self

    def _incremental_begin(self, X, y, classes=None):
        """Hook for :class:`dask_ml_tpu.wrappers.Incremental`'s fused-scan
        path: returns ``(step_fn, init_state, y_encoded)``."""
        y_enc = self._encode_y_partial(y, classes)
        step, _ = core.get_stream_step(**self._sgd_config())
        state = self._pf_state_device(int(X.shape[1]))
        return step, state, y_enc

    def _incremental_finalize(self, state):
        self._store_pf_state(state)
        return self

    # -- batched-candidate protocol (search driver fast path) -------------
    #
    # A C grid over one GLM is the same problem at different regularization
    # strengths: the driver's batched path solves the whole grid as ONE
    # vmapped program and scores every member in one pass + one fetch
    # (models/glm.py batched_glm_path; SURVEY §2.9 task-parallelism row).

    _batchable_params = frozenset({"C"})

    def _supports_batched(self, static_params) -> bool:
        """Pure-jit solvers only (ADMM keeps per-shard state in shard_map);
        plain 1-D data staging (no feature sharding) and no estimator-level
        solver_kwargs/checkpoint plumbing, whose per-member interactions
        the batched program does not model."""
        solver = static_params.get("solver", self.solver)
        if solver not in ("lbfgs", "proximal_grad", "newton",
                          "gradient_descent"):
            return False
        if static_params.get("solver_kwargs", self.solver_kwargs):
            return False
        if static_params.get("checkpoint", self.checkpoint):
            return False
        return self.family in ("logistic", "normal")

    def _member_lamduh(self, member):
        if self.solver in self._UNREGULARIZED_SOLVERS:
            # C never reaches these solvers (see _UNREGULARIZED_SOLVERS)
            return 0.0
        return 1.0 / float(member.get("C", self.C))

    def _batchable_member_ok(self, member_params, n_train_min) -> bool:
        """C=0 / non-finite C can't form a lamduh — such members run
        per-cell so only THEY fail under error_score, not their group.
        Resolves solver from the MERGED params (a grid can override it),
        like _supports_batched — reading self.solver would admit a C=0
        member planned against an unregularized default solver and poison
        the group at runtime."""
        if member_params.get(
                "solver", self.solver) in self._UNREGULARIZED_SOLVERS:
            return True
        try:
            c = float(member_params.get("C", self.C))
        except (TypeError, ValueError):
            return False
        return np.isfinite(c) and c != 0.0

    def _encode_eval_y(self, y):
        if self.family == "logistic":
            # labels OUTSIDE the train fold's class set encode to -1: a
            # {0,1} prediction never matches them, exactly as the
            # per-cell accuracy on raw labels counts them wrong (a plain
            # positive-class test would silently score them as negative
            # HITS when the model predicts the negative class)
            ye = np.asarray(y)
            return np.where(
                ye == self.classes_[1], np.float32(1.0),
                np.where(ye == self.classes_[0], np.float32(0.0),
                         np.float32(-1.0))).astype(np.float32)
        return np.asarray(y, dtype=np.float32)

    def _batched_fit_score(self, X, y, members, eval_sets):
        """One vmapped solve over the members' lamduh values + bulk scoring
        (accuracy / R², matching ``score``). Declines (NotImplemented) on
        meshes with a model axis and on multiclass targets — those run
        per-cell with identical results."""
        mesh = mesh_lib.default_mesh()
        if mesh_lib.n_model_shards(mesh) > 1:
            return NotImplemented
        y_enc = self._encode_y(y)
        if getattr(self, "classes_", None) is not None and len(
                self.classes_) > 2:
            return NotImplemented

        def prep(Xa, ya):
            import jax

            Xin = Xa if isinstance(Xa, jax.Array) else check_array(
                Xa, accept_sparse=True)
            return prepare_data(Xin, y=ya, mesh=mesh, y_dtype=jnp.float32)

        data = prep(X, y_enc)
        Xd = add_intercept(data.X) if self.fit_intercept else data.X
        d = int(Xd.shape[1])
        mask = np.ones(d, dtype=np.float32)
        if self.fit_intercept:
            mask[-1] = 0.0
        beta0 = jnp.zeros((d,), Xd.dtype)
        kwargs = self._get_solver_kwargs()
        lam = jnp.asarray([self._member_lamduh(m) for m in members],
                          jnp.float32)
        betas, n_iters = core.batched_glm_path(
            Xd, data.y, data.weights, beta0, jnp.asarray(mask), lam,
            solver=self.solver, family=kwargs["family"],
            regularizer=kwargs["regularizer"],
            max_iter=int(kwargs["max_iter"]), tol=kwargs["tol"])
        scores = []
        for E, y_e in eval_sets:
            ed = prep(E, self._encode_eval_y(y_e))
            Ed = add_intercept(ed.X) if self.fit_intercept else ed.X
            scores.append(core.batched_eval_scores(
                Ed, ed.y, ed.weights, betas, family=self.family))
        return {"n_iter": n_iters, "scores": scores}


class LogisticRegression(_GLM):
    """Logistic regression (reference: linear_model/glm.py:180-232).

    Multiclass (parity-plus — dask-glm is binary-only, so the reference's
    ``multiclass="ovr"`` constructor param never did anything): with > 2
    classes, ``multiclass="ovr"`` fits one binary problem per class against
    the SAME staged data (the class-indicator targets are built on device,
    so X uploads once) with sigmoid-normalized ``predict_proba``;
    ``multiclass="multinomial"`` fits ONE softmax cross-entropy problem
    over the (d, K) coefficient matrix with softmax ``predict_proba`` —
    by on-device L-BFGS (models/glm.py ``multinomial_lbfgs``), or by
    matrix-valued consensus ADMM when ``solver="admm"``
    (``admm_multinomial``). Either way
    ``coef_`` is (n_classes, n_features) and ``decision_function`` returns
    (n, n_classes). Binary fits keep the reference's exact surface (1-D
    ``coef_``, 1-D ``predict_proba``). Other ``multiclass`` values are
    rejected loudly.
    """

    family = "logistic"

    def _encode_y(self, y):
        pre = getattr(self, "_precomputed_y_enc", None)
        if pre is not None:
            return pre  # fit() already encoded this exact target
        # The logistic loss needs y ∈ {0, 1}; arbitrary binary labels are
        # encoded like sklearn does (classes_ + positional remap). The
        # reference would silently diverge on e.g. {1, 2} labels — dask-glm
        # feeds y straight into the loss — which we do not reproduce.
        if self.multiclass not in ("ovr", "multinomial"):
            raise ValueError(
                f"multiclass must be 'ovr' or 'multinomial', got "
                f"{self.multiclass!r}"
            )
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        if len(self.classes_) < 2:
            raise ValueError(
                f"LogisticRegression requires at least 2 classes, got "
                f"{len(self.classes_)}: {self.classes_!r}"
            )
        if len(self.classes_) == 2:
            return (y == self.classes_[1]).astype(np.float32)
        # multiclass: stage CLASS INDICES once; per-class {0,1} indicator
        # targets are derived on device in _solve_targets
        idx = np.searchsorted(self.classes_, y)
        return idx.astype(np.float32)

    def fit(self, X, y=None, sample_weight=None):
        if self.multiclass == "multinomial" and y is not None:
            idx = self._encode_y(y)  # one unique pass; sets classes_
            if len(self.classes_) > 2:
                return self._fit_multinomial(X, idx, sample_weight)
            # binary fallback: hand the encoding we just computed to the
            # base fit so y is not re-scanned
            self._precomputed_y_enc = idx
            try:
                return super().fit(X, y, sample_weight=sample_weight)
            finally:
                self._precomputed_y_enc = None
        return super().fit(X, y, sample_weight=sample_weight)

    def _fit_multinomial(self, X, idx, sample_weight=None):
        """One softmax problem over all classes (see class docstring):
        on-device L-BFGS for the smooth solvers, matrix-valued consensus
        ADMM for ``solver='admm'`` (models/glm.py ``admm_multinomial``).
        ``idx`` is the already-encoded class-index vector from fit()."""
        # the SAME validation + objective contract as every other fit path:
        # unknown solvers raise, unregularized solvers keep lamduh=0, and
        # solver_kwargs overrides apply (the minimizer is L-BFGS for every
        # smooth solver name and consensus ADMM for 'admm'; the OBJECTIVE
        # follows the estimator's configuration either way)
        kwargs = self._get_solver_kwargs()
        self._pf_state = None
        self._pf_classes = None
        X = check_array(X, accept_sparse=True)
        from dask_ml_tpu.parallel.sharding import is_sparse_input

        if is_sparse_input(X) and self.solver == "admm":
            raise ValueError(
                "multinomial ADMM does not support sparse inputs: its "
                "local Newton builds the (dK x dK) Hessian from dense "
                "rows. Use solver='lbfgs' (the softmax objective routes "
                "through the sparse gather-matmat kernels), or "
                "multiclass='ovr'")
        K = len(self.classes_)
        data = prepare_data(X, y=idx, sample_weight=sample_weight,
                            y_dtype=jnp.float32)
        Xd = add_intercept(data.X) if self.fit_intercept else data.X
        d = int(Xd.shape[1])
        mask = np.ones(d, dtype=np.float32)
        if self.fit_intercept:
            mask[-1] = 0.0
        B0 = jnp.zeros((d, K), jnp.float32)
        use_admm = self.solver == "admm"
        if use_admm:
            solver_name = "admm_multinomial"
            mesh = mesh_lib.default_mesh()
            mn_kwargs = dict(
                n_classes=K, regularizer=kwargs["regularizer"],
                lamduh=kwargs["lamduh"])
            # admm's extra knobs (rho, abstol, ...) from solver_kwargs
            mn_kwargs.update({k: v for k, v in kwargs.items()
                              if k not in ("max_iter", "family",
                                           "regularizer", "lamduh")})
        else:
            solver_name = "multinomial_lbfgs"
            mesh = None
            mn_kwargs = dict(
                n_classes=K, regularizer=kwargs["regularizer"],
                lamduh=kwargs["lamduh"], tol=kwargs.get("tol", self.tol))
        with telemetry.span(f"glm-{solver_name}", logger=logger):
            if self.checkpoint:
                # same per-problem fingerprint-suffixed snapshot scheme as
                # the binary solvers in fit() (SURVEY §5.4): the softmax
                # L-BFGS / consensus-ADMM carries round-trip via
                # solve_checkpointed's pseudo-solver branches
                from dask_ml_tpu.checkpoint import (problem_fingerprint,
                                                    solve_checkpointed)

                fp = problem_fingerprint(
                    solver_name, Xd, data.y, data.weights, B0,
                    jnp.asarray(mask), **mn_kwargs)
                B, n_iter = solve_checkpointed(
                    solver_name, Xd, data.y, data.weights, B0,
                    jnp.asarray(mask), mesh,
                    path=f"{self.checkpoint}.{fp[:16]}",
                    chunk_iters=int(self.checkpoint_every),
                    max_iter=int(kwargs["max_iter"]), fingerprint=fp,
                    **mn_kwargs)
            elif use_admm:
                B, n_iter = core.admm_multinomial(
                    Xd, data.y, data.weights, B0, jnp.asarray(mask),
                    mesh, max_iter=int(kwargs["max_iter"]), **mn_kwargs)
            else:
                B, n_iter = core.multinomial_lbfgs(
                    Xd, data.y, data.weights, B0, jnp.asarray(mask),
                    max_iter=int(kwargs["max_iter"]), **mn_kwargs)
        self._coef = np.asarray(B).T  # (K, width), the OVR layout
        self.n_iter_ = int(n_iter)
        self.coef_ = (self._coef[:, :-1] if self.fit_intercept
                      else self._coef)
        if self.fit_intercept:
            self.intercept_ = self._coef[:, -1]
        return self

    def _solve_targets(self, data):
        k = len(self.classes_)
        if k == 2:
            return [data.y]
        # OVR: the indicator for class c is a device-side comparison on the
        # staged index vector — X and y upload once for all k solves
        return [(data.y == float(c)).astype(jnp.float32) for c in range(k)]

    def _finalize_coef(self, betas):
        if len(betas) == 1:
            return super()._finalize_coef(betas)
        self._coef = np.stack(betas)  # (n_classes, width)
        if self.fit_intercept:
            self.coef_ = self._coef[:, :-1]
            self.intercept_ = self._coef[:, -1]
        else:
            self.coef_ = self._coef

    def _encode_y_partial(self, y, classes=None):
        # Streaming blocks may not contain every class; the class set is
        # pinned on the first call (explicitly via ``classes=`` — the same
        # requirement the reference's Partial* wrappers declare,
        # stochastic_gradient.py:7-15 — or inferred from the first block).
        y = np.asarray(y)
        if classes is not None:
            classes = np.asarray(classes)
            prior = getattr(self, "_pf_classes", None)
            if prior is not None and not np.array_equal(classes, prior):
                raise ValueError(
                    f"classes={classes!r} changed between partial_fit calls "
                    f"(was {prior!r})"
                )
            self._pf_classes = classes
        if getattr(self, "_pf_classes", None) is None:
            # warm-starting a batch-fitted model: its class set carries
            # over — inferring from one block would silently SHRINK
            # classes_ (and reset the coefficients) when the block
            # happens to miss a class
            fitted = getattr(self, "classes_", None)
            self._pf_classes = (np.asarray(fitted) if fitted is not None
                                else np.unique(y))
        k = len(self._pf_classes)
        if k < 2:
            raise ValueError(
                f"streaming partial_fit requires at least 2 classes, got "
                f"{k}: {self._pf_classes!r} (pass classes= on the first "
                "call when the first block can't show them all)"
            )
        if k > 2 and self.multiclass != "multinomial":
            raise ValueError(
                f"streaming partial_fit with {k} classes trains the "
                "softmax (multinomial) objective; construct the estimator "
                "with multiclass='multinomial' (per-class OVR streaming "
                "is not provided — use batch fit for OVR)"
            )
        self.classes_ = self._pf_classes
        if not np.isin(y, self._pf_classes).all():
            raise ValueError("y contains labels outside `classes`")
        if k == 2:
            return (y == self.classes_[1]).astype(np.float32)
        # class-index encoding robust to an unsorted explicit classes=
        idx = np.argmax(
            y[:, None] == np.asarray(self._pf_classes)[None, :], axis=1)
        return idx.astype(np.float32)

    def _sgd_config(self):
        cfg = super()._sgd_config()
        pf = getattr(self, "_pf_classes", None)
        if pf is not None and len(pf) > 2:
            cfg["n_classes"] = len(pf)
        return cfg

    def _pf_coef_shape(self, width: int) -> tuple:
        pf = getattr(self, "_pf_classes", None)
        if pf is not None and len(pf) > 2:
            return (width, len(pf))
        return (width,)

    def decision_function(self, X):
        return self._decision_function(X)

    def predict_proba(self, X):
        # Binary: 1-D probability of the positive class, like the reference
        # (glm.py:203-215 returns sigmoid(X·coef), not an (n, 2) matrix).
        # Multiclass: softmax over the joint logits for 'multinomial';
        # per-class sigmoids normalized per row for 'ovr' (sklearn's
        # OneVsRestClassifier semantics). The eta→proba map lives in the
        # module-level ``proba_from_eta`` so the serving runners share it
        # bit-for-bit.
        return proba_from_eta(self._decision_function(X), self.multiclass)

    def predict(self, X):
        return labels_from_proba(self.predict_proba(X),
                                 getattr(self, "classes_", None))

    def score(self, X, y):
        return accuracy_score(np.asarray(y), self.predict(X))


class LinearRegression(_GLM):
    """Linear (Normal-family) regression (reference: glm.py:235-290)."""

    family = "normal"

    def predict(self, X):
        return self._decision_function(X)

    def score(self, X, y):
        return r2_score(np.asarray(y), self.predict(X))


class PoissonRegression(_GLM):
    """Poisson count regression (reference: glm.py:293-325)."""

    family = "poisson"

    def _encode_y(self, y):
        y = np.asarray(y)
        if np.any(y < 0):
            raise ValueError("Poisson regression requires y >= 0")
        return y

    def predict(self, X):
        return np.exp(self._decision_function(X))

    def get_deviance(self, X, y):
        y = np.asarray(y, dtype=np.float64)
        mu = np.asarray(self.predict(X), dtype=np.float64)
        # 2·Σ [y·log(y/mu) − (y − mu)], with the y=0 limit handled
        # (dask-glm ``poisson_deviance`` semantics, used at glm.py:325).
        with np.errstate(divide="ignore", invalid="ignore"):
            term = np.where(y > 0, y * np.log(y / mu), 0.0)
        return float(2.0 * np.sum(term - (y - mu)))
