"""Deprecated Partial SGD wrappers
(reference: linear_model/stochastic_gradient.py:7-15)."""

from __future__ import annotations

from sklearn.linear_model import SGDClassifier as _SGDClassifier
from sklearn.linear_model import SGDRegressor as _SGDRegressor

from dask_ml_tpu._partial import _BigPartialFitMixin, _copy_partial_doc


@_copy_partial_doc
class PartialSGDClassifier(_BigPartialFitMixin, _SGDClassifier):
    _init_kwargs = ["classes"]
    _fit_kwargs = []


@_copy_partial_doc
class PartialSGDRegressor(_BigPartialFitMixin, _SGDRegressor):
    pass
