"""Generalized Linear Models with native TPU solvers
(reference: linear_model/glm.py; solver suite reference: SURVEY §2.4)."""

from dask_ml_tpu.linear_model.glm import (  # noqa: F401
    LinearRegression,
    LogisticRegression,
    PoissonRegression,
)
from dask_ml_tpu.linear_model.passive_aggressive import (  # noqa: F401
    PartialPassiveAggressiveClassifier,
    PartialPassiveAggressiveRegressor,
)
from dask_ml_tpu.linear_model.perceptron import PartialPerceptron  # noqa: F401
from dask_ml_tpu.linear_model.stochastic_gradient import (  # noqa: F401
    PartialSGDClassifier,
    PartialSGDRegressor,
)

__all__ = [
    "LogisticRegression",
    "LinearRegression",
    "PoissonRegression",
    "PartialSGDClassifier",
    "PartialSGDRegressor",
    "PartialPerceptron",
    "PartialPassiveAggressiveClassifier",
    "PartialPassiveAggressiveRegressor",
]
