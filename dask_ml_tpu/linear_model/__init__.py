"""Generalized Linear Models with native TPU solvers
(reference: linear_model/glm.py; solver suite reference: SURVEY §2.4)."""

from dask_ml_tpu.linear_model.glm import (  # noqa: F401
    LinearRegression,
    LogisticRegression,
    PoissonRegression,
)

__all__ = ["LogisticRegression", "LinearRegression", "PoissonRegression"]
