"""Deprecated Partial PassiveAggressive wrappers
(reference: passive_aggressive.py:7-15)."""

from __future__ import annotations

from sklearn.linear_model import (
    PassiveAggressiveClassifier as _PAClassifier,
)
from sklearn.linear_model import (
    PassiveAggressiveRegressor as _PARegressor,
)

from dask_ml_tpu._partial import _BigPartialFitMixin, _copy_partial_doc


@_copy_partial_doc
class PartialPassiveAggressiveClassifier(_BigPartialFitMixin, _PAClassifier):
    _init_kwargs = ["classes"]
    _fit_kwargs = []


@_copy_partial_doc
class PartialPassiveAggressiveRegressor(_BigPartialFitMixin, _PARegressor):
    pass
