"""Gaussian Naive Bayes on sharded data (reference: naive_bayes.py:25-120).

The reference computes per-class delayed means/vars with one task per class
(naive_bayes.py:43-52). Here all K classes' weighted moments come out of ONE
jitted program: a one-hot class-membership matmul against X and X² (the same
MXU segment-sum pattern as the KMeans M-step), with the cross-shard
reduction an automatic psum over the contraction of the sharded sample axis.
The joint log-likelihood is a single fused program as well.

Variance smoothing: sklearn adds ``var_smoothing * max column variance``;
the 2018 reference predates it (adds nothing). We take sklearn's behavior —
it is required for differential-parity with the modern oracle and prevents
division by zero on constant features.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin

from dask_ml_tpu.parallel.sharding import prepare_data, shard_rows, unpad_rows
from dask_ml_tpu.utils.validation import check_array

__all__ = ["GaussianNB", "PartialMultinomialNB", "PartialBernoulliNB",
           "logsumexp"]


def logsumexp(arr, axis=0):
    """Stable ``log(sum(exp(arr)))`` along ``axis``
    (reference: naive_bayes.py:123-147, itself a vendored sklearn helper).
    Jitted device reduction rather than a chunked max/exp/sum pipeline."""
    return jax.nn.logsumexp(jnp.asarray(arr), axis=axis)


@jax.jit
def _global_mean(X, w):
    """Weighted per-feature mean — the cheap shift point for the stabilized
    class moments."""
    return (w[:, None] * X).sum(axis=0) / jnp.maximum(w.sum(), 1e-12)


@jax.jit
def _class_moments(X, onehot, mu):
    """Weighted per-class counts, means, variances via SHIFTED moments.

    ``onehot`` is (n, K) row-class membership scaled by sample weight; the
    two matmuls contract the sharded axis (→ psum over ICI). Moments are
    taken about the global per-feature mean ``mu`` (shifted two-pass
    variance): computing ``E[x²]−θ²`` directly in f32 catastrophically
    cancels when ``|mean| ≫ std`` (e.g. mean ~1e4, std ~1 → variance 0 →
    inf/NaN likelihoods); about ``mu`` the magnitudes are O(std²) and the
    subtraction is benign. The reference/sklearn get the same protection
    from two-pass f64 computation."""
    Xc = X - mu[None, :]
    counts = onehot.sum(axis=0)  # (K,)
    safe = jnp.maximum(counts, 1e-12)
    m1 = (onehot.T @ Xc) / safe[:, None]  # (K, d): E_k[x-mu]
    ex2 = (onehot.T @ (Xc * Xc)) / safe[:, None]
    var = jnp.maximum(ex2 - m1**2, 0.0)
    theta = mu[None, :] + m1
    return counts, theta, var, m1


@jax.jit
def _joint_log_likelihood(X, theta, var, log_prior):
    """(n, K) fused JLL (reference: naive_bayes.py:110-120)."""
    # -0.5 Σ_d [ log(2π σ²_kd) + (x_d - θ_kd)²/σ²_kd ] + log π_k
    log_det = jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)  # (K,)
    diff = X[:, None, :] - theta[None, :, :]  # (n, K, d)
    quad = jnp.sum(diff * diff / var[None, :, :], axis=2)  # (n, K)
    return log_prior[None, :] - 0.5 * (log_det[None, :] + quad)


class GaussianNB(BaseEstimator, ClassifierMixin):
    """Gaussian Naive Bayes (reference: naive_bayes.py:25-120; the
    ``classes`` kwarg mirrors the reference's constructor)."""

    def __init__(self, priors=None, classes=None,
                 var_smoothing: float = 1e-9):
        self.priors = priors
        self.classes = classes
        self.var_smoothing = var_smoothing

    def fit(self, X, y=None, sample_weight=None):
        X = check_array(X)
        y = np.asarray(y)
        classes = (np.asarray(self.classes) if self.classes is not None
                   else np.unique(y))
        self.classes_ = classes
        # Map labels to positions in `classes` without assuming it is sorted
        # (user-supplied orders are legal, as in the reference which iterates
        # classes_ directly, naive_bayes.py:43-52).
        order = np.argsort(classes, kind="stable")
        sorted_classes = classes[order]
        pos = np.searchsorted(sorted_classes, y)
        in_range = pos < len(classes)
        if not in_range.all() or np.any(
                sorted_classes[np.where(in_range, pos, 0)] != y):
            raise ValueError("y contains labels not in `classes`")
        codes = order[pos]

        data = prepare_data(X, y=codes, sample_weight=sample_weight,
                            y_dtype=jnp.int32)
        onehot = jax.nn.one_hot(data.y, len(classes), dtype=data.X.dtype)
        onehot = onehot * data.weights[:, None]
        mu = _global_mean(data.X, data.weights)
        counts_d, theta_d, var_d, m1_d = _class_moments(data.X, onehot, mu)

        counts = np.asarray(counts_d, dtype=np.float64)
        theta = np.asarray(theta_d, dtype=np.float64)
        var = np.asarray(var_d, dtype=np.float64)
        m1 = np.asarray(m1_d, dtype=np.float64)
        # sklearn's numerical floor: var_smoothing × the largest TOTAL-data
        # feature variance (not per-class — per-class can be 0 on perfectly
        # separable data while the pooled variance is not). Pooled moments
        # come from the per-class SHIFTED ones by the law of total variance —
        # tiny (K, d) host math, no extra data pass, and stable because all
        # terms are O(std²) about the global mean.
        total_w = counts.sum()
        total_m1 = (counts[:, None] * m1).sum(0) / total_w  # ≈ 0 by shift
        total_e2 = (counts[:, None] * (var + m1**2)).sum(0) / total_w
        total_var = np.maximum(total_e2 - total_m1**2, 0.0)
        eps = float(self.var_smoothing * total_var.max()) \
            if total_var.size else 0.0
        # absolute floor so a fully-degenerate dataset (all features constant)
        # still yields finite likelihoods instead of dividing by exact zero
        self.epsilon_ = max(eps, float(np.finfo(np.float32).tiny))
        var += self.epsilon_

        self.class_count_ = counts
        self.theta_ = theta
        self.var_ = var
        self.sigma_ = var  # reference attribute name (naive_bayes.py:30)
        if self.priors is not None:
            priors = np.asarray(self.priors, dtype=np.float64)
            if len(priors) != len(classes):
                raise ValueError("Number of priors must match number of classes")
            # sklearn's validation messages, same checks
            if not np.isclose(priors.sum(), 1.0):
                raise ValueError("The sum of the priors should be 1.")
            if (priors < 0).any():
                raise ValueError("Priors must be non-negative.")
            self.class_prior_ = priors
        else:
            self.class_prior_ = self.class_count_ / self.class_count_.sum()
        return self

    def _jll(self, X):
        X = check_array(X)
        Xs, n = shard_rows(X)
        jll = _joint_log_likelihood(
            Xs,
            jnp.asarray(self.theta_, Xs.dtype),
            jnp.asarray(self.var_, Xs.dtype),
            jnp.log(jnp.asarray(self.class_prior_, Xs.dtype)),
        )
        return np.asarray(unpad_rows(jll, n))

    def predict(self, X):
        return self.classes_[np.argmax(self._jll(X), axis=1)]

    def predict_log_proba(self, X):
        jll = self._jll(X)
        from scipy.special import logsumexp

        return jll - logsumexp(jll, axis=1, keepdims=True)

    def predict_proba(self, X):
        return np.exp(self.predict_log_proba(X))

    def score(self, X, y):
        from dask_ml_tpu.metrics import accuracy_score

        return accuracy_score(np.asarray(y), self.predict(X))


# -- deprecated Partial* NB wrappers (reference: naive_bayes.py:123-132) -----

from sklearn.naive_bayes import BernoulliNB as _BernoulliNB  # noqa: E402
from sklearn.naive_bayes import MultinomialNB as _MultinomialNB  # noqa: E402

from dask_ml_tpu._partial import (  # noqa: E402
    _BigPartialFitMixin,
    _copy_partial_doc,
)


@_copy_partial_doc
class PartialMultinomialNB(_BigPartialFitMixin, _MultinomialNB):
    _init_kwargs = ["classes"]
    _fit_kwargs = []


@_copy_partial_doc
class PartialBernoulliNB(_BigPartialFitMixin, _BernoulliNB):
    _init_kwargs = ["classes"]
    _fit_kwargs = []
