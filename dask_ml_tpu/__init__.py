"""dask_ml_tpu — a TPU-native framework for scalable classical machine learning.

A from-scratch rebuild of the capabilities of ``dask-ml`` (reference:
``/root/reference``) designed for TPUs: sample-axis-chunked dask arrays become
``jax.Array`` shards laid out over a ``jax.sharding.Mesh``; per-block
NumPy/Cython kernels become jitted XLA programs; dask's task-graph
shuffle/reduce becomes ``psum``/``all_gather`` collectives over ICI/DCN; and the
hyper-parameter-search graph compiler becomes a host-side driver with
pipeline-prefix memoization.

Public subpackages mirror the reference API surface
(reference: docs/source/modules/api.rst):

- :mod:`dask_ml_tpu.cluster` — KMeans (k-means|| init), Nyström
  SpectralClustering
- :mod:`dask_ml_tpu.naive_bayes` — GaussianNB (one-pass per-class moments)
- :mod:`dask_ml_tpu.decomposition` — PCA / TruncatedSVD over native
  distributed tsqr + randomized SVD
- :mod:`dask_ml_tpu.linear_model` — GLMs (Logistic/Linear/Poisson) over the
  native solver suite (ADMM, L-BFGS, Newton, gradient/proximal descent)
- :mod:`dask_ml_tpu.metrics` — sharded metrics + pairwise kernels + scorers
- :mod:`dask_ml_tpu.model_selection` — ShuffleSplit/KFold/train_test_split,
  GridSearchCV/RandomizedSearchCV with work-sharing
- :mod:`dask_ml_tpu.preprocessing` — scalers/QuantileTransformer as sharded
  reductions; Categorizer/Dummy/OrdinalEncoder/LabelEncoder
- :mod:`dask_ml_tpu.wrappers` — ParallelPostFit / Incremental
  meta-estimators (+ ``incremental_scan`` fused partial_fit for jax cores)
- :mod:`dask_ml_tpu.datasets` — sharded data generators

Internal layers:

- :mod:`dask_ml_tpu.parallel` — mesh/runtime bootstrap, sharding, collectives
- :mod:`dask_ml_tpu.ops` — pairwise kernels, distributed linalg, reductions
- :mod:`dask_ml_tpu.models` — pure-functional model cores (init/step/predict)
"""

__version__ = "0.2.0"

from dask_ml_tpu.config import (  # noqa: F401
    config_context,
    get_config,
    set_config,
)

__all__ = [
    "checkpoint",
    "config",
    "config_context",
    "get_config",
    "set_config",
    "cluster",
    "decomposition",
    "linear_model",
    "metrics",
    "model_selection",
    "naive_bayes",
    "preprocessing",
    "wrappers",
    "datasets",
    "parallel",
    "ops",
    "utils",
]
