"""Regression metrics as weighted XLA reductions
(reference: metrics/regression.py:26-94 — ``uniform_average`` only, same
restriction kept here)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _check_multioutput(multioutput):
    if multioutput not in (None, "uniform_average"):
        raise ValueError(
            "Only multioutput='uniform_average' (or None) is supported "
            "(same restriction as the reference, metrics/regression.py:26)"
        )


def _prep(y_true, y_pred, sample_weight):
    y_true = jnp.asarray(y_true, dtype=jnp.float32)
    y_pred = jnp.asarray(y_pred, dtype=jnp.float32)
    if sample_weight is None:
        sample_weight = jnp.ones(y_true.shape[0], dtype=jnp.float32)
    else:
        sample_weight = jnp.asarray(sample_weight, dtype=jnp.float32)
    return y_true, y_pred, sample_weight


@jax.jit
def _mse(y_true, y_pred, w):
    err = (y_true - y_pred) ** 2
    if err.ndim > 1:
        err = err.mean(axis=1)
    return jnp.average(err, weights=w)


@jax.jit
def _mae(y_true, y_pred, w):
    err = jnp.abs(y_true - y_pred)
    if err.ndim > 1:
        err = err.mean(axis=1)
    return jnp.average(err, weights=w)


@jax.jit
def _r2(y_true, y_pred, w):
    num = jnp.sum(w * (y_true - y_pred) ** 2)
    mean = jnp.average(y_true, weights=w)
    den = jnp.sum(w * (y_true - mean) ** 2)
    return 1.0 - num / den


def mean_squared_error(
    y_true, y_pred, sample_weight=None, multioutput="uniform_average",
    compute: bool = True,
):
    _check_multioutput(multioutput)
    out = _mse(*_prep(y_true, y_pred, sample_weight))
    return float(out) if compute else out


def mean_absolute_error(
    y_true, y_pred, sample_weight=None, multioutput="uniform_average",
    compute: bool = True,
):
    _check_multioutput(multioutput)
    out = _mae(*_prep(y_true, y_pred, sample_weight))
    return float(out) if compute else out


def r2_score(
    y_true, y_pred, sample_weight=None, multioutput="uniform_average",
    compute: bool = True,
):
    _check_multioutput(multioutput)
    y_true, y_pred, w = _prep(y_true, y_pred, sample_weight)
    if y_true.ndim > 1:
        raise ValueError("r2_score supports 1-D targets only")
    out = _r2(y_true, y_pred, w)
    return float(out) if compute else out
