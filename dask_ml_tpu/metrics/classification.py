"""Classification metrics as weighted XLA reductions
(reference: metrics/classification.py:8-93).

``compute=True`` returns a Python float (the analogue of the reference's
eager path); ``compute=False`` returns the device scalar so callers can keep
the value on-device inside a larger fused computation (the analogue of the
reference's lazy dask scalar).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _accuracy(y_true, y_pred, sample_weight):
    if y_true.ndim > 1:
        # multilabel: a row counts only if every label matches
        # (reference: metrics/classification.py:60-69)
        match = jnp.all(y_true == y_pred, axis=1)
    else:
        match = y_true == y_pred
    match = match.astype(jnp.float32)
    return jnp.average(match, weights=sample_weight)


@jax.jit
def _accuracy_count(y_true, y_pred, sample_weight):
    if y_true.ndim > 1:
        match = jnp.all(y_true == y_pred, axis=1)
    else:
        match = y_true == y_pred
    return jnp.sum(match.astype(jnp.float32) * sample_weight)


def accuracy_score(
    y_true, y_pred, normalize: bool = True, sample_weight=None, compute: bool = True
):
    def _kind(a):
        dt = getattr(a, "dtype", None)
        # avoid materializing device arrays just to read a dtype; only
        # dtype-less inputs (lists) go through numpy
        return dt.kind if dt is not None else np.asarray(a).dtype.kind

    kt, kp = _kind(y_true), _kind(y_pred)
    if (kt in "USO") != (kp in "USO"):
        # one side strings, the other numeric: np.concatenate would promote
        # numerics to strings and '1' != '1.0' would score 0 silently —
        # raise loudly instead, as sklearn does
        raise TypeError(
            "Labels in y_true and y_pred should be of the same type, got "
            f"dtype kinds {kt!r} and {kp!r}"
        )
    if kt in "USO":
        # string/object labels (e.g. multiclass class names) can't stage to
        # device; map both through the label union — equality of indices is
        # equality of labels, so the device comparison is unchanged
        y_true = np.asarray(y_true)
        y_pred = np.asarray(y_pred)
        union = np.unique(np.concatenate([y_true.ravel(), y_pred.ravel()]))
        y_true = np.searchsorted(union, y_true)
        y_pred = np.searchsorted(union, y_pred)
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    if sample_weight is None:
        sample_weight = jnp.ones(y_true.shape[0], dtype=jnp.float32)
    else:
        sample_weight = jnp.asarray(sample_weight, dtype=jnp.float32)
    if normalize:
        out = _accuracy(y_true, y_pred, sample_weight)
    else:
        out = _accuracy_count(y_true, y_pred, sample_weight)
    return float(out) if compute else out


@jax.jit
def _log_loss(y_true, proba, sample_weight):
    # dtype-aware clip (sklearn uses finfo(dtype).eps too): a fixed 1e-15
    # is below f32 machine epsilon, so 1 - eps == 1 exactly and a confident
    # p == 1.0 prediction would hit log(0)·0 = NaN
    eps = jnp.finfo(proba.dtype).eps if jnp.issubdtype(
        proba.dtype, jnp.floating) else jnp.float32(1e-7)
    p = jnp.clip(proba, eps, 1.0 - eps)
    n_classes = 2 if p.ndim == 1 else p.shape[1]
    if p.ndim == 1:
        ll = -(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))
    else:
        onehot = jax.nn.one_hot(y_true.astype(jnp.int32), p.shape[1], dtype=p.dtype)
        ll = -jnp.sum(onehot * jnp.log(p), axis=1)
    # out-of-range codes poison the result loudly (NaN) instead of
    # contributing a silent zero loss — the device fast path has no host
    # validation, and raising is impossible under lazy semantics
    code = y_true.astype(jnp.int32)
    ll = jnp.where((code >= 0) & (code < n_classes), ll, jnp.nan)
    return jnp.average(ll, weights=sample_weight)


def log_loss(y_true, y_pred, sample_weight=None, labels=None,
             compute: bool = True):
    """Cross-entropy loss over probability predictions (capability-parity-plus:
    the reference has no dask log_loss, but its GLM scoring needs one).

    Labels are encoded positionally against the sorted class set (sklearn's
    column convention — an unsorted ``labels`` list is sorted first, as
    sklearn's LabelBinarizer does), so arbitrary label values — {-1, 1},
    {5, 7, 9} — score correctly instead of being treated as raw 0..K-1
    codes. Exception, for the module's ``compute=False`` on-device
    contract ONLY: a DEVICE-resident integer ``y_true`` with
    ``labels=None`` and ``compute=False`` skips host encoding and must
    already be 0..K-1 codes (pulling it to host for np.unique would force
    the device sync the lazy path exists to avoid); out-of-range codes
    return NaN rather than a silently understated loss. With the default
    ``compute=True`` the result comes to host anyway, so full host
    encoding/validation always runs there."""
    import numpy as np

    if not compute and isinstance(y_true, jax.Array) and labels is None \
            and jnp.issubdtype(y_true.dtype, jnp.integer):
        y_true = jnp.asarray(y_true)
        y_pred = jnp.asarray(y_pred)
        if sample_weight is None:
            sample_weight = jnp.ones(y_true.shape[0], dtype=jnp.float32)
        else:
            sample_weight = jnp.asarray(sample_weight, dtype=jnp.float32)
        return _log_loss(y_true, y_pred, sample_weight)

    y_arr = np.asarray(y_true)
    classes = np.unique(y_arr) if labels is None else np.unique(labels)
    if len(classes) < 2:
        raise ValueError(
            "y_true contains a single label; pass labels= with the full "
            "class set"
        )
    codes = np.searchsorted(classes, y_arr)
    in_range = codes < len(classes)
    if not (in_range.all()
            and np.array_equal(classes[codes], y_arr)):
        raise ValueError("y_true contains labels not in `labels`")
    y_pred = jnp.asarray(y_pred)
    if y_pred.ndim == 2 and y_pred.shape[1] != len(classes):
        raise ValueError(
            f"y_pred has {y_pred.shape[1]} columns but there are "
            f"{len(classes)} classes"
        )
    if y_pred.ndim == 1 and len(classes) != 2:
        raise ValueError(
            "1-D y_pred (probability of the positive class) requires "
            f"exactly 2 classes, got {len(classes)}"
        )
    y_true = jnp.asarray(codes)
    if sample_weight is None:
        sample_weight = jnp.ones(y_true.shape[0], dtype=jnp.float32)
    else:
        sample_weight = jnp.asarray(sample_weight, dtype=jnp.float32)
    out = _log_loss(y_true, y_pred, sample_weight)
    return float(out) if compute else out
