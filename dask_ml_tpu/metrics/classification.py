"""Classification metrics as weighted XLA reductions
(reference: metrics/classification.py:8-93).

``compute=True`` returns a Python float (the analogue of the reference's
eager path); ``compute=False`` returns the device scalar so callers can keep
the value on-device inside a larger fused computation (the analogue of the
reference's lazy dask scalar).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def _accuracy(y_true, y_pred, sample_weight):
    if y_true.ndim > 1:
        # multilabel: a row counts only if every label matches
        # (reference: metrics/classification.py:60-69)
        match = jnp.all(y_true == y_pred, axis=1)
    else:
        match = y_true == y_pred
    match = match.astype(jnp.float32)
    return jnp.average(match, weights=sample_weight)


@jax.jit
def _accuracy_count(y_true, y_pred, sample_weight):
    if y_true.ndim > 1:
        match = jnp.all(y_true == y_pred, axis=1)
    else:
        match = y_true == y_pred
    return jnp.sum(match.astype(jnp.float32) * sample_weight)


def accuracy_score(
    y_true, y_pred, normalize: bool = True, sample_weight=None, compute: bool = True
):
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    if sample_weight is None:
        sample_weight = jnp.ones(y_true.shape[0], dtype=jnp.float32)
    else:
        sample_weight = jnp.asarray(sample_weight, dtype=jnp.float32)
    if normalize:
        out = _accuracy(y_true, y_pred, sample_weight)
    else:
        out = _accuracy_count(y_true, y_pred, sample_weight)
    return float(out) if compute else out


@jax.jit
def _log_loss(y_true, proba, sample_weight, eps: float = 1e-15):
    p = jnp.clip(proba, eps, 1.0 - eps)
    if p.ndim == 1:
        ll = -(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))
    else:
        onehot = jax.nn.one_hot(y_true.astype(jnp.int32), p.shape[1], dtype=p.dtype)
        ll = -jnp.sum(onehot * jnp.log(p), axis=1)
    return jnp.average(ll, weights=sample_weight)


def log_loss(y_true, y_pred, sample_weight=None, compute: bool = True):
    """Cross-entropy loss over probability predictions (capability-parity-plus:
    the reference has no dask log_loss, but its GLM scoring needs one)."""
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    if sample_weight is None:
        sample_weight = jnp.ones(y_true.shape[0], dtype=jnp.float32)
    else:
        sample_weight = jnp.asarray(sample_weight, dtype=jnp.float32)
    out = _log_loss(y_true, y_pred, sample_weight)
    return float(out) if compute else out
