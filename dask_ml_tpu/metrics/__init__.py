"""Sharded metrics, pairwise ops, and the scorer registry
(reference: dask_ml/metrics/__init__.py)."""

from dask_ml_tpu.ops.pairwise import (  # noqa: F401
    check_pairwise_arrays,
    euclidean_distances,
    linear_kernel,
    pairwise_distances,
    pairwise_distances_argmin_min,
    pairwise_kernels,
    polynomial_kernel,
    rbf_kernel,
    sigmoid_kernel,
)
from dask_ml_tpu.metrics.classification import accuracy_score, log_loss  # noqa: F401
from dask_ml_tpu.metrics.regression import (  # noqa: F401
    mean_absolute_error,
    mean_squared_error,
    r2_score,
)
from dask_ml_tpu.metrics.scorer import (  # noqa: F401
    SCORERS,
    check_scoring,
    get_scorer,
)
