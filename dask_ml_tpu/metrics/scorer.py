"""Scorer registry over the sharded metrics
(reference: metrics/scorer.py:12-69).

Scorers follow the sklearn convention ``scorer(estimator, X, y) -> float`` so
they slot into both our search estimators and sklearn's.
"""

from __future__ import annotations

from sklearn.metrics import make_scorer

from dask_ml_tpu.metrics.classification import accuracy_score, log_loss
from dask_ml_tpu.metrics.regression import (
    mean_absolute_error,
    mean_squared_error,
    r2_score,
)

# Same registry contents as the reference (accuracy, neg MSE, r2), plus the
# obvious extensions its users get from sklearn.
SCORERS = {
    "accuracy": make_scorer(accuracy_score),
    "neg_mean_squared_error": make_scorer(mean_squared_error,
                                          greater_is_better=False),
    "neg_mean_absolute_error": make_scorer(mean_absolute_error,
                                           greater_is_better=False),
    "neg_log_loss": make_scorer(log_loss, greater_is_better=False,
                                response_method="predict_proba"),
    "r2": make_scorer(r2_score),
}


def get_scorer(scoring, compute: bool = True):
    """Resolve a scoring name or callable to a scorer
    (reference: metrics/scorer.py:25-50). Names not in our sharded registry
    fall back to sklearn's scorer registry (single authority for the whole
    package, incl. the search driver)."""
    if isinstance(scoring, str):
        try:
            return SCORERS[scoring]
        except KeyError:
            pass
        try:
            import sklearn.metrics

            return sklearn.metrics.get_scorer(scoring)
        except ValueError:
            raise ValueError(
                f"{scoring!r} is not a valid scoring value; valid options "
                f"are {sorted(SCORERS)} or any sklearn scorer name"
            )
    if callable(scoring):
        return scoring
    raise ValueError(f"Invalid scoring: {scoring!r}")


def _looks_like_raw_metric(fn) -> bool:
    """Structural test for a metric-style callable ``f(y_true, y_pred)``
    passed where a scorer ``s(estimator, X, y)`` is required.

    The rule is structural, like the reference's (a scorer is something
    ``make_scorer`` produced or an sklearn ``_BaseScorer``; reference:
    metrics/scorer.py:53-69) — NOT a module-name sniff, which both misses
    user-defined metrics and falsely rejects scorer-shaped functions that
    happen to live in a metrics module:

    - made scorers carry ``_score_func``/``_response_method`` → scorer;
    - otherwise inspect the signature: a first parameter named for a
      ground-truth vector (``y_true``/``y``/``labels``) or a two-positional
      ``(y_true, y_pred)`` shape marks a raw metric, while scorer-shaped
      callables lead with an estimator parameter.
    """
    if hasattr(fn, "_score_func") or hasattr(fn, "_response_method"):
        return False
    # plain functions living in a metrics module are metrics: libraries
    # never define scorer-shaped bare functions there (their scorers are
    # make_scorer products, caught above). This catches metrics whose
    # signatures don't look y-shaped, e.g. silhouette_score(X, labels).
    if getattr(fn, "__module__", "").startswith(
            ("dask_ml_tpu.metrics", "sklearn.metrics")):
        return True
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):  # builtins/C callables: can't tell
        return False
    positional = [
        p for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    if not positional:
        return False
    first = positional[0].name.lower()
    if first in ("y_true", "y", "labels", "labels_true"):
        return True
    second = positional[1].name.lower() if len(positional) > 1 else ""
    return second in ("y_pred", "y_score", "y_prob", "labels_pred")


def check_scoring(estimator, scoring=None, **kwargs):
    """Validate scoring for an estimator (reference: metrics/scorer.py:53-69).
    Raw metric functions (e.g. ``accuracy_score`` itself) are rejected — pass
    a name or a made scorer."""
    if scoring is None:
        if not hasattr(estimator, "score"):
            raise TypeError(
                f"estimator {estimator!r} has no score method; pass scoring="
            )
        return None
    if callable(scoring) and _looks_like_raw_metric(scoring):
        raise ValueError(
            "scoring value looks like a raw metric function "
            "(signature starts with y_true/y_pred, not an estimator); "
            "wrap it with sklearn.metrics.make_scorer (same rule as the "
            "reference, metrics/scorer.py:53-69)"
        )
    return get_scorer(scoring)
