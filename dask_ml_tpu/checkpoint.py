"""Checkpoint/resume for long-running solver fits and CV searches.

The reference has no real checkpointing — persistence there is pickling a
fitted estimator after the fact (reference:
tests/model_selection/dask_searchcv/test_model_selection_sklearn.py:892) and
``Incremental.partial_fit``'s logical resume from a previous model
(reference: wrappers.py:375-395). SURVEY §5.4 marks real checkpointing as a
capability-parity-plus item for this build, and it matters more here: a TPU
solver is ONE long-running on-device ``lax.while_loop``, so resumability has
to be designed in as state threading, not bolted on as object pickling.

Two tiers:

- **Solver checkpointing** (:func:`solve_checkpointed`): the GLM solvers
  expose their full optimizer carry (L-BFGS's curvature history, ADMM's
  per-shard primal/dual variables stacked along the data axis — see
  ``models/glm.py``), so a fit can run as host-driven chunks of device
  iterations with the carry snapshotted to disk between chunks. Resuming
  reloads the carry and takes the SAME trajectory as an uninterrupted run.
- **Search checkpointing** (:class:`CellJournal`, wired into
  ``TPUBaseSearchCV.fit(checkpoint=...)``): every completed
  (candidate, split) cell appends one content-addressed record to an
  append-only journal; a re-run with the same checkpoint path restores
  completed cells and computes only the remainder, reproducing identical
  ``cv_results_``.

All writes are atomic (temp file + ``os.replace``) or append-only with a
truncation-tolerant reader, so a kill mid-write never corrupts a restart.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
import threading
from typing import Any, Optional

import numpy as np

logger = logging.getLogger(__name__)


def _framing():
    """The shared frame codec, imported lazily: pulling the parallel
    package at module scope would drag jax into every process that merely
    imports this module (the faults layer's loader-process contract)."""
    from dask_ml_tpu.parallel import framing

    return framing


class CheckpointCorruptError(RuntimeError):
    """A snapshot file exists but fails its integrity check (torn write,
    truncation, bit rot). Raised LOUDLY instead of resuming garbage — the
    same discipline as :class:`CellJournal`'s torn-frame drop, except a
    snapshot has no earlier intact frames to fall back to, so corruption is
    an error the operator must see (delete the file to restart clean)."""


# ---------------------------------------------------------------------------
# atomic pytree snapshots
# ---------------------------------------------------------------------------

#: framed snapshot header: magic + 8-byte payload length + sha256 digest
#: (the shared codec in :mod:`dask_ml_tpu.parallel.framing` — the serving
#: wire protocol speaks the same frame layout under its own magic).
#: The frame is what turns "atomic rename" into an end-to-end guarantee —
#: rename protects against a kill mid-save, the checksum protects against
#: everything else (a torn copy, a truncated transfer off shared storage,
#: silent media corruption): any byte missing or flipped fails the digest
#: and raises :class:`CheckpointCorruptError` instead of unpickling noise.
_SNAPSHOT_MAGIC = b"DMLTCKPT1\n"


def _to_host(tree):
    """Device arrays → host numpy, leaving plain python leaves alone."""
    import jax

    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(jax.device_get(leaf)), tree
    )


def save_pytree(path: str, tree: Any, meta: Optional[dict] = None) -> None:
    """Atomically snapshot ``(tree, meta)`` to ``path``.

    The tree is pulled to host (numpy) first so the snapshot is
    device-independent and a resumed run re-places it through its own jit
    shardings. Note that :func:`solve_checkpointed` still binds a snapshot
    to its *staged problem* (shapes include mesh padding, and the content
    checksum reflects the staging's reduction order), so its resume path
    expects the same mesh/data staging as the original run; the snapshot
    FORMAT carries no device state. Atomicity: write to a temp file in the
    same directory, fsync, then ``os.replace`` — a kill mid-save leaves the
    previous snapshot intact.
    """
    payload = {"tree": _to_host(tree), "meta": meta or {}}
    body = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    frame = _framing().encode_frame(body, magic=_SNAPSHOT_MAGIC)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    logger.info("checkpoint saved: %s (meta=%s)", path, meta)


def load_pytree(path: str):
    """Load a :func:`save_pytree` snapshot → ``(tree, meta)``, or ``None``
    if the file does not exist.

    Integrity is verified end to end: the framed header's length + sha256
    must match the payload exactly, so a snapshot truncated at ANY byte
    offset — or with any byte altered — raises
    :class:`CheckpointCorruptError` instead of resuming garbage (swept in
    ``tests/test_checkpoint.py``). Pre-frame legacy snapshots (no magic)
    still load, with unpickling failures wrapped in the same loud error.
    """
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        head = f.read(len(_SNAPSHOT_MAGIC))
        if head == _SNAPSHOT_MAGIC:
            framing = _framing()
            data = head + f.read()
            try:
                body = framing.decode_frame(data, magic=_SNAPSHOT_MAGIC)
            except framing.FrameError as e:
                raise CheckpointCorruptError(
                    f"checkpoint {path}: {e} — the snapshot is torn or "
                    "corrupt; delete it to restart from scratch") from e
            payload = pickle.loads(body)
        else:
            # legacy (pre-frame) snapshot: no digest to verify, but failures
            # still surface loudly instead of as bare unpickling noise
            try:
                f.seek(0)
                payload = pickle.load(f)
            except Exception as e:
                raise CheckpointCorruptError(
                    f"checkpoint {path}: unreadable legacy snapshot "
                    f"({type(e).__name__}: {e}); delete it to restart from "
                    "scratch") from e
    if not (isinstance(payload, dict) and "tree" in payload
            and "meta" in payload):
        raise CheckpointCorruptError(
            f"checkpoint {path}: payload is not a snapshot (corrupt or "
            "foreign file); delete it to restart from scratch")
    logger.info("checkpoint loaded: %s (meta=%s)", path, payload["meta"])
    return payload["tree"], payload["meta"]


# ---------------------------------------------------------------------------
# chunked solver driver
# ---------------------------------------------------------------------------

#: solvers whose FULL optimizer carry round-trips through the checkpoint
#: (resume takes the identical trajectory). The rest restart each chunk from
#: the latest beta — exact for Newton (its carry IS beta), and correct but
#: with a reset step-size schedule for gradient_descent / proximal_grad.
STATEFUL_SOLVERS = ("lbfgs", "admm", "multinomial_lbfgs",
                    "admm_multinomial")


_moments_prog = None


def _all_moments(arrays):
    """Three f32-accumulated reductions per array, as ONE jitted program and
    ONE host fetch.

    The reductions run fused under jit (``astype`` + square + sum never
    materialize an upcast copy of the input — ADVICE r3: an eager
    ``asarray(a).astype(f32)`` doubled HBM for bf16-staged data exactly on
    the huge fits checkpointing targets), and batching all arrays into one
    program replaces 3·n_arrays round-trip fetches with one. The jitted
    program is module-level so repeated fingerprints (one per CV cell in a
    checkpointed search) hit the jit cache instead of retracing.
    """
    import jax
    import jax.numpy as jnp

    global _moments_prog
    if _moments_prog is None:
        def one(a):
            af = a.astype(jnp.float32)  # fused into the reductions by XLA
            return (jnp.sum(af), jnp.sum(af * af),
                    jnp.sum(jnp.abs(af[..., ::7])))

        _moments_prog = jax.jit(lambda xs: [one(x) for x in xs])

    present = [jnp.asarray(a) for a in arrays if a is not None]
    outs = [tuple(float(v) for v in t)
            for t in jax.device_get(_moments_prog(present))]
    it = iter(outs)
    return [next(it) if a is not None else (0.0,) for a in arrays]


def _problem_fingerprint(solver, X, y, w, beta0, mask, **kwargs) -> str:
    """Cheap content fingerprint binding a snapshot to its fit problem.

    A full host hash of X would defeat the point on a real TPU (the data may
    be tens of GB behind a slow host link), so the checksum is computed ON
    DEVICE as a handful of weighted moments — one tiny fetch — plus shapes,
    dtypes, the requested start point ``beta0``, and every hyperparameter.
    Any changed dataset/label/weight content, warm start, or solver config
    changes the fingerprint with overwhelming probability, and a mismatched
    resume is rejected instead of silently returning another problem's
    solution. The binding is to the problem AS STAGED: shapes include mesh
    padding and f32 sums reflect the sharding's reduction order, so resume
    expects the same mesh/data staging as the run that wrote the snapshot.
    """
    import hashlib

    # three independent f32-accumulated reductions per array make an
    # unnoticed collision vanishingly unlikely for real data edits
    mom = _all_moments([X, y, w, beta0, mask])
    h = hashlib.sha256()
    for part in (
        solver,
        tuple(getattr(X, "shape", ())), str(getattr(X, "dtype", "")),
        tuple(getattr(y, "shape", ())) if y is not None else None,
        *mom,
        sorted((k, repr(v)) for k, v in kwargs.items()),
    ):
        h.update(repr(part).encode())
    return h.hexdigest()[:32]


def problem_fingerprint(solver, X, y, w, beta0, mask, **kwargs) -> str:
    """Public alias of the snapshot↔problem binding checksum (see
    :func:`_problem_fingerprint`). Estimator facades use it to derive a
    per-problem checkpoint path suffix, so one configured path serves many
    fits (e.g. the same checkpointed estimator across CV cells) without
    fingerprint-mismatch errors."""
    return _problem_fingerprint(solver, X, y, w, beta0, mask, **kwargs)


def solve_checkpointed(solver: str, X, y, w, beta0, mask, mesh=None, *,
                       path: str, chunk_iters: int = 50, max_iter: int = 250,
                       save_every_chunks: int = 1, fingerprint: str = None,
                       preloaded_snapshot=None, **kwargs):
    """Run a GLM solver as resumable chunks of device iterations.

    Each chunk is one on-device solve of at most ``chunk_iters`` iterations
    starting from the threaded carry; after every ``save_every_chunks``
    chunks the carry is snapshotted to ``path``. If ``path`` already holds a
    snapshot for the SAME problem (solver + data/label/weight content
    checksum + hyperparameters, checked via metadata), the fit resumes from
    it — so a killed process continues where it stopped instead of
    restarting from zero, the capability SURVEY §5.4 asks for. A snapshot
    from a different problem at the same path is an error, never a silent
    wrong answer.

    Returns ``(beta, total_iters)`` with ``total_iters`` counted across all
    runs that contributed to the checkpoint. For the stateful solvers
    convergence is the solver loop's OWN done flag (so converging exactly on
    a chunk's last budgeted iteration is still recorded as converged —
    ADVICE r3); the carry-light solvers fall back to the chunk using fewer
    than its budgeted iterations. The snapshot is kept on completion
    (callers may delete it) with ``meta['converged']=True``.

    ``fingerprint`` may be passed pre-computed (see
    :func:`problem_fingerprint`) to skip the device reductions, e.g. when
    the caller already derived a per-problem path suffix from it; likewise
    ``preloaded_snapshot`` (a :func:`load_pytree` result for ``path``) skips
    re-reading a snapshot the caller already loaded — the carries can be
    large (L-BFGS history, ADMM per-shard stacks) and deserializing them
    twice on the huge-fit resume path is exactly the waste to avoid.
    """
    from dask_ml_tpu.models import glm as glm_core

    # "multinomial_lbfgs" / "admm_multinomial" are the softmax
    # pseudo-solvers (not in the facade's SOLVERS dispatch — reached via
    # multiclass='multinomial'); beta/beta0 are (d, K) matrices and
    # **kwargs must carry n_classes
    _MULTINOMIAL = ("multinomial_lbfgs", "admm_multinomial")
    if solver not in glm_core.SOLVERS and solver not in _MULTINOMIAL:
        raise ValueError(f"unknown solver {solver!r}")
    if solver == "admm_multinomial" and mesh is None:
        raise ValueError("admm_multinomial requires a mesh")
    if solver == "admm" and mesh is None:
        raise ValueError("admm requires a mesh")
    if fingerprint is None:
        fingerprint = _problem_fingerprint(solver, X, y, w, beta0, mask,
                                           **kwargs)

    state = None
    iters_done = 0
    beta = beta0
    snap = (preloaded_snapshot if preloaded_snapshot is not None
            else load_pytree(path))
    if snap is not None:
        tree, meta = snap
        if meta.get("solver") != solver:
            raise ValueError(
                f"checkpoint {path} was written by solver "
                f"{meta.get('solver')!r}, not {solver!r}"
            )
        if meta.get("fingerprint") != fingerprint:
            raise ValueError(
                f"checkpoint {path} was written for a different problem "
                "(data/weights/hyperparameters changed); delete it or use "
                "a distinct path per fit"
            )
        if meta.get("converged"):
            return tree["beta"], int(meta["iters_done"])
        state = tree["state"]
        beta = tree["beta"]
        iters_done = int(meta["iters_done"])

    stateful = solver in STATEFUL_SOLVERS

    def snapshot(converged):
        save_pytree(
            path,
            {"beta": beta, "state": state if stateful else None},
            meta={"solver": solver, "fingerprint": fingerprint,
                  "iters_done": iters_done, "converged": converged},
        )

    chunks_since_save = 0
    while iters_done < max_iter:
        budget = min(chunk_iters, max_iter - iters_done)
        if solver == "admm":
            z, n_it, state, done = glm_core.admm(
                X, y, w, beta, mask, mesh, max_iter=budget, state=state,
                return_state=True, **kwargs)
            beta = z
            converged = bool(done)
        elif solver == "lbfgs":
            beta, n_it, state, done = glm_core.lbfgs(
                X, y, w, beta, mask, max_iter=budget, state=state,
                return_state=True, **kwargs)
            converged = bool(done)
        elif solver == "multinomial_lbfgs":
            beta, n_it, state, done = glm_core.multinomial_lbfgs(
                X, y, w, beta, mask, max_iter=budget, state=state,
                return_state=True, **kwargs)
            converged = bool(done)
        elif solver == "admm_multinomial":
            beta, n_it, state, done = glm_core.admm_multinomial(
                X, y, w, beta, mask, mesh, max_iter=budget, state=state,
                return_state=True, **kwargs)
            converged = bool(done)
        else:
            # beta-restart chunking for the carry-light solvers, which do
            # not expose their loop's done flag
            beta, n_it = glm_core.solve(
                solver, X, y, w, beta, mask, mesh=mesh, max_iter=budget,
                **kwargs)
            converged = int(n_it) < budget
        n_it = int(n_it)
        iters_done += n_it
        chunks_since_save += 1
        if converged or chunks_since_save >= save_every_chunks:
            snapshot(converged)
            chunks_since_save = 0
        if converged:
            return beta, iters_done
    if chunks_since_save:
        # loop exited at max_iter between scheduled saves: persist the tail
        # chunks so a resume with a larger budget doesn't redo them
        snapshot(False)
    return beta, iters_done


# ---------------------------------------------------------------------------
# search-cell journal
# ---------------------------------------------------------------------------


class CellJournal:
    """Append-only journal of completed (candidate, split) search cells.

    Records are pickle frames ``(key, result)`` appended under a lock; the
    reader consumes frames until EOF and silently drops a torn final frame
    (the one a kill can produce), so resume never trips on a partial write.
    Keys are content-addressed (estimator config + params + the split's
    actual indices + scorer names — see ``_search.py``), which makes the
    journal self-invalidating: change the grid, data split, or scoring and
    the old records simply never match.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        #: lifetime records appended / restored through THIS handle —
        #: host-side mirrors of the ``checkpoint.cells_journaled`` /
        #: ``checkpoint.cells_restored`` registry counters, incremented at
        #: the same sites (docs/observability.md discipline)
        self.n_appended = 0
        self.n_restored = 0
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)

    def load(self) -> dict:
        done: dict = {}
        if not os.path.exists(self.path):
            return done
        with open(self.path, "rb") as f:
            while True:
                try:
                    key, result = pickle.load(f)
                except EOFError:
                    break
                except (pickle.UnpicklingError, AttributeError, ValueError,
                        IndexError):
                    logger.warning(
                        "search checkpoint %s: dropping torn trailing "
                        "record", self.path)
                    break
                done[key] = result
        if done:
            logger.info("search checkpoint %s: restored %d completed cells",
                        self.path, len(done))
            self.n_restored += len(done)
            from dask_ml_tpu.parallel import telemetry

            if telemetry.enabled():
                telemetry.metrics().counter(
                    "checkpoint.cells_restored").inc(len(done))
        return done

    def append(self, key: str, result) -> None:
        with self._lock:
            with open(self.path, "ab") as f:
                pickle.dump((key, result), f,
                            protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            self.n_appended += 1
        from dask_ml_tpu.parallel import telemetry

        if telemetry.enabled():
            telemetry.metrics().counter("checkpoint.cells_journaled").inc()
