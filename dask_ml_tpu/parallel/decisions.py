"""Measured autotuner decision cache (the persisted side of auto-dispatch).

The kernel-dispatch predicates — ``models/kmeans.py::_pallas_auto_wins`` /
``_bounded_auto_wins``, ``ops/fused_distance.py::_fused_auto_wins``, and the
sparse SpMM rule in ``ops/sparse.py::_use_pallas`` — are hand-written
inequalities distilled from bench sweeps. Those stay as the COLD-START
fallback; this module adds the measured tier on top: ``bench.py`` timings
persist per-``(rule, backend)`` verdicts into a JSON cache, and the dispatch
predicates consult it FIRST through one lookup helper.

Entry shape (``_decisions.json``, committed next to this module)::

    {"rule": "sparse.spmv.pallas",
     "backend": "cpu",
     "match": {"n": [4096, 16384], "k": 16},
     "verdict": false,
     "measured": {"xla_ms": 0.8, "pallas_ms": 41.0, "n": 8192}}

``match`` values are either a scalar (exact equality; dtypes compare by
``str``) or an inclusive ``[lo, hi]`` range. An entry applies only when its
``backend`` equals ``jax.default_backend()`` at call time (read dynamically,
so backend mocks in tests see their mocked world) and EVERY match key is
present and satisfied. First matching entry wins; no entry → fallback.

Ranges are kept deliberately NARROW (the bench writes ±50% brackets around
each measured point): the cache answers where a measurement exists and the
inequalities keep answering everywhere else, so a cache populated on one
host never silently overrides regimes it has no data for.

Guard predicates that are about CORRECTNESS, not speed — pallas support
checks, row-count tiling, mesh-compatibility — always stay OUTSIDE the
lookup in the calling predicate: the cache decides "would it be faster",
never "is it legal".

``DASK_ML_TPU_DECISIONS`` points the loader at an alternate cache file
(bench drills, scratch experiments); ``save()`` is only ever invoked by
``bench.py`` under ``DECISIONS_WRITE=1``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

__all__ = ["lookup", "record", "save", "reset_cache", "cache_path",
           "entries"]

_lock = threading.Lock()
_cache: Optional[list] = None  # lazy-loaded entry list


def cache_path() -> str:
    """The active cache file: ``$DASK_ML_TPU_DECISIONS`` if set, else the
    ``_decisions.json`` committed next to this module."""
    env = os.environ.get("DASK_ML_TPU_DECISIONS")
    if env:
        return env
    return os.path.join(os.path.dirname(__file__), "_decisions.json")


def _load() -> list:
    global _cache
    with _lock:
        if _cache is not None:
            return _cache
        path = cache_path()
        entries_ = []
        try:
            with open(path) as fh:
                raw = json.load(fh)
            entries_ = list(raw.get("entries", []))
        except (OSError, ValueError):  # missing/corrupt cache = cold start
            entries_ = []
        _cache = entries_
        return _cache


def reset_cache() -> None:
    """Drop the in-memory cache; the next lookup reloads from disk. Tests
    use this around ``DASK_ML_TPU_DECISIONS`` monkeypatching."""
    global _cache
    with _lock:
        _cache = None


def entries() -> list:
    """The loaded entry list (a copy)."""
    return list(_load())


def _matches(spec, value) -> bool:
    if isinstance(spec, (list, tuple)):
        if len(spec) != 2:
            return False
        try:
            return float(spec[0]) <= float(value) <= float(spec[1])
        except (TypeError, ValueError):
            return False
    if isinstance(spec, str) or isinstance(value, str):
        return str(spec) == str(value)
    try:
        return float(spec) == float(value)
    except (TypeError, ValueError):
        return spec == value


def lookup(rule: str, params: dict, fallback: bool) -> bool:
    """Measured verdict for ``rule`` at ``params``, else ``fallback``.

    ``params`` holds the dispatch-relevant scalars (sizes as ints, dtypes
    pre-stringified by the caller). Backend is matched dynamically against
    ``jax.default_backend()``.
    """
    cached = _load()
    if not cached:
        return bool(fallback)
    import jax

    backend = jax.default_backend()
    for e in cached:
        if e.get("rule") != rule or e.get("backend") != backend:
            continue
        match = e.get("match", {})
        if all(k in params and _matches(v, params[k])
               for k, v in match.items()):
            return bool(e.get("verdict"))
    return bool(fallback)


def record(rule: str, match: dict, verdict: bool, measured: dict = None,
           backend: str = None) -> dict:
    """Append a measured entry to the in-memory cache (bench-side; persist
    with :func:`save`). Returns the entry."""
    import jax

    entry = {
        "rule": rule,
        "backend": backend or jax.default_backend(),
        "match": match,
        "verdict": bool(verdict),
    }
    if measured:
        entry["measured"] = measured
    cached = _load()
    with _lock:
        cached.append(entry)
    return entry


def save(path: str = None) -> str:
    """Write the in-memory cache to ``path`` (default: the active cache
    file). Only ``bench.py`` calls this, and only under
    ``DECISIONS_WRITE=1`` — imports never write."""
    path = path or cache_path()
    cached = _load()
    with _lock:
        payload = {"entries": cached}
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return path
