"""Shape canonicalization: bucketed sample-axis padding + compile observability.

Every pillar-1 algorithm here is a jitted program over sample-axis blocks, so
each DISTINCT sample count traces and compiles its own XLA executable: a
K-fold search compiles per fold shape (K-1 vs K-fold train sizes differ by a
row whenever n % K != 0), every dataset size is a cold compile, and — before
this layer — the streamed tier refused ragged tail blocks entirely. That
fixed per-program overhead scales with the number of distinct shapes rather
than with the data, the same redundant-work class the communication-avoiding
formulations eliminate per iteration (PAPERS.md: arxiv 2601.17136).

The answer is the standard batch-bucketing take from inference serving,
adapted to the weight-masked layout this package already carries everywhere:

- :class:`PadPolicy` maps any sample count ``n`` to a small set of padded
  bucket sizes — "powers-of-two-ish" growth bounded by a configurable
  ``waste_cap``. The quantum is the largest power of two ``q`` with
  ``q <= waste_cap * n``; the bucket is ``n`` rounded up to a multiple of
  ``q`` (then to the mesh alignment), so relative padding waste stays under
  ``waste_cap`` while the number of distinct buckets per octave is
  ``~1/waste_cap``. Counts below ``min_rows`` all land in the single
  smallest bucket: their absolute waste is bounded by ``min_rows`` rows and
  every tiny fit shares ONE compiled program.
- Rows past ``n_valid`` carry **weight 0** (``sharding.row_weights``), which
  the algorithm cores are already written for: KMeans assignment/M-step and
  inertia (``fused_argmin_weight`` takes validity masks), PCA centering and
  streamed moments (weight-0 rows contribute nothing to mean or Gram), the
  GLM/ADMM sample-weighted objectives. Padded and exact runs therefore
  produce the same results (bit-identical against a manually-padded run of
  the same shape; within reduction-order float tolerance against an
  unpadded run of a different shape).

The policy is threaded through the consumers via the config knob
``pad_policy`` (:mod:`dask_ml_tpu.config`): ``shard_rows``/``shard_2d``/
``prepare_data`` bucket the sample axis at staging (so every estimator fit,
CV-fold slice from ``CVCache.extract``, and batched candidate group lands in
a shared bucket), and :class:`~dask_ml_tpu.parallel.stream.HostBlockSource`
zero-pads ragged tail blocks instead of raising (one per-block program per
epoch).

Compile observability makes the win provable: :func:`compile_stats` counts
trace/compile events through ``jax.monitoring`` (``n_compiles``,
``compile_seconds``, ``n_traces``, ``trace_seconds``) and records which
buckets staging actually chose (``shape_buckets``). ``bench.py
--compile-report`` writes those keys next to the phase metrics, and the CI
``compile`` job gates a K-fold grid search's compile count on the batch
plan's bucket count instead of candidates x folds. A persistent-compilation-
cache knob (``set_config(compilation_cache=dir)``) makes repeat invocations
start warm; see ``docs/compile.md``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import threading
from typing import Optional, Sequence, Union

import numpy as np

__all__ = [
    "PadPolicy",
    "DEFAULT_POLICY",
    "active_policy",
    "bucket_rows",
    "bucket_nnz",
    "pad_tail",
    "compile_stats",
    "reset_compile_stats",
    "track_compiles",
    "enable_persistent_cache",
]


@dataclasses.dataclass(frozen=True)
class PadPolicy:
    """Maps sample counts to a small set of padded bucket sizes.

    ``waste_cap`` bounds the RELATIVE padding waste: the bucket quantum is
    the largest power of two ``q <= waste_cap * n``, so
    ``(bucket(n) - n) / n < waste_cap`` (plus at most one mesh-alignment
    round-up) and consecutive buckets grow by a factor ``<= 1 + waste_cap``
    — powers-of-two-ish growth with ``~1/waste_cap`` buckets per octave,
    ``O(log(n_max) / waste_cap)`` buckets total.

    ``min_rows`` is the smallest bucket: every ``n <= min_rows`` pads to it,
    trading at most ``min_rows`` rows of (absolute) waste for ONE shared
    compiled program across all tiny inputs — the relative cap deliberately
    does not apply below it.
    """

    waste_cap: float = 0.125
    min_rows: int = 64

    def __post_init__(self):
        if not 0.0 < self.waste_cap <= 1.0:
            raise ValueError(
                f"waste_cap must be in (0, 1], got {self.waste_cap}")
        if self.min_rows < 1:
            raise ValueError(f"min_rows must be >= 1, got {self.min_rows}")

    def bucket(self, n: int, align: int = 1) -> int:
        """The padded sample count for ``n`` true rows: the smallest bucket
        ``>= max(n, min_rows)``, rounded up to a multiple of ``align`` (the
        mesh's data-shard count — every bucket must split evenly)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        target = max(n, self.min_rows, 1)
        q = 1 << max(int(math.floor(
            math.log2(max(target * self.waste_cap, 1.0)))), 0)
        b = -(-target // q) * q
        align = max(int(align), 1)
        return -(-b // align) * align

    def signature(self) -> tuple:
        """Hashable identity for staging-memo keys."""
        return ("PadPolicy", self.waste_cap, self.min_rows)


DEFAULT_POLICY = PadPolicy()


def active_policy() -> Optional[PadPolicy]:
    """The policy the staging layer should apply, resolved from the config
    knob ``pad_policy``: ``"auto"`` (default) → :data:`DEFAULT_POLICY`,
    ``None`` → bucketing disabled (exact mesh-multiple padding, the
    pre-bucketing behavior), a :class:`PadPolicy` → itself."""
    from dask_ml_tpu.config import get_config

    knob = get_config()["pad_policy"]
    if knob is None:
        return None
    if knob == "auto":
        return DEFAULT_POLICY
    if isinstance(knob, PadPolicy):
        return knob
    raise ValueError(
        f"pad_policy must be 'auto', None, or a PadPolicy; got {knob!r}")


def bucket_rows(n: int, align: int = 1,
                policy: Union[PadPolicy, None, str] = "active",
                record: bool = True) -> int:
    """Padded row count for ``n`` under ``policy`` (default: the active
    config policy). With no policy this is plain align-rounding — exactly
    the mesh-multiple padding the staging layer always did.

    ``record=True`` notes the (bucket, n) pair into
    ``compile_stats()['shape_buckets']`` — the STAGING paths keep that
    default; pure size queries (bucket planning, reporting) must pass
    ``record=False`` so the stats only reflect data actually staged."""
    if policy == "active":
        policy = active_policy()
    if policy is None:
        align = max(int(align), 1)
        return -(-int(n) // align) * align
    padded = policy.bucket(n, align=align)
    if record:
        note_bucket(int(n), padded)
    return padded


def bucket_nnz(k: int, min_slots: int = 1, record: bool = True) -> int:
    """Padded per-row nonzero budget for an ELL width of ``k`` true slots:
    the next power of two (PR-4-style buckets, <= 2x slot waste), floored
    at ``min_slots``. This is the SECOND half of the sparse compile-once
    key — a staged :class:`~dask_ml_tpu.ops.sparse.SparseRows` compiles one
    program per ``(row bucket, nnz bucket)`` pair, so mixed batches whose
    max row-nnz lands in the same power of two share their executables
    exactly like mixed sample counts sharing a row bucket do.

    Unlike row padding (weight-0 rows), a padded SLOT is inert by value:
    it carries ``value=0`` at ``col=0``, contributing exactly 0.0 to every
    contraction — no mask needed. ``record=True`` notes the ``(bucket, k)``
    pair into ``compile_stats()['nnz_buckets']``; size queries pass
    ``record=False``."""
    k = int(k)
    if k < 0:
        raise ValueError(f"k must be >= 0, got {k}")
    b = max(int(min_slots), 1)
    target = max(k, 1)
    bucket = max(1 << (target - 1).bit_length(), b)
    if record:
        with _stats_lock:
            _nnz_buckets.setdefault(int(bucket), set()).add(k)
    return bucket


def bucket_cols(d: int, align: int = 1, record: bool = True) -> int:
    """Padded FEATURE count for ``d`` true columns on a mesh whose model
    axis has ``align`` shards: plain align-rounding, deliberately WITHOUT
    the row policy's waste-capped bucketing. Fitted-state shapes (coef
    vectors, components, Hessian blocks) follow the padded width, so
    bucketing d would let nearby feature counts silently change the shape
    of returned model state; columns only ever pad to the exact model
    multiple. ``record=True`` notes the pair into
    ``compile_stats()['col_buckets']`` so the compile census shows which
    feature paddings actually staged; size queries pass ``record=False``.
    """
    d = int(d)
    if d < 0:
        raise ValueError(f"d must be >= 0, got {d}")
    align = max(int(align), 1)
    padded = -(-d // align) * align
    if record:
        with _stats_lock:
            _col_buckets.setdefault(int(padded), set()).add(d)
    return padded


def pad_tail(arrays: Sequence[np.ndarray], rows: int) -> tuple:
    """Zero-pad every array of a block tuple along axis 0 up to ``rows``.

    The contract that makes zero the right fill: the consuming solvers all
    carry an explicit per-row weight array in the block tuple ((X, w) for
    the moment accumulators, (X, y, w) for the GLMs), and a zero-padded
    weight row is weight 0 — the padding is inert in every weighted
    reduction. A consumer without a weight array must not use this.
    """
    import jax

    def pad_one(a):
        a = np.asarray(a)
        if a.shape[0] > rows:
            raise ValueError(
                f"block has {a.shape[0]} rows, more than the target {rows}")
        if a.shape[0] < rows:
            pad = np.zeros((rows - a.shape[0],) + a.shape[1:], a.dtype)
            a = np.concatenate([a, pad], axis=0)
        return a

    # leaf-wise over each element: a plain array is its own leaf; a sparse
    # container (a registered pytree, docs/sparse.md) pads BOTH its leaves
    # — padded rows hold zero values at col 0, inert by value
    return tuple(jax.tree_util.tree_map(pad_one, a) for a in arrays)


# ---------------------------------------------------------------------------
# compile observability (jax.monitoring listeners)
# ---------------------------------------------------------------------------

# One actual XLA compile emits exactly one backend_compile duration event;
# every trace (including cache hits re-tracing under new avals) emits a
# jaxpr_trace event. Event names verified against the pinned jax (0.4.x).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_stats_lock = threading.Lock()
_stats = {
    "n_compiles": 0,
    "compile_seconds": 0.0,
    "n_traces": 0,
    "trace_seconds": 0.0,
}
# padded bucket size -> set of distinct true row counts staged into it
_buckets: dict = {}
# padded ELL width -> set of distinct true max-row-nnz values staged into it
_nnz_buckets: dict = {}
# padded feature count -> set of distinct true column counts staged into it
_col_buckets: dict = {}
_listeners_installed = False


def _on_duration(event: str, duration: float, **_kw) -> None:
    if event == _COMPILE_EVENT:
        with _stats_lock:
            _stats["n_compiles"] += 1
            _stats["compile_seconds"] += float(duration)
        _mirror_compile_event("compile.n_compiles",
                              "compile.compile_seconds", duration)
    elif event == _TRACE_EVENT:
        with _stats_lock:
            _stats["n_traces"] += 1
            _stats["trace_seconds"] += float(duration)
        _mirror_compile_event("compile.n_traces",
                              "compile.trace_seconds", duration)


def _mirror_compile_event(count_name: str, seconds_name: str,
                          duration: float) -> None:
    """Registry mirror of one compile/trace event (telemetry on only; the
    compiling thread runs the listener, so the thread-local knob sees the
    scope that triggered the compile)."""
    from dask_ml_tpu.parallel import telemetry

    if telemetry.enabled():
        reg = telemetry.metrics()
        reg.counter(count_name).inc()
        reg.counter(seconds_name).inc(float(duration))


def _install_listeners() -> None:
    """Idempotent registration of the jax.monitoring duration listener.
    Installed lazily on first stats use (registration is global and
    permanent in jax; the callback is a couple of guarded counter
    increments, negligible next to any compile)."""
    global _listeners_installed
    with _stats_lock:
        if _listeners_installed:
            return
        _listeners_installed = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_duration)


def note_bucket(n_valid: int, padded: int) -> None:
    """Record that ``n_valid`` true rows were staged into the ``padded``
    bucket — the data behind ``compile_stats()['shape_buckets']``. Also
    counts the hit into the telemetry registry
    (``shapes.bucket_hits{bucket=...}``) when the knob is on: the
    compile-stats set records only DISTINCT (bucket, n) pairs, the
    telemetry counter every staging that landed in the bucket."""
    with _stats_lock:
        _buckets.setdefault(int(padded), set()).add(int(n_valid))
    from dask_ml_tpu.parallel import telemetry

    if telemetry.enabled():
        telemetry.metrics().counter(
            "shapes.bucket_hits", bucket=int(padded)).inc()


def compile_stats() -> dict:
    """Snapshot of the process-wide compile counters since the last
    :func:`reset_compile_stats`:

    - ``n_compiles`` / ``compile_seconds`` — actual XLA backend compiles
      (cache hits do not count);
    - ``n_traces`` / ``trace_seconds`` — jaxpr traces (a re-trace that hits
      the executable cache still counts here);
    - ``shape_buckets`` — ``{padded_size: sorted true row counts}`` staged
      by the bucketing layer, i.e. which distinct sample counts shared a
      program shape.

    Counters only start accumulating once the listener is installed, which
    happens on the first call to any function in this section — call
    :func:`reset_compile_stats` (or this) BEFORE the workload you want to
    measure.
    """
    _install_listeners()
    with _stats_lock:
        out = dict(_stats)
        out["shape_buckets"] = {k: sorted(v) for k, v in _buckets.items()}
        out["nnz_buckets"] = {k: sorted(v)
                              for k, v in _nnz_buckets.items()}
        out["col_buckets"] = {k: sorted(v)
                              for k, v in _col_buckets.items()}
    return out


def reset_compile_stats() -> dict:
    """Zero the counters (and install the listener if needed); returns the
    pre-reset snapshot."""
    _install_listeners()
    with _stats_lock:
        out = dict(_stats)
        out["shape_buckets"] = {k: sorted(v) for k, v in _buckets.items()}
        out["nnz_buckets"] = {k: sorted(v)
                              for k, v in _nnz_buckets.items()}
        out["col_buckets"] = {k: sorted(v)
                              for k, v in _col_buckets.items()}
        _stats.update(n_compiles=0, compile_seconds=0.0,
                      n_traces=0, trace_seconds=0.0)
        _buckets.clear()
        _nnz_buckets.clear()
        _col_buckets.clear()
    return out


@contextlib.contextmanager
def track_compiles():
    """Scoped delta capture: ``with track_compiles() as t: ...`` leaves
    ``t['n_compiles']`` etc. holding the counts accumulated INSIDE the
    scope (process-wide — concurrent compiles from other threads land in
    the same delta; use from the driving thread of the workload under
    measurement). The global counters are not reset."""
    _install_listeners()
    with _stats_lock:
        before = dict(_stats)
    delta: dict = {}
    try:
        yield delta
    finally:
        with _stats_lock:
            for k, v in _stats.items():
                delta[k] = v - before[k]


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------


def enable_persistent_cache(path: Optional[str]) -> None:
    """Point XLA's persistent compilation cache at ``path`` (process-wide),
    so a second process re-running the same shapes loads executables from
    disk instead of recompiling — the warm start ``bench.py
    --compile-report`` measures. ``None`` disables it again.

    The minimum-compile-time threshold is dropped to 0: this stack runs
    MANY tiny programs (per-shape staging pads, gathers, reductions) whose
    fixed per-program overhead is exactly what a warm start should erase.
    """
    import os

    import jax

    if path is not None:
        path = os.path.expanduser(str(path))
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except AttributeError:  # older jaxlib: knob absent, default is fine
            pass
    else:
        jax.config.update("jax_compilation_cache_dir", None)
    # jax initializes its cache object lazily ONCE; flipping the dir after
    # any compile has happened would otherwise be silently ignored for the
    # rest of the process
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:  # pragma: no cover - private API moved; dir still
        pass  # applies to processes that set the knob before first compile
