"""Online inference service: continuously-batched, compile-once serving.

Every ``predict``/``transform`` call in this package is a one-shot facade:
validate, stage, dispatch one program, fetch. That is the right shape for a
fit-time evaluation and exactly the wrong shape for live traffic — a
service handling concurrent small requests would pay per-call staging, a
fresh dispatch per request, and (before PR 4/PR 9's staging work) a
compile per distinct request length. This module converts the substrate
the previous PRs built — :class:`~dask_ml_tpu.parallel.shapes.PadPolicy`
shape buckets, the PR-5 precision wire, the PR-7 telemetry registry —
into a persistent serving subsystem (ROADMAP item 1; the
continuous-batching discipline of modern inference servers applied to the
``ParallelPostFit`` wrapper the reference ships, reference wrappers.py:
124-272):

- :class:`ModelRegistry` holds many FITTED estimators resident behind
  stable names. Registration builds one *runner* per predict family —
  KMeans assignment (``models/kmeans.py::predict_labels`` over the fused
  distance kernels), GLM ``predict``/``predict_proba``
  (``linear_model/glm.py::eta_program`` + the shared host epilogues), PCA
  ``transform`` (``decomposition/pca.py::transform_program``), and
  spectral out-of-sample ``predict``
  (``SpectralClustering._assign_staged``) — each closing over the fitted
  state staged device-side ONCE. Anything else (foreign sklearn
  estimators included) gets a host-fallback runner, so the batching path
  is universal even where the compile-once guarantee is not.
- :class:`ServingLoop` owns a long-lived dispatch thread and a bounded
  queue. ``submit()`` validates a request host-side (no device work on
  the client thread) and returns a ``concurrent.futures.Future``; the
  dispatch thread coalesces queued requests for the same (model, method)
  into one micro-batch, zero-pads it HOST-side to a serving-tuned
  :class:`~dask_ml_tpu.parallel.shapes.PadPolicy` bucket in the precision
  wire dtype, stages it with a single sharded ``device_put``, runs the
  family's jitted program, and scatters per-request row slices back to
  the caller futures. Because padding happens on host and the per-bucket
  programs are pre-warmed (:meth:`ServingLoop.warmup`), steady-state
  traffic compiles NOTHING — not even the per-shape pad/slice trivia a
  direct call used to pay — gated via
  :func:`~dask_ml_tpu.parallel.shapes.compile_stats` by ``bench.py
  --serving`` and the CI ``serving`` job.
- **Bit-identity.** Every runner routes through the SAME jitted program
  and host epilogue as the estimator's direct method, and every program
  is row-independent (each output row depends only on its input row and
  the replicated fitted state), so a served result equals the direct call
  bit-for-bit regardless of how requests were coalesced or padded
  (pinned per family across ragged sizes in ``tests/test_serving.py``).
- **Observability** goes through the PR-7 telemetry layer only (no new
  surface): ``serving.request`` spans on the blocking client path
  (:meth:`ServingLoop.call`), ``serving.batch`` spans in the dispatch
  thread, ``serving.queue_depth`` / ``serving.batch_occupancy`` gauges,
  per-model ``serving.requests``/``serving.rows``/``serving.batches``/
  ``serving.errors`` counters, and ``serving.request_seconds`` /
  ``serving.batch_seconds`` latency histograms whose
  :meth:`~dask_ml_tpu.parallel.telemetry.Histogram.percentiles` are the
  p50/p99 the bench commits. The dispatch thread inherits the creating
  thread's effective ``telemetry`` knob at :meth:`ServingLoop.start`.
- **Lifecycle.** The loop composes with
  :class:`~dask_ml_tpu.parallel.faults.GracefulDrain`: on SIGTERM (or
  ``drain.request()``) it stops accepting, flushes every queued batch,
  resolves all futures, and exits. A
  :class:`~dask_ml_tpu.parallel.faults.FaultInjector` transfer fault
  surfaces as per-request errors on the affected batch only — optionally
  retried under a :class:`~dask_ml_tpu.parallel.faults.RetryPolicy` —
  and never wedges the queue.

- **SLO-aware admission** (ISSUE 14): ``submit(priority=, deadline=)``
  — the dispatcher coalesces EARLIEST-DEADLINE-FIRST (priority breaks
  ties and orders the deadline-less best-effort tier), and a request
  whose deadline passes before dispatch is SHED with
  :class:`DeadlineExceeded` instead of queueing to death. A stop/drain
  is race-free by construction: once one begins, ``submit`` raises
  :class:`ServingStopped`, and the dispatch thread's exit hygiene fails
  everything it can no longer serve — a future is NEVER left pending,
  even if the thread dies (``fatal``).
- **Versioning**: registry entries carry a monotonic ``version``;
  ``publish()``/``build()+install()`` are the zero-downtime hot-swap
  seams the fleet builds on.

``ParallelPostFit(serving=loop)`` turns the sklearn-facing wrapper into a
thin client of this loop (a :class:`~dask_ml_tpu.parallel.fleet.
ServingFleet` drops in the same way); see ``docs/serving.md`` for bucket
tuning, the latency-vs-occupancy tradeoff, and the fleet tier above this
loop.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Optional

import numpy as np

from dask_ml_tpu.parallel.shapes import PadPolicy

__all__ = [
    "ServingLoop",
    "ModelRegistry",
    "ServedModel",
    "ServingError",
    "ServingClosed",
    "ServingStopped",
    "ServingQueueFull",
    "DeadlineExceeded",
    "DEFAULT_SERVING_POLICY",
    "serving_buckets",
]


class ServingError(RuntimeError):
    """Base class for serving-layer errors."""


class ServingClosed(ServingError):
    """The loop is draining or stopped: no new requests are accepted."""


class ServingStopped(ServingClosed):
    """The loop has stopped (drain finished, ``stop(drain=False)``, or the
    dispatch thread died): a request that reached it will NEVER be served
    here. Raised synchronously by ``submit()`` once a stop/drain has
    begun, and set on any future the stopped loop could no longer serve —
    a request is never left forever-pending (pinned by the barrier test in
    ``tests/test_serving.py``). The fleet router treats this as the
    re-route-and-replay signal (``parallel/fleet.py``)."""


class ServingQueueFull(ServingError):
    """The bounded request queue is at capacity (backpressure): the caller
    should retry with backoff or shed load. At fleet level the router
    spills over to a sibling replica before surfacing this
    (``parallel/fleet.py``)."""


class DeadlineExceeded(ServingError):
    """The request's SLO deadline passed before it could be dispatched:
    it was SHED (failed fast) instead of queueing to death. Raised
    synchronously when the deadline is already past at ``submit()``, set
    on the future when it expires while queued."""


#: Serving-tuned bucket policy: pure powers of two from a 32-row floor.
#: ``waste_cap=1.0`` keeps the bucket count minimal (one per octave —
#: "a handful of pre-warmed programs" to cover any mix of request sizes)
#: at the price of up to 2x padded rows per batch; padding rows cost only
#: device FLOPs, which the small-batch regime has to spare, while every
#: extra bucket costs a warmup compile per (model, method).
DEFAULT_SERVING_POLICY = PadPolicy(waste_cap=1.0, min_rows=32)


def serving_buckets(policy: PadPolicy, max_rows: int, align: int = 1):
    """The distinct bucket sizes ``policy`` can produce for batches of 1..
    ``max_rows`` rows — the program shapes :meth:`ServingLoop.warmup`
    pre-compiles. Ascending; the top bucket covers ``max_rows`` itself."""
    out = []
    n = 1
    while n <= int(max_rows):
        b = policy.bucket(n, align=align)
        out.append(b)
        n = b + 1
    return out


# ---------------------------------------------------------------------------
# per-family runners
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Runner:
    """One served method: ``kind`` is ``"device"`` (``run`` takes a staged
    padded device array, returns padded host outputs to row-slice) or
    ``"host"`` (``run`` takes the unpadded concatenated host batch)."""

    kind: str
    run: Callable


def _glm_runners(est) -> dict:
    import jax.numpy as jnp

    from dask_ml_tpu.linear_model import glm as glm_lib

    coef = jnp.asarray(est._coef, jnp.float32)
    intercept = bool(est.fit_intercept)

    def eta(Xs):
        return np.asarray(
            glm_lib.eta_program(Xs, coef, intercept=intercept))

    runners = {}
    family = getattr(est, "family", None)
    if hasattr(est, "predict_proba"):  # classifier
        multiclass = getattr(est, "multiclass", "ovr")
        classes = getattr(est, "classes_", None)

        def run_proba(Xs):
            return glm_lib.proba_from_eta(eta(Xs), multiclass)

        def run_predict(Xs):
            return glm_lib.labels_from_proba(run_proba(Xs), classes)

        runners["predict_proba"] = _Runner("device", run_proba)
        runners["predict"] = _Runner("device", run_predict)
    elif family == "poisson":
        runners["predict"] = _Runner("device", lambda Xs: np.exp(eta(Xs)))
    else:  # linear
        runners["predict"] = _Runner("device", eta)
    return runners


def _kmeans_runners(est) -> dict:
    import jax.numpy as jnp

    from dask_ml_tpu.models import kmeans as km_core

    if getattr(est, "fast_transform_", None) is not None:
        # sketched model: serve through the SAME dispatch facade
        # KMeans.predict uses (against sketch_centers_, so whichever
        # branch the decisions cache picks, served labels are
        # bit-identical to direct predict calls by construction)
        sketch_args = est._sketch_args()

        def run(Xs):
            labels = km_core.predict_labels_sketched(Xs, *sketch_args)
            if int(est.n_clusters) <= 255:
                return np.asarray(
                    labels.astype(jnp.uint8)).astype(np.int32)
            return np.asarray(labels)

        return {"predict": _Runner("device", run)}

    centers = jnp.asarray(est.cluster_centers_)

    def run(Xs):
        # same program + uint8-wire epilogue as KMeans.predict's host path
        labels = km_core.predict_labels(Xs, centers)
        if int(est.n_clusters) <= 255:
            return np.asarray(labels.astype(jnp.uint8)).astype(np.int32)
        return np.asarray(labels)

    return {"predict": _Runner("device", run)}


def _pca_runners(est) -> dict:
    import jax.numpy as jnp

    from dask_ml_tpu.decomposition import pca as pca_lib

    mean = jnp.asarray(est.mean_)
    components = jnp.asarray(est.components_)
    ev = jnp.asarray(est.explained_variance_)
    whiten = bool(est.whiten)

    def run(Xs):
        return np.asarray(pca_lib.transform_program(
            Xs, mean, components, ev, whiten=whiten))

    return {"transform": _Runner("device", run)}


def _spectral_runners(est) -> dict:
    def run(Xs):
        return np.asarray(est._assign_staged(Xs)).astype(np.int32)

    return {"predict": _Runner("device", run)}


def _host_runners(est, methods) -> dict:
    """Fallback for anything else (foreign sklearn estimators included):
    the loop still coalesces concurrent requests into one host batch per
    dispatch — sklearn kernels amortize per-call overhead over the batch —
    but there is no staged program, so the compile-once guarantee does
    not apply."""
    out = {}
    for m in methods:
        fn = getattr(est, m, None)
        if callable(fn):
            out[m] = _Runner("host", fn)
    return out


_SERVABLE_METHODS = ("predict", "predict_proba", "transform")


def _build_runners(est, methods=None) -> dict:
    """Family detection → runners. Explicit ``methods`` restricts the
    served surface; by default every servable method the family supports
    is exposed."""
    from dask_ml_tpu.cluster.k_means import KMeans
    from dask_ml_tpu.cluster.kernel_kmeans import KernelKMeans
    from dask_ml_tpu.cluster.minibatch import MiniBatchKMeans
    from dask_ml_tpu.cluster.spectral import SpectralClustering
    from dask_ml_tpu.decomposition.pca import PCA
    from dask_ml_tpu.linear_model.glm import _GLM

    if isinstance(est, KMeans):
        runners = _kmeans_runners(est)
    elif isinstance(est, MiniBatchKMeans):
        # same fitted surface as KMeans (cluster_centers_, n_clusters,
        # never sketched), so the same staged runner serves it
        runners = _kmeans_runners(est)
    elif isinstance(est, KernelKMeans):
        # landmark assignment program, shared with predict (bit-equal)
        runners = _spectral_runners(est)
    elif isinstance(est, SpectralClustering):
        km = getattr(est, "assign_labels_", None)
        if isinstance(km, KMeans) and not callable(est.affinity):
            runners = _spectral_runners(est)
        else:  # eager kernel strip / foreign assigner: host path
            runners = _host_runners(est, _SERVABLE_METHODS)
    elif isinstance(est, PCA):
        runners = _pca_runners(est)
    elif isinstance(est, _GLM):
        runners = _glm_runners(est)
    else:
        runners = _host_runners(est, _SERVABLE_METHODS)
    if methods is not None:
        missing = [m for m in methods if m not in runners]
        if missing:
            raise ValueError(
                f"estimator {type(est).__name__} cannot serve "
                f"method(s) {missing}; available: {sorted(runners)}")
        runners = {m: runners[m] for m in methods}
    if not runners:
        raise ValueError(
            f"estimator {type(est).__name__} exposes none of "
            f"{_SERVABLE_METHODS}")
    return runners


def _n_features_of(est) -> Optional[int]:
    for attr, width in (
        # landmark models first: their cluster_centers_ live in the
        # l-dimensional Nyström feature space, not the input space
        ("_landmarks_", lambda a: a.shape[1]),
        ("cluster_centers_", lambda a: a.shape[1]),
        ("mean_", lambda a: a.shape[0]),
    ):
        a = getattr(est, attr, None)
        if a is not None:
            return int(width(np.asarray(a)))
    coef = getattr(est, "_coef", None)
    if coef is not None:
        return int(np.asarray(coef).shape[-1]
                   - (1 if getattr(est, "fit_intercept", False) else 0))
    nf = getattr(est, "n_features_in_", None)
    return int(nf) if nf is not None else None


@dataclasses.dataclass
class ServedModel:
    """A registered, fitted estimator with its per-method runners, the
    expected request width (``n_features``; ``None`` disables the width
    check for host-fallback models that do not declare one), and the
    registry-assigned monotonic ``version`` (0 until installed) — the
    hot-swap coordinate: a dispatched batch holds ITS ServedModel, so
    publishing a new version never perturbs in-flight work."""

    name: str
    estimator: object
    runners: dict
    n_features: Optional[int]
    version: int = 0

    @property
    def methods(self) -> tuple:
        return tuple(sorted(self.runners))


class ModelRegistry:
    """Named, fitted estimators resident behind one serving mesh.

    ``register`` builds the family runners (staging fitted state
    device-side once); ``ensure`` is the idempotent variant keyed on
    estimator identity that :class:`~dask_ml_tpu.wrappers.ParallelPostFit`
    uses. Every installed entry carries a registry-wide MONOTONIC version
    number; :meth:`publish` is the zero-downtime hot-swap seam — it
    atomically replaces whatever currently holds the name (bumping the
    version), while batches already dispatched finish on the ServedModel
    they resolved (``invalidate`` + re-``register`` remains the refit
    path for the same estimator object, same versioning). For swap with
    no cold-start blip, :meth:`build` + warmup + :meth:`install` splits
    publication so the new version's programs compile BEFORE it takes
    traffic (:meth:`ServingLoop.warmup_model`, ``ServingFleet.swap``).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._models: dict = {}
        self._by_id: dict = {}  # id(estimator) -> name (ensure() memo)
        self._next_version = 0

    def build(self, name: str, estimator, *, methods=None) -> ServedModel:
        """Construct a ServedModel (family detection + runners closing
        over device-staged state) WITHOUT installing it: version 0 until
        :meth:`install` publishes it."""
        return ServedModel(name=str(name), estimator=estimator,
                           runners=_build_runners(estimator, methods),
                           n_features=_n_features_of(estimator))

    def install(self, model: ServedModel) -> ServedModel:
        """Atomically publish ``model`` under its name, bumping the
        monotonic version — replaces any current holder (the hot-swap
        seam; use :meth:`register` when accidental replacement should be
        an error)."""
        with self._lock:
            self._next_version += 1
            model.version = self._next_version
            prior = self._models.get(model.name)
            if prior is not None and prior.estimator is not model.estimator:
                self._by_id.pop(id(prior.estimator), None)
            self._models[model.name] = model
            self._by_id[id(model.estimator)] = model.name
        return model

    def publish(self, name: str, estimator, *, methods=None) -> ServedModel:
        """Hot-swap: build + install in one call. New requests resolve the
        new version from their dispatch on; in-flight batches finish on
        the old one."""
        return self.install(self.build(name, estimator, methods=methods))

    def register(self, name: str, estimator, *, methods=None) -> ServedModel:
        model = self.build(name, estimator, methods=methods)
        with self._lock:
            prior = self._models.get(model.name)
            if prior is not None and prior.estimator is not estimator:
                raise ValueError(
                    f"model name {model.name!r} is already registered to a "
                    "different estimator; unregister it first (or pick a "
                    "distinct name, or publish() to hot-swap)")
            self._next_version += 1
            model.version = self._next_version
            self._models[model.name] = model
            self._by_id[id(estimator)] = model.name
        return model

    def version(self, name: str) -> int:
        """The installed version serving ``name`` (KeyError if absent)."""
        return self.get(name).version

    def ensure(self, estimator, name: Optional[str] = None) -> str:
        """Idempotent registration keyed on estimator identity: returns
        the existing name when this object is already registered."""
        with self._lock:
            existing = self._by_id.get(id(estimator))
            if existing is not None and existing in self._models \
                    and self._models[existing].estimator is estimator:
                return existing
        if name is None:
            name = f"{type(estimator).__name__.lower()}-{id(estimator):x}"
        return self.register(name, estimator).name

    def get(self, name: str) -> ServedModel:
        with self._lock:
            model = self._models.get(str(name))
        if model is None:
            raise KeyError(f"no model registered under {name!r}")
        return model

    def names(self) -> list:
        with self._lock:
            return sorted(self._models)

    def unregister(self, name: str) -> None:
        with self._lock:
            model = self._models.pop(str(name), None)
            if model is not None:
                self._by_id.pop(id(model.estimator), None)

    def invalidate(self, estimator) -> None:
        """Drop every entry serving ``estimator`` (by identity) — called
        after a refit mutates the fitted state the runners closed over."""
        with self._lock:
            stale = [n for n, m in self._models.items()
                     if m.estimator is estimator]
            for n in stale:
                del self._models[n]
            self._by_id.pop(id(estimator), None)


# ---------------------------------------------------------------------------
# the serving loop
# ---------------------------------------------------------------------------


def _fail_future(fut: Future, exc: BaseException) -> bool:
    """Deliver ``exc`` to ``fut`` whatever state it is in: claims an
    unclaimed future first (a client-cancelled one is dropped), tolerates
    one already claimed or already resolved by a racing path. Returns
    True when this call delivered the exception."""
    if fut.done():
        return False  # resolved/cancelled already (benign race)
    try:
        if not fut.set_running_or_notify_cancel():
            return False  # client cancelled while queued
    except RuntimeError:
        pass  # already claimed by the dispatch path
    try:
        fut.set_exception(exc)
        return True
    except Exception:
        return False  # already resolved — the race went the other way


@dataclasses.dataclass(eq=False)  # identity equality: deque.remove must
class _Request:                   # match THIS request, not array contents
    model: str
    method: str
    X: np.ndarray
    n: int
    future: Future
    t_enqueue: float
    #: coalesce key: (model, method) for device runners; host runners
    #: additionally split by input dtype so a foreign estimator sees each
    #: request's rows in exactly the dtype the caller passed (numpy
    #: concatenation would silently promote a mixed-dtype batch)
    key: tuple = ()
    #: SLO coordinates: higher ``priority`` wins among equal deadlines;
    #: ``deadline`` is the ABSOLUTE perf_counter instant past which the
    #: request is shed (None = best-effort, sorts after every deadline)
    priority: int = 0
    deadline: Optional[float] = None
    #: admission sequence (FIFO tiebreak inside one (deadline, priority))
    seq: int = 0

    def edf_key(self) -> tuple:
        """Earliest-deadline-first admission order: deadline, then
        priority (higher first), then arrival."""
        d = self.deadline if self.deadline is not None else float("inf")
        return (d, -self.priority, self.seq)


class ServingLoop:
    """Persistent dispatch loop coalescing concurrent requests into
    compile-once micro-batches (module docstring has the architecture).

    Parameters
    ----------
    registry : ModelRegistry, optional
        Shared registry; a private one is created by default.
    policy : PadPolicy
        Serving bucket policy (default :data:`DEFAULT_SERVING_POLICY`,
        powers of two from 32). Smaller ``min_rows``/more buckets trade
        warmup compiles for less padding waste; see docs/serving.md.
    max_batch_rows : int
        Row budget per micro-batch AND the per-request row cap
        (:attr:`max_request_rows`): larger batches amortize dispatch
        further but add head-of-line latency for the requests in them.
    max_queue : int
        Bounded queue capacity in REQUESTS; ``submit`` past it raises
        :class:`ServingQueueFull` (backpressure, never silent dropping).
    coalesce_window_s : float or "adaptive"
        Extra time the dispatcher may wait after picking a batch's first
        request to let the batch fill. ``"adaptive"`` (the default) runs
        the arrival-rate controller: at dispatch time the window is the
        predicted time for the batch to fill its CURRENT pad bucket —
        rows the padded program computes anyway, so occupancy is free —
        at the submit-side rows/s EWMA, clamped to
        ``coalesce_window_max_s`` and to the batch's tightest deadline
        slack (minus a compute-latency margin), and collapsed to EXACT
        zero when arrivals went idle. A float keeps the fixed-window
        semantics: 0 never waits (batching emerges from dispatch
        latency alone); a positive value always waits that long.
    coalesce_window_max_s : float
        Ceiling on the adaptive window (default 10 ms) — the most p50
        latency the controller may ever spend buying occupancy.
    mesh, drain, retry_policy, fault_injector
        Mesh override; a :class:`~dask_ml_tpu.parallel.faults.
        GracefulDrain` to compose shutdown with SIGTERM; a
        :class:`~dask_ml_tpu.parallel.faults.RetryPolicy` for transient
        transfer failures; a :class:`~dask_ml_tpu.parallel.faults.
        FaultInjector` whose ``on_transfer`` hook the batch staging calls
        (the same hook contract the streamed tier drills).
    """

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 policy: Optional[PadPolicy] = None,
                 max_batch_rows: int = 2048,
                 max_queue: int = 4096,
                 coalesce_window_s="adaptive",
                 coalesce_window_max_s: float = 0.010,
                 mesh=None,
                 drain=None,
                 retry_policy=None,
                 fault_injector=None,
                 name: str = "serving"):
        self.registry = registry if registry is not None else ModelRegistry()
        self.policy = policy if policy is not None else DEFAULT_SERVING_POLICY
        self.max_batch_rows = int(max_batch_rows)
        self.max_queue = int(max_queue)
        if isinstance(coalesce_window_s, str):
            if coalesce_window_s != "adaptive":
                raise ValueError(
                    f"coalesce_window_s must be a float or 'adaptive', "
                    f"got {coalesce_window_s!r}")
            self.coalesce_window_s = "adaptive"
        else:
            self.coalesce_window_s = float(coalesce_window_s)
        self.coalesce_window_max_s = float(coalesce_window_max_s)
        self.name = str(name)
        self._mesh = mesh
        self._drain = drain
        self._retry_policy = retry_policy
        self._fault_injector = fault_injector

        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._closed = False
        self._stopped = True
        self._stopped_requested = False
        self._thread: Optional[threading.Thread] = None
        self._telemetry_inherit = False
        self._wire = None
        self._sharding = None
        self._align = 1
        self._batch_seq = 0
        self._submit_seq = 0
        self._last_beat = time.monotonic()
        #: the exception that killed the dispatch thread (None = clean);
        #: submit() surfaces it so a crashed loop fails fast, and the
        #: fleet's health monitor reads it to classify the death
        self.fatal: Optional[BaseException] = None
        #: EWMA of reported batch latency (seconds) — the same quantity
        #: the serving.batch_seconds histogram observes (incl. any
        #: injected slow-replica penalty); the fleet router balances on
        #: this together with queue_depth()
        self._latency_ewma = 0.0
        # arrival-rate controller state (written under _cond at submit,
        # read — racily but benignly, they're floats — at dispatch):
        # inter-arrival gap EWMA, rows-per-request EWMA, last arrival
        self._ia_ewma = 0.0
        self._arrival_rows_ewma = 0.0
        self._last_arrival: Optional[float] = None
        #: the window the dispatcher chose for the LAST batch (the
        #: serving.window_s gauge's source)
        self.last_window_s = 0.0
        #: True while the dispatch thread is inside _execute — an
        #: in-flight batch is load the queue no longer shows, so the
        #: fleet router counts it
        self.busy = False
        # operational counters (drain/flush logic + stats(); the
        # OBSERVABILITY surface is the telemetry registry, not these)
        self.n_submitted = 0
        self.n_completed = 0
        self.n_errors = 0
        self.n_batches = 0
        self.rows_served = 0
        self.n_shed = 0

    # -- lifecycle ---------------------------------------------------------

    @property
    def max_request_rows(self) -> int:
        """Largest single request ``submit`` accepts (clients chunk above
        it — :class:`~dask_ml_tpu.wrappers.ParallelPostFit` does)."""
        return self.max_batch_rows

    def start(self) -> "ServingLoop":
        """Resolve the mesh/wire (facade-level, in the CALLING thread so
        scoped config is honored), then start the dispatch thread."""
        from dask_ml_tpu.parallel import mesh as mesh_lib
        from dask_ml_tpu.parallel import precision as precision_lib
        from dask_ml_tpu.parallel import telemetry

        if self._thread is not None and self._thread.is_alive():
            return self
        mesh = self._mesh or mesh_lib.default_mesh()
        self._mesh = mesh
        self._sharding = mesh_lib.data_sharding(mesh, ndim=2)
        self._align = mesh_lib.n_data_shards(mesh)
        self._wire = precision_lib.staging_wire_dtype()
        self._telemetry_inherit = telemetry.enabled()
        self._closed = False
        self._stopped = False
        self._stopped_requested = False
        self.fatal = None
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name=f"{self.name}-dispatch", daemon=True)
        self._thread.start()
        return self

    def __enter__(self) -> "ServingLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Stop the loop. ``drain=True`` (default) stops accepting new
        requests, lets the dispatch thread flush every queued batch, and
        resolves all futures before returning; ``drain=False`` fails
        queued requests with :class:`ServingClosed` immediately."""
        dropped: list = []
        with self._cond:
            self._closed = True
            if not drain:
                dropped = list(self._queue)
                self._queue = deque()
            self._stopped_requested = True
            self._cond.notify_all()
        for r in dropped:
            _fail_future(r.future, ServingStopped(
                "serving loop stopped without drain"))
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout)
        self._stopped = True

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def stopped(self) -> bool:
        return self._stopped

    def queue_depth(self) -> int:
        """Current queued request count — the same value the
        ``serving.queue_depth`` gauge exports; the fleet router reads it
        here so balancing works with telemetry off."""
        with self._cond:
            return len(self._queue)

    def latency_s(self) -> float:
        """EWMA of reported batch latency in seconds (the
        ``serving.batch_seconds`` surface, including any injected
        slow-replica penalty) — the router's second balancing signal."""
        return self._latency_ewma

    def heartbeat_age(self) -> float:
        """Seconds since the dispatch thread last proved liveness. It
        beats every COLLECT iteration — the beat cannot run inside a
        runner, so a batch that executes longer than the fleet's
        heartbeat timeout reads as a stall. That false positive is
        designed for: the fleet replays idempotently (duplicate compute
        only) and REVIVES a declared-dead replica whose heartbeat
        returns (``ServingFleet._monitor_loop``); a genuinely wedged
        batch never beats again and stays dead."""
        return time.monotonic() - self._last_beat

    def alive(self) -> bool:
        """True while the dispatch thread is running (started, not
        stopped, not crashed)."""
        t = self._thread
        return (t is not None and t.is_alive() and not self._stopped
                and self.fatal is None)

    def warmup(self, buckets=None, models=None) -> dict:
        """Pre-compile every (model, method, bucket) program by pushing a
        zero batch of each bucket size through the EXACT serving staging
        path. Returns ``{"n_programs", "n_compiles", "compile_seconds"}``
        so callers can log what warmup actually cost. After a warmup
        covering the traffic's buckets, steady-state serving compiles
        nothing (the ``bench.py --serving`` gate)."""
        from dask_ml_tpu.parallel.shapes import track_compiles

        if self._sharding is None:
            raise ServingError("start() the loop before warmup()")
        sizes = list(buckets) if buckets is not None else serving_buckets(
            self.policy, self.max_batch_rows, align=self._align)
        names = list(models) if models is not None else self.registry.names()
        n_programs = 0
        with track_compiles() as t:
            for name in names:
                n_programs += self.warmup_model(self.registry.get(name),
                                                buckets=sizes)
        return {"n_programs": n_programs,
                "n_compiles": t["n_compiles"],
                "compile_seconds": round(t["compile_seconds"], 3)}

    def warmup_model(self, model: ServedModel, buckets=None) -> int:
        """Pre-compile one ServedModel's device programs through the
        exact serving staging path — works on a NOT-yet-installed model
        (:meth:`ModelRegistry.build`), which is how a zero-downtime
        hot-swap compiles the incoming version before it takes traffic
        (``ServingFleet.swap``). Returns the program count."""
        if self._sharding is None:
            raise ServingError("start() the loop before warmup")
        sizes = list(buckets) if buckets is not None else serving_buckets(
            self.policy, self.max_batch_rows, align=self._align)
        d = model.n_features
        if d is None:
            return 0
        n_programs = 0
        for runner in model.runners.values():
            if runner.kind != "device":
                continue
            for b in sizes:
                buf = np.zeros((int(b), d), self._batch_dtype())
                runner.run(self._stage(buf))
                n_programs += 1
        return n_programs

    # -- client side -------------------------------------------------------

    def submit(self, model: str, X, method: str = "predict", *,
               priority: int = 0,
               deadline: Optional[float] = None) -> Future:
        """Enqueue one inference request; returns a Future resolving to
        the method's host-numpy result for exactly these rows.

        Validation runs HOST-side here so the dispatch thread only ever
        sees well-formed requests — a malformed request fails ITS caller,
        never a batch it would have shared. Device families get the same
        checks ``check_array`` applies on the direct path (staging cast +
        finiteness); host-fallback models receive the batch exactly as
        given (dtype preserved, NaN passed through) so a foreign
        estimator behaves identically to calling it directly — NaN-native
        models keep working, and its own validation errors stay its
        own.

        SLO admission: ``deadline`` is this request's latency budget in
        SECONDS from now; the dispatcher admits earliest-deadline-first
        (``priority`` breaks ties, and orders the deadline-less
        best-effort tier), and a request whose deadline passes before it
        can dispatch is SHED with :class:`DeadlineExceeded` — immediately
        when the budget is already non-positive here — instead of
        queueing to death."""
        from dask_ml_tpu.parallel import telemetry
        from dask_ml_tpu.utils.validation import staging_dtype

        model = str(model)
        entry = self.registry.get(model)  # KeyError for unknown names
        runner = entry.runners.get(method)
        if runner is None:
            raise ValueError(
                f"model {model!r} does not serve {method!r}; "
                f"available: {list(entry.methods)}")
        arr = np.asarray(X)
        if arr.ndim != 2:
            raise ValueError(
                f"Expected 2D array, got {arr.ndim}D array of shape "
                f"{arr.shape}")
        if arr.shape[0] < 1:
            raise ValueError("request has no rows")
        if arr.shape[0] > self.max_request_rows:
            raise ValueError(
                f"request has {arr.shape[0]} rows, above the per-request "
                f"cap {self.max_request_rows}; split it (ParallelPostFit's "
                "serving mode chunks automatically)")
        if entry.n_features is not None and arr.shape[1] != entry.n_features:
            raise ValueError(
                f"model {model!r} expects {entry.n_features} features, "
                f"request has {arr.shape[1]}")
        if runner.kind == "device":
            kind = np.dtype(arr.dtype).kind
            if kind not in "fiub":
                raise ValueError(f"Unsupported dtype {arr.dtype}")
            sd = staging_dtype(arr.dtype)
            if sd is not None:
                arr = arr.astype(sd)
            if np.dtype(arr.dtype).kind == "f" \
                    and not bool(np.isfinite(arr).all()):
                raise ValueError("Input contains NaN or infinity")
            key = (model, str(method))
        else:
            key = (model, str(method), str(arr.dtype))

        now = time.perf_counter()
        if deadline is not None and float(deadline) <= 0.0:
            self._count_shed(model)
            raise DeadlineExceeded(
                f"request deadline {float(deadline):.3f}s is already past "
                "at admission")
        fut: Future = Future()
        req = _Request(model=model, method=str(method), X=arr,
                       n=int(arr.shape[0]), future=fut,
                       t_enqueue=now, key=key, priority=int(priority),
                       deadline=(None if deadline is None
                                 else now + float(deadline)))
        with self._cond:
            if self._drain is not None and self._drain.requested:
                # SIGTERM landed: stop accepting IMMEDIATELY (the dispatch
                # thread flushes what is already queued)
                self._closed = True
                self._cond.notify_all()
            if self._stopped or self.fatal is not None:
                raise ServingStopped(
                    f"serving loop {self.name!r} has stopped"
                    + (f" ({self.fatal!r})" if self.fatal is not None
                       else ""))
            if self._closed:
                raise ServingStopped(
                    f"serving loop {self.name!r} is draining and not "
                    "accepting requests")
            if len(self._queue) >= self.max_queue:
                raise ServingQueueFull(
                    f"serving queue at capacity ({self.max_queue})")
            req.seq = self._submit_seq
            self._submit_seq += 1
            self._queue.append(req)
            depth = len(self._queue)
            self.n_submitted += 1
            # arrival-rate tracking for the adaptive coalesce window
            if self._last_arrival is not None:
                gap = max(now - self._last_arrival, 1e-06)
                self._ia_ewma = (gap if self._ia_ewma == 0.0
                                 else 0.8 * self._ia_ewma + 0.2 * gap)
            self._arrival_rows_ewma = (
                float(req.n) if self._arrival_rows_ewma == 0.0
                else 0.8 * self._arrival_rows_ewma + 0.2 * req.n)
            self._last_arrival = now
            self._cond.notify()
        if telemetry.enabled():
            telemetry.metrics().gauge("serving.queue_depth").set(depth)
        return fut

    def _count_shed(self, model: str, n: int = 1) -> None:
        from dask_ml_tpu.parallel import telemetry

        self.n_shed += n
        if telemetry.enabled():
            telemetry.metrics().counter("serving.shed", model=model).inc(n)

    def call(self, model: str, X, method: str = "predict",
             timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: ``submit`` + wait, wrapped in a
        ``serving.request`` span — the canonical client-side request
        (per-request latency lands in the span tree AND, loop-side, in
        the ``serving.request_seconds`` histogram)."""
        from dask_ml_tpu.parallel import telemetry

        with telemetry.span("serving.request", model=str(model),
                            method=str(method)):
            return self.submit(model, X, method=method).result(timeout)

    def stats(self) -> dict:
        """Operational snapshot (observability lives in the telemetry
        registry — ``telemetry_report()`` — not here)."""
        with self._cond:
            depth = len(self._queue)
        return {
            "models": self.registry.names(),
            "queue_depth": depth,
            "submitted": self.n_submitted,
            "completed": self.n_completed,
            "errors": self.n_errors,
            "batches": self.n_batches,
            "rows_served": self.rows_served,
            "shed": self.n_shed,
            "latency_ewma_s": round(self._latency_ewma, 6),
            "closed": self._closed,
        }

    # -- dispatch side -----------------------------------------------------

    def _batch_dtype(self):
        if self._wire is not None:
            return np.dtype(self._wire)
        return np.dtype(np.float32)

    def _stage(self, buf: np.ndarray):
        """One sharded ``device_put`` of the host-padded batch — the
        fault-injection hook and retry policy wrap exactly this transfer,
        mirroring the streamed tier's ``device_put`` contract."""
        import jax

        seq = self._batch_seq

        def put():
            if self._fault_injector is not None:
                self._fault_injector.on_transfer(seq)
            return jax.device_put(buf, self._sharding)

        if self._retry_policy is not None:
            return self._retry_policy.run(
                put, kind="serving-transfer", detail=f"batch {seq}")
        return put()

    def _shed_expired_locked(self) -> list:
        """Under the lock: pull every queued request whose deadline has
        passed. The caller resolves them OUTSIDE the lock (future
        callbacks — e.g. the fleet router's — must never run under it)."""
        now = time.perf_counter()
        if not any(r.deadline is not None and r.deadline < now
                   for r in self._queue):
            return []
        live: deque = deque()
        shed = []
        for r in self._queue:
            if r.deadline is not None and r.deadline < now:
                shed.append(r)
            else:
                live.append(r)
        self._queue = live
        return shed

    def _resolve_shed(self, shed: list) -> None:
        for r in shed:
            late = time.perf_counter() - r.deadline
            if _fail_future(r.future, DeadlineExceeded(
                    f"request for {r.model!r}.{r.method} shed: deadline "
                    f"passed {late * 1e3:.1f} ms before dispatch")):
                self._count_shed(r.model)

    def _pull_mates_locked(self, key, batch, rows) -> int:
        """Under the lock: move every queued request sharing ``key`` into
        ``batch`` (earliest-deadline-first) while the row budget holds.
        One sort + one queue rebuild — O(n log n); per-mate
        ``deque.remove`` would be O(n²) exactly when the queue is
        deepest, with every submit blocked on this lock."""
        mates = [r for r in self._queue if r.key == key]
        if not mates:
            return rows
        mates.sort(key=_Request.edf_key)
        taken = set()
        for r in mates:
            if rows + r.n <= self.max_batch_rows:
                taken.add(id(r))
                batch.append(r)
                rows += r.n
        if taken:
            self._queue = deque(r for r in self._queue
                                if id(r) not in taken)
        return rows

    def _collect(self) -> list:
        """Under the condition lock: wait for work, shed past-deadline
        requests, then pull the earliest-deadline (then highest-priority,
        then oldest) request plus every queued request sharing its
        (model, method) coalesce key, up to the batch row budget.
        Returns [] when told to exit."""
        shed: list = []
        try:
            with self._cond:
                while True:
                    self._last_beat = time.monotonic()
                    shed.extend(self._shed_expired_locked())
                    if self._queue:
                        break
                    if self._closed or self._stopped \
                            or self._stopped_requested:
                        return []
                    if self._drain is not None and self._drain.requested:
                        self._closed = True
                        return []
                    self._cond.wait(timeout=0.05)
                first = min(self._queue, key=_Request.edf_key)
                self._queue.remove(first)
                batch = [first]
                rows = self._pull_mates_locked(first.key, batch, first.n)
        finally:
            self._resolve_shed(shed)
        if self.coalesce_window_s == "adaptive":
            now = time.perf_counter()
            window = self._adaptive_window(batch, rows, now)
            deadline = now + window
        else:
            window = self.coalesce_window_s
            deadline = first.t_enqueue + window
        self.last_window_s = window
        if window > 0:
            while time.perf_counter() < deadline \
                    and rows < self.max_batch_rows:
                with self._cond:
                    if not self._queue:
                        remaining = deadline - time.perf_counter()
                        if remaining > 0:
                            self._cond.wait(timeout=remaining)
                    before = len(batch)
                    rows = self._pull_mates_locked(first.key, batch, rows)
                    pulled = len(batch) > before
                    if self._closed or self._stopped:
                        break
                if not pulled and time.perf_counter() >= deadline:
                    break
        return batch

    #: arrivals older than max(this, 10 inter-arrival EWMAs) read as an
    #: idle trace — the adaptive window collapses to exact zero
    IDLE_AFTER_S = 0.005

    def _adaptive_window(self, batch: list, rows: int,
                         now: float) -> float:
        """The arrival-rate controller's window for one batch: the
        predicted time for ``rows`` to grow into their CURRENT pad
        bucket (capacity the padded program computes whether or not it
        is used, so filling it is free occupancy), at the submit-side
        rows/s EWMA. Zero when idle, when the batch is already full or
        at a bucket boundary, or when the rate says waiting buys
        nothing within the ``coalesce_window_max_s`` budget; otherwise
        clamped to that budget and to the batch's tightest deadline
        slack minus a compute-latency margin."""
        ia = self._ia_ewma
        if ia <= 0.0 or rows >= self.max_batch_rows:
            return 0.0
        last = self._last_arrival
        if last is None \
                or now - last > max(10.0 * ia, self.IDLE_AFTER_S):
            return 0.0  # idle trace: dispatch immediately
        bucket = min(self.policy.bucket(rows, align=self._align),
                     self.max_batch_rows)
        if rows >= bucket:
            return 0.0  # at the boundary: more rows would cost a recompile-sized bucket
        rate = self._arrival_rows_ewma / ia  # rows per second
        if rate <= 0.0:
            return 0.0
        window = (bucket - rows) / rate
        if window > self.coalesce_window_max_s:
            # the bucket cannot fill within the budget: wait the budget
            # only if it still buys at least one more arrival, else the
            # wait is pure latency — dispatch now
            if ia > self.coalesce_window_max_s:
                return 0.0
            window = self.coalesce_window_max_s
        slack = min((r.deadline - now for r in batch
                     if r.deadline is not None), default=None)
        if slack is not None:
            # leave room to actually compute the batch before the
            # tightest deadline sheds it
            window = min(window, slack - 1.5 * self._latency_ewma)
        return max(window, 0.0)

    def _execute(self, batch: list) -> None:
        from dask_ml_tpu.parallel import telemetry

        # claim every future FIRST: a request its caller cancelled before
        # dispatch is dropped here, and a claimed (running) future can no
        # longer be cancelled, so the set_result/set_exception below
        # cannot race a client-side cancel into an InvalidStateError that
        # would kill the dispatch thread
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        model_name, method = batch[0].model, batch[0].method
        rows = sum(r.n for r in batch)
        tel = telemetry.enabled()
        t0 = time.perf_counter()
        self._batch_seq += 1
        try:
            model = self.registry.get(model_name)
            runner = model.runners[method]
            with telemetry.span("serving.batch", model=model_name,
                                method=method, n_requests=len(batch),
                                rows=rows) as sp:
                if runner.kind == "host":
                    hb = (batch[0].X if len(batch) == 1 else
                          np.concatenate([r.X for r in batch], axis=0))
                    out = np.asarray(runner.run(hb))
                    bucket = rows
                else:
                    bucket = self.policy.bucket(rows, align=self._align)
                    buf = np.zeros((bucket, model.n_features),
                                   self._batch_dtype())
                    off = 0
                    for r in batch:
                        buf[off:off + r.n] = r.X
                        off += r.n
                    out = np.asarray(runner.run(self._stage(buf)))
                sp.set(bucket=bucket)
        except Exception as e:  # noqa: BLE001 — per-request error delivery
            self.n_errors += len(batch)
            for r in batch:
                r.future.set_exception(e)
            if tel:
                telemetry.metrics().counter(
                    "serving.errors", model=model_name).inc(len(batch))
            return
        dt = time.perf_counter() - t0
        # synthetic straggler penalty (FaultInjector.slow_replica): added
        # to every latency this replica REPORTS — the EWMA/histograms its
        # router balances on — without sleeping, so failover drills are
        # deterministic and wall-clock-free
        penalty = (self._fault_injector.dispatch_penalty(self.name)
                   if self._fault_injector is not None else 0.0)
        dt += penalty
        now = time.perf_counter()
        off = 0
        for r in batch:
            r.future.set_result(out[off:off + r.n].copy())
            off += r.n
        self.n_completed += len(batch)
        self.n_batches += 1
        self.rows_served += rows
        self._latency_ewma = (dt if self._latency_ewma == 0.0
                              else 0.7 * self._latency_ewma + 0.3 * dt)
        if tel:
            reg = telemetry.metrics()
            reg.counter("serving.batches", model=model_name).inc()
            reg.counter("serving.requests", model=model_name).inc(len(batch))
            reg.counter("serving.rows", model=model_name).inc(rows)
            reg.gauge("serving.batch_occupancy").set(rows / max(bucket, 1))
            reg.gauge("serving.window_s").set(self.last_window_s)
            reg.histogram("serving.occupancy").observe(
                rows / max(bucket, 1))
            reg.histogram("serving.batch_rows").observe(rows)
            reg.histogram("serving.batch_seconds").observe(dt)
            lat = reg.histogram("serving.request_seconds", model=model_name)
            for r in batch:
                lat.observe(now - r.t_enqueue + penalty)

    def _run(self) -> None:
        import contextlib

        from dask_ml_tpu import config as config_lib
        from dask_ml_tpu.parallel import telemetry
        from dask_ml_tpu.parallel.faults import SimulatedReplicaDeath

        # the dispatch thread inherits an ENABLED telemetry scope from the
        # thread that called start() (thread-local scopes don't cross
        # threads; this makes config_context(telemetry=True) around
        # start() behave the way it reads). When start() saw the knob off,
        # install NO override: the thread then follows the global knob, so
        # set_config(telemetry=True) on a long-running loop takes effect
        # mid-flight.
        ctx = (config_lib.config_context(telemetry=True)
               if self._telemetry_inherit else contextlib.nullcontext())
        pending: list = []
        try:
            with ctx:
                while True:
                    batch = self._collect()
                    if not batch:
                        with self._cond:
                            drain_hit = (self._drain is not None
                                         and self._drain.requested)
                            if drain_hit:
                                self._closed = True
                            if (self._closed or self._stopped_requested) \
                                    and not self._queue:
                                self._stopped = True
                                self._cond.notify_all()
                                return
                        continue
                    pending = batch
                    fi = self._fault_injector
                    if fi is not None:
                        if fi.should_kill_replica(self.name,
                                                  self.n_batches):
                            raise SimulatedReplicaDeath(
                                f"replica {self.name!r} killed by fault "
                                f"plan after {self.n_batches} batches")
                        fi.on_dispatch(self._batch_seq)
                        # real-straggler plan (straggle_replica): stalls
                        # THIS dispatch's wall clock — the hedging drill's
                        # tail-latency source (getattr: foreign injectors
                        # predate the hook)
                        straggle = getattr(fi, "dispatch_sleep", None)
                        if straggle is not None:
                            straggle(self.name)
                    if telemetry.enabled():
                        with self._cond:
                            depth = len(self._queue)
                        telemetry.metrics().gauge(
                            "serving.queue_depth").set(depth)
                    self.busy = True
                    try:
                        self._execute(batch)
                    finally:
                        self.busy = False
                    pending = []
        except BaseException as e:  # noqa: BLE001 — record, then fail fast
            self.fatal = e
        finally:
            self._finalize(pending)

    def _finalize(self, pending: list) -> None:
        """Dispatch-thread exit hygiene, clean or not: close the loop and
        fail EVERY request the thread can no longer serve — the collected
        batch it never executed plus the whole queue — with the fatal
        error (crash) or :class:`ServingStopped`. A request is never left
        forever-pending, whatever killed the thread."""
        with self._cond:
            self._closed = True
            self._stopped = True
            leftovers = list(pending) + list(self._queue)
            self._queue = deque()
            self._cond.notify_all()
        if not leftovers and self.fatal is None:
            return
        exc = self.fatal if self.fatal is not None else ServingStopped(
            f"serving loop {self.name!r} stopped before this request "
            "could dispatch")
        for r in leftovers:
            _fail_future(r.future, exc)
        if self.fatal is not None:
            import logging

            logging.getLogger(__name__).warning(
                "serving loop %r dispatch thread died: %r (%d request(s) "
                "failed over)", self.name, self.fatal, len(leftovers))
