"""Remote-spawn launchers and machine rosters for the cross-machine fleet.

PR 15's :class:`~dask_ml_tpu.parallel.procfleet.ProcessFleet` spawned
every :class:`~dask_ml_tpu.parallel.replica.ReplicaHost` with a bare
``subprocess.Popen`` — process isolation, but all fault domains still
share one kernel, one disk, one power cord. This module is the seam that
lets the fleet leave the box, the way dask-ml leaves it to
``dask.distributed``'s ``SSHCluster``/``dask-worker`` (PAPER.md,
delegated distribution), without taking the dependency:

- :class:`MachineSpec` is one row of the fleet's roster: a machine name,
  its (machine-local) coordination workdir, and its DEVICE INVENTORY —
  how many accelerators it owns — so placement is capacity-weighted, not
  round-robin-blind.
- :class:`Launcher` is the pluggable spawn hook. The contract is tiny on
  purpose: ``spawn(machine, argv, env=, log_path=)`` returns a Popen-like
  handle with ``pid``/``poll()``/``terminate()``/``kill()``/``wait()``.
  :class:`LocalLauncher` execs the argv directly (the single-box default
  and what tests use — "machines" are isolated workdirs on loopback);
  :class:`ExecLauncher` formats a COMMAND TEMPLATE around the argv
  (``{cmd}`` is the shell-quoted replica command, ``{host}``/
  ``{machine}``/``{workdir}`` come from the roster row), which is how an
  SSH launcher is spelled: ``ExecLauncher(["ssh", "{host}", "cd
  {workdir} && exec {cmd}"])``. The local handle then tracks the ssh
  client process — liveness still flows through the machine workdir's
  :class:`~dask_ml_tpu.parallel.elastic.FileHeartbeat` (a shared mount in
  a real deployment) fused with the wire signals, exactly as on one box.
- :func:`plan_placement` assigns replica slots to roster rows
  least-loaded-first, weighted by device inventory — a 4-chip machine
  takes twice the slots of a 2-chip one before either doubles up.

The router side (machine-death detection — ALL of a machine's heartbeats
stopping at once — replay on survivors, respawn on a surviving machine)
lives in ``parallel/procfleet.py``; snapshot distribution to freshly
launched machines is ``parallel/snapshots.py``. docs/serving.md ("The
multi-machine fleet") has the full contract.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
from typing import Optional

__all__ = [
    "MachineSpec",
    "Launcher",
    "LocalLauncher",
    "ExecLauncher",
    "plan_placement",
]


@dataclasses.dataclass(eq=False)
class MachineSpec:
    """One machine in the fleet roster.

    Parameters
    ----------
    name : str
        Roster-unique machine name — the label on machine-scoped
        telemetry (``fleet.machine_deaths{machine=}``) and the address
        of ``kill_machine``/``slow_link`` chaos plans.
    workdir : str
        The machine's coordination directory: its replicas' heartbeats,
        tombstones, address files, logs, and chunk cache live here. The
        ROUTER must be able to read it (same box in tests; a shared
        mount, or a future wire-forwarded variant, across real
        machines) — it is the per-machine half of the liveness fabric.
    devices : int
        Device inventory (accelerator count) for capacity-weighted
        placement; ``0`` means unknown — the machine weighs as 1 and
        replicas inherit the parent's device pinning policy.
    host : str
        Address handed to command templates (``{host}``) and, in a real
        deployment, where the replica's announced server binds.
    env : dict
        Extra environment for every replica spawned on this machine
        (merged over the router-computed child env).
    """

    name: str
    workdir: str
    devices: int = 0
    host: str = "127.0.0.1"
    env: dict = dataclasses.field(default_factory=dict)
    #: whether the router may OFFER a shared-memory ring to replicas on
    #: this machine (the offer is still attach-verified at negotiation —
    #: a genuinely remote machine falls back to TCP on its own — so this
    #: flag only short-circuits the attempt, e.g. for a roster entry
    #: known to sit behind a network hop or a broken /dev/shm)
    shm: bool = True


class Launcher:
    """Spawn-hook contract (see module docstring): subclasses implement
    :meth:`spawn` and return a ``subprocess.Popen``-shaped handle the
    router can ``poll()``/``terminate()``/``kill()``/``wait()``."""

    def spawn(self, machine: MachineSpec, argv, *, env: dict,
              log_path: Optional[str] = None) -> subprocess.Popen:
        raise NotImplementedError


class LocalLauncher(Launcher):
    """Exec the replica argv directly — the single-box launcher, and the
    test stand-in for remote machines (isolation = the machine's own
    workdir + its own OS process + loopback TCP)."""

    def spawn(self, machine: MachineSpec, argv, *, env: dict,
              log_path: Optional[str] = None) -> subprocess.Popen:
        os.makedirs(machine.workdir, exist_ok=True)
        merged = dict(env)
        merged.update(machine.env)
        log = open(log_path, "ab") if log_path is not None \
            else subprocess.DEVNULL
        try:
            return subprocess.Popen(
                list(argv), stdout=log, stderr=subprocess.STDOUT,
                env=merged, cwd=machine.workdir)
        finally:
            if log_path is not None:
                log.close()


class ExecLauncher(Launcher):
    """Command-template launcher: each template element has ``{cmd}``
    (the shell-quoted replica argv), ``{host}``, ``{machine}``, and
    ``{workdir}`` substituted, then the result is exec'd locally. This
    is the SSH shape without hardcoding ssh::

        ExecLauncher(["ssh", "{host}", "cd {workdir} && exec {cmd}"])

    The returned handle tracks the LOCAL template process (for ssh, the
    client); replica liveness does not depend on it — heartbeats in the
    machine workdir and the wire itself carry that — but its exit code
    still surfaces launch failures fast.

    Env forwarding: template launchers exec through another program, so
    the child env cannot be injected by the kernel. The spawn prefixes
    the command with ``env KEY=VALUE...`` for ``env_forward`` keys
    (default: the device-pinning and path variables the replica needs).
    """

    #: env vars prefixed onto the templated command (the ones the
    #: router's device pinning and module resolution depend on)
    DEFAULT_ENV_FORWARD = (
        "JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH",
        "TPU_VISIBLE_DEVICES", "CUDA_VISIBLE_DEVICES",
    )

    def __init__(self, template, *, env_forward=None):
        if not template:
            raise ValueError("template must name at least one argv element")
        self.template = [str(t) for t in template]
        self.env_forward = tuple(env_forward) if env_forward is not None \
            else self.DEFAULT_ENV_FORWARD

    def spawn(self, machine: MachineSpec, argv, *, env: dict,
              log_path: Optional[str] = None) -> subprocess.Popen:
        os.makedirs(machine.workdir, exist_ok=True)
        merged = dict(env)
        merged.update(machine.env)
        prefix = ["env"] + [
            f"{k}={merged[k]}" for k in self.env_forward if k in merged]
        cmd = shlex.join(prefix + [str(a) for a in argv])
        final = [t.format(cmd=cmd, host=machine.host,
                          machine=machine.name, workdir=machine.workdir)
                 for t in self.template]
        log = open(log_path, "ab") if log_path is not None \
            else subprocess.DEVNULL
        try:
            return subprocess.Popen(
                final, stdout=log, stderr=subprocess.STDOUT,
                env=merged, cwd=machine.workdir)
        finally:
            if log_path is not None:
                log.close()


def plan_placement(n_slots: int, machines, *,
                   loads: Optional[dict] = None) -> list:
    """Assign ``n_slots`` replica slots to roster rows, least-loaded
    first, weighted by device inventory: each assignment goes to the
    machine minimizing ``(assigned + existing_load) / max(devices, 1)``.
    ``loads`` seeds per-machine slot counts already placed (respawn and
    scale-up placement pass the live roster state). Returns one
    :class:`MachineSpec` per slot."""
    machines = list(machines)
    if not machines:
        raise ValueError("placement needs at least one machine")
    counts = {m.name: int((loads or {}).get(m.name, 0)) for m in machines}
    out = []
    for i in range(int(n_slots)):
        m = min(machines,
                key=lambda m: ((counts[m.name]) / max(m.devices, 1),
                               (machines.index(m) + i) % len(machines)))
        counts[m.name] += 1
        out.append(m)
    return out
