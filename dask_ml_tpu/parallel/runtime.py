"""Multi-host runtime bootstrap.

The reference distributes across machines by pointing its graph executor at a
``dask.distributed`` TCP scheduler (reference: model_selection/_search.py:
841-852 scheduler resolution; tests spin real worker subprocesses via
``distributed.utils_test.cluster``, conftest.py:131-141). The TPU-native
equivalent is JAX's multi-controller runtime: every host runs THIS SAME
program, :func:`initialize` wires them into one runtime via
``jax.distributed.initialize``, and a mesh built over ``jax.devices()``
(which, after initialization, lists every device on every host) spans the
whole system. Collectives inside ``shard_map``/``jit`` then ride ICI within
a slice and DCN across slices — placement follows the mesh's device order,
which :func:`global_mesh` keeps contiguous per host so the sample axis maps
host-locally wherever possible.

There is no driver/worker asymmetry to manage (the reference's
scheduler/client split collapses into SPMD): each process stages ITS OWN
sample-axis shard with :func:`process_rows`, and only the hyperparameter
search layer remains host-side Python.

Single-host use needs none of this — :mod:`dask_ml_tpu.parallel.mesh`
lazily builds a mesh over the local devices.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from dask_ml_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger(__name__)

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Join this process into a multi-host JAX runtime.

    Thin, idempotent wrapper over ``jax.distributed.initialize``: on TPU
    pods the arguments are discovered from the environment and may all be
    None; on CPU/GPU clusters pass ``coordinator_address`` (``"host:port"``
    of process 0), ``num_processes``, and this process's ``process_id``.

    Call BEFORE any other JAX/device use (backends must not exist yet) —
    the same constraint dask has that the Client must exist before work is
    submitted. After this returns, ``jax.devices()`` spans every host and
    :func:`global_mesh` builds the system-wide mesh.
    """
    global _initialized
    if _initialized:
        logger.debug("runtime.initialize: already initialized, skipping")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def is_initialized() -> bool:
    return _initialized


def global_mesh(axis_names=(mesh_lib.DATA_AXIS,), shape=None) -> "jax.sharding.Mesh":
    """A mesh over every device on every participating host.

    ``jax.devices()`` orders devices process-contiguously, so a 1-D
    ``('data',)`` mesh gives each host a contiguous run of sample-axis
    shards: cross-shard psums reduce over ICI within the host/slice first
    and touch DCN only for the cross-host combine. For a 2-D
    ``('data', 'model')`` layout pass ``shape=(n_data, n_model)`` — keep
    the model axis within a slice (it carries the chattier collectives).
    """
    return mesh_lib.make_mesh(devices=jax.devices(), shape=shape,
                              axis_names=axis_names)


def process_rows(n_rows: int) -> tuple[int, int]:
    """This process's contiguous [start, stop) slice of a length-``n_rows``
    sample axis, by even split over processes (remainder to the front
    processes) — the staging contract for multi-host ``prepare_data``-style
    loading where each host reads only its own rows."""
    p, np_ = jax.process_index(), jax.process_count()
    base, rem = divmod(n_rows, np_)
    start = p * base + min(p, rem)
    stop = start + base + (1 if p < rem else 0)
    return start, stop
