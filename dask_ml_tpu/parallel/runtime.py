"""Multi-host runtime bootstrap.

The reference distributes across machines by pointing its graph executor at a
``dask.distributed`` TCP scheduler (reference: model_selection/_search.py:
841-852 scheduler resolution; tests spin real worker subprocesses via
``distributed.utils_test.cluster``, conftest.py:131-141). The TPU-native
equivalent is JAX's multi-controller runtime: every host runs THIS SAME
program, :func:`initialize` wires them into one runtime via
``jax.distributed.initialize``, and a mesh built over ``jax.devices()``
(which, after initialization, lists every device on every host) spans the
whole system. Collectives inside ``shard_map``/``jit`` then ride ICI within
a slice and DCN across slices — placement follows the mesh's device order,
which :func:`global_mesh` keeps contiguous per host so the sample axis maps
host-locally wherever possible.

There is no driver/worker asymmetry to manage (the reference's
scheduler/client split collapses into SPMD): each process stages ITS OWN
sample-axis shard with :func:`process_rows`, and only the hyperparameter
search layer remains host-side Python.

Single-host use needs none of this — :mod:`dask_ml_tpu.parallel.mesh`
lazily builds a mesh over the local devices.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from dask_ml_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger(__name__)

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Join this process into a multi-host JAX runtime.

    Thin, idempotent wrapper over ``jax.distributed.initialize``: on TPU
    pods the arguments are discovered from the environment and may all be
    None; on CPU/GPU clusters pass ``coordinator_address`` (``"host:port"``
    of process 0), ``num_processes``, and this process's ``process_id``.

    Call BEFORE any other JAX/device use (backends must not exist yet) —
    the same constraint dask has that the Client must exist before work is
    submitted. After this returns, ``jax.devices()`` spans every host and
    :func:`global_mesh` builds the system-wide mesh.
    """
    global _initialized
    if _initialized:
        logger.debug("runtime.initialize: already initialized, skipping")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    logger.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def is_initialized() -> bool:
    return _initialized


# ---------------------------------------------------------------------------
# process-rank plumbing (used by the elastic data plane)
# ---------------------------------------------------------------------------

# The elastic ingestion layer (parallel/elastic.py) coordinates HOSTS, not
# devices: its processes share a filesystem, not a jax.distributed runtime,
# so rank/world must be knowable without collectives existing. Resolution
# order: explicit set_process_info() > DASK_ML_TPU_PROCESS_ID /
# DASK_ML_TPU_NUM_PROCESSES env (how the bench drill launches workers) >
# the jax.distributed runtime when this process joined one > single-process
# defaults (0 of 1).

_process_info: "Optional[tuple[int, int]]" = None


def set_process_info(rank: Optional[int], count: Optional[int]) -> None:
    """Pin this process's (rank, world-size) for the elastic data plane.
    Pass ``None, None`` to clear back to env/runtime resolution."""
    global _process_info
    if rank is None and count is None:
        _process_info = None
        return
    rank, count = int(rank), int(count)
    if not 0 <= rank < count:
        raise ValueError(f"process rank {rank} out of range [0, {count})")
    _process_info = (rank, count)


def _env_process_info() -> "Optional[tuple[int, int]]":
    import os

    r = os.environ.get("DASK_ML_TPU_PROCESS_ID")
    n = os.environ.get("DASK_ML_TPU_NUM_PROCESSES")
    if r is None or n is None:
        return None
    return int(r), int(n)


def process_rank() -> int:
    """This process's host rank (see resolution order above)."""
    if _process_info is not None:
        return _process_info[0]
    env = _env_process_info()
    if env is not None:
        return env[0]
    if _initialized:
        return jax.process_index()
    return 0


def process_count() -> int:
    """The number of participating host processes."""
    if _process_info is not None:
        return _process_info[1]
    env = _env_process_info()
    if env is not None:
        return env[1]
    if _initialized:
        return jax.process_count()
    return 1


def global_mesh(axis_names=(mesh_lib.DATA_AXIS,), shape=None) -> "jax.sharding.Mesh":
    """A mesh over every device on every participating host.

    ``jax.devices()`` orders devices process-contiguously, so a 1-D
    ``('data',)`` mesh gives each host a contiguous run of sample-axis
    shards: cross-shard psums reduce over ICI within the host/slice first
    and touch DCN only for the cross-host combine. For a 2-D
    ``('data', 'model')`` layout pass ``shape=(n_data, n_model)`` — keep
    the model axis within a slice (it carries the chattier collectives).
    """
    return mesh_lib.make_mesh(devices=jax.devices(), shape=shape,
                              axis_names=axis_names)


def process_rows(n_rows: int) -> tuple[int, int]:
    """This process's contiguous [start, stop) slice of a length-``n_rows``
    sample axis, by even split over processes (remainder to the front
    processes) — the staging contract for multi-host ``prepare_data``-style
    loading where each host reads only its own rows."""
    p, np_ = jax.process_index(), jax.process_count()
    base, rem = divmod(n_rows, np_)
    start = p * base + min(p, rem)
    stop = start + base + (1 if p < rem else 0)
    return start, stop
