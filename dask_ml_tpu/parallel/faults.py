"""Fault tolerance for the long-running host-driven loops.

The >HBM streamed tier (``parallel/stream.py`` + the streamed solvers) and
the CV search pool (``model_selection/_search.py``) are the two places where
a fit is a HOST loop over many device dispatches rather than one compiled
program — which makes them the two places a single transient failure (a
loader ``OSError``, a failed ``device_put``, a hung candidate fit, a SIGTERM
from a preemptible slot) used to abort hours of work even though
``checkpoint.py`` already defines resumable carries. This module turns that
resumable state into actual fault tolerance:

- :class:`RetryPolicy` — error classification for transient host-I/O and
  device-transfer failures, exponential backoff with deterministic seeded
  jitter, a retry budget (``max_retries`` per operation) and a backoff
  deadline (total seconds the policy may spend sleeping), and counters that
  surface into bench/search reports. Wired into
  :class:`~dask_ml_tpu.parallel.stream.HostBlockSource` so loader-mode block
  fetches survive flaky storage, and into the search pool's cell fits.
- :class:`GracefulDrain` — SIGTERM/SIGINT trap used by the checkpointed
  streamed solvers: on a preemption signal the in-flight block finishes, the
  scan state snapshots through ``checkpoint.save_pytree``, and
  :class:`Preempted` is raised so the caller can exit cleanly and resume
  later with a bit-identical trajectory.
- :class:`ScanCheckpoint` — the ``(carry, outs, next_block, epoch)`` snapshot
  contract ``prefetched_scan`` saves/loads, with a binding ``meta`` so a
  snapshot from a different problem is an error, never a silent wrong
  trajectory (same policy as ``solve_checkpointed``'s fingerprints).
- :class:`FaultInjector` — deterministic, plan-driven fault injection (fail
  block b's load, fail a ``device_put``, delay a block, deliver a simulated
  preemption at block k of epoch e). Tests and ``bench.py --faults`` drill
  the SAME hooks the real failure paths use, so every recovery path runs in
  CI instead of being trusted.

Nothing here imports jax at module scope: the policy/injector are plain host
objects, and snapshots go through :mod:`dask_ml_tpu.checkpoint` (which pulls
jax lazily), so the layer stays importable in loader processes that never
touch a device.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "RetryPolicy", "FaultInjector", "GracefulDrain", "ScanCheckpoint",
    "Preempted", "BlockFetchError", "InjectedFault", "InjectedLoaderError",
    "InjectedTransferError", "SimulatedReplicaDeath",
    "scan_checkpoint_scope",
]


# ---------------------------------------------------------------------------
# exceptions
# ---------------------------------------------------------------------------


class Preempted(RuntimeError):
    """A graceful drain completed: the in-flight block finished, the scan
    state was snapshotted (``path``, when checkpointing was configured), and
    the run stopped cleanly. Re-running the same call with the same
    checkpoint path resumes from the snapshot with a bit-identical
    trajectory."""

    def __init__(self, message: str, path: Optional[str] = None):
        super().__init__(message)
        self.path = path


class BlockFetchError(RuntimeError):
    """Terminal (post-retry) failure fetching one block, naming the block
    index — replaces the bare ``KeyError`` a dead in-flight pipeline slot
    used to surface."""


class InjectedFault:
    """Marker mixin for injector-raised exceptions (always classified
    transient, so drills exercise the retry machinery end to end)."""


class InjectedLoaderError(InjectedFault, OSError):
    """Simulated host-I/O failure reading a block."""


class InjectedTransferError(InjectedFault, RuntimeError):
    """Simulated ``device_put`` failure transferring a block."""


class SimulatedReplicaDeath(RuntimeError):
    """A :meth:`FaultInjector.kill_replica` plan fired: the serving
    replica's dispatch thread dies abruptly — no drain, no flush — the
    in-process stand-in for kill -9 of a replica process. Deliberately NOT
    an :class:`InjectedFault`: a dead replica is terminal for that
    replica, never something its own retry policy should paper over; the
    FLEET survives it by re-routing and replaying
    (``parallel/fleet.py``)."""


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


#: exception types retried by default: host I/O (OSError covers IOError,
#: ConnectionError, and friends on py3) and timeouts. Device-transfer
#: failures are matched structurally (see _is_device_runtime_error) because
#: jaxlib's exception classes move between versions.
_DEFAULT_TRANSIENT = (OSError, TimeoutError, InjectedFault)


def _is_device_runtime_error(exc: BaseException) -> bool:
    """True for jax/jaxlib runtime errors (failed transfers, device OOM
    races, backend resets) without importing jaxlib internals: matched by
    class name/module so the classification survives jaxlib renames."""
    t = type(exc)
    return t.__name__ == "XlaRuntimeError" or t.__module__.startswith(
        ("jaxlib", "jax._src.lib"))


class RetryPolicy:
    """Retry transient failures with exponential backoff + seeded jitter.

    ``max_retries`` is the per-operation retry budget; ``deadline`` caps the
    TOTAL seconds the policy may spend in backoff across its lifetime (a
    whole streamed fit shares one policy, so a persistently-down loader
    exhausts the deadline instead of multiplying per-block budgets).
    Backoff for attempt ``a`` is ``min(base_delay·multiplier^a, max_delay)``
    plus uniform jitter in ``[0, jitter·delay]`` drawn from a seeded RNG —
    deterministic for a fixed seed and call order, so fault-injection drills
    reproduce exactly.

    Classification: an exception is transient when ``classify`` (if given)
    says so, or when it is an instance of ``transient_types`` (default:
    ``OSError``/``TimeoutError``/injected faults), or when it is a
    jax/jaxlib runtime error and ``retry_device_errors`` is True (the
    ``device_put`` failure mode this policy exists for). Everything else
    propagates immediately.

    Counters (``retries``, ``giveups``, ``by_kind``, ``delay_spent``) are
    thread-safe and surface through :meth:`stats`; ``reset_stats()``
    between timed runs keeps bench accounting honest.
    """

    def __init__(self, max_retries: int = 3, *, base_delay: float = 0.05,
                 max_delay: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5, deadline: Optional[float] = None,
                 seed: int = 0, transient_types: Optional[tuple] = None,
                 classify: Optional[Callable[[BaseException], bool]] = None,
                 retry_device_errors: bool = True,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.transient_types = (_DEFAULT_TRANSIENT if transient_types is None
                                else tuple(transient_types))
        self.classify = classify
        self.retry_device_errors = bool(retry_device_errors)
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.retries = 0
        self.giveups = 0
        self.delay_spent = 0.0
        self.by_kind: dict = {}

    def is_transient(self, exc: BaseException) -> bool:
        if self.classify is not None and self.classify(exc):
            return True
        if isinstance(exc, self.transient_types):
            return True
        return self.retry_device_errors and _is_device_runtime_error(exc)

    def backoff_delay(self, attempt: int) -> float:
        d = min(self.base_delay * self.multiplier ** attempt, self.max_delay)
        with self._lock:
            j = self._rng.uniform(0.0, self.jitter * d)
        return d + j

    def run(self, fn: Callable, *, kind: str = "op", detail: str = ""):
        """Call ``fn()``; on a transient failure back off and retry, up to
        ``max_retries`` times and within the deadline. The terminal attempt
        re-raises the last error (the caller wraps it with context — e.g.
        the block index)."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:
                if not self.is_transient(e):
                    raise
                from dask_ml_tpu.parallel import telemetry

                with self._lock:
                    exhausted = (
                        attempt >= self.max_retries
                        or (self.deadline is not None
                            and self.delay_spent >= self.deadline))
                    if exhausted:
                        self.giveups += 1
                if exhausted:
                    if telemetry.enabled():
                        telemetry.metrics().counter(
                            "faults.giveups", kind=kind).inc()
                    raise
                d = self.backoff_delay(attempt)
                with self._lock:
                    self.retries += 1
                    self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
                    self.delay_spent += d
                if telemetry.enabled():
                    # registry mirrors of the policy's own counters, same
                    # increment site (docs/observability.md); kind labels
                    # mirror by_kind
                    reg = telemetry.metrics()
                    reg.counter("faults.retries", kind=kind).inc()
                    reg.counter("faults.backoff_seconds").inc(d)
                logger.warning(
                    "transient %s failure%s — retry %d/%d in %.3fs: %r",
                    kind, f" ({detail})" if detail else "", attempt + 1,
                    self.max_retries, d, e)
                self._sleep(d)
                attempt += 1

    def stats(self) -> dict:
        with self._lock:
            return {"retries": self.retries, "giveups": self.giveups,
                    "delay_spent_seconds": round(self.delay_spent, 4),
                    "by_kind": dict(self.by_kind)}

    def reset_stats(self) -> None:
        with self._lock:
            self.retries = 0
            self.giveups = 0
            self.delay_spent = 0.0
            self.by_kind = {}


# ---------------------------------------------------------------------------
# graceful drain (preemption signals)
# ---------------------------------------------------------------------------


class GracefulDrain:
    """SIGTERM/SIGINT → "finish the in-flight block, snapshot, exit cleanly".

    Used as a context manager around a checkpointed streamed fit: on entry
    it installs handlers that set a flag (previous handlers are restored on
    exit); ``prefetched_scan`` polls the flag after every completed block
    and, when set, snapshots and raises :class:`Preempted`. ``request()``
    sets the flag programmatically — the deterministic path the
    :class:`FaultInjector` and tests use, identical to a real signal from
    the scan's point of view.

    Multi-process/nested use (the elastic epoch loop runs its own drain
    scope inside ``admm_streamed``'s): entering the SAME drain again is a
    no-op that bumps a depth counter — handlers install once and restore
    only when the outermost scope exits, so re-entry never saves its own
    handler as "previous" and leaks the trap. Entering a DISTINCT drain
    while another is installed chains: the inner handler sets its own flag
    and forwards the signal to the previously-installed handler, so every
    active drain scope observes one SIGTERM (the outer scope still drains
    after the inner one finishes). Pinned by the re-entrancy tests in
    ``tests/test_faults.py``.

    Handler installation is skipped off the main thread (``signal.signal``
    only works there); the drain still works via ``request()``.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: dict = {}
        self._depth = 0
        self.installed = False

    def request(self, *_args) -> None:
        self._event.set()

    def _on_signal(self, signum, frame) -> None:
        """Installed handler: set this drain's flag, then forward to the
        previously-installed handler IF that handler is another drain's —
        one signal reaches every active drain scope. Foreign handlers
        (``default_int_handler``, application traps) are NOT forwarded to:
        the drain's whole contract is that the signal means "finish the
        block and snapshot", not "raise KeyboardInterrupt mid-solve"."""
        self._event.set()
        prev = self._prev.get(signum)
        if isinstance(getattr(prev, "__self__", None), GracefulDrain):
            prev(signum, frame)

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def clear(self) -> None:
        self._event.clear()

    def __enter__(self) -> "GracefulDrain":
        self._depth += 1
        if self._depth > 1:
            # re-entered (nested scope on the same drain): handlers are
            # already installed; saving the current handler again would
            # record OURSELVES as "previous" and leak the trap on exit
            return self
        try:
            for s in self._signals:
                prev = signal.signal(s, self._on_signal)
                if prev == self._on_signal:  # pragma: no cover - paranoia
                    prev = signal.SIG_DFL
                self._prev[s] = prev
            self.installed = True
        except ValueError:  # not the main thread: request()-only mode
            self._prev.clear()
            self.installed = False
        return self

    def __exit__(self, *exc) -> None:
        self._depth = max(self._depth - 1, 0)
        if self._depth > 0:
            return None
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self.installed = False
        return None


# ---------------------------------------------------------------------------
# scan checkpoint
# ---------------------------------------------------------------------------


class ScanCheckpoint:
    """Snapshot/restore contract for ``prefetched_scan``.

    A snapshot is ``(carry, outs_so_far)`` plus metadata
    ``(next_block, epoch)`` — everything needed to replay the host-driven
    scan from the first incomplete block: the per-block programs are
    deterministic, so the resumed trajectory is bit-identical to an
    uninterrupted run (pinned by ``tests/test_faults.py``).

    ``every`` is the snapshot interval in completed blocks (interval saves
    force one device sync each — size it like ``solve_checkpointed``'s
    ``chunk_iters``: small enough to bound lost work, large enough that the
    sync cost stays in the noise). ``bind`` is a dict of problem-identity
    fields stored in the snapshot metadata; a loaded snapshot whose binding
    differs is an error, never a silent wrong trajectory. ``drain`` is the
    :class:`GracefulDrain` the scan polls.

    Writes go through :func:`dask_ml_tpu.checkpoint.save_pytree` (atomic
    temp-file + ``os.replace``), so a kill mid-save leaves the previous
    snapshot intact.
    """

    KIND = "prefetched_scan"

    def __init__(self, path: str, *, every: int = 1,
                 drain: Optional[GracefulDrain] = None,
                 bind: Optional[dict] = None):
        self.path = path
        self.every = max(int(every), 1)
        self.drain = drain
        self.bind = dict(bind or {})
        self._since = 0
        self.saves = 0
        #: full metadata of the last loaded snapshot — elastic resumes read
        #: the in-progress epoch's shuffled block sequence (``"blocks"``)
        #: from here, since the 4-tuple return predates shard-aware scans
        self.last_meta: Optional[dict] = None

    def load(self):
        """→ ``(carry, outs, next_block, epoch)`` or ``None`` when no
        snapshot exists (``next_block`` is a POSITION in the scanned block
        sequence — identical to the block id for the default
        ``range(n_blocks)`` scan; an explicit sequence is stored under
        ``last_meta['blocks']``). Raises on a snapshot from a different
        problem."""
        from dask_ml_tpu.checkpoint import load_pytree

        snap = load_pytree(self.path)
        if snap is None:
            return None
        tree, meta = snap
        if meta.get("kind") != self.KIND:
            raise ValueError(
                f"checkpoint {self.path} is not a prefetched_scan snapshot "
                f"(kind={meta.get('kind')!r})")
        stored = meta.get("bind", {})
        for k, v in self.bind.items():
            if stored.get(k) != v:
                raise ValueError(
                    f"checkpoint {self.path} was written for a different "
                    f"problem ({k}={stored.get(k)!r}, this run has {v!r}); "
                    "delete it or use a distinct path per fit")
        self.last_meta = dict(meta)
        return (tree["carry"], list(tree["outs"]),
                int(meta["next_block"]), int(meta["epoch"]))

    def save(self, carry, outs, next_block: int, epoch: int,
             reason: str = "interval", blocks=None) -> None:
        from dask_ml_tpu.checkpoint import save_pytree

        meta = {"kind": self.KIND, "next_block": int(next_block),
                "epoch": int(epoch), "bind": self.bind, "reason": reason}
        if blocks is not None:
            # shard-aware scan: the explicit (shuffled) block-id sequence
            # this epoch consumes, so a resume replays the SAME permutation
            # slice even if the roster has since changed
            meta["blocks"] = [int(b) for b in blocks]
        save_pytree(self.path, {"carry": carry, "outs": list(outs)},
                    meta=meta)
        self._since = 0
        self.saves += 1

    def tick(self, carry, outs, next_block: int, epoch: int,
             blocks=None) -> bool:
        """Interval bookkeeping: called once per completed block; saves when
        ``every`` blocks have completed since the last save."""
        self._since += 1
        if self._since >= self.every:
            self.save(carry, outs, next_block, epoch, reason="interval",
                      blocks=blocks)
            return True
        return False

    def delete(self) -> None:
        """Remove the snapshot (called on successful completion: the file is
        a resume artifact of an interrupted run, and leaving it behind would
        let a later run at the same path resume into stale state)."""
        try:
            os.unlink(self.path)
        except OSError:
            pass


@contextmanager
def scan_checkpoint_scope(path: Optional[str], *, every: int, bind: dict):
    """The checkpointed-scan setup every streamed consumer shares: build a
    :class:`GracefulDrain` + :class:`ScanCheckpoint`, install the signal
    handlers for the duration, and yield the checkpoint (``None`` when
    ``path`` is ``None`` — the caller's code path stays identical either
    way). The caller loads the snapshot (if it cares) and deletes it on
    successful completion."""
    if path is None:
        yield None
        return
    drain = GracefulDrain()
    ckpt = ScanCheckpoint(path, every=every, drain=drain, bind=bind)
    with drain:
        yield ckpt


# ---------------------------------------------------------------------------
# deterministic fault injection
# ---------------------------------------------------------------------------


class FaultInjector:
    """Deterministic, plan-driven fault injection for streamed pipelines.

    Attach to a :class:`~dask_ml_tpu.parallel.stream.HostBlockSource`
    (``fault_injector=``); the source calls :meth:`on_load` before reading a
    block and :meth:`on_transfer` inside each ``device_put`` attempt, and
    ``prefetched_scan`` calls :meth:`should_preempt` after each completed
    block. Plans are explicit and exact — *fail block 3's load twice*,
    *preempt at epoch 2 block 1* — so tests assert recovery, not luck;
    :meth:`random_load_failures` adds seeded random failures whose sequence
    is reproducible for a fixed seed and call order (the host loop is
    single-threaded, so call order is deterministic).

    ``injected`` counts delivered faults by kind; injected exceptions carry
    the :class:`InjectedFault` marker, which the default
    :class:`RetryPolicy` classifies transient.
    """

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._load_fail: dict = {}       # block -> [times_left, exc_type]
        self._transfer_fail: dict = {}   # block -> times_left
        self._load_delay: dict = {}      # block -> [times_left, seconds]
        self._preempt: set = set()       # {(epoch, block)}
        self._die: set = set()           # {(epoch, block)}
        self._dispatch_delay: dict = {}  # batch -> [times_left, seconds]
        self._slow_replica: dict = {}    # replica -> [batches_left, seconds]
        self._kill_replica: dict = {}    # replica -> after_batches
        self._kill_process: dict = {}    # name -> after_requests
        self._straggle: dict = {}        # replica -> [count, every, s, left]
        self._kill_machine: dict = {}    # machine -> after_results
        self._slow_link: dict = {}       # machine -> [chunks_left, seconds]
        self._p_load = 0.0
        self._p_exc = InjectedLoaderError
        self.injected = {"load": 0, "transfer": 0, "delay": 0, "preempt": 0,
                         "die": 0, "dispatch_delay": 0, "slow_replica": 0,
                         "replica_kill": 0, "process_kill": 0,
                         "straggle": 0, "machine_kill": 0, "slow_link": 0}

    # -- planning ----------------------------------------------------------

    def fail_load(self, block: int, *, times: int = 1,
                  exc_type=InjectedLoaderError) -> "FaultInjector":
        """Fail the next ``times`` reads of ``block`` (re-reads across
        retries/epochs count down the same budget)."""
        self._load_fail[int(block)] = [int(times), exc_type]
        return self

    def fail_transfer(self, block: int, *, times: int = 1) -> "FaultInjector":
        """Fail the next ``times`` ``device_put`` attempts of ``block``."""
        self._transfer_fail[int(block)] = int(times)
        return self

    def delay_load(self, block: int, seconds: float, *,
                   times: int = 1) -> "FaultInjector":
        """Sleep ``seconds`` before the next ``times`` reads of ``block``
        (models a slow storage stall; exercises overlap under skew)."""
        self._load_delay[int(block)] = [int(times), float(seconds)]
        return self

    def preempt_at(self, block: int, *, epoch: int = 0) -> "FaultInjector":
        """Deliver a simulated preemption after block ``block`` of epoch
        ``epoch`` completes — identical to a SIGTERM landing there, minus
        the race: the drill is exact."""
        self._preempt.add((int(epoch), int(block)))
        return self

    def die_at(self, block: int, *, epoch: int = 0) -> "FaultInjector":
        """Simulate the HOST dying (SIGKILL / machine loss — no drain, no
        snapshot, heartbeats just stop) after block ``block`` of epoch
        ``epoch`` completes. Unlike :meth:`preempt_at` nothing is saved:
        this is the failure mode the elastic rebalance protocol exists for
        (``parallel/elastic.py``), and the drill's stand-in for kill -9.
        The elastic layer polls :meth:`should_die` after each published
        block and raises
        :class:`~dask_ml_tpu.parallel.elastic.SimulatedHostDeath`; the
        bench worker turns that into ``os._exit``."""
        self._die.add((int(epoch), int(block)))
        return self

    def delay_dispatch(self, batch: int, seconds: float, *,
                       times: int = 1) -> "FaultInjector":
        """Sleep ``seconds`` before the serving loop dispatches batch
        number ``batch`` (0-based sequence number on that loop) — a REAL
        wall-clock straggler, for drills that need genuine skew. For
        router-failover tests prefer :meth:`slow_replica`, whose synthetic
        penalty needs no sleeping."""
        self._dispatch_delay[int(batch)] = [int(times), float(seconds)]
        return self

    def slow_replica(self, replica: str, seconds: float, *,
                     batches: Optional[int] = None) -> "FaultInjector":
        """Mark serving replica ``replica`` a straggler: every batch it
        dispatches reports ``seconds`` of SYNTHETIC extra latency — the
        loop adds the penalty to the latency surface its router reads
        (gauges/EWMA) without actually sleeping, so slow-replica failover
        is deterministic and wall-clock-free in tests. ``batches`` bounds
        how many dispatches are penalized (default: until cleared)."""
        self._slow_replica[str(replica)] = [
            -1 if batches is None else int(batches), float(seconds)]
        return self

    def kill_replica(self, replica: str, *,
                     after_batches: int = 0) -> "FaultInjector":
        """Kill serving replica ``replica`` once it has dispatched
        ``after_batches`` batches: the next dispatch raises
        :class:`SimulatedReplicaDeath` and the replica's loop dies
        abruptly — queued and in-flight requests fail with the death
        error (in-process we cannot suppress Python's unwinding the way a
        real SIGKILL would, so the loop's crash hygiene still runs), and
        the fleet's router re-routes + replays them
        (``parallel/fleet.py``). One-shot per replica."""
        self._kill_replica[str(replica)] = int(after_batches)
        return self

    def kill_process(self, name: str, *,
                     after_requests: int = 0) -> "FaultInjector":
        """Kill the OS process hosting ``name`` once it has served
        ``after_requests`` wire requests — REAL ``SIGKILL`` semantics,
        delivered by :meth:`maybe_kill_process` in the victim process
        itself: no drain, no atexit, no flush; heartbeats simply stop
        and the socket goes dark mid-stream. This is the process-fleet
        analogue of :meth:`kill_replica` (which kills a dispatch THREAD
        and therefore still unwinds Python): the ``ReplicaHost`` worker
        polls the plan so chaos drills can place the kill deterministically
        at a request count instead of a wall-clock race. One-shot per
        name."""
        self._kill_process[str(name)] = int(after_requests)
        return self

    def should_kill_process(self, name: str, n_requests: int) -> bool:
        """True exactly once, when ``name`` has served
        ``after_requests`` requests (see :meth:`kill_process`)."""
        with self._lock:
            after = self._kill_process.get(str(name))
            if after is None or int(n_requests) < after:
                return False
            del self._kill_process[str(name)]
            self.injected["process_kill"] += 1
        self._mirror("process_kill")
        return True

    def maybe_kill_process(self, name: str, n_requests: int) -> None:
        """Deliver the :meth:`kill_process` plan: ``SIGKILL`` to OUR OWN
        pid when the plan fires. Nothing after this line runs — which is
        the point."""
        if self.should_kill_process(name, n_requests):
            os.kill(os.getpid(), signal.SIGKILL)

    def kill_machine(self, machine: str, *,
                     after_results: int = 0) -> "FaultInjector":
        """Kill every replica process of roster machine ``machine`` at
        once, once the fleet has resolved ``after_results`` requests —
        the MACHINE-loss drill (power loss, kernel panic, network
        partition): all of its heartbeats stop in the same instant and
        all of its sockets go dark together, which is the signal the
        multi-machine router's machine-death detection keys on. The
        process-fleet ROUTER polls :meth:`should_kill_machine` from its
        monitor and delivers SIGKILL to each of the machine's replica
        pids (the router can reach them; a real machine loss would not
        need delivering). One-shot per machine."""
        self._kill_machine[str(machine)] = int(after_results)
        return self

    def should_kill_machine(self, machine: str, n_results: int) -> bool:
        """True exactly once, when the fleet has resolved
        ``after_results`` requests (see :meth:`kill_machine`)."""
        with self._lock:
            after = self._kill_machine.get(str(machine))
            if after is None or int(n_results) < after:
                return False
            del self._kill_machine[str(machine)]
            self.injected["machine_kill"] += 1
        self._mirror("machine_kill")
        return True

    def slow_link(self, machine: str, seconds: float, *,
                  chunks: Optional[int] = None) -> "FaultInjector":
        """Degrade the snapshot-distribution link TO roster machine
        ``machine``: the snapshot server sleeps ``seconds`` before each
        chunk it sends that machine (``chunks`` bounds how many sends
        are delayed; default unbounded). Real wall-clock delay — the
        drill for resumable transfer under a slow or flaky link
        (``parallel/snapshots.py``); the chunk requests carry the
        machine label, so only the targeted link degrades."""
        self._slow_link[str(machine)] = [
            -1 if chunks is None else int(chunks), float(seconds)]
        return self

    def link_delay(self, machine: str) -> float:
        """Seconds the snapshot server must stall before sending the
        next chunk to ``machine`` (0.0 when no :meth:`slow_link` plan
        fires). The CALLER sleeps — the injector only decides."""
        with self._lock:
            plan = self._slow_link.get(str(machine))
            if not plan or plan[0] == 0:
                return 0.0
            if plan[0] > 0:
                plan[0] -= 1
            self.injected["slow_link"] += 1
            seconds = plan[1]
        self._mirror("slow_link")
        return seconds

    def straggle_replica(self, replica: str, seconds: float, *,
                         every: int = 1,
                         batches: Optional[int] = None) -> "FaultInjector":
        """Make replica ``replica`` a REAL straggler: every ``every``-th
        dispatched batch sleeps ``seconds`` of wall clock before
        executing (``batches`` bounds the total penalized dispatches;
        default unbounded). Unlike :meth:`slow_replica` — whose penalty
        is synthetic, only REPORTED latency — this one actually stalls
        the dispatch thread, which is what a hedging drill needs: the
        router must rescue the request's tail latency, not just route
        around a number."""
        self._straggle[str(replica)] = [
            0, max(int(every), 1), float(seconds),
            -1 if batches is None else int(batches)]
        return self

    def random_load_failures(self, p: float,
                             exc_type=InjectedLoaderError) -> "FaultInjector":
        """Every block read fails with probability ``p`` (seeded RNG)."""
        self._p_load = float(p)
        self._p_exc = exc_type
        return self

    # -- hooks (called by the pipeline) ------------------------------------

    def on_load(self, block: int) -> None:
        with self._lock:
            plan = self._load_delay.get(block)
            delay = None
            if plan and plan[0] > 0:
                plan[0] -= 1
                delay = plan[1]
                self.injected["delay"] += 1
        if delay:
            time.sleep(delay)
        with self._lock:
            plan = self._load_fail.get(block)
            if plan and plan[0] > 0:
                plan[0] -= 1
                self.injected["load"] += 1
                exc = plan[1](f"injected load failure for block {block}")
            elif self._p_load and self._rng.random() < self._p_load:
                self.injected["load"] += 1
                exc = self._p_exc(f"injected load failure for block {block}")
            else:
                return
        raise exc

    def on_transfer(self, block: int) -> None:
        with self._lock:
            left = self._transfer_fail.get(block, 0)
            if left > 0:
                self._transfer_fail[block] = left - 1
                self.injected["transfer"] += 1
                exc = InjectedTransferError(
                    f"injected device_put failure for block {block}")
            else:
                return
        raise exc

    def should_preempt(self, block: int, epoch: int) -> bool:
        with self._lock:
            key = (int(epoch), int(block))
            if key in self._preempt:
                self._preempt.discard(key)  # one-shot: resume runs clean
                self.injected["preempt"] += 1
                return True
        return False

    def should_die(self, block: int, epoch: int) -> bool:
        with self._lock:
            key = (int(epoch), int(block))
            if key in self._die:
                self._die.discard(key)
                self.injected["die"] += 1
                return True
        return False

    # -- serving-loop hooks (called by ServingLoop/ServingFleet) -----------

    def _mirror(self, kind: str) -> None:
        """Registry mirror of the injector's own counter, at the same
        increment site (docs/observability.md mirror discipline)."""
        from dask_ml_tpu.parallel import telemetry

        if telemetry.enabled():
            telemetry.metrics().counter("faults.injected", kind=kind).inc()

    def on_dispatch(self, batch: int) -> None:
        """Real straggler: sleep per a :meth:`delay_dispatch` plan before
        the loop dispatches batch ``batch``."""
        with self._lock:
            plan = self._dispatch_delay.get(int(batch))
            delay = None
            if plan and plan[0] > 0:
                plan[0] -= 1
                delay = plan[1]
                self.injected["dispatch_delay"] += 1
        if delay:
            self._mirror("dispatch_delay")
            time.sleep(delay)

    def dispatch_sleep(self, replica: str) -> float:
        """Real straggler hook: sleep per the :meth:`straggle_replica`
        plan before replica ``replica`` dispatches a batch; returns the
        seconds slept (0.0 when the plan did not fire)."""
        with self._lock:
            plan = self._straggle.get(str(replica))
            if not plan or plan[3] == 0:
                return 0.0
            plan[0] += 1
            if plan[0] % plan[1] != 0:
                return 0.0
            if plan[3] > 0:
                plan[3] -= 1
            self.injected["straggle"] += 1
            seconds = plan[2]
        self._mirror("straggle")
        time.sleep(seconds)
        return seconds

    def dispatch_penalty(self, replica: str) -> float:
        """Synthetic straggler: extra seconds replica ``replica`` must
        REPORT for this dispatch (no sleep happens anywhere) — the loop
        adds it to the latency its router balances on."""
        with self._lock:
            plan = self._slow_replica.get(str(replica))
            if not plan or plan[0] == 0:
                return 0.0
            if plan[0] > 0:
                plan[0] -= 1
            self.injected["slow_replica"] += 1
        self._mirror("slow_replica")
        return plan[1]

    def should_kill_replica(self, replica: str, n_batches: int) -> bool:
        """True exactly once, when ``replica`` has dispatched
        ``after_batches`` batches (see :meth:`kill_replica`)."""
        with self._lock:
            after = self._kill_replica.get(str(replica))
            if after is None or int(n_batches) < after:
                return False
            del self._kill_replica[str(replica)]
            self.injected["replica_kill"] += 1
        self._mirror("replica_kill")
        return True
