"""ReplicaHost: one out-of-process serving replica — the worker half of
the process-isolated fleet.

PR 12's :class:`~dask_ml_tpu.parallel.fleet.ServingFleet` replicated the
reference's fault-tolerance POLICY (heartbeats, breaker, replay) without
its fault DOMAIN: every replica was a thread in one interpreter sharing
one XLA runtime, so a segfault, an OOM, or a wedged runtime took the
whole tier down at once. dask-ml never had that problem — its workers
are ``dask.distributed`` OS processes (PAPER.md, delegated
distribution). This module is that missing half: a worker ENTRYPOINT the
router (``parallel/procfleet.py``) spawns as its own OS process, so a
replica's crash is contained by the kernel, not by Python's unwinding.

One ``ReplicaHost`` process:

- owns its device subset — the parent pins ``JAX_PLATFORMS`` /
  ``XLA_FLAGS`` (CPU: ``--xla_force_host_platform_device_count``) /
  visible-devices env BEFORE spawn, so the child's jax runtime never
  even sees a sibling's chips;
- loads its models from a REGISTRY SNAPSHOT the router wrote
  (:func:`save_registry_snapshot` — the shared frame codec under its own
  magic, atomic rename + sha256, same durability discipline as
  checkpoints; trusted local disk, never the socket);
- warms every (model, method, bucket) program through the EXACT serving
  staging path before announcing itself, so a respawned replica rejoins
  rotation with ZERO steady-state compiles (the count is reported live
  via the wire ``stats`` op);
- serves a :class:`~dask_ml_tpu.parallel.serving.ServingLoop` behind a
  :class:`~dask_ml_tpu.parallel.fleet.FleetServer` speaking the typed
  pickle-free wire, announcing its address atomically in
  ``workdir/<name>.addr.json``;
- heartbeats through the PR-8
  :class:`~dask_ml_tpu.parallel.elastic.FileHeartbeat` mtime/tombstone
  liveness layer: SIGTERM drains gracefully and leaves a tombstone;
  SIGKILL leaves NOTHING — the beats just stop, which is exactly the
  signal the router's monitor fuses with the socket going dark;
- optionally carries deterministic chaos plans
  (:meth:`~dask_ml_tpu.parallel.faults.FaultInjector.kill_process` —
  real ``SIGKILL`` to itself after N served requests — and
  :meth:`~dask_ml_tpu.parallel.faults.FaultInjector.straggle_replica` —
  a real wall-clock straggler for the hedging drill).

Run as ``python -m dask_ml_tpu.parallel.replica --name r0 --snapshot
/path/snap.reg --workdir /path/fleet`` (the router does this; see
``bench.py --fleet-proc`` and docs/serving.md, "The process-isolated
fleet").
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import signal
import tempfile
import threading
from typing import Optional

__all__ = [
    "ReplicaHost",
    "save_registry_snapshot",
    "load_registry_snapshot",
    "main",
]

#: registry-snapshot magic (the shared frame codec of
#: ``parallel/framing.py`` under its own version byte). Snapshots are a
#: TRUSTED-DISK artifact written by the router and read by its own child
#: processes — they carry pickled fitted estimators, like checkpoints,
#: and never travel the socket (the wire is the typed codec).
REGISTRY_MAGIC = b"DMLTFREG1\n"


def save_registry_snapshot(path: str, models) -> None:
    """Atomically write the fleet's model registry snapshot: ``models``
    is a list of ``(name, fitted_estimator, methods_or_None)``. Framed
    (length + sha256) and renamed into place, so a child can never load
    a torn snapshot — it either sees the previous complete one or this
    one."""
    from dask_ml_tpu.parallel import framing

    body = pickle.dumps({"models": list(models)},
                        protocol=pickle.HIGHEST_PROTOCOL)
    frame = framing.encode_frame(body, magic=REGISTRY_MAGIC)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".reg.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_registry_snapshot(path: str):
    """→ the ``(name, estimator, methods)`` list of
    :func:`save_registry_snapshot` (frame-verified first: corruption
    raises a FrameError, never unpickles noise)."""
    from dask_ml_tpu.parallel import framing

    with open(path, "rb") as f:
        data = f.read()
    body = framing.decode_frame(data, magic=REGISTRY_MAGIC)
    return pickle.loads(body)["models"]


class ReplicaHost:
    """One serving-replica process (module docstring has the role).

    Parameters
    ----------
    name : str
        This replica's fleet-wide name — the heartbeat member name, the
        address-file stem, and the loop/telemetry label.
    snapshot_path : str
        The registry snapshot to serve (:func:`save_registry_snapshot`).
    workdir : str
        Shared coordination directory (heartbeats, tombstones, address
        files) — the router passes the same path to every replica.
    max_batch_rows, max_queue, policy
        Forwarded to the :class:`~dask_ml_tpu.parallel.serving.
        ServingLoop`.
    heartbeat_interval_s : float
        Beat cadence (the router declares death past ITS timeout).
    kill_after_requests : int, optional
        Deterministic chaos: arm a
        :meth:`~dask_ml_tpu.parallel.faults.FaultInjector.kill_process`
        plan — real ``SIGKILL`` to this process once that many wire
        requests were served.
    straggle_s, straggle_every : float, int
        Deterministic chaos: every ``straggle_every``-th batch sleeps
        ``straggle_s`` wall-clock seconds
        (:meth:`~dask_ml_tpu.parallel.faults.FaultInjector.
        straggle_replica`) — the hedging drill's tail-latency source.
    snapshot_server : str, optional
        ``host:port`` of the router's
        :class:`~dask_ml_tpu.parallel.snapshots.SnapshotServer`. When
        set, ``snapshot_path`` is the DESTINATION: the registry is
        FETCHED chunk-addressed through the machine's cache
        (:func:`~dask_ml_tpu.parallel.snapshots.fetch_snapshot`) before
        loading — a respawn on a warm machine ships only missing chunks.
    snapshot_cache : str, optional
        The machine-local chunk-cache directory (default:
        ``workdir/chunk-cache``).
    machine : str
        This replica's machine name — labels its snapshot-wire requests
        (``slow_link`` plans and ``snapshot.bytes_fetched{machine=}``).
    """

    def __init__(self, name: str, snapshot_path: str, workdir: str, *,
                 max_batch_rows: int = 1024,
                 max_queue: int = 4096,
                 heartbeat_interval_s: float = 0.05,
                 wedge_timeout_s: float = 10.0,
                 kill_after_requests: Optional[int] = None,
                 straggle_s: float = 0.0,
                 straggle_every: int = 1,
                 snapshot_server: Optional[str] = None,
                 snapshot_cache: Optional[str] = None,
                 machine: str = ""):
        self.name = str(name)
        self.snapshot_path = str(snapshot_path)
        self.workdir = str(workdir)
        self.snapshot_server = snapshot_server
        self.snapshot_cache = snapshot_cache
        self.machine = str(machine)
        self._fetch_stats: Optional[dict] = None
        self.max_batch_rows = int(max_batch_rows)
        self.max_queue = int(max_queue)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.kill_after_requests = kill_after_requests
        self.straggle_s = float(straggle_s)
        self.straggle_every = int(straggle_every)
        self._warm_compiles = 0
        self._loop = None
        self._server = None
        self._stop = threading.Event()

    # -- the wire `stats` op payload --------------------------------------

    def _extra_stats(self) -> dict:
        from dask_ml_tpu.parallel.shapes import compile_stats

        return {
            "replica": self.name,
            # the respawn gate: compiles since warmup finished must stay
            # 0 under steady-state traffic (docs/serving.md)
            "steady_compiles": int(
                compile_stats()["n_compiles"] - self._warm_compiles),
            "warm_compiles": int(self._warm_compiles),
        }

    def _addr_path(self) -> str:
        return os.path.join(self.workdir, f"{self.name}.addr.json")

    def _announce(self, warm: dict) -> None:
        """Atomically publish this replica's address + pid + warmup cost
        — the router's readiness signal (written only AFTER warmup, so a
        replica in rotation never compiles on the request path)."""
        info = {"name": self.name, "host": self._server.address[0],
                "port": int(self._server.address[1]),
                "pid": os.getpid(), "warmup": warm,
                "snapshot_fetch": self._fetch_stats}
        path = self._addr_path()
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, path)

    def run(self) -> int:
        """Serve until SIGTERM (graceful drain: flush, tombstone, exit 0)
        or SIGKILL (nothing at all — the liveness layer's silence IS the
        signal). Returns the exit code."""
        from dask_ml_tpu.parallel.elastic import FileHeartbeat
        from dask_ml_tpu.parallel.faults import FaultInjector, GracefulDrain
        from dask_ml_tpu.parallel.fleet import FleetServer
        from dask_ml_tpu.parallel.serving import ModelRegistry, ServingLoop
        from dask_ml_tpu.parallel.shapes import (
            reset_compile_stats,
            track_compiles,
        )

        os.makedirs(self.workdir, exist_ok=True)
        live = FileHeartbeat(self.workdir)
        live.beat(self.name)

        injector = FaultInjector()
        if self.straggle_s > 0.0:
            injector.straggle_replica(self.name, self.straggle_s,
                                      every=self.straggle_every)
        if self.kill_after_requests is not None:
            injector.kill_process(self.name,
                                  after_requests=int(
                                      self.kill_after_requests))

        if self.snapshot_server is not None:
            # machines mode: the registry arrives over the snapshot
            # wire, chunk-addressed through the machine's shared cache —
            # a respawn on a warm machine ships only the missing delta
            from dask_ml_tpu.parallel.snapshots import (
                fetch_snapshot,
                parse_address,
            )

            cache = self.snapshot_cache or os.path.join(
                self.workdir, "chunk-cache")
            self._fetch_stats = fetch_snapshot(
                parse_address(self.snapshot_server), self.snapshot_path,
                cache_dir=cache, machine=self.machine)

        registry = ModelRegistry()
        for mname, est, methods in load_registry_snapshot(
                self.snapshot_path):
            registry.register(mname, est, methods=methods)

        drain = GracefulDrain(signals=(signal.SIGTERM,))
        self._loop = ServingLoop(
            registry, max_batch_rows=self.max_batch_rows,
            max_queue=self.max_queue, drain=drain,
            fault_injector=injector, name=self.name)
        reset_compile_stats()
        with drain:
            self._loop.start()
            with track_compiles() as warm_t:
                warm = self._loop.warmup()
            self._warm_compiles = warm_t["n_compiles"]
            self._server = FleetServer(
                self._loop, extra_stats=self._extra_stats).start()
            self._announce(warm)
            live.beat(self.name)
            # the beat loop IS the main thread's job: liveness + chaos
            # polling until the drain (SIGTERM) or a stop lands
            while not self._stop.is_set() and not drain.requested:
                # gate the FILE beat on the dispatch thread's own beat:
                # a wedged (not crashed) batch stalls the loop heartbeat,
                # and past wedge_timeout_s this process goes silent too —
                # the process-level analogue of the in-process fleet's
                # heartbeat_age() death signal, so the router respawns a
                # wedged replica instead of routing to it forever. The
                # generous default (10 s) keeps a merely-slow batch from
                # reading as a wedge.
                if self._loop.heartbeat_age() <= self.wedge_timeout_s:
                    live.beat(self.name)
                injector.maybe_kill_process(self.name,
                                            self._server.n_requests)
                if not self._loop.alive() and self._loop.fatal is not None:
                    break  # dispatch crashed: die visibly, not silently
                self._stop.wait(self.heartbeat_interval_s)
            # graceful exit: flush the queue, resolve every future, leave
            # the tombstone so the router skips its timeout
            self._loop.stop(drain=True)
            self._server.stop()
            live.tombstone(self.name)
        return 0 if self._loop.fatal is None else 1

    def stop(self) -> None:
        self._stop.set()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dask_ml_tpu.parallel.replica",
        description="one out-of-process serving replica (spawned by the "
                    "process fleet router)")
    parser.add_argument("--name", required=True)
    parser.add_argument("--snapshot", required=True)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--max-batch-rows", type=int, default=1024)
    parser.add_argument("--max-queue", type=int, default=4096)
    parser.add_argument("--heartbeat-interval-s", type=float, default=0.05)
    parser.add_argument("--wedge-timeout-s", type=float, default=10.0)
    parser.add_argument("--kill-after-requests", type=int, default=None)
    parser.add_argument("--straggle-s", type=float, default=0.0)
    parser.add_argument("--straggle-every", type=int, default=1)
    parser.add_argument("--snapshot-server", default=None,
                        help="host:port — fetch the snapshot "
                             "chunk-addressed instead of reading it "
                             "from disk (--snapshot becomes the "
                             "destination path)")
    parser.add_argument("--snapshot-cache", default=None)
    parser.add_argument("--machine", default="")
    args = parser.parse_args(argv)
    host = ReplicaHost(
        args.name, args.snapshot, args.workdir,
        max_batch_rows=args.max_batch_rows,
        max_queue=args.max_queue,
        heartbeat_interval_s=args.heartbeat_interval_s,
        wedge_timeout_s=args.wedge_timeout_s,
        kill_after_requests=args.kill_after_requests,
        straggle_s=args.straggle_s,
        straggle_every=args.straggle_every,
        snapshot_server=args.snapshot_server,
        snapshot_cache=args.snapshot_cache,
        machine=args.machine)
    return host.run()


if __name__ == "__main__":
    raise SystemExit(main())
