"""Shared-memory ring transport: the zero-copy local data plane under
the typed serving wire.

PR 15 put replicas in their own OS processes and PR 18 put them on other
machines — but a same-machine replica link still paid the full socket
toll per request: two payload copies (encode concatenation, kernel
buffer), a cryptographic hash, and a loopback TCP round-trip. For the
co-located case all of that is avoidable: both processes can map the
SAME memory, so a request can be written once by the client and read
in place by the replica.

This module is that transport. One
:class:`multiprocessing.shared_memory.SharedMemory` segment per
connection, created by the CLIENT, carrying two single-producer/
single-consumer rings (client→server requests, server→client
responses). A message is one ring record::

    u32 status      (EMPTY → READY → FREE, or WRAP/WRAP_FREE markers)
    u32 payload length
    digest          (the wire integrity tier — crc32c by default;
                     sha256 supported, both fuzz-swept)
    payload         (the EXACT typed bytes of framing.encode_payload:
                     control JSON + dtype/shape-tagged buffers)
    padding to 8 bytes

The payload layout is the wire's typed codec unchanged —
:func:`~dask_ml_tpu.parallel.framing.decode_payload` decodes a
memoryview over the record IN PLACE, so the arrays a replica receives
are numpy views into the shared segment: zero payload copies on the
request path (pinned by buffer-pointer identity tests). The consumer
holds the record (``status=READY``, tracked by token) until the request
is fully served and only then releases it (``status=FREE``); a sweep
advances the reader cursor over contiguous FREE records, so
out-of-order completion — the fleet's normal case — never blocks the
ring behind one slow request.

Publication order is the SPSC contract: the writer fills length, digest,
and payload first and stores ``READY`` last; the reader never touches a
record before seeing ``READY``. Cursors are 8-byte-aligned values in the
segment written by exactly one side (x86-64 makes aligned 8-byte stores
atomic; ordering comes from the status word, not the cursor).

Negotiation lives in the fleet layer (``op="shm_hello"`` over the
established TCP connection): the client creates a segment and names it;
the server ATTACHES — which can only succeed when both ends share a
kernel — and answers yes/no; on no, traffic stays on the framed TCP
wire, byte-identical semantics. The TCP socket stays open either way as
the liveness/EOF channel, so a ``kill -9`` of either end is detected
exactly the way the socket wire detects it today.

Segment hygiene: the client (creator) unlinks on close; an abnormal
client death is covered by its ``resource_tracker``. The server
UNREGISTERS its attachment from its own tracker (Python 3.10 registers
attachments too — bpo-39959 — and would otherwise unlink the client's
live segment when the replica process exits, which is precisely the
respawn path). Segments carry the :data:`SEGMENT_PREFIX` name prefix so
the leak gate (``bench.py --wire``) can sweep ``/dev/shm``.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import uuid
from typing import Optional

from dask_ml_tpu.parallel import framing

__all__ = [
    "ShmClient",
    "ShmServer",
    "DEFAULT_RING_BYTES",
    "SEGMENT_PREFIX",
    "list_segments",
]

#: 8-byte segment magic + layout version (bumped on any layout change:
#: an attach to a foreign/stale layout must fail loudly, never misparse)
SEGMENT_MAGIC = b"DMLTSHM1"
SEGMENT_VERSION = 1

#: every segment name starts with this — the /dev/shm leak sweep's probe
SEGMENT_PREFIX = "dmlt_shm_"

#: per-direction ring capacity. Large enough that a full serving batch
#: of requests is in flight without backpressure; one message is capped
#: at half the ring (guarantees a wrapping record can always make
#: progress).
DEFAULT_RING_BYTES = 8 << 20

_HEADER_BYTES = 64
_RING_META_BYTES = 64
_REC_HEADER = 8  # u32 status + u32 payload length
_ALIGN = 8

_EMPTY, _READY, _FREE, _WRAP, _WRAP_FREE = 0, 1, 2, 3, 4

_CHECKSUM_CODES = {"sha256": 1, "crc32c": 2}
_CHECKSUM_NAMES = {v: k for k, v in _CHECKSUM_CODES.items()}


def list_segments() -> list:
    """Live dask-ml-tpu shm segments on this machine (``/dev/shm``
    scan) — the zero-leak gate's probe."""
    try:
        entries = os.listdir("/dev/shm")
    except OSError:
        return []
    return sorted(e for e in entries if e.startswith(SEGMENT_PREFIX))


def _align8(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _nbytes(p) -> int:
    return p.nbytes if isinstance(p, memoryview) else len(p)


class _Ring:
    """One SPSC ring region of the segment (meta + data offsets are
    fixed by the creator; both sides derive them from the header)."""

    def __init__(self, mv, meta_off: int, data_off: int, cap: int,
                 checksum: str):
        self._mv = mv
        self._meta = meta_off
        self._data = data_off
        self._cap = cap
        self._checksum = checksum
        self._dlen = framing.digest_length(checksum)

    def _status(self, off: int) -> int:
        return struct.unpack_from(">I", self._mv, self._data + off)[0]

    def _set_status(self, off: int, st: int) -> None:
        struct.pack_into(">I", self._mv, self._data + off, st)

    def _plen(self, off: int) -> int:
        return struct.unpack_from(">I", self._mv, self._data + off + 4)[0]

    def _rpos(self) -> int:
        return struct.unpack_from(">Q", self._mv, self._meta)[0]

    def _set_rpos(self, v: int) -> None:
        struct.pack_into(">Q", self._mv, self._meta, v)

    def rec_size(self, plen: int) -> int:
        return _align8(_REC_HEADER + self._dlen + plen)


class _RingWriter(_Ring):
    """The producing side: waits for space (bounded), writes the record,
    publishes READY last."""

    def __init__(self, *args):
        super().__init__(*args)
        self._wpos = 0
        self._lock = threading.Lock()

    def max_message_bytes(self) -> int:
        return self._cap // 2 - _REC_HEADER - self._dlen

    def write(self, parts, *, timeout: Optional[float],
              dead: threading.Event) -> int:
        total = sum(_nbytes(p) for p in parts)
        size = self.rec_size(total)
        if size > self._cap // 2:
            raise framing.PayloadError(
                f"message of {total} bytes exceeds this shm ring's "
                f"{self.max_message_bytes()}-byte record cap — raise "
                "ring_bytes on the client or let this link fall back to "
                "the TCP wire")
        deadline = (None if timeout is None
                    else time.perf_counter() + float(timeout))
        with self._lock:
            pause = 5e-05
            while True:
                off = self._wpos % self._cap
                need = (size if off + size <= self._cap
                        else (self._cap - off) + size)
                free = self._cap - (self._wpos - self._rpos())
                if free >= need:
                    break
                if dead.is_set():
                    raise ConnectionError(
                        "shm transport closed while awaiting ring space")
                if deadline is not None \
                        and time.perf_counter() >= deadline:
                    raise ConnectionError(
                        f"shm ring stayed full for {timeout}s: the peer "
                        "stopped consuming")
                time.sleep(pause)
                pause = min(pause * 2.0, 1e-03)
            if off + size > self._cap:
                # tail remnant too small for this record: mark the jump
                # and start at the ring head (the free check above
                # already covered the skipped bytes)
                self._set_status(off, _WRAP)
                self._wpos += self._cap - off
                off = 0
            base = self._data + off
            struct.pack_into(">I", self._mv, base + 4, total)
            self._mv[base + _REC_HEADER:base + _REC_HEADER + self._dlen] \
                = framing._digest(self._checksum, parts)
            pos = base + _REC_HEADER + self._dlen
            for p in parts:
                n = _nbytes(p)
                self._mv[pos:pos + n] = p
                pos += n
            self._set_status(off, _READY)  # publish LAST
            self._wpos += size
        return total


class _RingReader(_Ring):
    """The consuming side: polls for READY records, hands out in-place
    payload views with a release token, sweeps contiguous FREE records
    to advance the shared read cursor."""

    def __init__(self, *args):
        super().__init__(*args)
        self._next = 0
        self._swept = 0
        self._held: dict = {}
        self._lock = threading.Lock()

    def poll(self):
        """One non-blocking attempt → ``(payload_view, token)`` or
        ``None``. A structurally-invalid record (fuzzed status/length,
        failed digest) raises :class:`FrameCorruptError` — ring
        alignment is gone, the connection must die, exactly like a torn
        TCP frame."""
        while True:
            off = self._next % self._cap
            st = self._status(off)
            if st == _EMPTY:
                return None
            if st == _WRAP:
                with self._lock:
                    self._set_status(off, _WRAP_FREE)
                    self._next += self._cap - off
                    self._sweep()
                continue
            if st != _READY:
                raise framing.FrameCorruptError(
                    f"shm ring record at offset {off} has invalid "
                    f"status {st}")
            plen = self._plen(off)
            size = self.rec_size(plen)
            if off + size > self._cap:
                raise framing.FrameCorruptError(
                    f"shm ring record at offset {off} overruns the ring "
                    f"(torn length {plen})")
            base = self._data + off
            digest = bytes(
                self._mv[base + _REC_HEADER:base + _REC_HEADER
                         + self._dlen])
            payload = self._mv[base + _REC_HEADER + self._dlen:
                               base + _REC_HEADER + self._dlen + plen]
            if framing._digest(self._checksum, (payload,)) != digest:
                raise framing.FrameCorruptError(
                    "shm ring record checksum mismatch")
            token = self._next
            with self._lock:
                self._held[token] = size
            self._next += size
            return payload, token

    def release(self, token: int) -> None:
        with self._lock:
            size = self._held.pop(token, None)
            if size is None:
                return
            self._set_status(token % self._cap, _FREE)
            self._sweep()

    def _sweep(self) -> None:
        # under self._lock: advance the shared cursor over every
        # contiguous released record (out-of-order releases park as FREE
        # until the head of the line frees)
        rpos = self._swept
        while rpos < self._next:
            off = rpos % self._cap
            st = self._status(off)
            if st == _FREE:
                size = self.rec_size(self._plen(off))
                self._set_status(off, _EMPTY)
                rpos += size
            elif st == _WRAP_FREE:
                self._set_status(off, _EMPTY)
                rpos += self._cap - off
            else:
                break
        if rpos != self._swept:
            self._swept = rpos
            self._set_rpos(rpos)


class _ShmEndpoint:
    """Common send/recv/release surface of both ends (the transport
    seam the fleet layer drives; `_reader`/`_writer`/`_shm` are set by
    the subclass constructors)."""

    checksum: str
    ring_bytes: int

    def __init__(self):
        self._dead = threading.Event()
        self.n_sent = 0
        self.n_received = 0

    @property
    def segment(self) -> str:
        return self._shm.name

    @property
    def closed(self) -> bool:
        return self._dead.is_set()

    def send(self, control: dict, arrays=(), *,
             timeout: Optional[float] = 30.0) -> int:
        """Encode one typed message and write it into the outgoing ring
        (single digest pass, buffers copied once — caller memory →
        shared memory). Returns the payload byte count."""
        from dask_ml_tpu.parallel import telemetry

        if self._dead.is_set():
            raise ConnectionError("shm transport is closed")
        parts = framing.encode_payload_parts(control, arrays)
        n = self._writer.write(parts, timeout=timeout, dead=self._dead)
        self.n_sent += 1
        if telemetry.enabled():
            telemetry.metrics().counter(
                "wire.bytes", transport="shm").inc(n)
        return n

    def recv(self, timeout: Optional[float] = 0.05):
        """Poll the incoming ring for one message →
        ``(control, arrays, token)`` or ``None`` after ``timeout``.
        The arrays are ZERO-COPY views into the shared segment — they
        stay valid until ``release(token)``, which the caller owes
        exactly once per received message. A payload that fails its
        typed decode raises :class:`PayloadError` with the record
        already released (frame intact → the connection survives)."""
        deadline = (None if timeout is None
                    else time.perf_counter() + float(timeout))
        spin_until = time.perf_counter() + 1e-04
        pause = 2e-05
        while True:
            if self._dead.is_set():
                raise ConnectionError("shm transport is closed")
            rec = self._reader.poll()
            if rec is not None:
                break
            now = time.perf_counter()
            if deadline is not None and now >= deadline:
                return None
            if now >= spin_until:
                # escalating backoff: an actively-fed ring is drained at
                # tens-of-µs latency, an idle one costs ~500 GIL
                # acquisitions/s instead of 20k (many idle connections
                # must not starve the ones doing work)
                time.sleep(pause)
                pause = min(pause * 1.5, 2e-03)
        payload, token = rec
        try:
            control, arrays = framing.decode_payload(payload)
        except framing.PayloadError:
            self._reader.release(token)
            raise
        self.n_received += 1
        return control, arrays, token

    def release(self, token: int) -> None:
        """Return one received record to the ring (every ``recv`` owes
        exactly one — after the LAST read of its array views)."""
        if self._dead.is_set():
            return
        try:
            self._reader.release(token)
        except (ValueError, TypeError):
            pass  # segment already unmapped by a concurrent close

    def _close_mapping(self) -> None:
        self._dead.set()
        try:
            self._shm.close()
        except BufferError:
            # numpy views into the segment are still alive (held
            # records); the mapping falls with them at GC — what must
            # not leak is the /dev/shm NAME, and unlink (creator-side)
            # does not require the mapping to be gone
            pass
        except OSError:
            pass


class ShmClient(_ShmEndpoint):
    """The creating end (one per fleet-client connection): allocates the
    segment, lays out both rings, writes requests, reads responses.
    Owns the segment name — :meth:`close` unlinks it."""

    def __init__(self, *, ring_bytes: int = DEFAULT_RING_BYTES,
                 checksum: str = framing.WIRE_CHECKSUM):
        from multiprocessing import shared_memory

        super().__init__()
        if checksum not in _CHECKSUM_CODES:
            raise ValueError(
                f"unknown checksum {checksum!r} "
                f"(supported: {tuple(_CHECKSUM_CODES)})")
        cap = _align8(max(int(ring_bytes), 1 << 16))
        self.checksum = checksum
        self.ring_bytes = cap
        total = _HEADER_BYTES + 2 * (_RING_META_BYTES + cap)
        name = SEGMENT_PREFIX + uuid.uuid4().hex[:16]
        self._shm = shared_memory.SharedMemory(
            name=name, create=True, size=total)
        mv = self._shm.buf
        mv[0:len(SEGMENT_MAGIC)] = SEGMENT_MAGIC
        struct.pack_into(">I", mv, 8, SEGMENT_VERSION)
        struct.pack_into(">I", mv, 12, _CHECKSUM_CODES[checksum])
        struct.pack_into(">Q", mv, 16, cap)
        struct.pack_into(">Q", mv, 24, os.getpid())  # creator pid
        m0 = _HEADER_BYTES
        d0 = m0 + _RING_META_BYTES
        m1 = d0 + cap
        d1 = m1 + _RING_META_BYTES
        # ring 0: client → server; ring 1: server → client
        self._writer = _RingWriter(mv, m0, d0, cap, checksum)
        self._reader = _RingReader(mv, m1, d1, cap, checksum)

    def hello(self) -> dict:
        """The ``op="shm_hello"`` control envelope the fleet client
        sends over the established TCP connection to negotiate this
        segment."""
        return {"op": "shm_hello", "segment": self.segment,
                "ring_bytes": self.ring_bytes,
                "checksum": self.checksum,
                "version": SEGMENT_VERSION}

    def close(self, *, unlink: bool = True) -> None:
        self._close_mapping()
        if unlink:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
            except OSError:
                pass


class ShmServer(_ShmEndpoint):
    """The attaching end (the replica): maps the client's segment by
    name — possible only when both ends share a kernel, which IS the
    same-machine test the negotiation relies on — validates the layout
    header, reads requests, writes responses. Never unlinks (the
    creator owns the name)."""

    def __init__(self, segment: str, *,
                 ring_bytes: Optional[int] = None,
                 checksum: Optional[str] = None):
        from multiprocessing import shared_memory

        super().__init__()
        segment = str(segment)
        if not segment.startswith(SEGMENT_PREFIX):
            raise framing.PayloadError(
                f"shm segment name must carry the {SEGMENT_PREFIX!r} "
                f"prefix, got {segment!r}")
        self._shm = shared_memory.SharedMemory(name=segment)
        # Python 3.10's resource tracker registers ATTACHED segments too
        # (bpo-39959) and would unlink the client's live segment when
        # THIS process exits — exactly the replica-respawn path. The
        # creator owns cleanup; drop the spurious registration — but
        # only cross-process: a same-process attach (in-process tests)
        # was a no-op on the tracker's name set, and unregistering
        # there would strip the CREATOR's entry instead.
        try:
            creator_pid = struct.unpack_from(">Q", self._shm.buf, 24)[0]
            if creator_pid != os.getpid():
                from multiprocessing import resource_tracker

                resource_tracker.unregister(
                    self._shm._name, "shared_memory")
        except Exception:  # pragma: no cover - best-effort hygiene
            pass
        try:
            mv = self._shm.buf
            if bytes(mv[0:len(SEGMENT_MAGIC)]) != SEGMENT_MAGIC:
                raise framing.FrameCorruptError(
                    f"shm segment {segment!r} has a foreign magic")
            version = struct.unpack_from(">I", mv, 8)[0]
            if version != SEGMENT_VERSION:
                raise framing.FrameCorruptError(
                    f"shm segment {segment!r} has layout version "
                    f"{version}, this peer speaks {SEGMENT_VERSION}")
            code = struct.unpack_from(">I", mv, 12)[0]
            cname = _CHECKSUM_NAMES.get(code)
            if cname is None:
                raise framing.FrameCorruptError(
                    f"shm segment {segment!r} declares unknown checksum "
                    f"code {code}")
            cap = struct.unpack_from(">Q", mv, 16)[0]
            expected = _HEADER_BYTES + 2 * (_RING_META_BYTES + cap)
            if cap <= 0 or self._shm.size < expected:
                raise framing.FrameCorruptError(
                    f"shm segment {segment!r} is {self._shm.size} bytes "
                    f"but its header describes {expected}")
            if ring_bytes is not None and int(ring_bytes) != cap:
                raise framing.FrameCorruptError(
                    f"shm hello declared ring_bytes={ring_bytes} but "
                    f"the segment header says {cap}")
            if checksum is not None and checksum != cname:
                raise framing.FrameCorruptError(
                    f"shm hello declared checksum={checksum!r} but the "
                    f"segment header says {cname!r}")
        except BaseException:
            self._close_mapping()
            raise
        self.checksum = cname
        self.ring_bytes = int(cap)
        m0 = _HEADER_BYTES
        d0 = m0 + _RING_META_BYTES
        m1 = d0 + cap
        d1 = m1 + _RING_META_BYTES
        # mirror of the client: ring 0 is inbound here, ring 1 outbound
        self._reader = _RingReader(mv, m0, d0, cap, cname)
        self._writer = _RingWriter(mv, m1, d1, cap, cname)

    def close(self) -> None:
        self._close_mapping()
