"""Elastic multi-host data plane: sharded ingestion, seeded epoch
shuffling, and survivor rebalancing on host loss.

The streamed >HBM tier (``parallel/stream.py`` + the streamed solvers) is
single-host: one process owns the whole block space and the whole epoch.
This module spans that stream across a FLEET of processes and makes it
survive losing part of it — the capability dask-ml inherited for free from
the ``dask.distributed`` scheduler (worker-loss resilience + data
distribution), rebuilt on the substrate this repo actually owns:

- :class:`BlockPlan` — a deterministic, seeded cross-epoch block
  permutation plus the contiguous shard split that assigns each host its
  slice of an epoch. Every host computes the same plan from the same seed,
  so there is no scheduler process and nothing to elect: coordination is
  arithmetic.
- :class:`ElasticRun` — the per-process runtime handle: file-based
  heartbeats + tombstones for liveness (the processes share a filesystem,
  not a ``jax.distributed`` runtime — rebalancing must work exactly when
  collectives are the thing that died), and atomic per-block result
  publication through :func:`dask_ml_tpu.checkpoint.save_pytree` (torn
  writes impossible: temp-file + rename + sha256 frame).
- the rebalance protocol — when a host is lost (heartbeats stale / killed)
  or drained (SIGTERM via
  :class:`~dask_ml_tpu.parallel.faults.GracefulDrain`, which leaves a
  tombstone so survivors skip the timeout), its missing blocks are
  re-dealt round-robin to the survivors, deterministically, each survivor
  computing only its own share. A false-positive death (a host that was
  merely slow) costs duplicate compute, never correctness: block results
  are pure functions of (epoch-start state, block data), and publication
  is idempotent.

The bit-identity theorem the tests pin: because every per-block program
depends only on the epoch-start carry and the block's contents, and the
cross-block combine folds results in canonical block-id order, the final
trajectory is IDENTICAL — bit for bit — no matter how many hosts
participated, which of them died, or how the epoch was shuffled. An
elastic run that loses a host mid-epoch finishes with exactly the bytes
of the uninterrupted single-host run (``bench.py --faults --elastic``
gates this; ``tests/test_elastic.py`` pins it per consumer).

Consumers thread through the existing facades:
``models/glm.py::admm_streamed(..., elastic=run)`` and
``decomposition/streaming.py::streamed_moments`` /
``pca_fit_blocks(..., elastic=run)``; the scan side rides the shard-aware
``prefetched_scan(blocks=...)`` coordinates, so PR-3's
:class:`~dask_ml_tpu.parallel.faults.ScanCheckpoint` contract composes —
resume mid-shuffled-epoch replays the snapshot's own block sequence
(``meta['blocks']``) and stays bit-identical.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional, Sequence

import numpy as np

from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.parallel.faults import Preempted

logger = logging.getLogger(__name__)

__all__ = ["BlockPlan", "ElasticRun", "FileHeartbeat",
           "SimulatedHostDeath"]


class FileHeartbeat:
    """mtime-heartbeat + tombstone liveness over a shared directory —
    the PR-8 coordination primitive factored out of :class:`ElasticRun`
    so every fleet of PROCESSES shares one liveness layer (the elastic
    data plane's hosts here; the process-isolated serving replicas in
    ``parallel/procfleet.py``).

    ``workdir/hb/<member>`` holds heartbeat files whose MTIME is the
    signal (writes are atomic temp+rename, so readers never see a torn
    file); ``workdir/dead/<member>`` holds tombstones left by graceful
    leavers — a member that died for real (SIGKILL, machine loss) leaves
    nothing: its beats simply stop, and observers detect the silence by
    age. That asymmetry is the whole protocol: clean exits are observed
    immediately, dirty ones within the observer's timeout.
    """

    def __init__(self, workdir: str):
        self.workdir = str(workdir)
        self._hb = os.path.join(self.workdir, "hb")
        self._dead = os.path.join(self.workdir, "dead")
        os.makedirs(self._hb, exist_ok=True)
        os.makedirs(self._dead, exist_ok=True)

    def hb_path(self, member: str) -> str:
        return os.path.join(self._hb, str(member))

    def tomb_path(self, member: str) -> str:
        return os.path.join(self._dead, str(member))

    def beat(self, member: str) -> None:
        """Refresh ``member``'s heartbeat (atomic, mtime-signaled)."""
        path = self.hb_path(member)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{time.time():.6f}\n")
        os.replace(tmp, path)

    def tombstone(self, member: str) -> None:
        """Leave ``member``'s tombstone (the graceful-leaver courtesy:
        observers skip the timeout)."""
        with open(self.tomb_path(member), "w") as f:
            f.write(f"{time.time():.6f}\n")

    def has_tombstone(self, member: str) -> bool:
        return os.path.exists(self.tomb_path(member))

    def age(self, member: str) -> Optional[float]:
        """Seconds since ``member``'s last beat, or ``None`` when no
        heartbeat was ever observed (the caller decides how a
        never-launched member ages)."""
        try:
            return time.time() - os.path.getmtime(self.hb_path(member))
        except OSError:
            return None

    def clear(self, member: str) -> None:
        """Forget ``member``'s heartbeat AND tombstone — the respawn
        hygiene: a fresh incarnation must not inherit its predecessor's
        death record or stale beat."""
        for path in (self.hb_path(member), self.tomb_path(member)):
            try:
                os.unlink(path)
            except OSError:
                pass


class SimulatedHostDeath(RuntimeError):
    """An injected host death fired (``FaultInjector.die_at``): this
    process is simulating SIGKILL / machine loss — no drain, no snapshot,
    no tombstone; its heartbeats simply stop. In-process tests catch this
    where a real dead host would just be gone; the ``bench.py --faults
    --elastic`` drill worker turns it into ``os._exit``."""

    def __init__(self, message: str, rank: int = 0):
        super().__init__(message)
        self.rank = int(rank)


# ---------------------------------------------------------------------------
# the deterministic plan: seeded epoch permutation + shard split + re-deal
# ---------------------------------------------------------------------------


class BlockPlan:
    """Deterministic, seeded cross-epoch block permutation + shard split.

    ``epoch_order(e)`` is a permutation of ``range(n_blocks)`` drawn from
    ``np.random.RandomState([seed, e])`` — a pure function of (seed,
    epoch), so every host (and every resume) derives the identical order
    with no communication; ``shuffle=False`` keeps block-id order (the
    plan still shards). Cross-epoch reshuffling is what the massive-data
    epoch-streaming regime wants (PAPERS.md, arxiv 1605.02989: each epoch
    visits blocks in a fresh order) — and because the streamed consumers'
    results are permutation-invariant (per-block programs depend only on
    the epoch-start carry), the shuffle changes I/O order, never bytes.

    ``shard(order, rank, roster)`` deals ``order`` contiguously over the
    sorted ``roster`` (even split, remainder to the front — the same rule
    as ``runtime.process_rows``); :meth:`redeal` deals a missing-block
    list round-robin over the sorted survivors. Both are pure, so every
    host computes every other host's assignment without messages.
    """

    def __init__(self, n_blocks: int, *, seed: int = 0,
                 shuffle: bool = True):
        if int(n_blocks) < 1:
            raise ValueError("n_blocks must be a positive integer")
        self.n_blocks = int(n_blocks)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)

    def epoch_order(self, epoch: int) -> list:
        if not self.shuffle:
            return list(range(self.n_blocks))
        rs = np.random.RandomState(
            np.array([self.seed & 0xFFFFFFFF, int(epoch) & 0xFFFFFFFF],
                     dtype=np.uint32))
        return [int(b) for b in rs.permutation(self.n_blocks)]

    @staticmethod
    def shard(order: Sequence[int], rank: int, roster) -> list:
        """``rank``'s contiguous slice of ``order`` among the sorted
        ``roster`` (even split, remainder to the front ranks)."""
        roster = sorted(roster)
        i = roster.index(rank)
        base, rem = divmod(len(order), len(roster))
        start = i * base + min(i, rem)
        stop = start + base + (1 if i < rem else 0)
        return [int(b) for b in order[start:stop]]

    @staticmethod
    def redeal(missing: Sequence[int], survivors) -> dict:
        """Deal the ``missing`` blocks (in their given, epoch-position
        order) round-robin over the sorted ``survivors`` →
        ``{block: new_owner_rank}``. Pure, so every survivor derives the
        same re-deal from the same observed state."""
        survivors = sorted(survivors)
        return {int(b): survivors[j % len(survivors)]
                for j, b in enumerate(missing)}


# ---------------------------------------------------------------------------
# per-process runtime handle: liveness + atomic publication
# ---------------------------------------------------------------------------


class ElasticRun:
    """Per-process handle on one multi-host elastic fit.

    ``workdir`` is the shared-filesystem coordination directory (every
    participating process passes the same path): ``hb/`` holds heartbeat
    files (freshness by mtime), ``dead/`` tombstones (left by graceful
    leavers and by the deterministic test hook :meth:`mark_dead`), and
    ``blocks/`` the published per-block results — each written through
    :func:`~dask_ml_tpu.checkpoint.save_pytree`, so publication is atomic
    AND checksummed (a torn publish is impossible; a corrupt one raises
    loudly instead of poisoning a survivor).

    ``rank``/``world`` default to the
    :func:`~dask_ml_tpu.parallel.runtime.process_rank` /
    :func:`~dask_ml_tpu.parallel.runtime.process_count` resolution
    (explicit > ``DASK_ML_TPU_PROCESS_ID`` env > jax.distributed >
    single-process). ``shuffle_seed``/``shuffle`` configure the
    :class:`BlockPlan` the consuming drivers build. A host whose
    heartbeat is older than ``heartbeat_timeout`` seconds (or that left a
    tombstone) is considered lost; survivors re-deal its missing blocks.
    ``drain`` (a :class:`~dask_ml_tpu.parallel.faults.GracefulDrain`) is
    polled while waiting on peers: a requested drain leaves a tombstone
    (so survivors skip the timeout) and raises
    :class:`~dask_ml_tpu.parallel.faults.Preempted`.

    Counters ``hosts_lost`` / ``blocks_rebalanced`` /
    ``blocks_speculated`` mirror into the telemetry registry
    (``elastic.host_lost`` / ``elastic.blocks_rebalanced`` /
    ``elastic.blocks_speculated``) at their increment sites —
    docs/observability.md discipline, pinned in
    ``tests/test_telemetry.py``.
    """

    def __init__(self, workdir: str, *, rank: Optional[int] = None,
                 world: Optional[int] = None, shuffle_seed: int = 0,
                 shuffle: bool = True, heartbeat_timeout: float = 10.0,
                 poll_interval: float = 0.05, fault_injector=None,
                 drain=None, speculate_after: Optional[float] = None):
        from dask_ml_tpu.parallel import runtime

        self.rank = runtime.process_rank() if rank is None else int(rank)
        self.world = runtime.process_count() if world is None else int(world)
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"rank {self.rank} out of range [0, {self.world})")
        self.workdir = str(workdir)
        self.shuffle_seed = int(shuffle_seed)
        self.shuffle = bool(shuffle)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.poll_interval = float(poll_interval)
        self.fault_injector = fault_injector
        self.drain = drain
        #: straggler (not death) mitigation: with ``speculate_after`` set
        #: (seconds, sensibly < ``heartbeat_timeout``), an IDLE host —
        #: done with its own share, seeing every owner alive but no new
        #: publication for that long — speculatively computes a share of
        #: the missing blocks without declaring anyone dead. First
        #: publication wins by the existing idempotence (block results
        #: are pure functions of epoch-start state + block data, so the
        #: duplicate bytes are identical). ``None`` (default) disables
        #: speculation; the heartbeat-timeout re-deal remains the
        #: correctness backstop either way.
        self.speculate_after = (None if speculate_after is None
                                else float(speculate_after))
        self.hosts_lost = 0
        self.blocks_rebalanced = 0
        self.blocks_speculated = 0
        self._known_dead: set = set()
        #: ranks ever COUNTED as lost by this handle: `_known_dead` resets
        #: per problem namespace (a restarted peer may rejoin the next
        #: fit), but one physical death must not bump ``hosts_lost`` and
        #: its registry mirror once per fit on a reused run handle
        self._ever_lost: set = set()
        self._t0 = time.time()
        #: problem namespace: every fit binds its coordination tree
        #: (heartbeats, tombstones, published blocks) to a fingerprint of
        #: the problem via :meth:`bind_problem`, so a reused workdir can
        #: never fold a DIFFERENT fit's published results — or its stale
        #: tombstones — into this one. Direct API use (tests, custom
        #: drivers) runs in the "shared" namespace until bound.
        self._ns = "shared"
        #: this epoch's published trees, by (epoch, block): what this host
        #: computed (or already read) need not round-trip through disk
        #: again in collect_epoch's final assembly. Cleared per epoch.
        self._cache: dict = {}
        self._ensure_dirs()
        self.beat()

    def _dir(self, sub: str) -> str:
        return os.path.join(self.workdir, self._ns, sub)

    def _ensure_dirs(self) -> None:
        # liveness (hb/ + dead/) goes through the shared FileHeartbeat
        # primitive; blocks/ is this class's own publication directory
        self._live = FileHeartbeat(os.path.join(self.workdir, self._ns))
        os.makedirs(self._dir("blocks"), exist_ok=True)

    def bind_problem(self, kind: str, **bind) -> str:
        """Scope this run to a problem fingerprint: the coordination tree
        moves to ``workdir/<digest>/``, where the digest covers ``kind``
        plus the driver's bind payload (block count, width, family,
        hyperparameters, shuffle seed). Two different problems sharing a
        workdir therefore occupy DISJOINT namespaces — fit 2 can never
        read fit 1's published blocks as its own (the same discipline as
        :class:`~dask_ml_tpu.parallel.faults.ScanCheckpoint`'s bind, by
        construction instead of by check). Re-running the SAME problem
        reuses its published blocks — that is the resume path. The
        drivers call this at fit start; every host of a fleet derives
        the identical digest, so it never needs coordinating."""
        import hashlib
        import json as json_lib

        payload = json_lib.dumps({"kind": kind, **bind}, sort_keys=True,
                                 default=repr)
        ns = hashlib.sha256(payload.encode()).hexdigest()[:16]
        if ns != self._ns:
            self._ns = ns
            self._known_dead = set()  # loss views are per-namespace
            self._t0 = time.time()
            self._cache.clear()
            self._ensure_dirs()
            # the human-readable record of what this namespace is; all
            # writers hold identical content, so the replace race is moot
            # (tmp is per-writer — in-process multi-host tests share a pid,
            # so the thread id must disambiguate)
            import threading
            desc = os.path.join(self.workdir, ns, "problem.json")
            tmp = f"{desc}.{os.getpid()}.{threading.get_ident()}.tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, desc)
            self.beat()
        return ns

    # -- liveness ----------------------------------------------------------

    def _hb_path(self, rank: int) -> str:
        return self._live.hb_path(f"host{int(rank)}")

    def _tomb_path(self, rank: int) -> str:
        return self._live.tomb_path(f"host{int(rank)}")

    def beat(self) -> None:
        """Refresh this process's heartbeat (mtime is the signal; the
        write is atomic so readers never see a torn file)."""
        self._live.beat(f"host{self.rank}")

    def mark_dead(self, rank: int) -> None:
        """Leave a tombstone for ``rank`` — the deterministic test hook
        (and the graceful leaver's own exit courtesy): survivors observe
        the death immediately instead of waiting out the heartbeat
        timeout."""
        self._live.tombstone(f"host{int(rank)}")

    def lost_hosts(self) -> set:
        """Ranks currently considered lost: tombstoned, or heartbeat
        stale by more than ``heartbeat_timeout`` (a never-seen heartbeat
        ages from this run's start, so a worker that never launched is
        eventually declared dead too). Cumulative — a host observed dead
        stays dead for this run (rejoin is a restart's concern, not a
        wait loop's). Newly observed deaths bump ``hosts_lost`` and its
        registry mirror."""
        lost = set(self._known_dead)
        now = time.time()
        for r in range(self.world):
            if r == self.rank or r in lost:
                continue
            if self._live.has_tombstone(f"host{r}"):
                lost.add(r)
                continue
            age = self._live.age(f"host{r}")
            fresh_hb = age is not None
            if age is None:
                age = now - self._t0
            if age > self.heartbeat_timeout:
                lost.add(r)
            elif fresh_hb and r in self._ever_lost:
                # an actual heartbeat from a previously-counted rank: it
                # restarted and rejoined — a later death is a NEW loss
                self._ever_lost.discard(r)
        new = lost - self._known_dead
        if new:
            self._known_dead |= new
            logger.warning("elastic: host(s) %s lost (rank %d observing)",
                           sorted(new), self.rank)
            counted = new - self._ever_lost
            if counted:
                self._ever_lost |= counted
                self.hosts_lost += len(counted)
                if telemetry.enabled():
                    # registry mirror at the increment site (same
                    # discipline as stream.py's byte counters)
                    telemetry.metrics().counter(
                        "elastic.host_lost").inc(len(counted))
        return lost

    def alive_hosts(self) -> list:
        """Sorted ranks not currently lost (always includes self)."""
        lost = self.lost_hosts()
        return [r for r in range(self.world)
                if r == self.rank or r not in lost]

    # -- publication -------------------------------------------------------

    def _block_path(self, epoch: int, block: int) -> str:
        # one subdirectory per epoch: collect_epoch polls published()
        # every poll_interval, and block files are retained for the run's
        # lifetime — a flat dir would make each poll list EVERY past
        # epoch's files (O(epochs²·n_blocks) listdir work over a fit)
        return os.path.join(self._dir("blocks"), f"e{int(epoch):04d}",
                            f"b{int(block):05d}.ckpt")

    def publish(self, epoch: int, block: int, tree) -> None:
        """Atomically publish ``block``'s result for ``epoch``. Idempotent
        by construction: results are pure functions of (epoch-start
        state, block data), so concurrent publishers write identical
        bytes and the rename race is harmless. The tree (drivers pass
        host arrays) is also kept in the per-epoch cache so this host's
        own results need no disk round-trip at epoch assembly."""
        from dask_ml_tpu.checkpoint import save_pytree

        save_pytree(self._block_path(epoch, block), tree,
                    meta={"kind": "elastic_block", "epoch": int(epoch),
                          "block": int(block), "by": self.rank})
        self._cache[(int(epoch), int(block))] = tree

    def published(self, epoch: int) -> set:
        """Block ids with a visible published result for ``epoch``."""
        out = set()
        try:
            names = os.listdir(
                os.path.join(self._dir("blocks"), f"e{int(epoch):04d}"))
        except OSError:
            return out
        for name in names:
            if name.startswith("b") and name.endswith(".ckpt"):
                out.add(int(name[1:-len(".ckpt")]))
        return out

    def read_block(self, epoch: int, block: int):
        """A published block result (corruption raises
        :class:`~dask_ml_tpu.checkpoint.CheckpointCorruptError` — a
        survivor never resumes from a torn publish)."""
        from dask_ml_tpu.checkpoint import load_pytree

        snap = load_pytree(self._block_path(epoch, block))
        if snap is None:
            raise FileNotFoundError(
                f"elastic block e{epoch} b{block} is not published")
        return snap[0]

    # -- failure hooks -----------------------------------------------------

    def maybe_die(self, block: int, epoch: int) -> None:
        """Poll the injector's host-death plan (``die_at``): fires AFTER
        ``block`` published, simulating SIGKILL — no tombstone, no
        snapshot; survivors must detect the silence."""
        if (self.fault_injector is not None
                and self.fault_injector.should_die(block, epoch)):
            raise SimulatedHostDeath(
                f"injected host death after block {block} of epoch "
                f"{epoch} (rank {self.rank})", rank=self.rank)

    def check_drain(self) -> None:
        """While waiting on peers: a requested drain means leave NOW —
        our shard is published, so we tombstone (survivors skip the
        heartbeat timeout) and raise
        :class:`~dask_ml_tpu.parallel.faults.Preempted`."""
        if self.drain is not None and self.drain.requested:
            self.mark_dead(self.rank)
            raise Preempted(
                f"graceful drain: rank {self.rank} leaving the elastic "
                "run; its published blocks stand and survivors rebalance "
                "the rest")

    def leaving(self):
        """Context manager for the drivers' compute: a
        :class:`~dask_ml_tpu.parallel.faults.Preempted` escaping it (the
        drain fired mid-scan, after snapshotting) leaves this rank's
        tombstone on the way out, so survivors observe the graceful exit
        immediately instead of waiting out the heartbeat timeout — the
        SIGTERM half of the rebalance contract (``die_at`` deaths leave
        nothing; survivors must detect the silence)."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            try:
                yield
            except Preempted:
                self.mark_dead(self.rank)
                raise

        return _scope()

    # -- the rebalance protocol -------------------------------------------

    def collect_epoch(self, plan: BlockPlan, epoch: int,
                      order: Sequence[int], owner: dict,
                      compute_publish: Callable[[list], None]) -> dict:
        """Wait until every block of ``epoch`` is published, re-dealing
        lost hosts' missing blocks to survivors (this process computes
        only its own share of each re-deal). Returns ``{block: tree}``
        for the whole epoch.

        ``owner`` maps block → rank under the current assignment view;
        re-deals update it in place. Views may transiently diverge across
        hosts (deaths are observed at different times) — that costs
        duplicate compute at worst, never a gap: dead owners are re-dealt
        on observation, publication is idempotent, and when epoch-start
        views CROSS (a death near the epoch boundary can leave a block
        that every live host believes some OTHER live host owns — so it
        is neither anyone's ``mine`` nor an orphan in any view), the
        no-progress fallback below re-deals every still-missing block
        over the current survivors after ``heartbeat_timeout`` seconds
        without a new publication, restoring liveness at the price of
        duplicate compute."""
        last_progress = time.time()
        n_have = -1
        speculated: set = set()
        while True:
            have = self.published(epoch)
            if len(have) != n_have:
                n_have = len(have)
                last_progress = time.time()
            missing = [b for b in order if b not in have]
            if not missing:
                out = {b: self._cache.get((int(epoch), int(b)))
                       for b in order}
                for b in order:
                    if out[b] is None:
                        out[b] = self.read_block(epoch, b)
                self._cache.clear()  # per-epoch: the assembly consumed it
                return out
            self.beat()
            self.check_drain()
            # blocks assigned to SELF but still unpublished (a resume
            # whose snapshot sequence predates a roster change can leave
            # some): nobody else will compute them — do it now. Strictly
            # local, so it cannot race another host's view.
            stale_mine = [b for b in missing
                          if owner.get(b) == self.rank]
            if stale_mine:
                compute_publish(stale_mine)
                continue
            lost = self.lost_hosts()
            orphans = [b for b in missing
                       if owner.get(b) in lost or owner.get(b) is None]
            if (not orphans and self.speculate_after is not None
                    and time.time() - last_progress
                    > self.speculate_after):
                # speculative re-deal (straggler mitigation): every owner
                # is alive yet nothing has landed for speculate_after
                # seconds — someone is merely SLOW. The idle hosts (those
                # not owning any missing block: stalled owners are busy
                # computing, not polling here) deal the stragglers' blocks
                # among themselves and duplicate the work WITHOUT marking
                # anyone dead; the owner's own publication may still land
                # first, and either way the bytes are identical (per-block
                # purity), so first-publication-wins costs duplicate
                # compute, never correctness. Each idle host speculates a
                # given block at most once per epoch — the heartbeat
                # fallback below stays the backstop if speculation itself
                # stalls.
                stalled = {owner.get(b) for b in missing}
                idle = [r for r in range(self.world)
                        if r not in lost and r not in stalled]
                if self.rank in idle:
                    deal = BlockPlan.redeal(
                        [b for b in missing if b not in speculated], idle)
                    grab = [b for b, r in deal.items() if r == self.rank]
                    if grab:
                        logger.warning(
                            "elastic: rank %d speculatively computing %d "
                            "straggler block(s) of epoch %d: %s",
                            self.rank, len(grab), epoch, grab)
                        speculated.update(grab)
                        with telemetry.span("elastic.speculate",
                                            epoch=epoch,
                                            blocks=len(grab)):
                            compute_publish(grab)
                        self.blocks_speculated += len(grab)
                        if telemetry.enabled():
                            telemetry.metrics().counter(
                                "elastic.blocks_speculated").inc(len(grab))
                        continue
            if not orphans and (time.time() - last_progress
                                > self.heartbeat_timeout):
                # crossed-views liveness fallback (see docstring): every
                # live owner has had a full timeout to publish and
                # nothing landed — stop trusting the assignment view and
                # re-deal the lot
                logger.warning(
                    "elastic: rank %d saw no progress on %d missing "
                    "block(s) of epoch %d for %.1fs — re-dealing them "
                    "over the current survivors", self.rank, len(missing),
                    epoch, self.heartbeat_timeout)
                orphans = list(missing)
                last_progress = time.time()
            if orphans:
                survivors = [r for r in range(self.world) if r not in lost]
                owner.update(BlockPlan.redeal(orphans, survivors))
                grab = [b for b in orphans if owner[b] == self.rank]
                if grab:
                    logger.warning(
                        "elastic: rank %d rebalancing %d orphaned "
                        "block(s) of epoch %d: %s", self.rank, len(grab),
                        epoch, grab)
                    with telemetry.span("elastic.rebalance", epoch=epoch,
                                        blocks=len(grab)):
                        compute_publish(grab)
                    self.blocks_rebalanced += len(grab)
                    if telemetry.enabled():
                        telemetry.metrics().counter(
                            "elastic.blocks_rebalanced").inc(len(grab))
                    continue
            time.sleep(self.poll_interval)


# ---------------------------------------------------------------------------
# consumer drivers (invoked by the solver facades)
# ---------------------------------------------------------------------------


def _epoch_assignment(run: ElasticRun, order) -> dict:
    """The epoch-start assignment view: ``order`` dealt contiguously over
    the hosts alive right now → ``{block: rank}``."""
    alive = run.alive_hosts()
    owner = {}
    for r in alive:
        for b in BlockPlan.shard(order, r, alive):
            owner[b] = r
    return owner


def elastic_admm_host(run: ElasticRun, source, z0, x0, u0, mask, lamduh,
                      rho, abstol, reltol, inner_tol, sw_total, *,
                      check_done, family, regularizer, max_iter,
                      inner_max_iter, scan_checkpoint=None):
    """The elastic multi-host analogue of
    ``models/glm.py::_admm_streamed_host``: each epoch, this host consumes
    its shard of the seeded block permutation through the shard-aware
    ``prefetched_scan``, publishes each per-block primal update as it
    completes, then waits/rebalances until the whole epoch is published
    and runs the consensus locally (deterministic, so every host derives
    the same (z, u, done) without a collective).

    Bit-identity: per-block prox results depend only on the epoch-start
    (z, x, u) and the block's rows, the primal stack is assembled in
    block-id order, and the consensus program is shared with the
    single-host path — so the trajectory equals the uninterrupted
    single-host run byte for byte, whatever the roster did
    (``tests/test_elastic.py``).

    The full consensus state stays replicated per host (O(B·d) — the same
    memory class as the single-host streamed solver); only block COMPUTE
    and block INGESTION are sharded. Published block files are retained
    for the run's lifetime (a few d-vectors per epoch at streamed scale);
    the drill's workdir is a tempdir.
    """
    import jax.numpy as jnp

    from dask_ml_tpu.models import glm as glm_core
    from dask_ml_tpu.parallel.stream import prefetched_scan

    n_blocks = int(x0.shape[0])
    plan = BlockPlan(n_blocks, seed=run.shuffle_seed, shuffle=run.shuffle)
    # scope the workdir to THIS problem: a reused directory can never
    # serve another fit's published blocks as this one's
    run.bind_problem(
        "admm_streamed", n_blocks=n_blocks, d=int(z0.shape[0]),
        family=family, regularizer=regularizer,
        params=repr((float(lamduh), float(rho), float(abstol),
                     float(reltol), float(inner_tol), float(sw_total),
                     int(inner_max_iter))),
        shuffle_seed=run.shuffle_seed, shuffle=run.shuffle)
    if source.host_rank is None:
        # per-host wire-byte attribution (stream.bytes{host=}) without
        # extra caller wiring
        source.host_rank = run.rank
    if run.fault_injector is None:
        run.fault_injector = getattr(source, "fault_injector", None)
    if run.drain is None and scan_checkpoint is not None:
        run.drain = scan_checkpoint.drain

    b32 = [jnp.asarray(b, jnp.int32) for b in range(n_blocks)]
    z, x, u = z0, x0, u0
    done = jnp.asarray(False)
    n_iter = 0

    start_epoch, resume = 0, None
    if scan_checkpoint is not None:
        snap = scan_checkpoint.load()
        if snap is not None:
            carry, outs0, pos0, ep0 = snap
            z, x, u = (jnp.asarray(t) for t in carry)
            seq0 = (scan_checkpoint.last_meta or {}).get("blocks")
            resume = (list(outs0), int(pos0), list(seq0 or []))
            start_epoch = ep0
            n_iter = ep0

    for it in range(start_epoch, max_iter):
        with run.leaving(), telemetry.span("elastic.epoch", epoch=it,
                                           rank=run.rank,
                                           blocks=n_blocks):
            # a drain requested since the last epoch means leave at the
            # boundary (tombstone + Preempted) — same point the wait loop
            # checks, so a drained host never starts work it won't finish
            run.check_drain()
            order = plan.epoch_order(it)
            owner = _epoch_assignment(run, order)
            z_e, x_e, u_e = z, x, u  # the epoch-start carry

            def step(carry, b, blk):
                x_b = glm_core._host_block_prox(
                    blk, b32[b], z_e, x_e, u_e, rho, inner_tol, sw_total,
                    family=family, inner_max_iter=inner_max_iter,
                    transform=source.transform)
                # publish forces the block's compute (device→host) — the
                # robustness tax that makes this host's completed work
                # survive its own death
                run.publish(it, b, np.asarray(x_b))
                run.beat()
                run.maybe_die(b, it)
                return carry, x_b

            def compute_publish(blocks_seq, start_pos=0, outs=None):
                prefetched_scan(step, (z_e, x_e, u_e), source,
                                blocks=blocks_seq,
                                checkpoint=scan_checkpoint, epoch=it,
                                start_block=start_pos, outs=outs)

            if resume is not None and it == start_epoch and resume[2]:
                # replay the snapshot's OWN block sequence from its saved
                # position — the roster (and therefore the fresh shard
                # split) may have changed since the snapshot
                outs0, pos0, seq0 = resume
                compute_publish(seq0, start_pos=pos0, outs=outs0)
            else:
                mine = [b for b in order if owner.get(b) == run.rank]
                compute_publish(mine)

            results = run.collect_epoch(plan, it, order, owner,
                                        compute_publish)
            x = jnp.asarray(
                np.stack([np.asarray(results[b])
                          for b in range(n_blocks)]))
            # per-axis traffic accounting (parallel/hierarchy.py): the
            # elastic z-consensus imports every OTHER host's published
            # x-blocks over the cross-host (DCN-analog) link — the fleet
            # is the pod level of the two-level hierarchy, so the bytes
            # land under axis "pod" like the sharded solver's cross-pod
            # stage. Recorded per epoch (the driver is a host loop, so
            # call-site accounting here IS per-execution).
            n_foreign = sum(1 for b in range(n_blocks)
                            if owner.get(b) != run.rank)
            from dask_ml_tpu.parallel.hierarchy import ledger
            ledger().record(
                "glm.admm.consensus", "pod",
                n_foreign * int(np.asarray(results[0]).nbytes)
                if n_blocks else 0)
            with telemetry.span("elastic.consensus", epoch=it):
                z, u, done = glm_core._host_consensus(
                    z, x, u, mask, lamduh, rho, abstol, reltol, sw_total,
                    regularizer=regularizer)
        n_iter = it + 1
        if check_done and bool(done):
            # deterministic consensus → every surviving host computes the
            # same done flag and exits the same epoch together
            break
    source.discard_inflight()
    if scan_checkpoint is not None:
        scan_checkpoint.delete()
    return z, jnp.asarray(n_iter, jnp.int32), x, u, done


def elastic_moments_host(run: ElasticRun, source, scan_checkpoint=None):
    """Elastic multi-host moment pass (the
    ``streamed_moments``/``pca_fit_blocks`` driver): one epoch of the
    seeded permutation, sharded over hosts; each block's moments are
    computed INDEPENDENTLY (from zeros) and published, and every host
    folds the published per-block moments in canonical block-id order
    with Neumaier compensation — one jitted scan, so the combine is
    deterministic and roster-independent.

    Per-block independence is what buys elasticity here: a running
    accumulator dies with its host, an independent block moment does not.
    The price is a different (but fixed) summation tree than the
    single-host running chain — elastic results are bit-identical across
    rosters/deaths/resumes (pinned), and match the non-elastic path to
    Neumaier accuracy (O(eps), not O(n_blocks·eps)).

    Resume needs no carry: the published block files ARE the progress, so
    a restarted host just computes whatever of its shard is missing
    (``scan_checkpoint`` still provides the drain + snapshot plumbing the
    preempt path raises through)."""
    import jax.numpy as jnp

    from dask_ml_tpu.decomposition import streaming as sm
    from dask_ml_tpu.parallel.stream import prefetched_scan

    n_blocks = source.n_blocks
    d = int(source.out_struct[0].shape[1])
    plan = BlockPlan(n_blocks, seed=run.shuffle_seed, shuffle=run.shuffle)
    run.bind_problem("streamed_moments", n_blocks=n_blocks, d=d,
                     shuffle_seed=run.shuffle_seed, shuffle=run.shuffle)
    if source.host_rank is None:
        source.host_rank = run.rank
    if run.fault_injector is None:
        run.fault_injector = getattr(source, "fault_injector", None)
    if run.drain is None and scan_checkpoint is not None:
        run.drain = scan_checkpoint.drain

    with run.leaving(), telemetry.span("elastic.moments", rank=run.rank,
                                       blocks=n_blocks, d=d):
        run.check_drain()
        order = plan.epoch_order(0)
        owner = _epoch_assignment(run, order)

        def step(carry, b, blk):
            m = sm._moments_step(sm._moments_init(d), blk,
                                 transform=source.transform)
            sw_b, s_b, G_b = sm._moments_finalize(m)
            run.publish(0, b, (np.asarray(sw_b), np.asarray(s_b),
                               np.asarray(G_b)))
            run.beat()
            run.maybe_die(b, 0)
            return carry, None

        def compute_publish(blocks_seq):
            prefetched_scan(step, None, source, blocks=blocks_seq,
                            checkpoint=scan_checkpoint, epoch=0)

        have = run.published(0)
        mine = [b for b in order
                if owner.get(b) == run.rank and b not in have]
        compute_publish(mine)
        results = run.collect_epoch(plan, 0, order, owner, compute_publish)

        sws = jnp.asarray(np.stack(
            [np.asarray(results[b][0]) for b in range(n_blocks)]))
        ss = jnp.asarray(np.stack(
            [np.asarray(results[b][1]) for b in range(n_blocks)]))
        Gs = jnp.asarray(np.stack(
            [np.asarray(results[b][2]) for b in range(n_blocks)]))
        sw, s, G = _fold_moments(sws, ss, Gs)
    source.discard_inflight()
    if scan_checkpoint is not None:
        scan_checkpoint.delete()
    return sw, s, G


def _fold_moments(sws, ss, Gs):
    """Canonical block-id-order Neumaier fold of per-block moments — one
    compiled scan, shared by every host, so the combine can only agree."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.parallel import precision

    @jax.jit
    def fold(sws, ss, Gs):
        d = ss.shape[1]
        init = (jnp.asarray(0.0, jnp.float32),
                jnp.zeros((d,), jnp.float32), jnp.zeros((d,), jnp.float32),
                jnp.zeros((d, d), jnp.float32),
                jnp.zeros((d, d), jnp.float32))

        def body(carry, inp):
            sw, s, cs, G, cG = carry
            sw_b, s_b, G_b = inp
            sw = sw + sw_b
            s, cs = precision.neumaier_add(s, cs, s_b)
            G, cG = precision.neumaier_add(G, cG, G_b)
            return (sw, s, cs, G, cG), None

        (sw, s, cs, G, cG), _ = jax.lax.scan(body, init, (sws, ss, Gs))
        return sw, s + cs, G + cG

    return fold(sws, ss, Gs)
