"""Host→device sharding of sample-axis data, with padding and row weights.

The reference represents a dataset as a dask array chunked along axis 0 and
lets chunks be uneven (reference: utils.py:177-214 ``check_chunks``). XLA SPMD
wants equal shards, so we pad the sample axis up to a multiple of the mesh's
``data`` axis and carry an explicit per-row weight vector (1 for real rows,
0 for padding) through every reduction. Algorithm cores in
:mod:`dask_ml_tpu.models` are written to be weight-aware, which also gives us
``sample_weight`` support mostly for free.

Under the default ``pad_policy`` config knob the sample axis additionally
pads up to a SHAPE BUCKET (:mod:`dask_ml_tpu.parallel.shapes`): nearby
sample counts stage to the same padded size, so every consumer of a staged
array — estimator fits, CV fold slices, batched candidate groups — shares
one compiled program per bucket instead of one per distinct ``n``. The
bucket is always a multiple of the mesh's data-axis size, and the extra
rows are ordinary weight-0 padding, so nothing downstream changes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from dask_ml_tpu.parallel import mesh as mesh_lib
from dask_ml_tpu.utils._log import log_array

logger = logging.getLogger(__name__)

ArrayLike = Union[np.ndarray, jax.Array]


class StagingMemo:
    """Scoped host→device staging cache.

    The reference's search graphs embed each data array under one
    content-addressed key, so every candidate fit shares a single placement
    of the training slice (reference: model_selection/utils.py:53-68
    ``to_keys``). Our jax-native estimators stage their own inputs inside
    ``fit``, which — uncached — re-uploads the same CV slice once per
    candidate×split cell. Inside a ``with staging_memo():`` scope,
    :func:`shard_rows` / :func:`prepare_data` memoize on the *identity* of
    the source arrays (+ mesh + dtypes), so a grid search pays one transfer
    per distinct (slice, role) no matter how many candidates share it.

    Identity keying is safe only because the scope holds strong references
    to every source object (no id reuse) and search CV slices are immutable
    by convention; that is why the cache is scoped, not global.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict = {}
        self._trusted: dict = {}  # id -> strong ref: arrays validated once
        self.hits = 0

    def trust(self, arr):
        """Mark a device array as already validated (NaN/inf-scanned, or
        derived from validated input): ``check_array`` skips re-scanning it
        within this scope. Strong refs make id-keying safe, as for staging."""
        with self._lock:
            self._trusted[id(arr)] = arr
        return arr

    def is_trusted(self, arr) -> bool:
        with self._lock:
            return id(arr) in self._trusted

    def get_or_stage(self, key, refs, compute):
        with self._lock:
            if key in self._entries:
                self.hits += 1
                return self._entries[key][1]
        # staging itself runs outside the lock (device_put of a big array);
        # a racing duplicate upload is possible but benign — last write wins
        value = compute()
        with self._lock:
            self._entries.setdefault(key, (refs, value))
            return self._entries[key][1]

    @property
    def n_stagings(self) -> int:
        return len(self._entries)


_memo_stack: list = []
_memo_lock = threading.Lock()


@contextlib.contextmanager
def staging_memo():
    """Enable staging memoization for the dynamic scope (see StagingMemo)."""
    memo = StagingMemo()
    with _memo_lock:
        _memo_stack.append(memo)
    try:
        yield memo
    finally:
        with _memo_lock:
            _memo_stack.remove(memo)


def _current_memo() -> Optional[StagingMemo]:
    return _memo_stack[-1] if _memo_stack else None


def _content_key(a) -> Optional[str]:
    """Content-hash key part for SMALL per-row arrays (y, sample_weight).

    Estimator facades re-encode y on every fit (label remapping allocates a
    fresh array), so identity keying would defeat the staging memo for every
    supervised fit in a search. Hashing the bytes of a 1-D label/weight
    vector is cheap next to staging X; X itself stays identity-keyed."""
    if a is None:
        return None
    import hashlib

    arr = np.ascontiguousarray(np.asarray(a))
    if arr.dtype == object:  # unhashable content: fall back to identity
        return f"id:{id(a)}"
    h = hashlib.sha256(arr.tobytes())
    return f"{arr.shape}:{arr.dtype}:{h.hexdigest()[:24]}"


def pad_rows(n: int, n_shards: int) -> int:
    """Rows of padding needed to make ``n`` divisible by ``n_shards``."""
    return (-n) % n_shards


def _policy_sig():
    """Identity of the active pad policy for staging-memo keys: the same
    source array staged under different policies must not collide."""
    from dask_ml_tpu.parallel import shapes

    policy = shapes.active_policy()
    return None if policy is None else policy.signature()


def _padded_rows(n: int, mesh) -> int:
    """Padded sample count for ``n`` on ``mesh``: the active policy's shape
    bucket (a multiple of the data-axis size), or the exact mesh multiple
    when bucketing is disabled."""
    from dask_ml_tpu.parallel import shapes

    return shapes.bucket_rows(n, align=mesh_lib.n_data_shards(mesh))


def is_sparse_input(x) -> bool:
    """True for inputs that stage through the SPARSE tier: a scipy sparse
    matrix, or an already-encoded :class:`~dask_ml_tpu.ops.sparse.SparseRows`
    container (host or device)."""
    from dask_ml_tpu.ops.sparse import SparseRows

    if isinstance(x, SparseRows):
        return True
    try:
        import scipy.sparse

        return scipy.sparse.issparse(x)
    except ImportError:  # pragma: no cover - scipy is a hard dep in practice
        return False


def shard_rows(
    x: ArrayLike,
    mesh: Optional[Mesh] = None,
    dtype=None,
) -> tuple[jax.Array, int]:
    """Pad ``x`` along axis 0 to its shape bucket (always an even multiple
    of the data-axis size; the exact mesh multiple when the ``pad_policy``
    knob is off) and place it sharded ``P('data', None, ...)``. Returns
    ``(sharded, n_valid)``.

    Padding rows are zeros; callers must mask them via weights from
    :func:`row_weights` (or :func:`prepare_data`, which does both).

    Sparse inputs (scipy CSR, or a
    :class:`~dask_ml_tpu.ops.sparse.SparseRows` container) stage through
    :func:`shard_sparse_rows` — same row bucketing, same sharding spec on
    both container leaves, plus per-row nonzero-slot padding to a
    power-of-two bucket (``shapes.bucket_nnz``) so nearby nnz widths share
    compiled programs the way nearby sample counts do.
    """
    mesh = mesh or mesh_lib.default_mesh()
    if is_sparse_input(x):
        memo = _current_memo()
        if memo is not None:
            return memo.get_or_stage(
                ("sparse-rows", id(x), id(mesh), str(dtype), _policy_sig()),
                (x, mesh),
                lambda: shard_sparse_rows(x, mesh, dtype),
            )
        return shard_sparse_rows(x, mesh, dtype)
    memo = _current_memo()
    if memo is not None:
        return memo.get_or_stage(
            ("rows", id(x), id(mesh), str(dtype), _policy_sig()),
            (x, mesh),
            lambda: _shard_rows_impl(x, mesh, dtype),
        )
    return _shard_rows_impl(x, mesh, dtype)


def shard_sparse_rows(x, mesh=None, dtype=None):
    """Stage a sparse row matrix onto the mesh as a sharded blocked-ELL
    :class:`~dask_ml_tpu.ops.sparse.SparseRows`. Returns
    ``(container, n_valid)``.

    The sample axis pads to the SAME shape bucket dense staging uses
    (weight-0 rows downstream); the per-row nonzero axis pads to a
    power-of-two slot bucket (:func:`~dask_ml_tpu.parallel.shapes.bucket_nnz`
    — padded slots are value-0 and inert with no mask). Both leaves place
    ``P('data', None)``, so the container shards exactly like a dense row
    matrix and every consumer of the sharded layout takes it unchanged.
    ``dtype`` casts the VALUES only (the wire dtype under a bf16 policy);
    column indices stay int32 exact.
    """
    import scipy.sparse

    from dask_ml_tpu.ops.sparse import SparseRows, ell_from_csr
    from dask_ml_tpu.parallel import shapes

    mesh = mesh or mesh_lib.default_mesh()
    if scipy.sparse.issparse(x):
        x = ell_from_csr(x, dtype=dtype)
    elif not isinstance(x, SparseRows):
        raise TypeError(
            f"shard_sparse_rows expects a scipy sparse matrix or a "
            f"SparseRows container, got {type(x).__name__}")
    n = int(x.values.shape[0])
    k = int(x.values.shape[1])
    k_pad = shapes.bucket_nnz(k) - k
    pad = _padded_rows(n, mesh) - n
    vals, cols = x.values, x.cols
    on_host = isinstance(vals, np.ndarray)
    xp = np if on_host else jnp
    if dtype is not None and vals.dtype != jnp.dtype(dtype):
        vals = vals.astype(dtype)
    if k_pad or pad:
        vals = xp.pad(vals, [(0, pad), (0, k_pad)])
        cols = xp.pad(cols, [(0, pad), (0, k_pad)])
    sharding = mesh_lib.data_sharding(mesh, ndim=2)
    staged = SparseRows(jax.device_put(vals, sharding),
                        jax.device_put(cols, sharding), x.d)
    return staged, n


def _shard_rows_impl(x, mesh, dtype):
    if not isinstance(x, jax.Array):
        # HOST input: cast + zero-pad in numpy, then ONE sharded
        # device_put. The former jnp route staged an unsharded copy first
        # and compiled a tiny pad program per distinct n — fixed overhead
        # a serving/predict path pays per request (docs/serving.md); this
        # path compiles NOTHING. Values are bit-identical (same cast, same
        # zero fill).
        x = np.asarray(x)
        if dtype is not None and x.dtype != np.dtype(dtype):
            x = x.astype(dtype)
        n = int(x.shape[0])
        pad = _padded_rows(n, mesh) - n
        if pad:
            padded = np.zeros((n + pad,) + x.shape[1:], x.dtype)
            padded[:n] = x
            x = padded
        sharding = mesh_lib.data_sharding(mesh, ndim=x.ndim)
        return jax.device_put(x, sharding), n
    x = jnp.asarray(x, dtype=dtype)
    n = int(x.shape[0])
    pad = _padded_rows(n, mesh) - n
    if pad:
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, widths)
    sharding = mesh_lib.data_sharding(mesh, ndim=x.ndim)
    return jax.device_put(x, sharding), n


def row_weights(
    n_padded: int,
    n_valid: int,
    mesh: Optional[Mesh] = None,
    sample_weight: Optional[ArrayLike] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Sharded per-row weights: user ``sample_weight`` (default 1) on real
    rows, 0 on padding rows."""
    mesh = mesh or mesh_lib.default_mesh()
    if sample_weight is None:
        w = np.ones(n_valid, dtype=np.float32)
    else:
        w = np.asarray(sample_weight, dtype=np.float32)
        if w.shape != (n_valid,):
            raise ValueError(
                f"sample_weight shape {w.shape} != ({n_valid},)"
            )
    if n_padded > n_valid:
        w = np.concatenate([w, np.zeros(n_padded - n_valid, dtype=np.float32)])
    return jax.device_put(
        jnp.asarray(w, dtype=dtype), mesh_lib.data_sharding(mesh, ndim=1)
    )


def shard_2d(
    x: ArrayLike,
    mesh: Optional[Mesh] = None,
    dtype=None,
) -> tuple[jax.Array, int, int]:
    """Pad BOTH axes of an (n, d) array and place it ``P('data', 'model')``
    on a 2-D mesh — sample shards over ``data``, features over ``model``
    (SURVEY §2.9 1-D tensor parallelism; the reference forbids feature
    chunking, utils.py:120-125). Returns ``(sharded, n_valid, d_valid)``.

    Padding columns are zeros: zero features contribute nothing to linear
    predictors, gradients, or Gram matrices, so weight-aware algorithm
    cores need no extra masking for them (their coefficients stay 0 under
    any ridge/prox that fixes 0 at 0; callers slice results back to
    ``d_valid``).

    Host inputs stage through the same no-compile path as
    :func:`shard_rows`: cast + zero-pad in numpy, one sharded device_put.
    The padded width routes through ``shapes.bucket_cols`` (a plain
    model-multiple round-up; recorded into ``compile_stats()['col_buckets']``
    so width-padding decisions are observable next to the row buckets).
    """
    from dask_ml_tpu.parallel import shapes

    mesh = mesh or mesh_lib.default_mesh()
    on_host = not isinstance(x, jax.Array)
    if on_host:
        x = np.asarray(x)
        if dtype is not None and x.dtype != np.dtype(dtype):
            x = x.astype(dtype)
    else:
        x = jnp.asarray(x, dtype=dtype)
    n, d = int(x.shape[0]), int(x.shape[1])
    # sample axis takes the shape bucket (same rule as shard_rows: weight-0
    # rows are inert); the feature axis keeps exact model-multiple padding —
    # fitted-state shapes follow the padded width, and only cores written
    # for padded features enable this path at all (see prepare_data)
    pad_n = _padded_rows(n, mesh) - n
    pad_d = shapes.bucket_cols(d, mesh_lib.n_model_shards(mesh)) - d
    if pad_n or pad_d:
        if on_host:
            padded = np.zeros((n + pad_n, d + pad_d), x.dtype)
            padded[:n, :d] = x
            x = padded
        else:
            x = jnp.pad(x, [(0, pad_n), (0, pad_d)])
    return jax.device_put(x, mesh_lib.feature_sharding(mesh)), n, d


def unpad_rows(x: ArrayLike, n_valid: int) -> jax.Array:
    """Drop padding rows from a padded per-row result (labels, transforms).
    Dispatches on sparse containers (row-slices both leaves)."""
    from dask_ml_tpu.ops.sparse import SparseRows

    if isinstance(x, SparseRows):
        return SparseRows(x.values[:n_valid], x.cols[:n_valid], x.d)
    return jnp.asarray(x)[:n_valid]


def replicate(x: ArrayLike, mesh: Optional[Mesh] = None, dtype=None) -> jax.Array:
    """Place small state (centers, coefs) fully replicated on the mesh."""
    mesh = mesh or mesh_lib.default_mesh()
    return jax.device_put(
        jnp.asarray(x, dtype=dtype), mesh_lib.replicated_sharding(mesh)
    )


@dataclasses.dataclass
class DeviceData:
    """A dataset staged onto the mesh: padded, sharded, weight-masked.

    The moral equivalent of the reference's "checked dask array"
    (reference: utils.py:95-143 ``check_array``): by the time an algorithm core
    sees a ``DeviceData`` the layout and dtype invariants hold.
    """

    X: jax.Array  # (n_padded, d_padded), sharded P('data', None) or
    #               P('data', 'model') when feature-sharded
    weights: jax.Array  # (n_padded,), sharded P('data'); 0 on padding
    n: int  # true number of rows
    y: Optional[jax.Array] = None  # (n_padded, ...), sharded, 0-padded
    mesh: Optional[Mesh] = None
    d: Optional[int] = None  # true feature count when columns are padded

    @property
    def n_padded(self) -> int:
        return int(self.X.shape[0])

    @property
    def n_features(self) -> int:
        """TRUE feature count (excludes feature-axis padding columns)."""
        return int(self.X.shape[1]) if self.d is None else self.d


def prepare_data(
    X: ArrayLike,
    y: Optional[ArrayLike] = None,
    sample_weight: Optional[ArrayLike] = None,
    mesh: Optional[Mesh] = None,
    dtype=None,
    y_dtype=None,
    shard_features: Optional[bool] = None,
    append_ones: bool = False,
) -> DeviceData:
    """Stage ``(X, y, sample_weight)`` onto the mesh as a :class:`DeviceData`.

    ``shard_features=True`` on a mesh with a ``model`` axis additionally
    shards the feature axis (``P('data', 'model')`` via :func:`shard_2d`);
    on a data-only mesh it is a no-op, so callers can pass it
    unconditionally. ``append_ones=True`` appends an intercept column as a
    TRUE column before any feature padding — done HERE (not by the caller)
    so the staging memo still keys on the identity of the caller's original
    array and search cells sharing a CV slice share one staged copy.

    ``dtype`` left unset falls back to the global/scoped config
    (:mod:`dask_ml_tpu.config`): ``config_context(dtype=bfloat16)`` runs
    every staged fit in bf16 without touching estimator code.
    ``shard_features`` is deliberately NOT config-driven — feature padding
    changes the shape of fitted state, so only cores written for it may
    enable it. Current callers and their padding-safety arguments: the
    GLMs (slice coefficients back to the true width) and PCA (passes it
    only when d divides the model axis, so no padding columns enter its
    n_features-dependent variance formulas). A new caller must satisfy one
    of those two disciplines.

    Inside a :func:`staging_memo` scope, repeated calls on the same source
    objects return the already-staged ``DeviceData`` (one transfer per
    distinct slice, however many search candidates share it)."""
    from dask_ml_tpu import config as config_lib
    from dask_ml_tpu.parallel import precision as precision_lib

    if dtype is None:
        dtype = config_lib.get_config()["dtype"]
    if dtype is None:
        # the mixed-precision policy's storage dtype (docs/precision.md):
        # "auto" is bf16 on TPU / keep-input elsewhere, so every estimator
        # fit stages bf16 wire+HBM bytes without touching estimator code.
        # Resolved HERE (facade level) so the staged dtype — part of every
        # jit signature downstream — is the only channel the policy takes
        # into traced code, and the memo key below sees the resolved value.
        dtype = precision_lib.resolve().storage_dtype()
    mesh = mesh or mesh_lib.default_mesh()
    # EFFECTIVE flag: on a data-only mesh feature sharding is a no-op, so
    # the memo key must not distinguish callers that pass it unconditionally
    # from callers that don't — they produce identical staged data
    shard_features = bool(shard_features) and mesh_lib.n_model_shards(mesh) > 1
    memo = _current_memo()
    if memo is not None:
        return memo.get_or_stage(
            ("data", id(X), _content_key(y), _content_key(sample_weight),
             id(mesh), str(dtype), str(y_dtype), shard_features,
             bool(append_ones), _policy_sig()),
            (X, y, sample_weight, mesh),
            lambda: _prepare_data_impl(X, y, sample_weight, mesh, dtype,
                                       y_dtype, shard_features, append_ones),
        )
    return _prepare_data_impl(X, y, sample_weight, mesh, dtype, y_dtype,
                              shard_features, append_ones)


def _prepare_data_impl(X, y, sample_weight, mesh, dtype, y_dtype,
                       shard_features=False, append_ones=False):
    sparse_in = is_sparse_input(X)
    if append_ones and not sparse_in:
        Xa = np.asarray(X)
        X = np.concatenate(
            [Xa, np.ones((Xa.shape[0], 1), Xa.dtype)], axis=1)
    d = None
    if sparse_in:
        # sparse staging: feature sharding is declined (the sparse tier is
        # sample-parallel; the facade forces the data-parallel path), and
        # the intercept — when requested — joins as one extra nonzero slot
        # per row, the sparse analogue of the true ones column
        Xs, n = shard_sparse_rows(X, mesh=mesh, dtype=dtype)
        if append_ones:
            from dask_ml_tpu.ops.sparse import add_intercept_ell

            Xs = add_intercept_ell(Xs)
    elif shard_features and mesh_lib.n_model_shards(mesh) > 1:
        Xs, n, d = shard_2d(X, mesh=mesh, dtype=dtype)
    else:
        Xs, n = shard_rows(X, mesh=mesh, dtype=dtype)
    ys = None
    if y is not None:
        # keep host y on host until the one sharded put (same no-compile
        # staging rule as X; device y — search CV slices — stays device)
        if isinstance(y, jax.Array):
            y_arr = jnp.asarray(y, dtype=y_dtype)
        else:
            y_arr = np.asarray(y, dtype=y_dtype)
            if y_dtype is None and y_arr.dtype.kind in "iuf" \
                    and y_arr.dtype.itemsize > 4:
                # match jnp.asarray's x32 canonicalization for untyped y
                y_arr = y_arr.astype(
                    np.int32 if y_arr.dtype.kind in "iu" else np.float32)
        if y_arr.shape[0] != n:
            raise ValueError(
                f"X has {n} rows but y has {y_arr.shape[0]}"
            )
        ys, _ = shard_rows(y_arr, mesh=mesh)
    w = row_weights(int(Xs.shape[0]), n, mesh=mesh, sample_weight=sample_weight)
    log_array(logger, "prepare_data: X", Xs)
    if ys is not None:
        log_array(logger, "prepare_data: y", ys, level=logging.DEBUG)
    return DeviceData(X=Xs, weights=w, n=n, y=ys, mesh=mesh, d=d)
