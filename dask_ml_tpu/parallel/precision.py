"""Mixed-precision execution policy: bf16 wire + compute, f32 accumulation.

PR 1 measured the host→device link as the real bottleneck of the >HBM
streamed tier, and every solver core in this package is matmul-shaped —
exactly the workloads where bf16 storage/compute with f32 accumulation
halves the bytes moved and lands on the MXU's native path (the
communication-minimizing framing of PAPERS.md's communication-avoiding
k-means kernels). Before this module the precision story was implicit:
everything-f32 unless the caller staged bf16 through the ``dtype`` config
knob, with each consumer improvising its own accumulation discipline. This
module makes it a policy surface — the same shape a training stack's AMP
layer takes:

- :class:`PrecisionPolicy` names the three dtypes that matter (``storage``
  = what arrays weigh on the wire and in HBM, ``compute`` = what matmul
  operands feed the MXU, ``accum`` = what reductions/solver state
  accumulate in) plus per-op ``overrides`` (e.g. keep a specific
  contraction f32 while the rest of the fit runs bf16).
- The thread-local ``precision`` config knob
  (:mod:`dask_ml_tpu.config`) selects the active policy: ``"auto"``
  (default) resolves to :data:`BF16` on TPU and :data:`F32` everywhere
  else, ``None``/``"f32"`` forces full f32, ``"bf16"`` forces the
  bf16-wire policy, and an explicit :class:`PrecisionPolicy` customizes.
- :func:`pdot` / :func:`pmatmul` are the contraction helpers every
  precision-aware consumer routes through: operands cast to the COMPUTE
  dtype, ``preferred_element_type`` forced to the accumulation dtype
  (float32), so a bf16 matmul never accumulates in bf16.
- :func:`neumaier_add` / :func:`neumaier_sum` provide compensated
  (Neumaier-variant Kahan) summation for long accumulation chains over
  low-precision inputs — the streamed moment accumulators
  (:mod:`dask_ml_tpu.decomposition.streaming`) carry compensation terms so
  a 40-block Gram/mean pass over bf16 blocks does not drift.

**Where the policy acts — and the compile-cache rule.** The policy is
resolved at FACADE level (staging in ``prepare_data``, the wire cast in
:class:`~dask_ml_tpu.parallel.stream.HostBlockSource`, the PCA sketch
dtype), never inside a jitted trace. Jitted solvers key their compile
caches on input shapes+dtypes, so everything precision-dependent inside a
trace must be derivable from the operand dtypes alone: the compute dtype
follows the data array's dtype (bf16-staged X ⇒ bf16 matmuls), the state
dtype is :func:`state_dtype` (a pure function of the data dtype — at
least f32, fixing the silent bf16-optimizer-state case), and the
accumulation dtype is structurally f32. This is what keeps the PR-4
compile-once invariant intact: switching the policy mid-process changes
the STAGED dtype, which is part of the jit signature, so a K-fold search
under a new policy recompiles each group program exactly once — not per
fold, and never a stale-cache wrong answer
(``tests/test_precision.py::test_compile_gate_with_precision_policy``).

Accuracy is gated, not hoped for: every solver family pins a tolerance
against its f32 baseline (``tests/test_precision.py``; the tolerances are
tabulated in ``docs/precision.md``) and ``bench.py --precision`` runs the
f32-vs-bf16 grid — wire bytes, effective GB/s, end-to-end fit time,
accuracy deltas — committed as ``PRECISION_r01.json``, exiting nonzero if
any gate fails.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "PrecisionPolicy",
    "F32",
    "BF16",
    "resolve",
    "state_dtype",
    "lloyd_bounds_dtype",
    "fast_transform_dtype",
    "pdot",
    "pmatmul",
    "neumaier_add",
    "neumaier_sum",
    "cast_wire",
    "staging_wire_dtype",
]

#: dtypes considered "low precision" for the state-dtype floor: optimizer
#: carries (step sizes, objective values, curvature history, consensus
#: state) can never live below f32 — 8 mantissa bits cannot represent
#: line-search/convergence arithmetic, and ops like linalg.solve promote
#: anyway (which would break while_loop carry typing mid-solve).
_LOW_PRECISION = (jnp.bfloat16, jnp.float16)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """The three dtypes of a mixed-precision execution, plus per-op
    overrides.

    - ``storage`` — the dtype big arrays are staged/streamed in (the WIRE
      dtype: host→device transfers and HBM residency). ``None`` keeps the
      input dtype (the f32 status quo).
    - ``compute`` — the dtype matmul operands are cast to before hitting
      the MXU. ``None`` follows the data array's dtype (so bf16 storage
      implies bf16 compute with no further casts).
    - ``accum`` — the dtype contractions accumulate in and solver state
      lives in; floored at float32 (see :func:`state_dtype`).
    - ``overrides`` — ``{op_name: dtype}`` consulted by
      :meth:`compute_for`: e.g. ``{"sketch": jnp.bfloat16}`` runs only the
      PCA range-finder sketch in bf16, or ``{"sketch": jnp.float32}``
      keeps the sketch f32 under an otherwise-bf16 policy.

    Frozen + hashable so a policy can key jit static arguments and
    staging-memo entries.
    """

    storage: Any = None
    compute: Any = None
    accum: Any = jnp.float32
    overrides: Any = None

    def __post_init__(self):
        ov = self.overrides
        if isinstance(ov, dict):
            object.__setattr__(self, "overrides",
                               tuple(sorted(ov.items(), key=lambda kv: kv[0])))
        elif ov is not None:
            object.__setattr__(self, "overrides", tuple(ov))

    # -- dtype resolution --------------------------------------------------

    def storage_dtype(self, default=None):
        """The staging/wire dtype, or ``default`` when the policy keeps
        input dtypes."""
        return self.storage if self.storage is not None else default

    def compute_for(self, op: Optional[str] = None):
        """Compute dtype for ``op`` (overrides first, then the policy-wide
        ``compute``); ``None`` means "follow the data array's dtype"."""
        if op is not None and self.overrides:
            for name, dt in self.overrides:
                if name == op:
                    return dt
        return self.compute

    def state_dtype(self, data_dtype):
        """Solver-state dtype for data of ``data_dtype`` under this
        policy: the accumulation dtype, floored at f32 (never a
        low-precision carry)."""
        return state_dtype(data_dtype, accum=self.accum)

    def signature(self) -> tuple:
        """Hashable identity for cache/memo keys."""
        return ("PrecisionPolicy", str(self.storage), str(self.compute),
                str(self.accum), self.overrides)


#: the f32 status quo: input dtypes kept, f32 accumulation. The null policy
#: every pre-precision behavior reduces to.
F32 = PrecisionPolicy()

#: bf16 wire + compute with f32 accumulation — the MXU-native policy
#: ``"auto"`` resolves to on TPU: staged arrays and streamed blocks move
#: as bf16 (half the host→device bytes), matmul operands feed the MXU as
#: bf16, every contraction and all solver state stays f32.
BF16 = PrecisionPolicy(storage=jnp.bfloat16, compute=jnp.bfloat16)


def resolve(knob: Any = "__config__") -> PrecisionPolicy:
    """The active :class:`PrecisionPolicy`, resolved from the thread-local
    ``precision`` config knob (:mod:`dask_ml_tpu.config`):

    - ``"auto"`` (the default) → :data:`BF16` on a TPU backend, :data:`F32`
      everywhere else — low precision only where the MXU makes it native;
    - ``None`` / ``"f32"`` / ``"float32"`` → :data:`F32`;
    - ``"bf16"`` / ``"bfloat16"`` → :data:`BF16` on any backend;
    - a :class:`PrecisionPolicy` → itself.

    Resolution happens at facade level (staging, stream construction,
    sketch-dtype selection) — never inside a jitted trace; see the module
    docstring for the compile-cache rule that forces this.
    """
    if knob == "__config__":
        from dask_ml_tpu.config import get_config

        knob = get_config()["precision"]
    if knob is None:
        return F32
    if isinstance(knob, PrecisionPolicy):
        return knob
    if knob == "auto":
        return BF16 if jax.default_backend() == "tpu" else F32
    if knob in ("bf16", "bfloat16"):
        return BF16
    if knob in ("f32", "float32"):
        return F32
    raise ValueError(
        "precision must be 'auto', None, 'f32'/'float32', "
        f"'bf16'/'bfloat16', or a PrecisionPolicy; got {knob!r}")


def state_dtype(data_dtype, accum=jnp.float32):
    """Optimizer/solver-state dtype for data of ``data_dtype``: at least
    float32, regardless of how low the data's storage dtype goes.

    This is the ONE definition of the mixed-precision state rule (the GLM
    solvers' ``_state_dtype`` and the streamed tier's state initialization
    both route through it): X may be staged bf16 — the matmuls read it on
    the MXU and accumulate f32 — but the carries (beta, objective values,
    step sizes, curvature history, ADMM consensus state) stay ≥ f32.
    Deliberately a pure function of the data dtype, not of the thread-local
    policy: jitted solvers key their compile caches on input dtypes, so an
    in-trace thread-local read would go stale when the policy changes
    without the signature changing (the policy reaches the solvers by
    choosing the storage dtype the data ARRIVES in). ``accum`` raises the
    floor (e.g. f64 accumulation for a custom policy) but can never lower
    it below f32 — passing ``accum=bf16`` still yields an f32 state, which
    is exactly the silent-bf16-state case this function exists to close.
    """
    dt = jnp.dtype(data_dtype)
    if dt in (jnp.dtype(t) for t in _LOW_PRECISION):
        dt = jnp.dtype(jnp.float32)
    floor = jnp.promote_types(dt, jnp.float32)
    acc = jnp.dtype(accum)
    if acc in (jnp.dtype(t) for t in _LOW_PRECISION):
        acc = jnp.dtype(jnp.float32)
    return jnp.promote_types(floor, acc)


def lloyd_bounds_dtype(data_dtype, policy=None):
    """Dtype of the bounded-Lloyd bound state (the ``ub``/``lb`` carries of
    :func:`dask_ml_tpu.models.kmeans.lloyd_loop_bounded`) under the active
    policy: the ``"lloyd_bounds"`` op override when the policy sets one,
    else :func:`state_dtype` of the data dtype — and in EITHER case never
    below f32. The override can only *raise* the floor (e.g. f64 bounds
    for a paranoid audit policy): bounds are solver state whose entire job
    is out-resolving FP noise on distances, so the bf16 wire policy must
    not narrow them (``lloyd_bounds: bf16`` still yields f32 — the same
    silent-low-precision-state case :func:`state_dtype` closes).

    Resolved at FACADE level like every policy read (the bound dtype
    enters the jitted loop as a static argument, so the compile-once rule
    holds: flipping the policy changes the signature and recompiles the
    loop exactly once, never a stale-cache wrong answer).
    """
    p = resolve() if policy is None else policy
    base = state_dtype(data_dtype, accum=p.accum)
    override = p.compute_for("lloyd_bounds")
    if override is None:
        return base
    return jnp.promote_types(state_dtype(override), base)


def fast_transform_dtype(data_dtype, policy=None):
    """Compute dtype of the fast-transform factor fits and applications
    (:mod:`dask_ml_tpu.ops.fast_transform`) under the active policy: the
    ``"fast_transform"`` op override when the policy sets one, else
    :func:`state_dtype` of the data dtype — and in EITHER case never
    below f32, exactly the :func:`lloyd_bounds_dtype` contract. Rotation
    angles and the palm4MSA loss ladder are SOLVER STATE: the sketched
    quality gates (inertia-ratio, ARI — bench.py ``--sketch``) budget for
    the approximation error of the p-column sketch, not for bf16 drift in
    the factors themselves, so the bf16 wire policy must not narrow the
    fit (``fast_transform: bf16`` still yields f32). The override can
    only *raise* the floor (f64 for an audit fit). Resolved at FACADE
    level; :func:`~dask_ml_tpu.ops.fast_transform.ft_apply` casts back to
    the data dtype on exit so the staging wire is unchanged."""
    p = resolve() if policy is None else policy
    base = state_dtype(data_dtype, accum=p.accum)
    override = p.compute_for("fast_transform")
    if override is None:
        return base
    return jnp.promote_types(state_dtype(override), base)


def staging_wire_dtype():
    """The dtype inference facades stage X in: the explicit ``dtype``
    config knob when set (it outranks the policy, same precedence as
    ``prepare_data``), else the active policy's storage dtype, else
    ``None`` (keep the input dtype). This is the ONE rule that keeps every
    predict/transform path — direct calls and the serving loop's batch
    staging (:mod:`dask_ml_tpu.parallel.serving`) — on the same wire, so
    serving results can be bit-identical to direct calls. Resolved at
    facade level, never inside a trace (module docstring)."""
    from dask_ml_tpu.config import get_config

    dtype = get_config()["dtype"]
    if dtype is not None:
        return dtype
    return resolve().storage_dtype()


# ---------------------------------------------------------------------------
# precision-aware contractions
# ---------------------------------------------------------------------------


def pdot(a, b, dimension_numbers, *, compute=None, accum=jnp.float32):
    """``lax.dot_general`` with both operands cast to the COMPUTE dtype and
    accumulation forced to ``accum`` (f32) via ``preferred_element_type``.

    ``compute=None`` follows the FIRST operand's dtype — by convention the
    data array (X / a streamed block), whose staged dtype carries the
    active policy into the trace. A bf16-staged X therefore pulls the
    second operand (coefficients, test matrices) down to bf16 so the
    matmul runs on the MXU's native path, while the f32 output keeps
    gradients/objectives/epilogues in full precision. For f32 data this is
    bit-identical to the plain ``@`` it replaces (same contraction, same
    f32 accumulation), so enabling the policy is a no-op until low-
    precision data actually arrives.
    """
    cd = compute if compute is not None else a.dtype
    return lax.dot_general(a.astype(cd), b.astype(cd), dimension_numbers,
                           preferred_element_type=accum)


def pmatmul(a, b, **kwargs):
    """``a @ b`` through :func:`pdot`: contract ``a``'s last axis with
    ``b``'s first (the matmul/matvec shapes the solvers use)."""
    dn = (((a.ndim - 1,), (0,)), ((), ()))
    return pdot(a, b, dn, **kwargs)


# ---------------------------------------------------------------------------
# compensated summation (Neumaier's improved Kahan)
# ---------------------------------------------------------------------------


def neumaier_add(total, comp, x):
    """One compensated-summation step: ``(total, comp) += x`` with the
    rounding error captured in ``comp`` (Neumaier's variant, which unlike
    plain Kahan stays correct when ``|x| > |total|``). The true running sum
    is ``total + comp``; add them once at the END of the accumulation
    chain. Shapes broadcast elementwise, so the same step serves scalars
    (Σw), vectors (column sums), and matrices (the streamed Gram)."""
    t = total + x
    comp = comp + jnp.where(jnp.abs(total) >= jnp.abs(x),
                            (total - t) + x, (x - t) + total)
    return t, comp


def neumaier_sum(x, axis: int = 0, dtype=jnp.float32):
    """Compensated sum of ``x`` along ``axis``, accumulated in ``dtype``.

    The utility for moment/inertia accumulation over low-precision inputs:
    a plain f32 ``sum`` over n terms drifts like O(n·eps) in the worst
    case, while the compensated sum holds O(eps) — the difference shows up
    exactly where bf16 inputs meet long accumulation chains (many streamed
    blocks, large-n inertia totals). Implemented as a ``lax.fori_loop``
    over the reduced axis (vectorized over all others), so it works inside
    jitted programs.
    """
    x = jnp.moveaxis(jnp.asarray(x), axis, 0).astype(dtype)
    n = x.shape[0]
    zero = jnp.zeros(x.shape[1:], dtype)

    def body(i, carry):
        return neumaier_add(*carry, x[i])

    total, comp = lax.fori_loop(0, n, body, (zero, zero))
    return total + comp


# ---------------------------------------------------------------------------
# host-side wire casting (the streamed tier's storage cast)
# ---------------------------------------------------------------------------


def cast_wire(block: tuple, storage) -> tuple:
    """Cast a host block tuple to the wire/storage dtype.

    Only floating arrays with ``ndim >= 2`` (the data matrix) are cast —
    1-D per-row vectors (labels, sample weights) stay exact: they are a
    vanishing fraction of the wire bytes, weight exactness is what makes
    padding rows inert, and {0, 1} labels gain nothing from narrowing.
    Never upcasts (an f16 input is not widened to bf16's byte width), so
    ``storage=None`` or an already-narrow block is a no-op returning the
    same tuple.

    A sparse block element (:class:`~dask_ml_tpu.ops.sparse.SparseRows`,
    docs/sparse.md) is a registered pytree whose leaves follow the SAME
    per-leaf rule: the float (n, k) values narrow, the int32 column
    indices are exact coordinates and never do — the sparse wire is
    values-at-storage-dtype + exact indices.
    """
    if storage is None:
        return tuple(block)
    import jax
    import numpy as np

    st = jnp.dtype(storage)

    def cast_leaf(leaf):
        leaf = np.asarray(leaf)
        if (leaf.ndim >= 2 and np.issubdtype(leaf.dtype, np.floating)
                and leaf.dtype.itemsize > st.itemsize):
            leaf = leaf.astype(st)
        return leaf

    return tuple(jax.tree_util.tree_map(cast_leaf, a) for a in block)
