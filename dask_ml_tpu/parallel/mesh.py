"""Device-mesh management.

The reference delegates placement to dask's pluggable schedulers
(reference: model_selection/_search.py:841-852, tests/conftest.py:131-141).
The TPU-native equivalent is a :class:`jax.sharding.Mesh`: datasets are sharded
along the ``"data"`` mesh axis, model state is replicated (the reference also
replicates model state — centers/coefs are broadcast into every task,
e.g. metrics/pairwise.py:38-40), and a second ``"model"`` axis is available for
feature-axis tensor parallelism of Gram/QR work, which the reference forbids
outright (reference: utils.py:120-125 "feature axis must be one chunk").

A process-wide default mesh is created lazily over all visible devices; tests
and multi-host runs override it with :func:`use_mesh`.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` directly (with the vma checker
    controlled by ``check_vma``); on older releases the same transform
    lives at ``jax.experimental.shard_map.shard_map``, whose ``check_rep``
    replication checker predates the vma machinery and rejects collectives
    inside ``lax.while_loop`` bodies — every solver here keeps its whole
    optimization loop on device, so the checker is disabled on that path
    (the new-API path keeps its own vma checks)."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)

_lock = threading.Lock()
_default_mesh: Optional[Mesh] = None
_mesh_stack: list[Mesh] = []


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
) -> Mesh:
    """Build a mesh over ``devices`` (default: all visible devices).

    With the default 1-D ``("data",)`` axis layout every device holds one
    sample-axis shard — the analogue of "one chunk per core"
    (reference: utils.py:204-214 check_chunks default).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_devices is not None:
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),) if len(axis_names) == 1 else None
    if shape is None:
        raise ValueError("shape is required for multi-axis meshes")
    arr = np.asarray(devices, dtype=object).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def default_mesh() -> Mesh:
    """The active mesh: innermost :func:`use_mesh` override, else the
    process-wide ``set_config(mesh=...)`` default, else a lazily created
    1-D mesh over every visible device."""
    if _mesh_stack:
        return _mesh_stack[-1]
    from dask_ml_tpu import config as config_lib

    configured = config_lib.get_config()["mesh"]
    if configured is not None:
        return configured
    global _default_mesh
    if _default_mesh is None:
        with _lock:
            if _default_mesh is None:
                _default_mesh = make_mesh()
    return _default_mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Scoped override of the default mesh (the analogue of dask's
    ``scheduler=`` kwarg / config scoping)."""
    _mesh_stack.append(mesh)
    try:
        yield mesh
    finally:
        _mesh_stack.pop()


def make_2d_mesh(
    n_data: int,
    n_model: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """An ``(n_data, n_model)`` mesh with axes ``('data', 'model')``.

    The TPU analogue of SURVEY §2.9's 1-D tensor parallelism: the sample
    axis shards over ``data`` as usual, and the FEATURE axis shards over
    ``model`` so the O(n·d²) Gram/Hessian work (and its (d, d) outputs)
    split across devices — parallelism the reference forbids outright
    (reference: utils.py:120-125 "feature axis must be one chunk"). Keep
    the model axis within a slice: its collectives (the d-axis psums of
    ``X.T @ …``) are chattier than the data axis's.
    """
    return make_mesh(devices=devices, shape=(n_data, n_model),
                     axis_names=(DATA_AXIS, MODEL_AXIS))


def n_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    return mesh.shape[DATA_AXIS]


def n_model_shards(mesh: Optional[Mesh] = None) -> int:
    """Size of the feature-parallel axis; 1 on a data-only mesh."""
    mesh = mesh or default_mesh()
    return mesh.shape.get(MODEL_AXIS, 1)


def data_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Axis-0 ("sample"-axis) sharding: ``P('data', None, ...)``."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS, *([None] * (ndim - 1))))


def feature_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Both-axes sharding for (n, d) data on a 2-D mesh:
    ``P('data', 'model')`` (or ``P('model')`` for per-feature vectors)."""
    mesh = mesh or default_mesh()
    if ndim == 1:
        return NamedSharding(mesh, PartitionSpec(MODEL_AXIS))
    return NamedSharding(
        mesh, PartitionSpec(DATA_AXIS, MODEL_AXIS, *([None] * (ndim - 2)))
    )


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully replicated placement (model state, small matrices)."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, PartitionSpec())
