"""Device-mesh management.

The reference delegates placement to dask's pluggable schedulers
(reference: model_selection/_search.py:841-852, tests/conftest.py:131-141).
The TPU-native equivalent is a :class:`jax.sharding.Mesh`: datasets are sharded
along the ``"data"`` mesh axis, model state is replicated (the reference also
replicates model state — centers/coefs are broadcast into every task,
e.g. metrics/pairwise.py:38-40), and a second ``"model"`` axis is available for
feature-axis tensor parallelism of Gram/QR work, which the reference forbids
outright (reference: utils.py:120-125 "feature axis must be one chunk").

A process-wide default mesh is created lazily over all visible devices; tests
and multi-host runs override it with :func:`use_mesh`.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"

_lock = threading.Lock()
_default_mesh: Optional[Mesh] = None
_mesh_stack: list[Mesh] = []


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
) -> Mesh:
    """Build a mesh over ``devices`` (default: all visible devices).

    With the default 1-D ``("data",)`` axis layout every device holds one
    sample-axis shard — the analogue of "one chunk per core"
    (reference: utils.py:204-214 check_chunks default).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_devices is not None:
        devices = devices[:n_devices]
    if shape is None:
        shape = (len(devices),) if len(axis_names) == 1 else None
    if shape is None:
        raise ValueError("shape is required for multi-axis meshes")
    arr = np.asarray(devices, dtype=object).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def default_mesh() -> Mesh:
    """The active mesh: innermost :func:`use_mesh` override, else a lazily
    created 1-D mesh over every visible device."""
    if _mesh_stack:
        return _mesh_stack[-1]
    global _default_mesh
    if _default_mesh is None:
        with _lock:
            if _default_mesh is None:
                _default_mesh = make_mesh()
    return _default_mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Scoped override of the default mesh (the analogue of dask's
    ``scheduler=`` kwarg / config scoping)."""
    _mesh_stack.append(mesh)
    try:
        yield mesh
    finally:
        _mesh_stack.pop()


def n_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    return mesh.shape[DATA_AXIS]


def data_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Axis-0 ("sample"-axis) sharding: ``P('data', None, ...)``."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully replicated placement (model state, small matrices)."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, PartitionSpec())
