"""Device-mesh management.

The reference delegates placement to dask's pluggable schedulers
(reference: model_selection/_search.py:841-852, tests/conftest.py:131-141).
The TPU-native equivalent is a :class:`jax.sharding.Mesh`: datasets are sharded
along the ``"data"`` mesh axis, model state is replicated (the reference also
replicates model state — centers/coefs are broadcast into every task,
e.g. metrics/pairwise.py:38-40), and a second ``"model"`` axis is available for
feature-axis tensor parallelism of Gram/QR work, which the reference forbids
outright (reference: utils.py:120-125 "feature axis must be one chunk").

A process-wide default mesh is created lazily over all visible devices; tests
and multi-host runs override it with :func:`use_mesh`.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"

#: two-level scale-out axes (parallel/hierarchy.py): the sample axis shards
#: over BOTH — ``pod`` is the slow cross-pod (DCN) dimension, ``chip`` the
#: fast within-pod (ICI) dimension. Hot reductions lower chip-first so only
#: one already-reduced partial per pod crosses the DCN (docs/scale-out.md).
POD_AXIS = "pod"
CHIP_AXIS = "chip"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map`` directly (with the vma checker
    controlled by ``check_vma``); on older releases the same transform
    lives at ``jax.experimental.shard_map.shard_map``, whose ``check_rep``
    replication checker predates the vma machinery and rejects collectives
    inside ``lax.while_loop`` bodies — every solver here keeps its whole
    optimization loop on device, so the checker is disabled on that path
    (the new-API path keeps its own vma checks)."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)

_lock = threading.Lock()
_default_mesh: Optional[Mesh] = None
_mesh_stack: list[Mesh] = []


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
) -> Mesh:
    """Build a mesh over ``devices`` (default: all visible devices).

    With the default 1-D ``("data",)`` axis layout every device holds one
    sample-axis shard — the analogue of "one chunk per core"
    (reference: utils.py:204-214 check_chunks default).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_devices is not None:
        devices = devices[:n_devices]
    if shape is None:
        if len(axis_names) != 1:
            raise ValueError(
                f"make_mesh needs a shape for the {len(axis_names)}-axis "
                f"mesh {tuple(axis_names)} over {len(devices)} devices; "
                "pass shape=... (one entry may be None to auto-factor it "
                "from the device count)")
        shape = (len(devices),)
    shape = list(shape)
    if len(shape) != len(axis_names):
        raise ValueError(
            f"mesh shape {tuple(shape)} has {len(shape)} entries but "
            f"axis_names {tuple(axis_names)} has {len(axis_names)}")
    # auto-factor: exactly one unspecified axis size (None or -1) is solved
    # from the device count, so callers can say e.g. shape=(2, None) —
    # "2 pods over whatever devices exist"
    free = [i for i, s in enumerate(shape) if s is None or s == -1]
    if len(free) > 1:
        raise ValueError(
            f"mesh shape {tuple(shape)} leaves more than one axis of "
            f"{tuple(axis_names)} unspecified; at most one entry may be "
            "None/-1")
    if free:
        known = int(np.prod([int(s) for i, s in enumerate(shape)
                             if i != free[0]])) if len(shape) > 1 else 1
        if known <= 0 or len(devices) % known:
            raise ValueError(
                f"cannot auto-factor axis {axis_names[free[0]]!r}: "
                f"{len(devices)} devices do not divide by the specified "
                f"sizes {tuple(shape)} of axes {tuple(axis_names)}")
        shape[free[0]] = len(devices) // known
    shape = tuple(int(s) for s in shape)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh shape {shape} for axes {tuple(axis_names)} needs "
            f"{int(np.prod(shape))} devices but {len(devices)} are "
            "available; pass devices=/n_devices= or adjust the shape "
            "(one entry may be None to auto-factor)")
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def default_mesh() -> Mesh:
    """The active mesh: innermost :func:`use_mesh` override, else the
    process-wide ``set_config(mesh=...)`` default, else a lazily created
    1-D mesh over every visible device."""
    if _mesh_stack:
        return _mesh_stack[-1]
    from dask_ml_tpu import config as config_lib

    configured = config_lib.get_config()["mesh"]
    if configured is not None:
        return configured
    global _default_mesh
    if _default_mesh is None:
        with _lock:
            if _default_mesh is None:
                _default_mesh = make_mesh()
    return _default_mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Scoped override of the default mesh (the analogue of dask's
    ``scheduler=`` kwarg / config scoping)."""
    _mesh_stack.append(mesh)
    try:
        yield mesh
    finally:
        _mesh_stack.pop()


def make_2d_mesh(
    n_data: int,
    n_model: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """An ``(n_data, n_model)`` mesh with axes ``('data', 'model')``.

    The TPU analogue of SURVEY §2.9's 1-D tensor parallelism: the sample
    axis shards over ``data`` as usual, and the FEATURE axis shards over
    ``model`` so the O(n·d²) Gram/Hessian work (and its (d, d) outputs)
    split across devices — parallelism the reference forbids outright
    (reference: utils.py:120-125 "feature axis must be one chunk"). Keep
    the model axis within a slice: its collectives (the d-axis psums of
    ``X.T @ …``) are chattier than the data axis's.
    """
    return make_mesh(devices=devices, shape=(n_data, n_model),
                     axis_names=(DATA_AXIS, MODEL_AXIS))


def is_hierarchical(mesh: Optional[Mesh] = None) -> bool:
    """True for a mesh with the two-level ``('pod', 'chip')`` sample axes
    (:func:`dask_ml_tpu.parallel.hierarchy.make_hierarchical_mesh`) —
    including the 3-axis ``('pod', 'chip', 'model')`` feature-parallel
    variant, whose SAMPLE axis still shards over (pod, chip)."""
    mesh = mesh or default_mesh()
    return POD_AXIS in mesh.axis_names and CHIP_AXIS in mesh.axis_names


def data_axes(mesh: Optional[Mesh] = None) -> tuple:
    """The mesh axes the SAMPLE axis shards over: ``('pod', 'chip')`` on a
    hierarchical mesh, ``('data',)`` otherwise. Everything that builds
    in_specs/shardings for row-sharded arrays routes through this (and
    :func:`data_pspec`), so solvers are agnostic to the mesh's level count."""
    mesh = mesh or default_mesh()
    if is_hierarchical(mesh):
        return (POD_AXIS, CHIP_AXIS)
    return (DATA_AXIS,)


def data_pspec(mesh: Optional[Mesh] = None, ndim: int = 2) -> PartitionSpec:
    """The row-sharded PartitionSpec for ``mesh``: ``P('data', None, ...)``
    flat, ``P(('pod', 'chip'), None, ...)`` hierarchical (axis 0 split over
    both levels, pod-major — so device order matches the flat mesh built
    from the same device list, and e.g. ADMM's per-shard stacked state keeps
    its shard←row correspondence across the two layouts)."""
    mesh = mesh or default_mesh()
    axes = data_axes(mesh)
    first = axes[0] if len(axes) == 1 else axes
    return PartitionSpec(first, *([None] * (ndim - 1)))


def n_data_shards(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    return int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))


def n_model_shards(mesh: Optional[Mesh] = None) -> int:
    """Size of the feature-parallel axis; 1 on a data-only mesh."""
    mesh = mesh or default_mesh()
    return mesh.shape.get(MODEL_AXIS, 1)


def data_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Axis-0 ("sample"-axis) sharding: ``P('data', None, ...)``, or the
    two-level ``P(('pod', 'chip'), None, ...)`` on a hierarchical mesh."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, data_pspec(mesh, ndim=ndim))


def has_model_axis(mesh: Optional[Mesh] = None) -> bool:
    """True when ``mesh`` carries a feature-parallel ``model`` axis of size
    > 1 — a 2-D ``('data', 'model')`` mesh or the 3-axis
    ``('pod', 'chip', 'model')`` hierarchical mesh."""
    mesh = mesh or default_mesh()
    return n_model_shards(mesh) > 1


def feature_pspec(mesh: Optional[Mesh] = None, ndim: int = 2) -> PartitionSpec:
    """The feature-sharded PartitionSpec for ``mesh``: rows over the data
    axes (``'data'``, or ``('pod', 'chip')`` on a hierarchical mesh — same
    rule as :func:`data_pspec`), columns over ``'model'``. ``ndim=1`` is the
    per-feature-vector case (coef slices, per-column stats): ``P('model')``.
    """
    mesh = mesh or default_mesh()
    if ndim == 1:
        return PartitionSpec(MODEL_AXIS)
    axes = data_axes(mesh)
    first = axes[0] if len(axes) == 1 else axes
    return PartitionSpec(first, MODEL_AXIS, *([None] * (ndim - 2)))


def feature_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Both-axes sharding for (n, d) data on a mesh with a ``model`` axis:
    ``P('data', 'model')`` flat, ``P(('pod', 'chip'), 'model')`` on the
    3-axis hierarchical mesh (or ``P('model')`` for per-feature vectors)."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, feature_pspec(mesh, ndim=ndim))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully replicated placement (model state, small matrices)."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, PartitionSpec())
