"""Length-prefixed, checksummed frame codec shared by snapshots and wire,
plus the typed (pickle-free) wire payload the serving fleet speaks.

One framing discipline serves two very different transports:

- **Checkpoint snapshots** (``dask_ml_tpu.checkpoint``): ``save_pytree``
  frames its pickle payload so that atomic-rename durability becomes an
  END-TO-END guarantee — rename protects against a kill mid-save, the
  frame's length + sha256 protect against everything else (a torn copy, a
  truncated transfer off shared storage, silent media corruption). Any
  byte missing or flipped fails the digest and surfaces loudly instead of
  unpickling noise (swept at every byte offset in
  ``tests/test_checkpoint.py``).
- **The serving wire protocol** (``dask_ml_tpu.parallel.fleet``):
  out-of-process clients submit inference requests over a socket as
  frames of exactly this layout. A frame that fails validation fails THE
  CALLER — the connection's error response names the corrupt frame, and
  no partial request ever reaches a batch another client shares (the
  serving layer's validation-fails-the-caller-not-the-batch contract,
  docs/serving.md).

Frame layout (everything big-endian)::

    magic (caller-chosen, includes a version byte)
    8-byte unsigned payload length
    checksum(payload) — 32-byte sha256 or 4-byte crc32c (Castagnoli)
    payload

**Integrity tiers.** The two transports want different checksums:
snapshots/checkpoints are written once and read across process
lifetimes, where a 32-byte cryptographic digest is cheap insurance
against silent media corruption — they KEEP sha256 (the default, so the
on-disk layout is byte-identical to every frame ever written). Wire data
frames are hashed per request per hop, where sha256 was the measured
hot-path cost — they use crc32c (:data:`WIRE_CHECKSUM`), which detects
the same torn/flipped-byte failures ~20x cheaper (hardware-accelerated
via ``google-crc32c`` when available, pure-python table fallback
otherwise — same digest either way, swept by the fuzz suite under both
checksums). The checksum is a codec parameter, not a frame field: each
magic's owner fixes its tier, and a peer speaking the wrong tier fails
the version-byte magic check loudly.

The codec is transport-agnostic: :func:`encode_frame`/:func:`decode_frame`
work on whole byte strings (the snapshot path reads the file in one go),
:func:`read_frame`/:func:`write_frame` work on stream objects with
``recv``-style partial reads (the socket path). Errors are typed —
:class:`FrameTruncatedError` for missing bytes, :class:`FrameCorruptError`
for a failed digest or foreign magic — so callers can map them onto their
own error surface (``checkpoint.py`` wraps both in
``CheckpointCorruptError`` with its original messages, bit-identical
behavior to the pre-extraction code).

**The typed wire payload.** The serving wire's frame payloads are NOT
pickle: they are a self-describing, capped layout that deserializes no
objects anywhere, so ``FleetServer`` can face untrusted clients —
:func:`encode_payload`/:func:`decode_payload` (layout documented there).
A payload that fails its caps or structure raises the typed
:class:`PayloadError`, which the serving layer maps to a per-frame error
response (the frame boundary is intact, so the connection survives — only
a torn FRAME ends a stream). :func:`encode_payload_parts` +
:func:`write_frame_parts` are the zero-copy senders: the same bytes on
the wire, but the array buffers are hashed and written straight from the
caller's memory — no ``tobytes()`` copy, no payload concatenation, and
exactly ONE digest pass per frame (the server's response path retains
its result buffer and writes from it).
"""

from __future__ import annotations

import json
import struct
import hashlib
import time
from typing import Optional

import numpy as np

__all__ = [
    "FrameError",
    "FrameTruncatedError",
    "FrameCorruptError",
    "PayloadError",
    "encode_frame",
    "decode_frame",
    "read_frame",
    "write_frame",
    "write_frame_parts",
    "encode_payload",
    "encode_payload_parts",
    "decode_payload",
    "header_length",
    "digest_length",
    "crc32c",
    "crc32c_engine",
    "WIRE_MAGIC",
    "WIRE_CHECKSUM",
    "CHECKSUMS",
    "PAYLOAD_DTYPES",
]

#: serving wire-protocol magic (docs/serving.md, "The wire");
#: the checkpoint magic lives with its owner in ``dask_ml_tpu.checkpoint``.
#: The version byte is 3: version 1 framed pickle payloads, version 2
#: framed the typed payload under sha256, version 3 frames the same typed
#: payload under the crc32c integrity tier — a v2 peer fails the magic
#: check loudly instead of misparsing the 4-byte digest as payload.
WIRE_MAGIC = b"DMLTWIRE3\n"

#: the wire's integrity tier — crc32c for per-request data frames
#: (snapshots and checkpoints keep the sha256 default; see the module
#: docstring's integrity-tier rationale).
WIRE_CHECKSUM = "crc32c"

_LEN_BYTES = 8
_SHA256_BYTES = 32
_CRC32C_BYTES = 4

#: the two supported integrity tiers (the fuzz suites sweep both)
CHECKSUMS = ("sha256", "crc32c")


class FrameError(RuntimeError):
    """Base class for framing failures."""


class FrameTruncatedError(FrameError):
    """The buffer/stream ended before the frame did (torn write, cut
    connection): the header promised more bytes than arrived."""


class FrameCorruptError(FrameError):
    """The frame is structurally complete but wrong: foreign magic, or a
    payload whose checksum does not match the header's digest."""


class PayloadError(FrameError):
    """A typed wire payload failed decoding: malformed control envelope,
    a dtype outside the allowlist, a shape that disagrees with the buffer
    bytes, or a cap violation. The FRAME was intact (length + digest
    passed), so the error is attributable to one request and the
    connection keeps serving."""


# ---------------------------------------------------------------------------
# checksum engines
# ---------------------------------------------------------------------------

try:  # hardware/C-accelerated crc32c when the wheel is present
    import google_crc32c as _google_crc32c
except Exception:  # pragma: no cover - environment-dependent
    _google_crc32c = None

# CRC-32C (Castagnoli): reflected polynomial 0x82F63B78, init/xorout
# 0xFFFFFFFF — the iSCSI/ext4 variant google-crc32c implements, so the
# pure fallback and the C engine produce identical digests.
_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)
del _i, _c


class _PureCrc32c:
    """Streaming pure-python CRC-32C with the hashlib update/digest
    shape (correctness fallback; the C engine is the fast path)."""

    def __init__(self):
        self._crc = 0

    def update(self, data) -> None:
        table = _CRC32C_TABLE
        c = self._crc ^ 0xFFFFFFFF
        for b in bytes(data):
            c = table[(c ^ b) & 0xFF] ^ (c >> 8)
        self._crc = c ^ 0xFFFFFFFF

    def digest(self) -> bytes:
        return struct.pack(">I", self._crc)


class _CCrc32c:
    """The google-crc32c C engine behind the hashlib update/digest
    shape. The extension's argument parser rejects memoryview and
    bytearray objects (it wants a read-only bytes-like) but it DOES
    accept numpy arrays, so a ``np.frombuffer`` uint8 wrap feeds it any
    buffer without the flat ``tobytes()`` copy — the digest pass stays
    a single traversal of the payload."""

    __slots__ = ("_c",)

    def __init__(self):
        self._c = _google_crc32c.Checksum()

    def update(self, data) -> None:
        if isinstance(data, (memoryview, bytearray)):
            import numpy as _np

            data = _np.frombuffer(data, dtype=_np.uint8)
        self._c.update(data)

    def digest(self) -> bytes:
        return self._c.digest()


def crc32c_engine() -> str:
    """Which crc32c implementation is active: ``"google-crc32c"`` (C)
    or ``"pure-python"`` (table-driven fallback)."""
    return "google-crc32c" if _google_crc32c is not None else "pure-python"


def crc32c(data) -> int:
    """CRC-32C (Castagnoli) of ``data`` as an unsigned 32-bit int."""
    h = _new_hasher("crc32c")
    h.update(data)
    return struct.unpack(">I", h.digest())[0]


def _new_hasher(checksum: str):
    if checksum == "sha256":
        return hashlib.sha256()
    if checksum == "crc32c":
        if _google_crc32c is not None:
            return _CCrc32c()
        return _PureCrc32c()
    raise ValueError(
        f"unknown checksum {checksum!r} (supported: {CHECKSUMS})")


def digest_length(checksum: str) -> int:
    """Digest size in bytes for one of :data:`CHECKSUMS`."""
    if checksum == "sha256":
        return _SHA256_BYTES
    if checksum == "crc32c":
        return _CRC32C_BYTES
    raise ValueError(
        f"unknown checksum {checksum!r} (supported: {CHECKSUMS})")


def _digest(checksum: str, chunks) -> bytes:
    """One digest pass over ``chunks`` (bytes/memoryviews), with the
    ``wire.hash_seconds{algo=}`` telemetry mirror at this — the only —
    hash site (enabled-guarded: disabled telemetry costs one boolean)."""
    from dask_ml_tpu.parallel import telemetry

    h = _new_hasher(checksum)
    if not telemetry.enabled():
        for c in chunks:
            h.update(c)
        return h.digest()
    t0 = time.perf_counter()
    for c in chunks:
        h.update(c)
    d = h.digest()
    telemetry.metrics().histogram(
        "wire.hash_seconds", algo=checksum).observe(time.perf_counter() - t0)
    return d


def header_length(magic: bytes, checksum: str = "sha256") -> int:
    """Total header size for ``magic``: magic + length + digest."""
    return len(magic) + _LEN_BYTES + digest_length(checksum)


def encode_frame(payload: bytes, *, magic: bytes,
                 checksum: str = "sha256") -> bytes:
    """``magic + len(payload) (8B BE) + checksum(payload) + payload``."""
    return (magic + struct.pack(">Q", len(payload))
            + _digest(checksum, (payload,)) + payload)


def decode_frame(data: bytes, *, magic: bytes,
                 checksum: str = "sha256") -> bytes:
    """Decode one whole-buffer frame → payload, verifying magic, length,
    and digest. ``data`` must be exactly one frame (the snapshot file
    case); trailing bytes are corruption, not a second frame."""
    dlen = digest_length(checksum)
    if data[:len(magic)] != magic:
        raise FrameCorruptError(
            f"bad frame magic {data[:len(magic)]!r} (expected {magic!r})")
    rest = data[len(magic):]
    if len(rest) < _LEN_BYTES + dlen:
        raise FrameTruncatedError(
            f"truncated frame header ({len(data)} bytes)")
    (length,) = struct.unpack(">Q", rest[:_LEN_BYTES])
    digest = rest[_LEN_BYTES:_LEN_BYTES + dlen]
    payload = rest[_LEN_BYTES + dlen:]
    if len(payload) < length:
        raise FrameTruncatedError(
            f"frame payload is {len(payload)} bytes but the header "
            f"recorded {length}")
    if len(payload) > length:
        raise FrameCorruptError(
            f"frame carries {len(payload) - length} trailing bytes past "
            f"the recorded payload length {length}")
    if _digest(checksum, (payload,)) != digest:
        raise FrameCorruptError("frame payload checksum mismatch")
    return payload


def _read_exact(stream, n: int) -> bytes:
    """Read exactly ``n`` bytes from a stream exposing ``recv`` (socket)
    or ``read`` (file object), tolerating partial reads. Returns fewer
    bytes only at EOF."""
    recv = getattr(stream, "recv", None) or stream.read
    chunks = []
    got = 0
    while got < n:
        chunk = recv(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(stream, *, magic: bytes,
               max_payload: Optional[int] = None,
               checksum: str = "sha256") -> Optional[bytes]:
    """Read one frame from a stream → payload, or ``None`` on clean EOF
    (no bytes at all — the peer closed between frames).

    EOF mid-frame raises :class:`FrameTruncatedError`; wrong magic or a
    failed digest raises :class:`FrameCorruptError`. ``max_payload``
    bounds the allocation a hostile/corrupt length prefix could demand.
    """
    dlen = digest_length(checksum)
    head = _read_exact(stream, len(magic))
    if not head:
        return None
    if len(head) < len(magic) or head != magic:
        if len(head) < len(magic):
            raise FrameTruncatedError(
                f"truncated frame magic ({len(head)} bytes)")
        raise FrameCorruptError(
            f"bad frame magic {head!r} (expected {magic!r})")
    meta = _read_exact(stream, _LEN_BYTES + dlen)
    if len(meta) < _LEN_BYTES + dlen:
        raise FrameTruncatedError(
            f"truncated frame header ({len(head) + len(meta)} bytes)")
    (length,) = struct.unpack(">Q", meta[:_LEN_BYTES])
    if max_payload is not None and length > max_payload:
        raise FrameCorruptError(
            f"frame payload length {length} exceeds the {max_payload}-byte "
            "cap")
    digest = meta[_LEN_BYTES:]
    payload = _read_exact(stream, length)
    if len(payload) < length:
        raise FrameTruncatedError(
            f"frame payload is {len(payload)} bytes but the header "
            f"recorded {length}")
    if _digest(checksum, (payload,)) != digest:
        raise FrameCorruptError("frame payload checksum mismatch")
    return payload


def write_frame(stream, payload: bytes, *, magic: bytes,
                checksum: str = "sha256") -> int:
    """Write one frame to a stream exposing ``sendall`` (socket) or
    ``write`` (file object). Returns the payload byte count."""
    return write_frame_parts(stream, (payload,), magic=magic,
                             checksum=checksum)


def write_frame_parts(stream, parts, *, magic: bytes,
                      checksum: str = "sha256") -> int:
    """Write one frame whose payload is the concatenation of ``parts``
    (bytes/memoryviews) WITHOUT materializing it: the digest is computed
    incrementally across the parts (one pass) and each part is sent from
    the caller's buffer. With :func:`encode_payload_parts` this is the
    zero-copy response path — array buffers are never copied host-side
    between the compute result and the socket. Returns the payload byte
    count (the transports' ``wire.bytes`` increment)."""
    parts = [p if isinstance(p, (bytes, bytearray, memoryview))
             else memoryview(p) for p in parts]
    total = sum(p.nbytes if isinstance(p, memoryview) else len(p)
                for p in parts)
    header = (magic + struct.pack(">Q", total)
              + _digest(checksum, parts))
    send = getattr(stream, "sendall", None)
    if send is not None:
        # small frames go out in one syscall (and one TCP segment);
        # large array buffers are sent from their own memory instead of
        # paying a concatenation copy
        if total < (64 << 10):
            send(b"".join([header, *parts]))
        else:
            send(header)
            for p in parts:
                send(p)
        return total
    stream.write(header)
    for p in parts:
        stream.write(p)
    flush = getattr(stream, "flush", None)
    if flush is not None:
        flush()
    return total


# ---------------------------------------------------------------------------
# the typed wire payload: JSON control envelope + dtype/shape-tagged buffers
# ---------------------------------------------------------------------------

#: numpy dtypes allowed on the wire — fixed-width numerics only. No
#: object/void/str dtypes: nothing on this list can smuggle code or force
#: deserialization, which is the whole point of the typed payload.
PAYLOAD_DTYPES = frozenset({
    "bool",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "float64",
})

#: decode caps (hostile-input bounds; the frame-level ``max_payload`` cap
#: already bounds the total allocation — these bound the SHAPE of it)
MAX_CONTROL_BYTES = 1 << 20   # control envelope: 1 MiB of JSON, far above
#                               any real request header
MAX_ARRAYS = 64               # buffers per payload
MAX_NDIM = 8                  # dims per buffer

_CTRL_LEN_BYTES = 4


def encode_payload_parts(control: dict, arrays=()) -> list:
    """Encode one wire message as a PARTS LIST — ``[prefix, buf, ...]``
    where ``prefix`` is the control-length + control-JSON bytes and each
    ``buf`` is a memoryview over the (C-contiguous) array's own memory.
    ``b"".join(parts)`` is exactly the :func:`encode_payload` bytes, but
    the parts can be hashed and written without ever concatenating —
    :func:`write_frame_parts` — so a large result crosses from numpy to
    the socket with zero host copies. Non-contiguous inputs are made
    contiguous here (that copy is the caller's encode-time cost, and the
    only one).

    Layout (inside one :data:`WIRE_MAGIC` frame)::

        4-byte unsigned BE control length
        control JSON (utf-8) — ``control`` plus an ``"arrays"`` list of
            ``{"dtype", "shape"}`` descriptors, one per buffer
        the raw array buffers, C-contiguous, concatenated in order

    ``control`` must be JSON-serializable (strings/numbers/bools/lists/
    dicts — enforced by ``json.dumps``); arrays must have an allowlisted
    dtype (:data:`PAYLOAD_DTYPES`). Everything a peer decodes is
    reconstructed from (dtype, shape, bytes) — no object deserialization
    exists on this path.
    """
    metas = []
    bufs = []
    for a in arrays:
        a = np.asarray(a)
        shape = list(a.shape)  # before ascontiguousarray 0-d→1-d quirk
        a = np.ascontiguousarray(a)
        name = a.dtype.name
        if name not in PAYLOAD_DTYPES:
            raise PayloadError(
                f"dtype {name!r} is not wire-encodable "
                f"(allowed: {sorted(PAYLOAD_DTYPES)})")
        metas.append({"dtype": name, "shape": shape})
        bufs.append(memoryview(a.reshape(-1)).cast("B"))
    ctrl = dict(control)
    if "arrays" in ctrl:
        raise PayloadError(
            "'arrays' is the codec's buffer-descriptor key — a control "
            "envelope cannot carry its own (it would be silently "
            "replaced on encode and stripped on decode)")
    ctrl["arrays"] = metas
    head = json.dumps(ctrl, separators=(",", ":")).encode("utf-8")
    if len(head) > MAX_CONTROL_BYTES:
        raise PayloadError(
            f"control envelope is {len(head)} bytes "
            f"(cap {MAX_CONTROL_BYTES})")
    return [struct.pack(">I", len(head)) + head, *bufs]


def encode_payload(control: dict, arrays=()) -> bytes:
    """One wire message as a single byte string — the concatenation of
    :func:`encode_payload_parts` (layout and contract documented
    there)."""
    return b"".join(encode_payload_parts(control, arrays))


def decode_payload(payload, *,
                   max_control_bytes: int = MAX_CONTROL_BYTES):
    """Decode one typed wire message → ``(control, arrays)``.

    ``payload`` may be ``bytes`` (the socket path) or a ``memoryview``
    (the shared-memory path — the decoded arrays are then ZERO-COPY
    views into that buffer, pinned by the buffer-identity tests).

    Strict by construction: the control length is capped, the envelope
    must be a JSON object, every buffer descriptor must carry an
    allowlisted dtype and a sane shape (``<= MAX_NDIM`` non-negative
    dims), the described bytes must tile the remaining payload EXACTLY
    (no trailing garbage, no short buffers), and at most
    :data:`MAX_ARRAYS` buffers are accepted. Any violation raises
    :class:`PayloadError`; nothing here ever deserializes an object.
    """
    if len(payload) < _CTRL_LEN_BYTES:
        raise PayloadError(
            f"payload is {len(payload)} bytes — too short for the "
            "control-length prefix")
    (hlen,) = struct.unpack_from(">I", payload, 0)
    if hlen > max_control_bytes:
        raise PayloadError(
            f"control envelope length {hlen} exceeds the "
            f"{max_control_bytes}-byte cap")
    if _CTRL_LEN_BYTES + hlen > len(payload):
        raise PayloadError(
            f"control envelope length {hlen} overruns the "
            f"{len(payload)}-byte payload")
    try:
        control = json.loads(
            bytes(payload[_CTRL_LEN_BYTES:_CTRL_LEN_BYTES + hlen])
            .decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise PayloadError(f"control envelope is not valid JSON: {e}")
    if not isinstance(control, dict):
        raise PayloadError(
            f"control envelope must be a JSON object, got "
            f"{type(control).__name__}")
    metas = control.pop("arrays", [])
    if not isinstance(metas, list) or len(metas) > MAX_ARRAYS:
        raise PayloadError(
            "control 'arrays' must be a list of at most "
            f"{MAX_ARRAYS} descriptors")
    arrays = []
    off = _CTRL_LEN_BYTES + hlen
    for i, m in enumerate(metas):
        if not isinstance(m, dict):
            raise PayloadError(f"array descriptor {i} is not an object")
        name = m.get("dtype")
        if name not in PAYLOAD_DTYPES:
            raise PayloadError(
                f"array {i} dtype {name!r} is not wire-decodable "
                f"(allowed: {sorted(PAYLOAD_DTYPES)})")
        shape = m.get("shape")
        if (not isinstance(shape, list) or len(shape) > MAX_NDIM
                or not all(isinstance(s, int) and not isinstance(s, bool)
                           and 0 <= s for s in shape)):
            raise PayloadError(
                f"array {i} shape {shape!r} is not a list of <= "
                f"{MAX_NDIM} non-negative integers")
        dt = np.dtype(name)
        n = 1
        for s in shape:
            n *= s
        nbytes = n * dt.itemsize
        if off + nbytes > len(payload):
            raise PayloadError(
                f"array {i} ({name}, shape {tuple(shape)}) needs "
                f"{nbytes} bytes but only {len(payload) - off} remain")
        arrays.append(np.frombuffer(
            payload, dtype=dt, count=n, offset=off).reshape(shape))
        off += nbytes
    if off != len(payload):
        raise PayloadError(
            f"payload carries {len(payload) - off} trailing bytes past "
            "the described buffers")
    return control, arrays
