"""Length-prefixed, checksummed frame codec shared by snapshots and wire.

One framing discipline serves two very different transports:

- **Checkpoint snapshots** (``dask_ml_tpu.checkpoint``): ``save_pytree``
  frames its pickle payload so that atomic-rename durability becomes an
  END-TO-END guarantee — rename protects against a kill mid-save, the
  frame's length + sha256 protect against everything else (a torn copy, a
  truncated transfer off shared storage, silent media corruption). Any
  byte missing or flipped fails the digest and surfaces loudly instead of
  unpickling noise (swept at every byte offset in
  ``tests/test_checkpoint.py``).
- **The serving wire protocol** (``dask_ml_tpu.parallel.fleet``):
  out-of-process clients submit inference requests over a socket as
  frames of exactly this layout. A frame that fails validation fails THE
  CALLER — the connection's error response names the corrupt frame, and
  no partial request ever reaches a batch another client shares (the
  serving layer's validation-fails-the-caller-not-the-batch contract,
  docs/serving.md).

Frame layout (everything big-endian)::

    magic (caller-chosen, includes a version byte)
    8-byte unsigned payload length
    32-byte sha256(payload)
    payload

The codec is transport-agnostic: :func:`encode_frame`/:func:`decode_frame`
work on whole byte strings (the snapshot path reads the file in one go),
:func:`read_frame`/:func:`write_frame` work on stream objects with
``recv``-style partial reads (the socket path). Errors are typed —
:class:`FrameTruncatedError` for missing bytes, :class:`FrameCorruptError`
for a failed digest or foreign magic — so callers can map them onto their
own error surface (``checkpoint.py`` wraps both in
``CheckpointCorruptError`` with its original messages, bit-identical
behavior to the pre-extraction code).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Optional

__all__ = [
    "FrameError",
    "FrameTruncatedError",
    "FrameCorruptError",
    "encode_frame",
    "decode_frame",
    "read_frame",
    "write_frame",
    "header_length",
    "WIRE_MAGIC",
]

#: serving wire-protocol magic (docs/serving.md, "The wire protocol");
#: the checkpoint magic lives with its owner in ``dask_ml_tpu.checkpoint``
WIRE_MAGIC = b"DMLTWIRE1\n"

_LEN_BYTES = 8
_DIGEST_BYTES = 32


class FrameError(RuntimeError):
    """Base class for framing failures."""


class FrameTruncatedError(FrameError):
    """The buffer/stream ended before the frame did (torn write, cut
    connection): the header promised more bytes than arrived."""


class FrameCorruptError(FrameError):
    """The frame is structurally complete but wrong: foreign magic, or a
    payload whose sha256 does not match the header's digest."""


def header_length(magic: bytes) -> int:
    """Total header size for ``magic``: magic + length + digest."""
    return len(magic) + _LEN_BYTES + _DIGEST_BYTES


def encode_frame(payload: bytes, *, magic: bytes) -> bytes:
    """``magic + len(payload) (8B BE) + sha256(payload) + payload``."""
    return (magic + struct.pack(">Q", len(payload))
            + hashlib.sha256(payload).digest() + payload)


def decode_frame(data: bytes, *, magic: bytes) -> bytes:
    """Decode one whole-buffer frame → payload, verifying magic, length,
    and digest. ``data`` must be exactly one frame (the snapshot file
    case); trailing bytes are corruption, not a second frame."""
    if data[:len(magic)] != magic:
        raise FrameCorruptError(
            f"bad frame magic {data[:len(magic)]!r} (expected {magic!r})")
    rest = data[len(magic):]
    if len(rest) < _LEN_BYTES + _DIGEST_BYTES:
        raise FrameTruncatedError(
            f"truncated frame header ({len(data)} bytes)")
    (length,) = struct.unpack(">Q", rest[:_LEN_BYTES])
    digest = rest[_LEN_BYTES:_LEN_BYTES + _DIGEST_BYTES]
    payload = rest[_LEN_BYTES + _DIGEST_BYTES:]
    if len(payload) < length:
        raise FrameTruncatedError(
            f"frame payload is {len(payload)} bytes but the header "
            f"recorded {length}")
    if len(payload) > length:
        raise FrameCorruptError(
            f"frame carries {len(payload) - length} trailing bytes past "
            f"the recorded payload length {length}")
    if hashlib.sha256(payload).digest() != digest:
        raise FrameCorruptError("frame payload checksum mismatch")
    return payload


def _read_exact(stream, n: int) -> bytes:
    """Read exactly ``n`` bytes from a stream exposing ``recv`` (socket)
    or ``read`` (file object), tolerating partial reads. Returns fewer
    bytes only at EOF."""
    recv = getattr(stream, "recv", None) or stream.read
    chunks = []
    got = 0
    while got < n:
        chunk = recv(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(stream, *, magic: bytes,
               max_payload: Optional[int] = None) -> Optional[bytes]:
    """Read one frame from a stream → payload, or ``None`` on clean EOF
    (no bytes at all — the peer closed between frames).

    EOF mid-frame raises :class:`FrameTruncatedError`; wrong magic or a
    failed digest raises :class:`FrameCorruptError`. ``max_payload``
    bounds the allocation a hostile/corrupt length prefix could demand.
    """
    head = _read_exact(stream, len(magic))
    if not head:
        return None
    if len(head) < len(magic) or head != magic:
        if len(head) < len(magic):
            raise FrameTruncatedError(
                f"truncated frame magic ({len(head)} bytes)")
        raise FrameCorruptError(
            f"bad frame magic {head!r} (expected {magic!r})")
    meta = _read_exact(stream, _LEN_BYTES + _DIGEST_BYTES)
    if len(meta) < _LEN_BYTES + _DIGEST_BYTES:
        raise FrameTruncatedError(
            f"truncated frame header ({len(head) + len(meta)} bytes)")
    (length,) = struct.unpack(">Q", meta[:_LEN_BYTES])
    if max_payload is not None and length > max_payload:
        raise FrameCorruptError(
            f"frame payload length {length} exceeds the {max_payload}-byte "
            "cap")
    digest = meta[_LEN_BYTES:]
    payload = _read_exact(stream, length)
    if len(payload) < length:
        raise FrameTruncatedError(
            f"frame payload is {len(payload)} bytes but the header "
            f"recorded {length}")
    if hashlib.sha256(payload).digest() != digest:
        raise FrameCorruptError("frame payload checksum mismatch")
    return payload


def write_frame(stream, payload: bytes, *, magic: bytes) -> None:
    """Write one frame to a stream exposing ``sendall`` (socket) or
    ``write`` (file object)."""
    data = encode_frame(payload, magic=magic)
    send = getattr(stream, "sendall", None)
    if send is not None:
        send(data)
        return
    stream.write(data)
    flush = getattr(stream, "flush", None)
    if flush is not None:
        flush()
