"""Length-prefixed, checksummed frame codec shared by snapshots and wire,
plus the typed (pickle-free) wire payload the serving fleet speaks.

One framing discipline serves two very different transports:

- **Checkpoint snapshots** (``dask_ml_tpu.checkpoint``): ``save_pytree``
  frames its pickle payload so that atomic-rename durability becomes an
  END-TO-END guarantee — rename protects against a kill mid-save, the
  frame's length + sha256 protect against everything else (a torn copy, a
  truncated transfer off shared storage, silent media corruption). Any
  byte missing or flipped fails the digest and surfaces loudly instead of
  unpickling noise (swept at every byte offset in
  ``tests/test_checkpoint.py``).
- **The serving wire protocol** (``dask_ml_tpu.parallel.fleet``):
  out-of-process clients submit inference requests over a socket as
  frames of exactly this layout. A frame that fails validation fails THE
  CALLER — the connection's error response names the corrupt frame, and
  no partial request ever reaches a batch another client shares (the
  serving layer's validation-fails-the-caller-not-the-batch contract,
  docs/serving.md).

Frame layout (everything big-endian)::

    magic (caller-chosen, includes a version byte)
    8-byte unsigned payload length
    32-byte sha256(payload)
    payload

The codec is transport-agnostic: :func:`encode_frame`/:func:`decode_frame`
work on whole byte strings (the snapshot path reads the file in one go),
:func:`read_frame`/:func:`write_frame` work on stream objects with
``recv``-style partial reads (the socket path). Errors are typed —
:class:`FrameTruncatedError` for missing bytes, :class:`FrameCorruptError`
for a failed digest or foreign magic — so callers can map them onto their
own error surface (``checkpoint.py`` wraps both in
``CheckpointCorruptError`` with its original messages, bit-identical
behavior to the pre-extraction code).

**The typed wire payload.** The serving wire's frame payloads are NOT
pickle: they are a self-describing, capped layout that deserializes no
objects anywhere, so ``FleetServer`` can face untrusted clients —
:func:`encode_payload`/:func:`decode_payload` (layout documented there).
A payload that fails its caps or structure raises the typed
:class:`PayloadError`, which the serving layer maps to a per-frame error
response (the frame boundary is intact, so the connection survives — only
a torn FRAME ends a stream).
"""

from __future__ import annotations

import json
import struct
import hashlib
from typing import Optional

import numpy as np

__all__ = [
    "FrameError",
    "FrameTruncatedError",
    "FrameCorruptError",
    "PayloadError",
    "encode_frame",
    "decode_frame",
    "read_frame",
    "write_frame",
    "encode_payload",
    "decode_payload",
    "header_length",
    "WIRE_MAGIC",
    "PAYLOAD_DTYPES",
]

#: serving wire-protocol magic (docs/serving.md, "The wire protocol");
#: the checkpoint magic lives with its owner in ``dask_ml_tpu.checkpoint``.
#: The version byte is 2: version 1 framed pickle payloads, version 2
#: frames the typed payload below — a v1 peer fails the magic check loudly
#: instead of misparsing bytes.
WIRE_MAGIC = b"DMLTWIRE2\n"

_LEN_BYTES = 8
_DIGEST_BYTES = 32


class FrameError(RuntimeError):
    """Base class for framing failures."""


class FrameTruncatedError(FrameError):
    """The buffer/stream ended before the frame did (torn write, cut
    connection): the header promised more bytes than arrived."""


class FrameCorruptError(FrameError):
    """The frame is structurally complete but wrong: foreign magic, or a
    payload whose sha256 does not match the header's digest."""


class PayloadError(FrameError):
    """A typed wire payload failed decoding: malformed control envelope,
    a dtype outside the allowlist, a shape that disagrees with the buffer
    bytes, or a cap violation. The FRAME was intact (length + digest
    passed), so the error is attributable to one request and the
    connection keeps serving."""


def header_length(magic: bytes) -> int:
    """Total header size for ``magic``: magic + length + digest."""
    return len(magic) + _LEN_BYTES + _DIGEST_BYTES


def encode_frame(payload: bytes, *, magic: bytes) -> bytes:
    """``magic + len(payload) (8B BE) + sha256(payload) + payload``."""
    return (magic + struct.pack(">Q", len(payload))
            + hashlib.sha256(payload).digest() + payload)


def decode_frame(data: bytes, *, magic: bytes) -> bytes:
    """Decode one whole-buffer frame → payload, verifying magic, length,
    and digest. ``data`` must be exactly one frame (the snapshot file
    case); trailing bytes are corruption, not a second frame."""
    if data[:len(magic)] != magic:
        raise FrameCorruptError(
            f"bad frame magic {data[:len(magic)]!r} (expected {magic!r})")
    rest = data[len(magic):]
    if len(rest) < _LEN_BYTES + _DIGEST_BYTES:
        raise FrameTruncatedError(
            f"truncated frame header ({len(data)} bytes)")
    (length,) = struct.unpack(">Q", rest[:_LEN_BYTES])
    digest = rest[_LEN_BYTES:_LEN_BYTES + _DIGEST_BYTES]
    payload = rest[_LEN_BYTES + _DIGEST_BYTES:]
    if len(payload) < length:
        raise FrameTruncatedError(
            f"frame payload is {len(payload)} bytes but the header "
            f"recorded {length}")
    if len(payload) > length:
        raise FrameCorruptError(
            f"frame carries {len(payload) - length} trailing bytes past "
            f"the recorded payload length {length}")
    if hashlib.sha256(payload).digest() != digest:
        raise FrameCorruptError("frame payload checksum mismatch")
    return payload


def _read_exact(stream, n: int) -> bytes:
    """Read exactly ``n`` bytes from a stream exposing ``recv`` (socket)
    or ``read`` (file object), tolerating partial reads. Returns fewer
    bytes only at EOF."""
    recv = getattr(stream, "recv", None) or stream.read
    chunks = []
    got = 0
    while got < n:
        chunk = recv(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(stream, *, magic: bytes,
               max_payload: Optional[int] = None) -> Optional[bytes]:
    """Read one frame from a stream → payload, or ``None`` on clean EOF
    (no bytes at all — the peer closed between frames).

    EOF mid-frame raises :class:`FrameTruncatedError`; wrong magic or a
    failed digest raises :class:`FrameCorruptError`. ``max_payload``
    bounds the allocation a hostile/corrupt length prefix could demand.
    """
    head = _read_exact(stream, len(magic))
    if not head:
        return None
    if len(head) < len(magic) or head != magic:
        if len(head) < len(magic):
            raise FrameTruncatedError(
                f"truncated frame magic ({len(head)} bytes)")
        raise FrameCorruptError(
            f"bad frame magic {head!r} (expected {magic!r})")
    meta = _read_exact(stream, _LEN_BYTES + _DIGEST_BYTES)
    if len(meta) < _LEN_BYTES + _DIGEST_BYTES:
        raise FrameTruncatedError(
            f"truncated frame header ({len(head) + len(meta)} bytes)")
    (length,) = struct.unpack(">Q", meta[:_LEN_BYTES])
    if max_payload is not None and length > max_payload:
        raise FrameCorruptError(
            f"frame payload length {length} exceeds the {max_payload}-byte "
            "cap")
    digest = meta[_LEN_BYTES:]
    payload = _read_exact(stream, length)
    if len(payload) < length:
        raise FrameTruncatedError(
            f"frame payload is {len(payload)} bytes but the header "
            f"recorded {length}")
    if hashlib.sha256(payload).digest() != digest:
        raise FrameCorruptError("frame payload checksum mismatch")
    return payload


def write_frame(stream, payload: bytes, *, magic: bytes) -> None:
    """Write one frame to a stream exposing ``sendall`` (socket) or
    ``write`` (file object)."""
    data = encode_frame(payload, magic=magic)
    send = getattr(stream, "sendall", None)
    if send is not None:
        send(data)
        return
    stream.write(data)
    flush = getattr(stream, "flush", None)
    if flush is not None:
        flush()


# ---------------------------------------------------------------------------
# the typed wire payload: JSON control envelope + dtype/shape-tagged buffers
# ---------------------------------------------------------------------------

#: numpy dtypes allowed on the wire — fixed-width numerics only. No
#: object/void/str dtypes: nothing on this list can smuggle code or force
#: deserialization, which is the whole point of the typed payload.
PAYLOAD_DTYPES = frozenset({
    "bool",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "float64",
})

#: decode caps (hostile-input bounds; the frame-level ``max_payload`` cap
#: already bounds the total allocation — these bound the SHAPE of it)
MAX_CONTROL_BYTES = 1 << 20   # control envelope: 1 MiB of JSON, far above
#                               any real request header
MAX_ARRAYS = 64               # buffers per payload
MAX_NDIM = 8                  # dims per buffer

_CTRL_LEN_BYTES = 4


def encode_payload(control: dict, arrays=()) -> bytes:
    """Encode one wire message: a JSON control envelope plus zero or more
    numpy buffers, self-describing and pickle-free.

    Layout (inside one :data:`WIRE_MAGIC` frame)::

        4-byte unsigned BE control length
        control JSON (utf-8) — ``control`` plus an ``"arrays"`` list of
            ``{"dtype", "shape"}`` descriptors, one per buffer
        the raw array buffers, C-contiguous, concatenated in order

    ``control`` must be JSON-serializable (strings/numbers/bools/lists/
    dicts — enforced by ``json.dumps``); arrays must have an allowlisted
    dtype (:data:`PAYLOAD_DTYPES`). Everything a peer decodes is
    reconstructed from (dtype, shape, bytes) — no object deserialization
    exists on this path.
    """
    metas = []
    bufs = []
    for a in arrays:
        a = np.asarray(a)
        shape = list(a.shape)  # before ascontiguousarray 0-d→1-d quirk
        a = np.ascontiguousarray(a)
        name = a.dtype.name
        if name not in PAYLOAD_DTYPES:
            raise PayloadError(
                f"dtype {name!r} is not wire-encodable "
                f"(allowed: {sorted(PAYLOAD_DTYPES)})")
        metas.append({"dtype": name, "shape": shape})
        bufs.append(a.tobytes())
    ctrl = dict(control)
    if "arrays" in ctrl:
        raise PayloadError(
            "'arrays' is the codec's buffer-descriptor key — a control "
            "envelope cannot carry its own (it would be silently "
            "replaced on encode and stripped on decode)")
    ctrl["arrays"] = metas
    head = json.dumps(ctrl, separators=(",", ":")).encode("utf-8")
    if len(head) > MAX_CONTROL_BYTES:
        raise PayloadError(
            f"control envelope is {len(head)} bytes "
            f"(cap {MAX_CONTROL_BYTES})")
    return (struct.pack(">I", len(head)) + head + b"".join(bufs))


def decode_payload(payload: bytes, *,
                   max_control_bytes: int = MAX_CONTROL_BYTES):
    """Decode one typed wire message → ``(control, arrays)``.

    Strict by construction: the control length is capped, the envelope
    must be a JSON object, every buffer descriptor must carry an
    allowlisted dtype and a sane shape (``<= MAX_NDIM`` non-negative
    dims), the described bytes must tile the remaining payload EXACTLY
    (no trailing garbage, no short buffers), and at most
    :data:`MAX_ARRAYS` buffers are accepted. Any violation raises
    :class:`PayloadError`; nothing here ever deserializes an object.
    """
    if len(payload) < _CTRL_LEN_BYTES:
        raise PayloadError(
            f"payload is {len(payload)} bytes — too short for the "
            "control-length prefix")
    (hlen,) = struct.unpack(">I", payload[:_CTRL_LEN_BYTES])
    if hlen > max_control_bytes:
        raise PayloadError(
            f"control envelope length {hlen} exceeds the "
            f"{max_control_bytes}-byte cap")
    if _CTRL_LEN_BYTES + hlen > len(payload):
        raise PayloadError(
            f"control envelope length {hlen} overruns the "
            f"{len(payload)}-byte payload")
    try:
        control = json.loads(
            payload[_CTRL_LEN_BYTES:_CTRL_LEN_BYTES + hlen].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise PayloadError(f"control envelope is not valid JSON: {e}")
    if not isinstance(control, dict):
        raise PayloadError(
            f"control envelope must be a JSON object, got "
            f"{type(control).__name__}")
    metas = control.pop("arrays", [])
    if not isinstance(metas, list) or len(metas) > MAX_ARRAYS:
        raise PayloadError(
            "control 'arrays' must be a list of at most "
            f"{MAX_ARRAYS} descriptors")
    arrays = []
    off = _CTRL_LEN_BYTES + hlen
    for i, m in enumerate(metas):
        if not isinstance(m, dict):
            raise PayloadError(f"array descriptor {i} is not an object")
        name = m.get("dtype")
        if name not in PAYLOAD_DTYPES:
            raise PayloadError(
                f"array {i} dtype {name!r} is not wire-decodable "
                f"(allowed: {sorted(PAYLOAD_DTYPES)})")
        shape = m.get("shape")
        if (not isinstance(shape, list) or len(shape) > MAX_NDIM
                or not all(isinstance(s, int) and not isinstance(s, bool)
                           and 0 <= s for s in shape)):
            raise PayloadError(
                f"array {i} shape {shape!r} is not a list of <= "
                f"{MAX_NDIM} non-negative integers")
        dt = np.dtype(name)
        n = 1
        for s in shape:
            n *= s
        nbytes = n * dt.itemsize
        if off + nbytes > len(payload):
            raise PayloadError(
                f"array {i} ({name}, shape {tuple(shape)}) needs "
                f"{nbytes} bytes but only {len(payload) - off} remain")
        arrays.append(np.frombuffer(
            payload, dtype=dt, count=n, offset=off).reshape(shape))
        off += nbytes
    if off != len(payload):
        raise PayloadError(
            f"payload carries {len(payload) - off} trailing bytes past "
            "the described buffers")
    return control, arrays
