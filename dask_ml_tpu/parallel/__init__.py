"""Mesh/runtime bootstrap, sharding helpers, and collective patterns.

This is the substrate layer: the TPU-native replacement for the reference's
dask schedulers + chunked collections (reference: dask_ml relies on dask
scheduler selection at model_selection/_search.py:841-852 and axis-0-chunked
``dask.array`` everywhere). Here a dataset is a ``jax.Array`` sharded along
axis 0 over the ``"data"`` axis of a :class:`jax.sharding.Mesh`; aggregation
happens through XLA collectives instead of task-graph reductions.
"""

from dask_ml_tpu.parallel.mesh import (  # noqa: F401
    CHIP_AXIS,
    DATA_AXIS,
    MODEL_AXIS,
    POD_AXIS,
    data_axes,
    data_pspec,
    data_sharding,
    default_mesh,
    feature_sharding,
    is_hierarchical,
    make_2d_mesh,
    make_mesh,
    n_data_shards,
    n_model_shards,
    replicated_sharding,
    use_mesh,
)
from dask_ml_tpu.parallel.hierarchy import (  # noqa: F401
    TrafficLedger,
    hpmean,
    hpsum,
    hpsum_scatter,
    ledger,
    ledger_snapshot,
    make_hierarchical_mesh,
    reset_ledger,
)
from dask_ml_tpu.parallel.sharding import (  # noqa: F401
    DeviceData,
    pad_rows,
    prepare_data,
    shard_2d,
    shard_rows,
    unpad_rows,
)
from dask_ml_tpu.parallel.faults import (  # noqa: F401
    BlockFetchError,
    FaultInjector,
    GracefulDrain,
    Preempted,
    RetryPolicy,
    ScanCheckpoint,
)
from dask_ml_tpu.parallel.shapes import (  # noqa: F401
    PadPolicy,
    compile_stats,
    pad_tail,
    reset_compile_stats,
    track_compiles,
)
from dask_ml_tpu.parallel.precision import (  # noqa: F401
    BF16,
    F32,
    PrecisionPolicy,
    neumaier_sum,
    pdot,
    pmatmul,
)
from dask_ml_tpu.parallel.telemetry import (  # noqa: F401
    MetricsRegistry,
    export_chrome_trace,
    render_report,
    reset_telemetry,
    span,
    telemetry_report,
)
from dask_ml_tpu.parallel.stream import (  # noqa: F401
    HostBlockSource,
    prefetched_scan,
)
from dask_ml_tpu.parallel.serving import (  # noqa: F401
    DeadlineExceeded,
    ModelRegistry,
    ServingClosed,
    ServingLoop,
    ServingQueueFull,
    ServingStopped,
)
from dask_ml_tpu.parallel.fleet import (  # noqa: F401
    FleetClient,
    FleetServer,
    FleetTimeoutError,
    RetryBudget,
    ServingFleet,
)
from dask_ml_tpu.parallel.elastic import (  # noqa: F401
    BlockPlan,
    ElasticRun,
    FileHeartbeat,
)

# the process-isolated fleet tier (out-of-process replicas): imported
# lazily by name to keep `import dask_ml_tpu.parallel` light — but the
# router class is small and pure-host, so re-exporting it here is cheap
from dask_ml_tpu.parallel.procfleet import (  # noqa: F401
    ProcessFleet,
)

# the cross-machine tier: remote-spawn launchers, content-addressed
# snapshot distribution, and the SLO autoscaler (all pure-host)
from dask_ml_tpu.parallel.launcher import (  # noqa: F401
    ExecLauncher,
    LocalLauncher,
    MachineSpec,
    plan_placement,
)
from dask_ml_tpu.parallel.snapshots import (  # noqa: F401
    ChunkCache,
    SnapshotCorruptError,
    SnapshotServer,
    SnapshotTransferError,
    fetch_snapshot,
    manifest_of,
)
from dask_ml_tpu.parallel.autoscaler import (  # noqa: F401
    SLO,
    Autoscaler,
)

# runtime (multi-host bootstrap) is imported lazily by users that need it:
#   from dask_ml_tpu.parallel import runtime; runtime.initialize(...)
# importing it here would pull jax.distributed into every single-host run.
