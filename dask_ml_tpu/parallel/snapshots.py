"""Content-addressed, chunk-level snapshot distribution over the framed
wire.

The process fleet ships its model registry to replicas as a snapshot
file (``parallel/replica.py``). On one box that is a path; across
machines it is BYTES ON A LINK — and a 10 GB registry that re-ships
whole on every respawn turns every machine loss into a transfer storm.
This module applies the communication-avoiding discipline the training
side already lives by (arxiv 2601.17136: account the bytes, then avoid
them) to the serving control plane:

- **Content addressing.** A snapshot is split into fixed-size chunks;
  each chunk's address IS its sha256 (:func:`manifest_of`). Two snapshot
  versions that differ in one model share every other chunk's address,
  so a version swap re-ships only what changed.
- **Per-machine chunk cache.** :class:`ChunkCache` stores chunks by hash
  in the machine's workdir — shared by every replica on that machine, so
  a respawn (same snapshot) or a second replica (same machine) fetches
  metadata only. Writes are atomic (tmp + rename: concurrent replicas
  race safely); reads RE-VERIFY the hash, so a stale or bit-flipped
  cache entry is discarded and re-fetched, never served.
- **Resumable transfer.** :func:`fetch_snapshot` persists each verified
  chunk into the cache before fetching the next; a transfer killed at
  any chunk boundary resumes exactly — the re-run fetches only the
  missing suffix. Assembly is atomic and verified against the manifest's
  whole-file sha256 before the destination is renamed into place.
- **Typed faults.** Transport failures (socket errors, torn frames)
  raise :class:`SnapshotTransferError` — an ``OSError``, so the default
  :class:`~dask_ml_tpu.parallel.faults.RetryPolicy` classifies it
  transient and retries with backoff + reconnect. Content corruption
  (a chunk whose bytes do not hash to their address) raises
  :class:`SnapshotCorruptError` and is NEVER retried: the frame
  checksums already rule out link noise, so a bad hash means a lying
  server or a poisoned store — fail loudly.

The wire is the shared frame codec (:mod:`dask_ml_tpu.parallel.framing`)
under its own magic (:data:`SNAP_MAGIC`) carrying the typed payload —
a JSON control envelope (``op="manifest"`` / ``op="chunk"``) plus at
most one uint8 buffer, no object deserialization anywhere.
:class:`SnapshotServer` runs in the router process and serves chunks by
hash with range reads (the snapshot is never held in memory);
``FaultInjector.slow_link`` plans inject per-machine transfer delay for
drills. docs/serving.md ("The multi-machine fleet") has the layout.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import tempfile
import threading
from typing import Optional

import numpy as np

from dask_ml_tpu.parallel import framing

__all__ = [
    "SNAP_MAGIC",
    "SnapshotError",
    "SnapshotCorruptError",
    "SnapshotTransferError",
    "manifest_of",
    "ChunkCache",
    "SnapshotServer",
    "fetch_snapshot",
]

#: snapshot-wire magic: the shared frame layout (magic + length + sha256)
#: under its own version byte, so a snapshot socket can never be confused
#: with the request wire (``DMLTWIRE2``) or a registry file on disk
SNAP_MAGIC = b"DMLTSNAP1\n"

#: default chunk size — large enough that manifest overhead is noise,
#: small enough that a one-model edit in a big registry shares most
#: chunk addresses with its predecessor
DEFAULT_CHUNK_BYTES = 4 * 1024 * 1024


class SnapshotError(RuntimeError):
    """Base class for snapshot-distribution failures."""


class SnapshotCorruptError(SnapshotError):
    """Chunk bytes do not hash to their content address (or an assembled
    snapshot fails its manifest hash). Deliberately NOT transient: the
    frame checksum already caught link corruption upstream, so this is a
    lying peer or a poisoned store — loud, never retried."""


class SnapshotTransferError(SnapshotError, OSError):
    """The transfer itself failed (socket error, torn frame, server
    refused). Subclasses ``OSError`` so the default
    :class:`~dask_ml_tpu.parallel.faults.RetryPolicy` retries it."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def manifest_of(path: str, *,
                chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> dict:
    """Chunk the file at ``path`` into fixed-size pieces and return its
    manifest: per-chunk ``{sha256, size, offset}`` rows plus the
    whole-file sha256 — the complete recipe for a content-addressed,
    resumable fetch."""
    chunk_bytes = int(chunk_bytes)
    if chunk_bytes < 1:
        raise ValueError("chunk_bytes must be >= 1")
    chunks = []
    total = hashlib.sha256()
    offset = 0
    with open(path, "rb") as f:
        while True:
            data = f.read(chunk_bytes)
            if not data:
                break
            total.update(data)
            chunks.append({"sha256": _sha256(data), "size": len(data),
                           "offset": offset})
            offset += len(data)
    return {"total_sha256": total.hexdigest(), "size": offset,
            "chunk_bytes": chunk_bytes, "chunks": chunks}


class ChunkCache:
    """Per-machine content-addressed chunk store: one file per chunk,
    named by its sha256. ``put`` verifies before writing (atomically);
    ``get`` re-verifies after reading — an entry whose CONTENT no longer
    matches its address (bit rot, a stale file landed on the colliding
    path) is discarded and counted, and the caller re-fetches."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self.n_hits = 0
        self.n_stale_discarded = 0

    def path(self, sha256: str) -> str:
        if not sha256 or os.sep in sha256 or "." in sha256:
            raise ValueError(f"malformed chunk address {sha256!r}")
        return os.path.join(self.root, f"{sha256}.chunk")

    def get(self, sha256: str) -> Optional[bytes]:
        p = self.path(sha256)
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError:
            return None
        if _sha256(data) != sha256:
            # stale/corrupt entry on the colliding path: resume exactly
            # by treating it as a miss (and never serving it)
            with self._lock:
                self.n_stale_discarded += 1
            try:
                os.unlink(p)
            except OSError:
                pass
            return None
        with self._lock:
            self.n_hits += 1
        return data

    def put(self, sha256: str, data: bytes) -> None:
        if _sha256(data) != sha256:
            raise SnapshotCorruptError(
                f"chunk does not hash to its address {sha256[:12]}… "
                f"({len(data)} bytes)")
        p = self.path(sha256)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, p)  # concurrent replicas race atomically
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


class SnapshotServer:
    """Serves one snapshot file's manifest and chunks over the framed
    wire (module docstring has the protocol). Runs in the ROUTER
    process; chunk reads are range reads against the file, verified
    against the cached manifest before sending — the server never ships
    bytes that stopped matching their address (a half-written swap reads
    as an error, and the client retries after the atomic rename lands).

    ``refresh()`` re-manifests after the snapshot file is replaced
    (version swap); it also runs automatically when the file's
    (mtime, size) changes. ``fault_injector`` arms per-machine
    ``slow_link`` plans (the client labels its requests with its
    machine name)."""

    def __init__(self, path: str, host: str = "127.0.0.1", port: int = 0,
                 *, chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 fault_injector=None):
        self.path = str(path)
        self.chunk_bytes = int(chunk_bytes)
        self._injector = fault_injector
        self._lock = threading.Lock()
        self._manifest: Optional[dict] = None
        self._by_hash: dict = {}
        self._stamp: Optional[tuple] = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: list = []
        self.n_manifests = 0
        self.n_chunks = 0
        self.n_bytes_sent = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SnapshotServer":
        if self._accept_thread is not None:
            return self
        self.refresh()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="snapshot-server-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    def __enter__(self) -> "SnapshotServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass

    def refresh(self) -> dict:
        """(Re)manifest the snapshot file — call after replacing it, or
        let the (mtime, size) stamp trigger it lazily."""
        st = os.stat(self.path)
        manifest = manifest_of(self.path, chunk_bytes=self.chunk_bytes)
        with self._lock:
            self._manifest = manifest
            self._by_hash = {c["sha256"]: c for c in manifest["chunks"]}
            self._stamp = (st.st_mtime_ns, st.st_size)
        return manifest

    def _current_manifest(self) -> dict:
        try:
            st = os.stat(self.path)
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError as e:
            raise SnapshotTransferError(
                f"snapshot file unreadable: {e!r}")
        with self._lock:
            if self._manifest is not None and self._stamp == stamp:
                return self._manifest
        return self.refresh()

    # -- the wire ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="snapshot-server-conn",
                             daemon=True).start()

    def _serve_conn(self, conn) -> None:
        try:
            while not self._stop.is_set():
                try:
                    payload = framing.read_frame(conn, magic=SNAP_MAGIC)
                except framing.FrameError as e:
                    self._reply(conn, {"ok": False,
                                       "error": type(e).__name__,
                                       "message": str(e)})
                    return
                if payload is None:
                    return  # clean EOF
                try:
                    self._handle(conn, payload)
                except OSError:
                    return  # peer went away mid-reply
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if conn in self._conns:
                self._conns.remove(conn)

    def _reply(self, conn, control: dict, arrays=()) -> None:
        framing.write_frame(conn, framing.encode_payload(control, arrays),
                            magic=SNAP_MAGIC)

    def _handle(self, conn, payload: bytes) -> None:
        try:
            msg, _arrays = framing.decode_payload(payload)
            op = msg.get("op")
            if op == "manifest":
                manifest = self._current_manifest()
                self.n_manifests += 1
                self._reply(conn, {"ok": True, "manifest": manifest})
                return
            if op != "chunk":
                raise ValueError(f"unknown snapshot op {op!r}")
            h = str(msg.get("sha256") or "")
            machine = str(msg.get("machine") or "")
            manifest = self._current_manifest()
            with self._lock:
                row = self._by_hash.get(h)
            if row is None:
                raise KeyError(f"no chunk {h[:12]}… in current manifest")
            with open(self.path, "rb") as f:
                f.seek(int(row["offset"]))
                data = f.read(int(row["size"]))
            if _sha256(data) != h:
                # the file changed under the manifest (mid-swap read):
                # an error the client retries, never silent bad bytes
                raise SnapshotError(
                    f"chunk {h[:12]}… changed on disk; re-fetch the "
                    "manifest")
            if self._injector is not None:
                delay = self._injector.link_delay(machine)
                if delay > 0.0:
                    import time as time_mod

                    time_mod.sleep(delay)
            self.n_chunks += 1
            self.n_bytes_sent += len(data)
            self._reply(conn, {"ok": True, "sha256": h},
                        arrays=(np.frombuffer(data, dtype=np.uint8),))
        except OSError:
            raise
        except Exception as e:  # noqa: BLE001 — per-request error delivery
            self._reply(conn, {"ok": False, "error": type(e).__name__,
                               "message": str(e)})


class _SnapClient:
    """One reconnecting snapshot-wire connection (request/response,
    strictly sequential — chunk fetches pipeline through the cache, not
    the socket)."""

    def __init__(self, address, timeout: Optional[float] = 30.0):
        self.address = (address[0], int(address[1]))
        self.timeout = timeout
        self._sock = None

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip(self, control: dict) -> tuple:
        try:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self.address, timeout=self.timeout)
            framing.write_frame(self._sock,
                                framing.encode_payload(control),
                                magic=SNAP_MAGIC)
            payload = framing.read_frame(self._sock, magic=SNAP_MAGIC)
            if payload is None:
                raise SnapshotTransferError(
                    "snapshot server closed the connection")
            return framing.decode_payload(payload)
        except (OSError, framing.FrameError) as e:
            # drop the connection: the NEXT attempt (under the caller's
            # RetryPolicy) reconnects cleanly
            self.close()
            if isinstance(e, SnapshotTransferError):
                raise
            raise SnapshotTransferError(
                f"snapshot transfer failed: {e!r}")

    def manifest(self) -> dict:
        msg, _arrays = self._roundtrip({"op": "manifest"})
        if not msg.get("ok"):
            raise SnapshotTransferError(
                f"manifest refused: [{msg.get('error')}] "
                f"{msg.get('message')}")
        return dict(msg["manifest"])

    def chunk(self, sha256: str, machine: str = "") -> bytes:
        msg, arrays = self._roundtrip(
            {"op": "chunk", "sha256": str(sha256),
             "machine": str(machine)})
        if not msg.get("ok"):
            raise SnapshotTransferError(
                f"chunk {sha256[:12]}… refused: [{msg.get('error')}] "
                f"{msg.get('message')}")
        if len(arrays) != 1:
            raise SnapshotTransferError(
                f"chunk response carried {len(arrays)} buffers")
        return arrays[0].tobytes()


def fetch_snapshot(address, dest_path: str, *, cache_dir: str,
                   machine: str = "", retry_policy=None,
                   timeout: Optional[float] = 30.0,
                   fetch_chunk=None) -> dict:
    """Fetch the server's current snapshot into ``dest_path`` through
    the per-machine :class:`ChunkCache` at ``cache_dir``; returns the
    transfer accounting (``bytes_fetched`` is the delta the link
    actually carried — the quantity the fleet's re-ship gates measure).

    Every verified chunk persists to the cache BEFORE the next is
    requested, so a fetch killed mid-transfer resumes exactly; transport
    faults retry under ``retry_policy`` (default: a fresh
    :class:`~dask_ml_tpu.parallel.faults.RetryPolicy`); a chunk whose
    bytes do not hash to their address raises
    :class:`SnapshotCorruptError` immediately. ``fetch_chunk`` overrides
    the wire fetch (tests inject truncation/corruption there)."""
    from dask_ml_tpu.parallel import telemetry
    from dask_ml_tpu.parallel.faults import RetryPolicy

    retry = retry_policy if retry_policy is not None else RetryPolicy()
    cache = ChunkCache(cache_dir)
    client = _SnapClient(address, timeout=timeout)
    stale0 = cache.n_stale_discarded
    try:
        manifest = retry.run(client.manifest, kind="snapshot.manifest")
        stats = {"chunks_total": len(manifest["chunks"]),
                 "chunks_fetched": 0, "chunks_cached": 0,
                 "bytes_fetched": 0, "bytes_total": int(manifest["size"]),
                 "stale_discarded": 0}
        d = os.path.dirname(os.path.abspath(dest_path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".snap.tmp")
        try:
            total = hashlib.sha256()
            with os.fdopen(fd, "wb") as out:
                for row in manifest["chunks"]:
                    h = row["sha256"]
                    data = cache.get(h)
                    if data is None:
                        if fetch_chunk is not None:
                            data = retry.run(
                                lambda h=h: fetch_chunk(h),
                                kind="snapshot.chunk", detail=h[:12])
                        else:
                            data = retry.run(
                                lambda h=h: client.chunk(h, machine),
                                kind="snapshot.chunk", detail=h[:12])
                        # content address is the trust boundary: verify
                        # BEFORE the cache (put re-checks) and fail loud
                        # — the frame checksum already ruled out link
                        # noise, so a mismatch is a lying peer
                        if _sha256(data) != h:
                            raise SnapshotCorruptError(
                                f"fetched chunk does not hash to "
                                f"{h[:12]}…")
                        cache.put(h, data)
                        stats["chunks_fetched"] += 1
                        stats["bytes_fetched"] += len(data)
                        if telemetry.enabled():
                            telemetry.metrics().counter(
                                "snapshot.bytes_fetched",
                                machine=machine or "local",
                            ).inc(len(data))
                    else:
                        stats["chunks_cached"] += 1
                    total.update(data)
                    out.write(data)
                out.flush()
                os.fsync(out.fileno())
            if total.hexdigest() != manifest["total_sha256"]:
                raise SnapshotCorruptError(
                    "assembled snapshot does not hash to the manifest's "
                    "total_sha256")
            os.replace(tmp, dest_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    finally:
        client.close()
    stats["stale_discarded"] = cache.n_stale_discarded - stale0
    stats["manifest_sha256"] = manifest["total_sha256"]
    return stats


def parse_address(spec: str) -> tuple:
    """``"host:port"`` → ``(host, port)`` (the replica CLI's snapshot
    server argument)."""
    host, _, port = str(spec).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"malformed snapshot server address {spec!r} "
                         "(want host:port)")
    return (host, int(port))


def _json_roundtrip_safe(manifest: dict) -> dict:
    """Manifest rows travel a JSON control envelope — assert nothing
    non-JSON leaked in (used by tests as the wire-layout pin)."""
    return json.loads(json.dumps(manifest))
