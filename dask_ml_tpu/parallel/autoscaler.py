"""SLO autoscaler: the closed control loop over the fleet's telemetry.

The serving tier exports its load honestly — queue depth, latency
percentiles, shed counts, all mirrored at their increment sites
(docs/observability.md) — but until now a human read those surfaces and
a human resized the fleet. This module closes the loop the ROADMAP's
"millions of users" item asks for: a control thread that watches the
same three signals the mirrors export and acts on the fleet's own
scale API:

- **Breach → spawn.** When p99 latency, queue depth, or shed RATE
  exceeds the :class:`SLO` for ``breach_ticks`` CONSECUTIVE control
  ticks (hysteresis: one slow batch is noise, a sustained breach is
  load) and the scale-up cooldown has passed, the autoscaler calls
  ``fleet.scale_up(1)`` — a fresh replica process that loads the
  snapshot (delta-only, through the per-machine chunk cache), warms
  every program, and only then joins rotation. Bounded by
  ``max_replicas``: a traffic storm can never fork-bomb the box.
- **Quiet → drain.** When every signal sits below ``clear_fraction`` of
  its SLO bound for ``quiet_ticks`` consecutive ticks and the (longer)
  scale-down cooldown has passed, the autoscaler calls
  ``fleet.drain_slot()`` — SIGTERM, graceful drain, TOMBSTONE, exit 0 —
  never a kill: a draining replica finishes its queue and resolves
  every future before leaving. Bounded by ``min_replicas``.
- **Thrash-proof by construction.** Hysteresis (consecutive-tick
  requirements) filters spikes; asymmetric cooldowns (scale-down waits
  longer than scale-up) bias toward capacity; and each action resets
  both streaks, so one burst produces one decision, not a flapping
  series. ``FaultInjector.kill_machine`` / ``slow_link`` plans drill
  exactly these properties (docs/robustness.md).

Counters mirror at their increment sites: ``autoscaler.scale_ups`` /
``autoscaler.scale_downs`` / ``autoscaler.breaches`` and the
``autoscaler.replicas`` gauge. The decision log (:attr:`Autoscaler.
decisions`) records every action with the signals that drove it — the
drill's scale-up/drain gates read it (``bench.py --fleet-machines``,
FLEET_r03.json).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["SLO", "Autoscaler"]


@dataclasses.dataclass
class SLO:
    """The service-level objective the autoscaler defends.

    ``target_p99_s`` bounds the fleet's p99 request latency (pooled
    router-side observations); ``max_queue_depth`` bounds total
    in-flight requests across replicas; ``max_shed_per_s`` bounds the
    rate of deadline sheds (0.0 = any sustained shedding is a breach).
    Set a bound to ``float("inf")`` to ignore that signal."""

    target_p99_s: float = 0.5
    max_queue_depth: float = 64.0
    max_shed_per_s: float = 0.0


class Autoscaler:
    """Control loop over ``fleet.signals()`` (module docstring has the
    policy). The fleet must expose ``signals() -> {"p99_s",
    "queue_depth", "shed_total", "replicas_up"}``, ``scale_up(k)``, and
    ``drain_slot()`` — :class:`~dask_ml_tpu.parallel.procfleet.
    ProcessFleet` does.

    Scale-up runs INLINE on the control thread (spawn + snapshot fetch +
    warmup can take seconds); the loop simply does not tick while a
    replica is coming up, which is itself a cooldown.

    Parameters
    ----------
    breach_ticks, quiet_ticks : int
        Hysteresis: consecutive breaching (resp. quiet) ticks required
        before acting. Quiet needs more ticks than breach — adding
        capacity late costs latency, removing it late costs only money.
    scale_up_cooldown_s, scale_down_cooldown_s : float
        Minimum seconds between successive scale-ups (resp. downs).
    clear_fraction : float
        The quiet threshold as a fraction of each SLO bound (0.5 = a
        signal is quiet below half its limit) — the hysteresis BAND
        between "not breaching" and "drain-worthy".
    """

    def __init__(self, fleet, slo: Optional[SLO] = None, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 interval_s: float = 0.25,
                 breach_ticks: int = 2, quiet_ticks: int = 8,
                 scale_up_cooldown_s: float = 2.0,
                 scale_down_cooldown_s: float = 10.0,
                 clear_fraction: float = 0.5):
        if int(min_replicas) < 1:
            raise ValueError("min_replicas must be >= 1")
        if int(max_replicas) < int(min_replicas):
            raise ValueError("max_replicas must be >= min_replicas")
        self.fleet = fleet
        self.slo = slo if slo is not None else SLO()
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.breach_ticks = int(breach_ticks)
        self.quiet_ticks = int(quiet_ticks)
        self.scale_up_cooldown_s = float(scale_up_cooldown_s)
        self.scale_down_cooldown_s = float(scale_down_cooldown_s)
        self.clear_fraction = float(clear_fraction)

        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._breach_streak = 0
        self._quiet_streak = 0
        self._last_up = -1e18     # monotonic instants of the last actions
        self._last_down = -1e18
        self._last_shed: Optional[float] = None
        self._last_tick_t: Optional[float] = None
        #: ring of decision records: {"action", "t", "signals", "reason"}
        self.decisions: deque = deque(maxlen=256)
        self.n_scale_ups = 0
        self.n_scale_downs = 0
        self.n_breaches = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        from dask_ml_tpu.parallel import telemetry

        if self._thread is not None and self._thread.is_alive():
            return self
        self._telemetry_inherit = telemetry.enabled()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout)

    def _loop(self) -> None:
        import contextlib
        import logging

        from dask_ml_tpu import config as config_lib

        ctx = (config_lib.config_context(telemetry=True)
               if getattr(self, "_telemetry_inherit", False)
               else contextlib.nullcontext())
        with ctx:
            while not self._stop.wait(self.interval_s):
                # the control loop must outlive a surprised tick: a
                # failed scale action is logged and retried next breach
                try:
                    self.tick()
                except Exception:  # noqa: BLE001
                    logging.getLogger(__name__).exception(
                        "autoscaler: tick failed (continuing)")

    # -- telemetry ---------------------------------------------------------

    def _telemetry_on(self) -> bool:
        from dask_ml_tpu.parallel import telemetry

        return telemetry.enabled() or getattr(
            self, "_telemetry_inherit", False)

    def _count(self, attr: str, counter: str, **labels) -> None:
        from dask_ml_tpu.parallel import telemetry

        with self._lock:
            setattr(self, attr, getattr(self, attr) + 1)
        if self._telemetry_on():
            telemetry.metrics().counter(counter, **labels).inc()

    def _set_gauge(self, replicas_up: int) -> None:
        from dask_ml_tpu.parallel import telemetry

        if self._telemetry_on():
            telemetry.metrics().gauge("autoscaler.replicas").set(
                int(replicas_up))

    # -- the control law ---------------------------------------------------

    def _classify(self, sig: dict, shed_rate: float) -> tuple:
        """→ (breaching, quiet, reasons): breach = ANY signal over its
        bound; quiet = EVERY signal under ``clear_fraction`` of it. The
        band between is hysteresis — no action either way."""
        slo = self.slo
        reasons = []
        if sig["p99_s"] > slo.target_p99_s:
            reasons.append(f"p99 {sig['p99_s']:.3f}s > "
                           f"{slo.target_p99_s:.3f}s")
        if sig["queue_depth"] > slo.max_queue_depth:
            reasons.append(f"queue {sig['queue_depth']} > "
                           f"{slo.max_queue_depth:g}")
        if shed_rate > slo.max_shed_per_s:
            reasons.append(f"shed {shed_rate:.2f}/s > "
                           f"{slo.max_shed_per_s:g}/s")
        breaching = bool(reasons)
        frac = self.clear_fraction
        quiet = (not breaching
                 and sig["p99_s"] <= frac * slo.target_p99_s
                 and sig["queue_depth"] <= frac * slo.max_queue_depth
                 and shed_rate <= frac * slo.max_shed_per_s)
        return breaching, quiet, reasons

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control evaluation (the loop calls this; tests may drive
        it directly with a synthetic clock). Returns the action taken
        (``"scale_up"`` / ``"scale_down"``) or None."""
        now = time.monotonic() if now is None else float(now)
        sig = self.fleet.signals()
        with self._lock:
            last_shed = self._last_shed
            last_t = self._last_tick_t
            self._last_shed = float(sig.get("shed_total", 0.0))
            self._last_tick_t = now
        dt = max(now - last_t, 1e-9) if last_t is not None else None
        shed_rate = 0.0 if (dt is None or last_shed is None) else \
            max(float(sig.get("shed_total", 0.0)) - last_shed, 0.0) / dt
        breaching, quiet, reasons = self._classify(sig, shed_rate)
        up = int(sig.get("replicas_up", 0))
        self._set_gauge(up)
        if breaching:
            self._count("n_breaches", "autoscaler.breaches")
        with self._lock:
            self._breach_streak = self._breach_streak + 1 if breaching \
                else 0
            self._quiet_streak = self._quiet_streak + 1 if quiet else 0
            fire_up = (self._breach_streak >= self.breach_ticks
                       and up < self.max_replicas
                       and now - self._last_up >= self.scale_up_cooldown_s)
            fire_down = (not fire_up
                         and self._quiet_streak >= self.quiet_ticks
                         and up > self.min_replicas
                         and now - self._last_down
                         >= self.scale_down_cooldown_s)
        record = {"t": now, "signals": dict(sig),
                  "shed_rate": round(shed_rate, 4)}
        if fire_up:
            names = self.fleet.scale_up(1)
            with self._lock:
                self._last_up = now
                self._breach_streak = 0
                self._quiet_streak = 0
            self._count("n_scale_ups", "autoscaler.scale_ups")
            self._set_gauge(int(self.fleet.signals().get(
                "replicas_up", up + 1)))
            self.decisions.append({**record, "action": "scale_up",
                                   "replicas": names,
                                   "reason": "; ".join(reasons)})
            return "scale_up"
        if fire_down:
            name = self.fleet.drain_slot()
            with self._lock:
                self._last_down = now
                self._breach_streak = 0
                self._quiet_streak = 0
            if name is not None:
                self._count("n_scale_downs", "autoscaler.scale_downs")
                self.decisions.append({**record, "action": "scale_down",
                                       "replicas": [name],
                                       "reason": "quiet"})
                return "scale_down"
        return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "scale_ups": self.n_scale_ups,
                "scale_downs": self.n_scale_downs,
                "breaches": self.n_breaches,
                "breach_streak": self._breach_streak,
                "quiet_streak": self._quiet_streak,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
            }
