"""ProcessFleet: the process-isolated serving tier — out-of-process
replicas on the elastic liveness layer, request hedging, respawn.

The PR-12 :class:`~dask_ml_tpu.parallel.fleet.ServingFleet` routes over
replica THREADS: one interpreter, one XLA runtime, one fault domain. This
module is the same router discipline promoted to real OS-process
isolation — the fault domain dask-ml got for free from
``dask.distributed`` workers (PAPER.md, delegated distribution), rebuilt
on the substrate this repo owns:

- **Replicas are processes.** :meth:`ProcessFleet.start` writes the
  registered models to a registry snapshot
  (:func:`~dask_ml_tpu.parallel.replica.save_registry_snapshot`), then
  spawns one :class:`~dask_ml_tpu.parallel.replica.ReplicaHost` per
  replica with its own pinned device subset (``JAX_PLATFORMS`` /
  ``XLA_FLAGS`` / visible-devices env set BEFORE spawn). The router
  holds nothing but :class:`~dask_ml_tpu.parallel.fleet.FleetClient`
  connections — it is a PURE CLIENT: a replica segfault, OOM, or wedged
  runtime is contained by the kernel and can never take the router (or a
  sibling) down with it.
- **Liveness is fused.** Replica health combines the PR-8
  :class:`~dask_ml_tpu.parallel.elastic.FileHeartbeat` mtime-heartbeat/
  tombstone layer (real process death — no drain, the beats just STOP)
  with socket-level signals (process exit codes via ``poll()``, the wire
  connection dying, request deadlines). SIGTERM leaves a tombstone
  (observed immediately); SIGKILL leaves silence (observed within the
  heartbeat timeout, and usually much sooner through the dead socket).
- **Re-route + replay + respawn.** A dead replica's in-flight requests
  replay on survivors from the router's host-side copy, idempotent by
  request id — first resolution wins, a false positive costs duplicate
  compute, never a drop or a double resolve. The dead slot is then
  RESPAWNED: a fresh process loads the snapshot, re-warms every program
  through the exact serving staging path, and only then rejoins rotation
  (its address file is written after warmup), so a respawned replica
  serves with zero steady-state compiles.
- **Request hedging.** A request whose wait passes an ADAPTIVE threshold
  — ``hedge_factor`` × a rolling quantile of its target replica's
  observed latencies (EWMA fallback while the window fills, floored at
  ``hedge_min_s``) — is speculatively re-submitted to the next-best
  replica. First resolution wins under the same idempotency; the
  duplicate work is deliberate and counted (``serving.hedged`` /
  ``serving.hedge_wins`` telemetry mirrors at the increment sites).
  Hedging is what cuts tail latency when a replica straggles
  UNPREDICTABLY — the EWMA router can only avoid a replica that is
  predictably slow.

Telemetry (increment-site mirrors, docs/observability.md discipline):
``serving.hedged{replica=}`` / ``serving.hedge_wins{replica=}``,
``fleet.respawns{replica=,pid=}``, ``fleet.replica_deaths{replica=,
pid=}``, ``fleet.reroutes{replica=}``, ``fleet.spillover{replica=}``,
``fleet.shed{model=}``, ``fleet.timeouts`` (client-side, in
``FleetClient``), and the ``fleet.replica_up`` gauge — per-replica
labels carry the OS pid where one exists.

``bench.py --fleet-proc`` drills the tier — ``kill -9`` of a live
replica process mid-traffic, hedging A/B under an injected straggler,
zero dropped/double-resolved requests, bit-identity, zero respawn
steady-state compiles — committed as FLEET_r02.json (docs/serving.md,
"The process-isolated fleet"); the CI ``chaos`` job runs it scaled to 2
replica processes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal as signal_mod
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future
from typing import Optional

import numpy as np

from dask_ml_tpu.parallel import framing
from dask_ml_tpu.parallel.fleet import (
    FleetClient,
    FleetTimeoutError,
    _set_future,
)
from dask_ml_tpu.parallel.launcher import (
    LocalLauncher,
    MachineSpec,
    plan_placement,
)
from dask_ml_tpu.parallel.replica import save_registry_snapshot
from dask_ml_tpu.parallel.serving import (
    DeadlineExceeded,
    ServingClosed,
    ServingError,
    ServingQueueFull,
    ServingStopped,
    _fail_future,
)

__all__ = ["ProcessFleet"]


@dataclasses.dataclass(eq=False)
class _ProcReplica:
    """Router-side record of one replica process slot."""

    slot: int
    name: str
    machine: Optional[MachineSpec] = None
    proc: Optional[subprocess.Popen] = None
    pid: Optional[int] = None
    address: Optional[tuple] = None
    client: Optional[FleetClient] = None
    warmup: Optional[dict] = None
    #: snapshot-transfer accounting from the incarnation's announce
    #: (bytes_fetched / chunks_cached — the delta-reship gate's source)
    fetch: Optional[dict] = None
    gen: int = 0
    dead: bool = False
    #: autoscaler retirement in progress: out of rotation, SIGTERM sent,
    #: waiting for the graceful drain's tombstone
    draining: bool = False
    #: drained/failed slot that will never respawn (its corpse stays in
    #: the roster for exit-code accounting)
    retired: bool = False
    inflight: int = 0
    ewma_s: float = 0.0
    lat: deque = dataclasses.field(
        default_factory=lambda: deque(maxlen=128))


@dataclasses.dataclass(eq=False)
class _PRequest:
    """The router's host-side copy of one request — everything needed to
    replay it on a survivor or hedge it onto a sibling."""

    rid: str
    model: str
    method: str
    X: np.ndarray
    priority: int
    deadline_abs: Optional[float]
    future: Future
    attempts: int = 0
    hedges: int = 0
    #: resolution claim token: the success path claims (and counts)
    #: under the router lock BEFORE resolving the future, so the
    #: exactly-once accounting is already visible when a caller's
    #: ``result()`` returns — no duplicate callback can count twice,
    #: and no reader can observe the resolution before the count
    claimed: bool = False
    #: replica name -> dispatch perf_counter instant, for every attempt
    #: still awaiting a response (the hedge monitor reads wait times off
    #: this; the completion path pops its own entry)
    outstanding: dict = dataclasses.field(default_factory=dict)

    def remaining(self) -> Optional[float]:
        if self.deadline_abs is None:
            return None
        return self.deadline_abs - time.perf_counter()


class ProcessFleet:
    """N out-of-process serving replicas behind a hedging, respawning
    router (module docstring has the architecture).

    Register models BEFORE :meth:`start` — they ship to the replicas as
    a registry snapshot; the replica processes stage and warm them
    before taking traffic.

    Parameters
    ----------
    n_replicas : int
        Replica PROCESS count; each gets a disjoint device-subset env
        (CPU: ``len(devices)//n`` virtual devices each).
    max_batch_rows, max_queue
        Forwarded to every replica's serving loop.
    heartbeat_interval_s, heartbeat_timeout_s
        Child beat cadence / router staleness threshold.
    request_timeout_s : float, optional
        Per-wire-attempt deadline: an attempt with no response in time
        fails as :class:`~dask_ml_tpu.parallel.fleet.FleetTimeoutError`
        and re-routes — the backstop for a process that died while its
        socket stayed open.
    hedge : bool
        Enable speculative re-submission (see module docstring).
    hedge_quantile, hedge_factor, hedge_min_s, hedge_cold_s
        Threshold = ``max(hedge_min_s, hedge_factor * quantile)`` of the
        target replica's recent latencies (EWMA while the window is
        short, ``hedge_cold_s`` before any sample exists).
    respawn : bool
        Respawn dead replica slots (warm before rejoining rotation).
    max_replays : int, optional
        Re-route budget per request (default: replica count).
    straggle : dict, optional
        Chaos: ``{slot: (seconds, every)}`` — the replica process
        sleeps ``seconds`` every ``every``-th batch
        (:meth:`~dask_ml_tpu.parallel.faults.FaultInjector.
        straggle_replica`).
    kill_after_requests : dict, optional
        Chaos: ``{slot: n}`` — the replica SIGKILLs ITSELF after ``n``
        wire requests (:meth:`~dask_ml_tpu.parallel.faults.FaultInjector.
        kill_process`). One-shot: only the slot's FIRST incarnation
        carries the plan; the respawn comes back clean.
    machines : list of MachineSpec, optional
        The multi-machine roster (``parallel/launcher.py``): replica
        slots are placed across machines capacity-weighted by device
        inventory, each machine's workdir carries its own heartbeat
        fabric, the registry snapshot ships chunk-addressed over the
        snapshot wire (``parallel/snapshots.py``) through a per-machine
        chunk cache, and a machine ALL of whose replicas die at once is
        marked down — its in-flight requests replay on survivors and its
        slots respawn on surviving machines. Default: one implicit local
        machine (single-box behavior, snapshot loads straight from
        disk).
    launcher : Launcher, optional
        The remote-spawn hook (default :class:`~dask_ml_tpu.parallel.
        launcher.LocalLauncher`; an SSH-shaped deployment passes an
        :class:`~dask_ml_tpu.parallel.launcher.ExecLauncher`).
    fault_injector : FaultInjector, optional
        Router-side chaos: ``kill_machine`` plans are polled from the
        monitor (SIGKILL to every replica of the machine at a request
        count) and ``slow_link`` plans degrade the snapshot wire
        per machine.
    """

    #: same routing quantum as the in-process fleet: EWMA differences
    #: below this are jitter, not signal
    LATENCY_QUANTUM_S = 0.1

    def __init__(self, *, n_replicas: int = 2,
                 name: str = "pfleet",
                 workdir: Optional[str] = None,
                 max_batch_rows: int = 1024,
                 max_queue: int = 4096,
                 heartbeat_interval_s: float = 0.05,
                 heartbeat_timeout_s: float = 2.0,
                 monitor_interval_s: float = 0.01,
                 spawn_timeout_s: float = 300.0,
                 request_timeout_s: Optional[float] = None,
                 hedge: bool = True,
                 hedge_quantile: float = 0.5,
                 hedge_factor: float = 3.0,
                 hedge_min_s: float = 0.05,
                 hedge_cold_s: float = 0.5,
                 respawn: bool = True,
                 max_replays: Optional[int] = None,
                 devices_per_replica: Optional[int] = None,
                 straggle: Optional[dict] = None,
                 kill_after_requests: Optional[dict] = None,
                 machines: Optional[list] = None,
                 launcher=None,
                 fault_injector=None,
                 snapshot_chunk_bytes: Optional[int] = None):
        if int(n_replicas) < 1:
            raise ValueError("n_replicas must be >= 1")
        self.n_replicas = int(n_replicas)
        self.name = str(name)
        self.workdir = workdir
        self.max_batch_rows = int(max_batch_rows)
        self.max_queue = int(max_queue)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.monitor_interval_s = float(monitor_interval_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.request_timeout_s = request_timeout_s
        self.hedge = bool(hedge)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_cold_s = float(hedge_cold_s)
        self.respawn = bool(respawn)
        self.max_replays = max_replays
        self.devices_per_replica = devices_per_replica
        self._straggle = dict(straggle or {})
        self._kill_after = dict(kill_after_requests or {})
        self._machines_spec = list(machines) if machines else None
        self._launcher = launcher if launcher is not None else LocalLauncher()
        self._fault_injector = fault_injector
        self.snapshot_chunk_bytes = snapshot_chunk_bytes

        self._models: list = []  # (name, estimator, methods)
        self._lock = threading.Lock()
        self._procs: list = []
        self._inflight: dict = {}  # rid -> _PRequest
        self._live = None  # FileHeartbeat, set at start
        self._machines: list = []  # MachineSpec roster, set at start
        self._live_by_machine: dict = {}  # machine name -> FileHeartbeat
        self._machine_down: dict = {}  # machine name -> monotonic instant
        self._snap_server = None  # SnapshotServer, machines mode only
        self._next_slot = self.n_replicas  # scale_up slot numbering
        self._closing = False
        self._started = False
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._respawners: list = []
        self._rr = 0
        self._snapshot_path: Optional[str] = None
        # operational counters (telemetry mirrors at the increment sites)
        self.n_reroutes = 0
        self.n_spillovers = 0
        self.n_shed = 0
        self.n_replica_deaths = 0
        self.n_respawns = 0
        self.n_machine_deaths = 0
        self.n_drains = 0
        self.n_scale_ups = 0
        self.n_hedged = 0
        self.n_hedge_wins = 0
        self.n_results = 0  # futures THIS router resolved (exactly once
        #                     each — the zero-double-resolve accounting)
        self._timeouts_base = 0  # timeouts of replaced (dead) clients

    # -- lifecycle ---------------------------------------------------------

    def register(self, name: str, estimator, *, methods=None) -> None:
        """Record a fitted model for the registry snapshot (before
        :meth:`start`; the replica processes build the actual
        runners)."""
        if self._started:
            raise ServingError(
                "register models before start(): replicas load the "
                "registry snapshot at spawn")
        self._models.append((str(name), estimator, methods))

    def _child_env(self, rep: _ProcReplica) -> dict:
        """The device-pinning env for ``rep``: each process owns a
        DISJOINT device subset, decided before its jax ever initializes.
        On a rostered machine with a declared device inventory, the
        machine's devices are split among ITS slots — placement already
        weighted slot counts by inventory (``plan_placement``)."""
        import sys as sys_mod

        slot = rep.slot
        env = dict(os.environ)
        # the child imports dask_ml_tpu by module path (-m): make sure
        # the package root wins whatever the parent's cwd was
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        if "jax" in sys_mod.modules:
            # the usual case: the models registered here were fit in
            # this process, so its runtime already exists
            import jax

            backend = jax.default_backend()
            devs = jax.devices()
        else:
            # a jax-free router (snapshot written elsewhere): do NOT
            # initialize a runtime just to count devices — on TPU that
            # would grab the chips the children are about to pin. Pin
            # from configuration instead.
            backend = env.get("JAX_PLATFORMS", "cpu").split(",")[0] or "cpu"
            devs = []
        m = rep.machine
        if self.devices_per_replica is not None:
            per = int(self.devices_per_replica)
        elif m is not None and m.devices > 0:
            mates = sum(1 for r in self._procs
                        if not r.retired and r.machine is m) or 1
            per = max(m.devices // mates, 1)
        else:
            per = max(len(devs) // self.n_replicas, 1)
        if backend == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append(
                f"--xla_force_host_platform_device_count={per}")
            env["XLA_FLAGS"] = " ".join(flags)
        elif devs:
            # accelerator backends: pin the slot's contiguous device-id
            # slice via the visible-devices env the runtime honors
            ids = [str(d.id) for d in devs[slot * per:(slot + 1) * per]] \
                or [str(devs[slot % len(devs)].id)]
            var = ("TPU_VISIBLE_DEVICES" if backend == "tpu"
                   else "CUDA_VISIBLE_DEVICES")
            env[var] = ",".join(ids)
        # accelerator backend with no parent-side device view: inherit
        # the env as-is (the operator pins visible devices per replica)
        return env

    def _hb(self, rep: _ProcReplica):
        """The heartbeat fabric ``rep`` beats on: its MACHINE's workdir
        (the single-box fleet's one fabric is just the implicit local
        machine's)."""
        if rep.machine is not None:
            hb = self._live_by_machine.get(rep.machine.name)
            if hb is not None:
                return hb
        return self._live

    def _rep_workdir(self, rep: _ProcReplica) -> str:
        return rep.machine.workdir if rep.machine is not None \
            else self.workdir

    def _spawn(self, rep: _ProcReplica) -> None:
        """Launch ``rep``'s process on its machine via the launcher
        (does not wait for readiness)."""
        m = rep.machine
        wd = self._rep_workdir(rep)
        self._hb(rep).clear(rep.name)  # respawn hygiene: no inherited death
        addr_path = os.path.join(wd, f"{rep.name}.addr.json")
        try:
            os.unlink(addr_path)
        except OSError:
            pass
        cmd = [sys.executable, "-m", "dask_ml_tpu.parallel.replica",
               "--name", rep.name,
               "--workdir", wd,
               "--max-batch-rows", str(self.max_batch_rows),
               "--max-queue", str(self.max_queue),
               "--heartbeat-interval-s", str(self.heartbeat_interval_s)]
        if self._snap_server is not None:
            # machines mode: the replica FETCHES the snapshot over the
            # chunk wire through its machine's cache, then loads the
            # assembled local copy
            host, port = self._snap_server.address
            cmd += ["--snapshot", os.path.join(wd, f"{rep.name}.reg"),
                    "--snapshot-server", f"{host}:{port}",
                    "--snapshot-cache", os.path.join(wd, "chunk-cache"),
                    "--machine", m.name if m is not None else ""]
        else:
            cmd += ["--snapshot", self._snapshot_path]
        if rep.slot in self._straggle:
            seconds, every = self._straggle[rep.slot]
            cmd += ["--straggle-s", str(float(seconds)),
                    "--straggle-every", str(int(every))]
        if rep.slot in self._kill_after:
            # one-shot, like the FaultInjector plan it arms: only the
            # FIRST incarnation carries the kill — re-arming on respawn
            # would make the chaos slot a permanent death loop
            cmd += ["--kill-after-requests",
                    str(int(self._kill_after.pop(rep.slot)))]
        target = m if m is not None else MachineSpec(
            name="local", workdir=self.workdir)
        rep.proc = self._launcher.spawn(
            target, cmd, env=self._child_env(rep),
            log_path=os.path.join(wd, f"{rep.name}.log"))
        rep.pid = rep.proc.pid
        rep.gen += 1

    def _wait_ready(self, rep: _ProcReplica,
                    timeout: Optional[float] = None) -> None:
        """Block until ``rep``'s process announced its warmed server
        (address file), then connect. Raises on exit or timeout."""
        timeout = self.spawn_timeout_s if timeout is None else timeout
        addr_path = os.path.join(self._rep_workdir(rep),
                                 f"{rep.name}.addr.json")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._closing:
                raise ServingStopped(
                    f"process fleet {self.name!r} is stopping")
            rc = rep.proc.poll()
            if rc is not None:
                raise ServingStopped(
                    f"replica process {rep.name!r} exited with {rc} "
                    "before becoming ready (see its .log in the fleet "
                    "workdir)")
            if os.path.exists(addr_path):
                with open(addr_path) as f:
                    info = json.load(f)
                if info.get("pid") == rep.pid:
                    rep.address = (info["host"], int(info["port"]))
                    rep.warmup = info.get("warmup")
                    rep.fetch = info.get("snapshot_fetch")
                    if rep.client is not None:
                        # the dead incarnation's timeout count must not
                        # vanish from stats() when its client is replaced
                        with self._lock:
                            self._timeouts_base += rep.client.n_timeouts
                    rep.client = FleetClient(
                        rep.address, timeout=10.0,
                        request_timeout=self.request_timeout_s,
                        shm=(rep.machine.shm
                             if rep.machine is not None else True))
                    rep.client.ping(timeout=30.0)
                    rep.lat.clear()
                    rep.ewma_s = 0.0
                    rep.inflight = 0
                    return
            time.sleep(0.01)
        raise FleetTimeoutError(
            f"replica process {rep.name!r} (pid {rep.pid}) not ready "
            f"within {timeout}s")

    def start(self) -> "ProcessFleet":
        from dask_ml_tpu.parallel import telemetry
        from dask_ml_tpu.parallel.elastic import FileHeartbeat

        if self._started:
            return self
        if not self._models:
            raise ServingError(
                "register at least one model before start()")
        if self.workdir is None:
            self.workdir = tempfile.mkdtemp(
                prefix=f"dask_ml_tpu_{self.name}_")
        os.makedirs(self.workdir, exist_ok=True)
        # the roster: explicit machines, or ONE implicit local machine
        # (single-box fleets behave exactly as before — same workdir,
        # same heartbeat fabric, snapshot loads straight from disk)
        self._machines = (list(self._machines_spec)
                          if self._machines_spec
                          else [MachineSpec(name="local",
                                            workdir=self.workdir)])
        names = [m.name for m in self._machines]
        if len(set(names)) != len(names):
            raise ValueError(f"machine names must be unique: {names}")
        self._live_by_machine = {}
        for m in self._machines:
            os.makedirs(m.workdir, exist_ok=True)
            self._live_by_machine[m.name] = FileHeartbeat(m.workdir)
        self._live = self._live_by_machine[self._machines[0].name]
        self._machine_down = {}
        self._snapshot_path = os.path.join(self.workdir, "registry.reg")
        save_registry_snapshot(self._snapshot_path, self._models)
        if self._machines_spec:
            # machines mode: the registry ships chunk-addressed over the
            # snapshot wire, not by path (parallel/snapshots.py)
            from dask_ml_tpu.parallel.snapshots import (
                DEFAULT_CHUNK_BYTES,
                SnapshotServer,
            )

            self._snap_server = SnapshotServer(
                self._snapshot_path,
                chunk_bytes=(self.snapshot_chunk_bytes
                             or DEFAULT_CHUNK_BYTES),
                fault_injector=self._fault_injector).start()
        placement = plan_placement(self.n_replicas, self._machines)
        self._procs = [
            _ProcReplica(slot=i, name=f"{self.name}-p{i}",
                         machine=placement[i])
            for i in range(self.n_replicas)]
        self._next_slot = self.n_replicas
        try:
            for rep in self._procs:
                self._spawn(rep)
            for rep in self._procs:
                self._wait_ready(rep)
        except BaseException:
            # partial-start hygiene: replicas are independent OS
            # processes — a failed start must not leave the ones that
            # DID come up serving forever
            for rep in self._procs:
                self._reap_slot(rep)
            if self._snap_server is not None:
                self._snap_server.stop()
            raise
        self._closing = False
        self._started = True
        self._telemetry_inherit = telemetry.enabled()
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name=f"{self.name}-monitor",
            daemon=True)
        self._monitor.start()
        self._set_replica_up()
        return self

    def __enter__(self) -> "ProcessFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _reap_slot(self, rep: _ProcReplica) -> None:
        """Tear one replica slot down hard-but-politely: close the wire,
        SIGTERM the process, escalate to SIGKILL if it lingers."""
        if rep.client is not None:
            rep.client.close()
        if rep.proc is None:
            return
        if rep.proc.poll() is None:
            rep.proc.terminate()
        try:
            rep.proc.wait(10.0)
        except subprocess.TimeoutExpired:
            rep.proc.kill()
            try:
                rep.proc.wait(10.0)
            except subprocess.TimeoutExpired:
                pass

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop the fleet: SIGTERM every replica (graceful drain: each
        flushes, tombstones, exits 0), reap, fail whatever replay
        bookkeeping remains."""
        with self._lock:
            self._closing = True
        self._monitor_stop.set()
        m = self._monitor
        if m is not None and m.is_alive() \
                and m is not threading.current_thread():
            m.join(timeout)
        # a respawn racing this stop re-checks _closing after readiness
        # and reaps its own child; give it a bounded chance to finish
        for t in list(self._respawners):
            t.join(15.0)
        for rep in self._procs:
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.terminate()
        deadline = time.monotonic() + (timeout or 30.0)
        for rep in self._procs:
            if rep.proc is None:
                continue
            try:
                rep.proc.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(10.0)
        for rep in self._procs:
            if rep.client is not None:
                rep.client.close()
        if self._snap_server is not None:
            self._snap_server.stop()
        with self._lock:
            leftovers = list(self._inflight.values())
            self._inflight.clear()
        for freq in leftovers:
            _fail_future(freq.future, ServingStopped(
                f"process fleet {self.name!r} stopped"))

    # -- telemetry helpers -------------------------------------------------

    def _telemetry_on(self) -> bool:
        """Telemetry knob for the router's mirror sites. Completion
        callbacks and respawn threads run on wire-reader/worker threads
        that never saw the creating thread's thread-local scope — like
        the loops' dispatch threads, they inherit the scope that was
        effective at :meth:`start` (so ``config_context(telemetry=True)``
        around ``start()`` behaves the way it reads)."""
        from dask_ml_tpu.parallel import telemetry

        return telemetry.enabled() or getattr(
            self, "_telemetry_inherit", False)

    def _set_replica_up(self) -> None:
        from dask_ml_tpu.parallel import telemetry

        if self._telemetry_on():
            telemetry.metrics().gauge("fleet.replica_up").set(
                self.replicas_up())

    def _count(self, attr: str, counter: str, **labels) -> None:
        """Bump an operational counter AND its registry mirror at this
        increment site (docs/observability.md discipline)."""
        from dask_ml_tpu.parallel import telemetry

        with self._lock:
            setattr(self, attr, getattr(self, attr) + 1)
        if self._telemetry_on():
            telemetry.metrics().counter(counter, **labels).inc()

    # -- routing -----------------------------------------------------------

    @property
    def max_request_rows(self) -> int:
        return self.max_batch_rows

    def replicas_up(self) -> int:
        return sum(1 for rep in self._procs
                   if not rep.dead and not rep.draining
                   and rep.client is not None)

    def _eligible(self, exclude) -> list:
        return [rep for rep in self._procs
                if rep.name not in exclude and not rep.dead
                and not rep.draining and rep.client is not None]

    def _pick(self, exclude) -> Optional[_ProcReplica]:
        """Least-loaded routing on (in-flight attempts, quantized
        client-observed latency EWMA, round-robin) — the same shape as
        the in-process router, but every signal is client-side: the
        router holds no loop references, only wires."""
        live = self._eligible(exclude)
        if not live:
            return None
        with self._lock:
            self._rr += 1
            rr = self._rr
        return min(
            live,
            key=lambda rep: (rep.inflight,
                             int(rep.ewma_s / self.LATENCY_QUANTUM_S),
                             (rep.slot + rr) % max(len(self._procs), 1)))

    def submit(self, model: str, X, method: str = "predict", *,
               priority: int = 0, deadline: Optional[float] = None,
               request_id: Optional[str] = None) -> Future:
        """Route one request to the least-loaded live replica process;
        returns a router-level Future that survives replica-process
        death (re-route + replay + hedge, idempotent by request id)."""
        if self._closing or not self._started:
            raise ServingStopped(
                f"process fleet {self.name!r} is not accepting requests")
        rid = str(request_id) if request_id is not None else uuid.uuid4().hex
        with self._lock:
            existing = self._inflight.get(rid)
            if existing is not None:
                return existing.future
        now = time.perf_counter()
        if deadline is not None and float(deadline) <= 0.0:
            self._count("n_shed", "fleet.shed", model=str(model))
            raise DeadlineExceeded(
                f"request deadline {float(deadline):.3f}s is already "
                "past at fleet admission")
        freq = _PRequest(
            rid=rid, model=str(model), method=str(method),
            X=np.asarray(X), priority=int(priority),
            deadline_abs=None if deadline is None else now + float(deadline),
            future=Future())
        self._route(freq, sync=True)
        return freq.future

    def call(self, model: str, X, method: str = "predict", *,
             priority: int = 0, deadline: Optional[float] = None,
             timeout: Optional[float] = None) -> np.ndarray:
        from dask_ml_tpu.parallel import telemetry

        with telemetry.span("fleet.request", model=str(model),
                            method=str(method)):
            return self.submit(model, X, method=method, priority=priority,
                               deadline=deadline).result(timeout)

    def _replay_budget(self) -> int:
        return (self.max_replays if self.max_replays is not None
                else max(len(self._procs), 1))

    def _terminal(self, freq: _PRequest, exc: BaseException,
                  sync: bool) -> None:
        with self._lock:
            self._inflight.pop(freq.rid, None)
        if sync:
            raise exc
        _fail_future(freq.future, exc)

    def _route(self, freq: _PRequest, *, sync: bool,
               exclude: Optional[set] = None,
               cause: Optional[BaseException] = None) -> None:
        """Place ``freq`` on a replica process. ``sync=True`` (first
        admission) propagates terminal errors to the caller;
        ``sync=False`` (replay/hedge-failure path) sets them on the
        router future. ``cause`` is the failure that triggered a replay
        — surfaced instead of a generic no-live-replica error when the
        route dead-ends (the replica that timed a request out may be
        perfectly alive)."""
        exclude = set() if exclude is None else set(exclude)
        while True:
            if self._closing:
                self._terminal(freq, ServingStopped(
                    f"process fleet {self.name!r} is stopping"), sync)
                return
            rep = self._pick(exclude)
            if rep is None:
                self._terminal(freq, cause if cause is not None
                               else ServingStopped(
                                   f"process fleet {self.name!r} has no "
                                   "live replica"),
                               sync)
                return
            remaining = freq.remaining()
            if remaining is not None and remaining <= 0.0:
                self._count("n_shed", "fleet.shed", model=freq.model)
                self._terminal(freq, DeadlineExceeded(
                    f"request {freq.rid} deadline passed during routing"),
                    sync)
                return
            if self._dispatch(freq, rep, hedge=False):
                return
            exclude.add(rep.name)

    def _dispatch(self, freq: _PRequest, rep: _ProcReplica, *,
                  hedge: bool) -> bool:
        """One wire attempt of ``freq`` on ``rep``; False when the send
        itself failed (caller excludes the replica and retries)."""
        remaining = freq.remaining()
        t0 = time.perf_counter()
        try:
            cfut = rep.client.submit(
                freq.model, freq.X, method=freq.method,
                priority=freq.priority, deadline=remaining,
                timeout=self.request_timeout_s)
        except Exception:  # noqa: BLE001 — transport refusal, not request
            return False
        with self._lock:
            freq.attempts += 1
            rep.inflight += 1
            freq.outstanding[rep.name] = t0
            self._inflight[freq.rid] = freq
        cfut.add_done_callback(
            lambda f, freq=freq, rep=rep, t0=t0, hedge=hedge:
            self._on_client_done(freq, rep, t0, hedge, f))
        return True

    def _observe_latency(self, rep: _ProcReplica, dt: float) -> None:
        with self._lock:
            rep.lat.append(dt)
            rep.ewma_s = (dt if rep.ewma_s == 0.0
                          else 0.7 * rep.ewma_s + 0.3 * dt)

    def _maybe_retire(self, freq: _PRequest) -> None:
        """Drop ``freq`` from the in-flight table once its future is
        resolved and no attempt is still outstanding."""
        with self._lock:
            if freq.future.done() and not freq.outstanding:
                self._inflight.pop(freq.rid, None)

    def _on_client_done(self, freq: _PRequest, rep: _ProcReplica,
                        t0: float, hedge: bool, cfut) -> None:
        """One wire attempt completed (on the client's reader/reaper
        thread). Success resolves the router future (first resolution
        wins); transport-class failures re-route; request-class failures
        are terminal.

        Replay ownership: popping the attempt's ``outstanding`` entry IS
        the replay ticket. When a replica dies, this callback (fired by
        the client close) and ``_declare_dead``'s victim sweep both see
        the same failed attempt — whoever pops the entry first owns the
        reroute; the other path skips it, so one failed attempt never
        burns two units of replay budget."""
        with self._lock:
            rep.inflight = max(rep.inflight - 1, 0)
            owned = freq.outstanding.get(rep.name) == t0
            if owned:
                freq.outstanding.pop(rep.name, None)
        try:
            result = cfut.result()
        except ServingQueueFull:
            # remote backpressure: spill over to a sibling — same replay
            # ticket as the transport branch (a racing _declare_dead may
            # already have claimed this attempt)
            if owned:
                self._count("n_spillovers", "fleet.spillover",
                            replica=rep.name)
                self._reroute_or_fail(freq, rep, ServingQueueFull(
                    f"replica {rep.name!r} queue full"))
            else:
                self._maybe_retire(freq)
        except framing.PayloadError as e:
            # request-class, deterministic (e.g. the model's output is
            # not wire-encodable): replaying it on a sibling would just
            # fail n_replicas times — fail THIS caller once, like the
            # in-process tier does. Must precede the transport branch:
            # PayloadError subclasses FrameError.
            self._terminal(freq, e, sync=False)
        except (ServingStopped, ServingClosed, FleetTimeoutError,
                OSError, framing.FrameError) as e:
            # the REPLICA (or its wire) went away, not the request —
            # reroute only if this callback owns the attempt (see
            # docstring; _declare_dead may have claimed it already)
            if owned:
                self._reroute_or_fail(freq, rep, e)
            else:
                self._maybe_retire(freq)
        except DeadlineExceeded as e:
            if not freq.future.done():
                self._count("n_shed", "fleet.shed", model=freq.model)
            self._terminal(freq, e, sync=False)
        except BaseException as e:  # noqa: BLE001 — the request's error
            self._terminal(freq, e, sync=False)
        else:
            self._observe_latency(rep, time.perf_counter() - t0)
            with self._lock:
                won = not freq.claimed and not freq.future.done()
                if won:
                    freq.claimed = True
                    self.n_results += 1  # counted BEFORE the resolve:
                    #                      see _PRequest.claimed
            if won:
                if _set_future(freq.future, result):
                    if hedge:
                        self._count("n_hedge_wins", "serving.hedge_wins",
                                    replica=rep.name)
                else:
                    with self._lock:  # client cancelled under us
                        self.n_results -= 1
            self._maybe_retire(freq)

    def _reroute_or_fail(self, freq: _PRequest, rep: _ProcReplica,
                         cause: BaseException) -> None:
        if freq.future.done():
            self._maybe_retire(freq)
            return
        if freq.attempts > self._replay_budget():
            with self._lock:
                outstanding = bool(freq.outstanding)
            if outstanding:
                # another attempt (a hedge, an earlier dispatch on a
                # slow-but-healthy replica) may still resolve this
                # request — failing it now would hand the caller an
                # error for work the fleet is about to finish. If that
                # attempt fails too, ITS failure path lands here with
                # nothing outstanding and terminates.
                return
            self._terminal(freq, cause, sync=False)
            return
        if not self._eligible({rep.name}):
            # nowhere to replay: surface the REAL cause, and don't count
            # a reroute that never went out
            self._terminal(freq, cause, sync=False)
            return
        self._count("n_reroutes", "fleet.reroutes", replica=rep.name)
        self._route(freq, sync=False, exclude={rep.name}, cause=cause)

    # -- hedging -----------------------------------------------------------

    def _hedge_threshold(self, rep: _ProcReplica) -> float:
        """Adaptive hedge trigger for requests outstanding on ``rep``:
        ``hedge_factor`` × the ``hedge_quantile`` of its recent observed
        latencies (EWMA while the window is short, ``hedge_cold_s``
        before any), floored at ``hedge_min_s``. Adaptive means a
        uniformly-slow replica raises its own bar — hedging targets the
        TAIL, not the mean the router already balances on."""
        with self._lock:
            samples = list(rep.lat)
            ewma = rep.ewma_s
        if len(samples) >= 8:
            base = float(np.quantile(samples, self.hedge_quantile))
        elif ewma > 0.0:
            base = ewma
        else:
            return self.hedge_cold_s
        return max(self.hedge_min_s, self.hedge_factor * base)

    def _hedge_scan(self) -> None:
        now = time.perf_counter()
        with self._lock:
            candidates = [freq for freq in self._inflight.values()
                          if not freq.future.done() and freq.hedges < 1
                          and freq.outstanding]
        by_name = {rep.name: rep for rep in self._procs}
        # one threshold per replica per scan — recomputing the quantile
        # per outstanding attempt would put O(candidates) redundant
        # np.quantile calls on the monitor thread every tick
        thresholds: dict = {}
        for freq in candidates:
            with self._lock:
                waits = list(freq.outstanding.items())
            for rep_name, t0 in waits:
                rep = by_name.get(rep_name)
                if rep is None:
                    continue
                thr = thresholds.get(rep_name)
                if thr is None:
                    thr = thresholds[rep_name] = \
                        self._hedge_threshold(rep)
                if now - t0 > thr:
                    # exclude from the locked snapshot (`waits`), not the
                    # live dict a reader callback may be mutating
                    target = self._pick(
                        exclude={n for n, _ in waits} | {rep_name})
                    if target is None:
                        break
                    # consume the budget only when the hedge actually
                    # went out — a failed send (target died under us)
                    # leaves the request eligible for a later scan
                    freq.hedges += 1
                    if self._dispatch(freq, target, hedge=True):
                        self._count("n_hedged", "serving.hedged",
                                    replica=target.name)
                    else:
                        freq.hedges -= 1
                    break

    # -- health monitoring + respawn ---------------------------------------

    def _monitor_loop(self) -> None:
        import contextlib

        from dask_ml_tpu import config as config_lib

        ctx = (config_lib.config_context(telemetry=True)
               if getattr(self, "_telemetry_inherit", False)
               else contextlib.nullcontext())
        with ctx:
            while not self._monitor_stop.wait(self.monitor_interval_s):
                # the monitor is the fleet's ONLY death detector and
                # respawner: one surprised tick must never kill it
                try:
                    self._monitor_tick()
                except Exception:  # noqa: BLE001
                    import logging

                    logging.getLogger(__name__).exception(
                        "process fleet %r: monitor tick failed "
                        "(continuing)", self.name)

    def _maybe_kill_machines(self) -> None:
        """Deliver armed ``kill_machine`` plans: SIGKILL every live
        replica pid on the plan's machine once the fleet has resolved
        the plan's request count — all the machine's heartbeats stop AT
        ONCE, which is exactly the signature machine-death detection
        keys on."""
        inj = self._fault_injector
        if inj is None:
            return
        with self._lock:
            n = self.n_results
        for m in self._machines:
            if not inj.should_kill_machine(m.name, n):
                continue
            for rep in self._procs:
                if rep.machine is m and not rep.dead and not rep.retired \
                        and rep.pid is not None:
                    try:
                        os.kill(rep.pid, signal_mod.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass

    def _monitor_tick(self) -> None:
        if self.hedge:
            self._hedge_scan()
        self._maybe_kill_machines()
        # PASS 1 — observe: compute each live replica's death verdict
        # (with its CURRENT generation — the gen guard in _declare_dead
        # makes a stale verdict, read from a proc a racing respawn
        # already replaced, a no-op instead of a false kill)
        pending = []
        for rep in self._procs:
            if rep.dead or rep.client is None:
                continue
            gen = rep.gen
            reason = None
            rc = rep.proc.poll() if rep.proc is not None else None
            hb = self._hb(rep)
            if rc is not None:
                reason = f"process exited with {rc}"
            elif hb.has_tombstone(rep.name):
                reason = "tombstone (graceful leave)"
            else:
                age = hb.age(rep.name)
                if age is not None \
                        and age > self.heartbeat_timeout_s:
                    reason = f"heartbeat stale {age:.2f}s"
            if reason is not None and not self._closing:
                pending.append((rep, reason, gen))
        if not pending:
            return
        # PASS 2 — mark machine deaths BEFORE any slot is declared (so
        # the respawns this tick triggers already see the machine as
        # down and place elsewhere): a machine is dead when every
        # non-retired slot on it is dying/dead at once, none gracefully
        self._mark_machine_deaths(pending)
        for rep, reason, gen in pending:
            self._declare_dead(rep, reason, gen=gen)

    def _mark_machine_deaths(self, pending: list) -> None:
        if len(self._machines) < 2:
            return
        dying = {rep.name: reason for rep, reason, _gen in pending
                 if "tombstone" not in reason}
        for m in self._machines:
            if m.name in self._machine_down:
                continue
            slots = [rep for rep in self._procs
                     if rep.machine is m and not rep.retired]
            if not slots:
                continue
            now_dying = [rep for rep in slots if rep.name in dying]
            if not now_dying:
                continue
            if all(rep.dead or rep.name in dying for rep in slots):
                self._machine_down[m.name] = time.monotonic()
                import logging

                logging.getLogger(__name__).warning(
                    "process fleet %r: MACHINE %s declared dead "
                    "(%d replicas down at once)",
                    self.name, m.name, len(now_dying))
                self._count("n_machine_deaths", "fleet.machine_deaths",
                            machine=m.name)

    def _declare_dead(self, rep: _ProcReplica, reason: str, *,
                      gen: Optional[int] = None) -> None:
        """Terminal for this incarnation of the replica: out of
        rotation, in-flight attempts replayed on survivors, then (if
        enabled) the slot respawns — warm first, rotation after.

        ``gen`` is the incarnation the caller OBSERVED dying; if the
        slot respawned in between (gen moved on), the verdict is stale
        and this is a no-op — without the guard, a monitor thread
        descheduled between poll() and here could declare a freshly
        respawned healthy process dead (double-respawn race)."""
        import logging

        if rep.dead:
            return
        if gen is not None and gen != rep.gen:
            return
        if rep.draining:
            # autoscaler retirement completing (tombstone after graceful
            # drain): retire the slot — never respawn, never count a
            # death; the drain finished every queued request first
            rep.dead = True
            rep.retired = True
            self._set_replica_up()
            if rep.client is not None:
                rep.client.close()
            with self._lock:
                victims = [freq for freq in self._inflight.values()
                           if rep.name in freq.outstanding
                           and not freq.future.done()]
            cause = ServingStopped(
                f"replica process {rep.name!r} drained ({reason})")
            for freq in victims:
                with self._lock:
                    owned = freq.outstanding.pop(rep.name, None) \
                        is not None
                if owned:
                    self._reroute_or_fail(freq, rep, cause)
            self._count("n_drains", "fleet.drains", replica=rep.name)
            logging.getLogger(__name__).info(
                "process fleet %r: replica %s (pid %s) drained and "
                "retired: %s", self.name, rep.name, rep.pid, reason)
            return
        rep.dead = True
        self._set_replica_up()
        logging.getLogger(__name__).warning(
            "process fleet %r: replica %s (pid %s) declared dead: %s",
            self.name, rep.name, rep.pid, reason)
        self._count("n_replica_deaths", "fleet.replica_deaths",
                    replica=rep.name, pid=rep.pid)
        # close the wire: its pending futures fail over via their
        # completion callbacks (idempotent with the replay below)
        if rep.client is not None:
            rep.client.close()
        with self._lock:
            victims = [freq for freq in self._inflight.values()
                       if rep.name in freq.outstanding
                       and not freq.future.done()]
        cause = ServingStopped(
            f"replica process {rep.name!r} died ({reason})")
        for freq in victims:
            # popping the outstanding entry claims the replay ticket —
            # the close-triggered completion callback checks the same
            # entry, so each failed attempt reroutes exactly once
            # (rep.inflight is left to the callback's own decrement; a
            # respawn resets it anyway)
            with self._lock:
                owned = freq.outstanding.pop(rep.name, None) is not None
            if owned:
                self._reroute_or_fail(freq, rep, cause)
        if self.respawn and not self._closing:
            t = threading.Thread(
                target=self._respawn, args=(rep,),
                name=f"{rep.name}-respawn", daemon=True)
            # prune finished respawners so a death-looping fleet does
            # not grow this list (and stop()'s join) without bound
            self._respawners = [r for r in self._respawners
                                if r.is_alive()]
            self._respawners.append(t)
            t.start()

    def _pick_spawn_machine(self, exclude_rep=None) -> MachineSpec:
        """The roster row a (re)spawn lands on: surviving machines only
        (down-marked ones excluded, with a fallback to the full roster
        so a single-machine fleet still respawns locally), least loaded
        by live slot count weighted by device inventory."""
        candidates = [m for m in self._machines
                      if m.name not in self._machine_down]
        if not candidates:
            candidates = list(self._machines)
        loads: dict = {m.name: 0 for m in candidates}
        for r in self._procs:
            if r is exclude_rep or r.retired or r.machine is None:
                continue
            if r.machine.name in loads:
                loads[r.machine.name] += 1
        return plan_placement(1, candidates, loads=loads)[0]

    def _respawn(self, rep: _ProcReplica) -> None:
        """Bring the dead slot back: fresh process, snapshot load
        (delta-only through the machine's chunk cache in machines mode),
        warmup through the exact serving staging path, THEN rejoin
        rotation (the address file only appears after warmup). The slot
        is PLACED before spawn: a down-marked machine is skipped, so a
        machine loss respawns its slots on survivors. A stop() racing
        this re-checks ``_closing`` on both sides of the spawn — an
        incarnation born after the terminate loop ran is reaped HERE,
        never orphaned."""
        import logging

        old_client = rep.client
        try:
            if rep.proc is not None:
                try:
                    rep.proc.wait(5.0)  # reap the corpse
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
            if self._closing:
                return
            target = self._pick_spawn_machine(exclude_rep=rep)
            if rep.machine is not None and target is not rep.machine:
                logging.getLogger(__name__).warning(
                    "process fleet %r: respawning %s on machine %s "
                    "(was %s)", self.name, rep.name, target.name,
                    rep.machine.name)
            rep.machine = target
            self._spawn(rep)
            self._wait_ready(rep)
        except Exception as e:  # noqa: BLE001 — slot stays dead, visibly
            logging.getLogger(__name__).warning(
                "process fleet %r: respawn of %s failed: %r",
                self.name, rep.name, e)
            self._reap_slot(rep)
            return
        finally:
            if old_client is not None:
                old_client.close()
        if self._closing:
            # stop() ran while the child was warming: it never entered
            # the terminate loop's view, so it is ours to drain
            self._reap_slot(rep)
            return
        rep.dead = False
        # the machine a slot successfully came up on is alive by
        # construction: clear a stale down-mark so later placements may
        # use it again
        if rep.machine is not None:
            self._machine_down.pop(rep.machine.name, None)
        self._count("n_respawns", "fleet.respawns",
                    replica=rep.name, pid=rep.pid)
        self._set_replica_up()

    # -- scale (the autoscaler's levers) -----------------------------------

    def scale_up(self, k: int = 1) -> list:
        """Add ``k`` fresh replica slots (placed on the least-loaded
        surviving machines), each warmed through the full staging path
        before joining rotation — the autoscaler's breach response.
        Returns the new replica names. Blocks until ready: the caller's
        control loop not ticking while capacity comes up is itself a
        cooldown."""
        if not self._started or self._closing:
            raise ServingStopped(
                f"process fleet {self.name!r} is not running")
        names = []
        for _ in range(int(k)):
            with self._lock:
                slot = self._next_slot
                self._next_slot += 1
            rep = _ProcReplica(
                slot=slot, name=f"{self.name}-p{slot}",
                machine=self._pick_spawn_machine(), dead=True)
            # visible to the roster while warming, but dead=True keeps
            # it out of rotation until _wait_ready connects it
            self._procs.append(rep)
            try:
                self._spawn(rep)
                self._wait_ready(rep)
            except BaseException:
                rep.retired = True
                self._reap_slot(rep)
                raise
            rep.dead = False
            names.append(rep.name)
            self._count("n_scale_ups", "fleet.scale_ups",
                        replica=rep.name)
            self._set_replica_up()
        return names

    def drain_slot(self, name: Optional[str] = None) -> Optional[str]:
        """Retire one replica gracefully — the autoscaler's quiet
        response: TOMBSTONE, not kill. The slot leaves rotation
        immediately, gets SIGTERM (graceful drain: it finishes its
        queue, resolves every future, tombstones, exits 0), and the
        monitor retires it when the tombstone lands — no respawn, no
        death counter. Returns the draining replica's name, or None when
        draining would leave the fleet empty."""
        live = self._eligible(set())
        if len(live) <= 1:
            return None
        if name is not None:
            picked = [rep for rep in live if rep.name == name]
            if not picked:
                return None
            rep = picked[0]
        else:
            # least-loaded, newest slot first: scale-down unwinds
            # scale-up
            rep = min(live, key=lambda r: (r.inflight, -r.slot))
        rep.draining = True
        self._set_replica_up()
        if rep.proc is not None and rep.proc.poll() is None:
            rep.proc.terminate()
        return rep.name

    def signals(self) -> dict:
        """The autoscaler's input (:class:`~dask_ml_tpu.parallel.
        autoscaler.Autoscaler`): pooled p99 of router-observed request
        latencies, total in-flight depth, cumulative shed count, live
        replica count — all signals the fleet already exports, read
        without touching a replica."""
        with self._lock:
            lats = [dt for rep in self._procs if not rep.retired
                    for dt in rep.lat]
            queue = sum(rep.inflight for rep in self._procs
                        if not rep.dead)
            shed = self.n_shed
        p99 = float(np.quantile(lats, 0.99)) if lats else 0.0
        return {"p99_s": p99, "queue_depth": float(queue),
                "shed_total": float(shed),
                "replicas_up": self.replicas_up()}

    # -- observability -----------------------------------------------------

    def remote_stats(self, timeout: float = 10.0) -> dict:
        """Per-replica ``op="stats"`` snapshots (pid, queue depth,
        latency EWMA, steady-state compile count) from every live
        replica process."""
        out = {}
        for rep in self._procs:
            if rep.dead or rep.client is None:
                continue
            try:
                out[rep.name] = rep.client.stats(timeout=timeout)
            except (ServingError, OSError) as e:
                out[rep.name] = {"error": repr(e)}
        return out

    def stats(self) -> dict:
        with self._lock:
            counters = {
                "reroutes": self.n_reroutes,
                "spillovers": self.n_spillovers,
                "shed": self.n_shed,
                "replica_deaths": self.n_replica_deaths,
                "respawns": self.n_respawns,
                "machine_deaths": self.n_machine_deaths,
                "drains": self.n_drains,
                "scale_ups": self.n_scale_ups,
                "hedged": self.n_hedged,
                "hedge_wins": self.n_hedge_wins,
                "results": self.n_results,
                "inflight": len(self._inflight),
            }
        counters["timeouts"] = self._timeouts_base + sum(
            rep.client.n_timeouts for rep in self._procs
            if rep.client is not None)
        snap = self._snap_server
        return {
            "name": self.name,
            "replicas_up": self.replicas_up(),
            "machines": {m.name: {
                "workdir": m.workdir,
                "devices": m.devices,
                "down": m.name in self._machine_down,
                "replicas": [rep.name for rep in self._procs
                             if rep.machine is m and not rep.retired],
            } for m in self._machines},
            "snapshot_server": None if snap is None else {
                "address": list(snap.address),
                "manifests": snap.n_manifests,
                "chunks": snap.n_chunks,
                "bytes_sent": snap.n_bytes_sent,
            },
            "replicas": {rep.name: {
                "pid": rep.pid,
                "gen": rep.gen,
                "dead": rep.dead,
                "draining": rep.draining,
                "retired": rep.retired,
                "machine": None if rep.machine is None
                else rep.machine.name,
                "inflight": rep.inflight,
                "latency_ewma_s": round(rep.ewma_s, 6),
                "warmup": rep.warmup,
                "snapshot_fetch": rep.fetch,
            } for rep in self._procs},
            **counters,
        }
