"""Two-level (pod, chip) mesh scale-out: hierarchical, communication-avoiding
collectives with per-axis traffic accounting.

Every solver in this package reduces over a sharded sample axis. On a flat
``("data",)`` mesh that reduction is one ``psum`` whose traffic XLA routes
however the topology allows — on a real multi-pod machine that means every
partial may cross the slow inter-pod DCN, even though partials from chips in
the same pod could have been folded over the fast intra-pod ICI first. The
communication-avoiding restructure (the kernel k-means playbook of
arxiv 2601.17136, with arxiv 1605.02989's partition-then-combine framing):

1. build the mesh as a two-level ``("pod", "chip")`` grid
   (:func:`make_hierarchical_mesh`); the sample axis shards over BOTH axes,
   pod-major, so device order matches the flat mesh over the same devices;
2. lower every hot reduction as reduce-over-``chip`` (ICI) **then**
   reduce-over-``pod`` (DCN) — the :func:`hpsum` / :func:`hpmean` /
   :func:`hpsum_scatter` family. Only ONE already-reduced partial per pod
   crosses the DCN, shrinking cross-pod combining bytes by exactly
   ``chips_per_pod``x versus the topology-oblivious flat worst case.

On a flat mesh the family degrades to today's single ``psum`` over
``"data"`` — same expression, same program, bit-identical. The degenerate
hierarchical mesh ``n_pods=1`` runs the two-stage lowering with a size-1
pod stage (an identity), so it is bit-identical to the flat mesh on the
same devices as well.

**Traffic ledger.** Each collective call records its LOGICAL combining
bytes per mesh axis into a process-wide :class:`TrafficLedger` (and mirrors
the same increments into the telemetry registry as
``collective.bytes{axis=}`` / ``collective.calls{axis=,op=}`` when the
``telemetry`` knob is on). The model, per reduction over an axis of size
``s`` with an ``B``-byte operand: ``(s - 1) * B`` bytes per independent
reduction group (a combining tree moves exactly s-1 partial-sized messages;
the post-reduction broadcast is symmetric on both layouts and is not
counted). The ``chip`` stage runs one group per pod; the ``pod`` stage one
group total. A flat ``psum`` records all its combining bytes under
``"data"`` — the topology-oblivious accounting in which every partial is
DCN-exposed, which is what the MULTICHIP bench compares the hierarchical
``"pod"`` bytes against:

    flat  : (N - 1) * B            over axis "data"  (DCN-exposed)
    hier  : n_pods * (cpp - 1) * B over axis "chip"  (ICI)
            (n_pods - 1) * B       over axis "pod"   (DCN)

so cross-pod bytes shrink by ``(N - 1) / (n_pods - 1) >= chips_per_pod``.

**The model axis.** ``make_hierarchical_mesh(..., model_parallel=m)`` adds
a third, innermost ``'model'`` axis for feature-axis tensor parallelism:
coef/center/component state shards over ``P(..., 'model')`` while sample
reductions stay on the (pod, chip) path above. Feature-axis collectives —
:func:`mpsum` (d-contraction partials), :func:`mpgather` (coef slice
all-gather), :func:`mpsum_scatter` (gradient reduce-scatter) — record
under their own ``model`` ledger axis, one reduction group per data
coordinate; sample-axis collectives on a 3-axis mesh multiply their
chip/pod terms by ``m`` (one group per model coordinate). Degenerate
``model_parallel=1`` returns the plain two-axis mesh, and every model
collective guards on axis size — the ``model=1`` path is zero-collective
and bit-identical, with an EMPTY model row in the ledger
(docs/scale-out.md "The model axis").

Recording happens at the Python call site, i.e. once per TRACE of the
enclosing program — the ledger counts logical bytes per traced execution of
each collective site. Loops (``lax.while_loop`` bodies) re-execute sites
without re-recording, and a jit cache hit records nothing: multiply by
iteration/invocation counts for totals (the bench does). This is exactly
what makes the accounting deterministic and pinnable, and it composes with
the compile-once gate: zero new steady-state traces means zero new ledger
growth.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh

from dask_ml_tpu.parallel.mesh import (
    CHIP_AXIS,
    DATA_AXIS,
    MODEL_AXIS,
    POD_AXIS,
    data_axes,
    data_pspec,
    is_hierarchical,
    make_mesh,
    n_data_shards,
    n_model_shards,
)

__all__ = [
    "make_hierarchical_mesh",
    "hpsum",
    "hpmean",
    "hpsum_scatter",
    "mpsum",
    "mpgather",
    "mpsum_scatter",
    "model_metered",
    "record_model_collective",
    "TrafficLedger",
    "ledger",
    "reset_ledger",
    "ledger_snapshot",
    "collective_bytes",
    "record_collective",
    "record_axis_collective",
]


def make_hierarchical_mesh(
    n_pods: int,
    chips_per_pod: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
) -> Mesh:
    """An ``(n_pods, chips_per_pod)`` mesh with axes ``('pod', 'chip')`` —
    or, with ``model_parallel=m > 1``, an ``(n_pods, chips_per_pod, m)``
    mesh with axes ``('pod', 'chip', 'model')`` whose innermost axis lives
    INSIDE pods (model-parallel groups never straddle the DCN; the feature
    axis's chatty collectives stay on the ICI).

    ``chips_per_pod=None`` auto-factors from the device count. Devices fill
    the grid pod-major (row-major reshape of the device list), so shard
    ``i`` of a row-sharded array lives on the same device as shard ``i`` of
    the flat mesh over the same list — which is what lets flat-vs-
    hierarchical trajectory pins compare like with like, and lets e.g. ADMM
    consensus state (bound to shard indices) resume across the two layouts.
    ``n_pods=1`` is the degenerate case: the two-stage collectives' pod
    stage is a size-1 identity and every program is bit-identical to the
    flat mesh on the same devices.

    ``model_parallel=1`` returns the plain two-axis mesh — the degenerate
    feature-parallel case is STRUCTURALLY the 2-axis path (no third axis,
    no model collectives, no model ledger entries), which is the strongest
    form of the "model=1 bit-identical" pin. A caller who builds an
    explicit size-1 ``model`` axis via :func:`make_mesh` gets the same
    behavior from the collective family's size-1 guards.

    On a real multi-host deployment, build it so the pod axis coincides
    with the host/pod boundary (processes own contiguous device ranges, so
    ``n_pods = process_count`` does exactly that — see
    ``tests/test_multihost.py``).
    """
    if model_parallel and int(model_parallel) > 1:
        return make_mesh(
            devices=devices,
            shape=(n_pods, chips_per_pod, int(model_parallel)),
            axis_names=(POD_AXIS, CHIP_AXIS, MODEL_AXIS))
    return make_mesh(devices=devices, shape=(n_pods, chips_per_pod),
                     axis_names=(POD_AXIS, CHIP_AXIS))


# ---------------------------------------------------------------------------
# per-axis traffic ledger
# ---------------------------------------------------------------------------


class TrafficLedger:
    """Thread-safe per-(op, axis) logical-byte/call accounting.

    One process-wide instance (:func:`ledger`) backs the collective family;
    tests may construct private ones. Increments mirror into the telemetry
    registry at this site (``collective.bytes{axis=}``,
    ``collective.calls{axis=,op=}``) when the knob is on, so the two
    surfaces agree structurally, never by reconciliation.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict = {}  # (op, axis) -> [bytes, calls]

    def record(self, op: str, axis: str, nbytes: int, calls: int = 1) -> None:
        nbytes = int(nbytes)
        with self._lock:
            e = self._entries.setdefault((str(op), str(axis)), [0, 0])
            e[0] += nbytes
            e[1] += int(calls)
        from dask_ml_tpu.parallel import telemetry

        if telemetry.enabled():
            reg = telemetry.metrics()
            reg.counter("collective.bytes", axis=axis).inc(nbytes)
            reg.counter("collective.calls", axis=axis, op=op).inc(calls)

    def snapshot(self) -> dict:
        """JSON-round-trippable view::

            {"bytes": {axis: total_bytes},
             "calls": {"axis/op": n_calls},
             "ops":   {op: {axis: bytes}}}
        """
        with self._lock:
            items = sorted(self._entries.items())
        by_axis: dict = {}
        calls: dict = {}
        by_op: dict = {}
        for (op, axis), (b, c) in items:
            by_axis[axis] = by_axis.get(axis, 0) + b
            calls[f"{axis}/{op}"] = calls.get(f"{axis}/{op}", 0) + c
            by_op.setdefault(op, {})
            by_op[op][axis] = by_op[op].get(axis, 0) + b
        return {"bytes": by_axis, "calls": calls, "ops": by_op}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


_ledger = TrafficLedger()


def ledger() -> TrafficLedger:
    """The process-wide per-axis collective-traffic ledger."""
    return _ledger


def reset_ledger() -> None:
    _ledger.reset()


def ledger_snapshot() -> dict:
    return _ledger.snapshot()


def _axis_sizes(mesh: Mesh) -> dict:
    return {a: int(mesh.shape[a]) for a in mesh.axis_names}


def collective_bytes(mesh: Mesh, nbytes: int) -> dict:
    """Analytic per-axis logical combining bytes for ONE full sample-axis
    reduction of an ``nbytes``-byte operand over ``mesh`` (the module
    docstring's model): flat meshes charge ``(N-1)*B`` to ``"data"``;
    hierarchical meshes charge ``n_pods*(cpp-1)*B`` to ``"chip"`` (one
    combining tree per pod, over ICI) and ``(n_pods-1)*B`` to ``"pod"``
    (one tree over DCN). Axes of size 1 charge zero — the zero-collective
    path the ledger pins must show as exactly 0.

    On a mesh with a ``model`` axis every term additionally multiplies by
    ``m = n_model_shards(mesh)``: a sample-axis psum runs one independent
    reduction group per model coordinate. ``nbytes`` is the operand's bytes
    AT THE CALL SITE — the per-device shape inside ``shard_map`` — so the
    two feature layouts both come out honest: a model-REPLICATED operand
    (in_specs that don't mention ``model``) charges ``m`` redundant groups
    of the full operand, exactly what XLA executes; a model-SHARDED operand
    charges ``m`` groups of ``1/m``-slices, i.e. the full logical bytes
    once. ``m=1`` degenerates bit-exactly to the two-axis model."""
    nbytes = int(nbytes)
    m = n_model_shards(mesh)
    if is_hierarchical(mesh):
        n_pods = int(mesh.shape[POD_AXIS])
        cpp = int(mesh.shape[CHIP_AXIS])
        return {CHIP_AXIS: m * n_pods * (cpp - 1) * nbytes,
                POD_AXIS: m * (n_pods - 1) * nbytes}
    return {DATA_AXIS: m * (n_data_shards(mesh) - 1) * nbytes}


def record_collective(op: str, mesh: Mesh, shape, dtype) -> None:
    """Record one full sample-axis reduction of a ``(shape, dtype)`` operand
    at this call site (works on tracers: shapes/dtypes are static)."""
    nbytes = int(np.prod(shape, dtype=np.int64)) \
        * int(jax.numpy.dtype(dtype).itemsize)
    for axis, b in collective_bytes(mesh, nbytes).items():
        _ledger.record(op, axis, b)


def record_axis_collective(op: str, mesh: Mesh, axis: str,
                           nbytes: int) -> None:
    """Record a single-axis collective with the (size-1)*B-per-group model.

    Group counts follow the staged-reduction conventions of the collective
    family: the ``chip`` stage runs one group per (pod, model) coordinate;
    the ``pod`` stage runs after the chip fold, so one group per model
    coordinate; a ``model``-axis collective runs one group per DATA
    coordinate (feature-axis collectives are independent per sample shard);
    any other axis one group total. All the extra factors are 1 on meshes
    without the corresponding axes, so two-axis and flat accounting is
    unchanged."""
    s = int(mesh.shape[axis])
    m = n_model_shards(mesh)
    if axis == CHIP_AXIS and is_hierarchical(mesh):
        groups = int(mesh.shape[POD_AXIS]) * m
    elif axis == POD_AXIS and is_hierarchical(mesh):
        groups = m
    elif axis == MODEL_AXIS:
        groups = n_data_shards(mesh)
    else:
        groups = 1
    _ledger.record(op, axis, (s - 1) * int(nbytes) * groups)


# ---------------------------------------------------------------------------
# the hierarchical collective family (call INSIDE shard_map bodies)
# ---------------------------------------------------------------------------


def hpsum(x, mesh: Mesh, *, op: str = "psum"):
    """Hierarchical all-reduce-sum over the mesh's sample axes.

    On a hierarchical mesh: ``psum`` over ``"chip"`` (ICI) then ``"pod"``
    (DCN) — the explicit two-stage, communication-avoiding lowering. On a
    flat mesh: exactly today's ``lax.psum(x, "data")`` (same expression,
    bit-identical). The mesh choice is static (it selects the expression at
    trace time), so it reaches traced code only through program structure —
    the compile-once discipline of docs/compile.md.

    ``op`` labels the call site in the traffic ledger (and the telemetry
    ``collective.calls{axis=,op=}`` mirror), so per-reduction-family bytes
    stay separable in the MULTICHIP bench. Must be called inside a
    ``shard_map`` whose mesh binds the named axes."""
    record_collective(op, mesh, x.shape, x.dtype)
    if is_hierarchical(mesh):
        x = lax.psum(x, CHIP_AXIS)
        return lax.psum(x, POD_AXIS)
    return lax.psum(x, DATA_AXIS)


def hpmean(x, mesh: Mesh, *, op: str = "pmean"):
    """Hierarchical mean over the sample axes: :func:`hpsum` divided by the
    (static) total shard count — the z-consensus shape."""
    return hpsum(x, mesh, op=op) / n_data_shards(mesh)


def hpsum_scatter(x, mesh: Mesh, *, op: str = "psum_scatter"):
    """Hierarchical reduce-scatter: each chip keeps its ``1/chips_per_pod``
    slice of the full sum (axis 0 tiled over the ``chip`` axis — flat
    meshes tile over ``"data"``).

    Logically the same combining bytes as :func:`hpsum` (the ledger model
    charges identically); the difference is the LOWERING — the pod stage
    reduces distinct per-chip slices instead of ``chips_per_pod`` redundant
    copies of the full operand, so the wire matches the logical count. Use
    it when the consumer wants the result sharded anyway (a stacked-factor
    combine, a sharded epilogue); ``axis 0`` of ``x`` must divide the chip
    (flat: data) axis size."""
    record_collective(op, mesh, x.shape, x.dtype)
    if is_hierarchical(mesh):
        x = lax.psum_scatter(x, CHIP_AXIS, tiled=True)
        return lax.psum(x, POD_AXIS)
    return lax.psum_scatter(x, DATA_AXIS, tiled=True)


# ---------------------------------------------------------------------------
# the feature-axis ("model") collective family
# ---------------------------------------------------------------------------


def _local_nbytes(x) -> int:
    return int(np.prod(x.shape, dtype=np.int64)) * int(x.dtype.itemsize)


def mpsum(x, mesh: Mesh, *, op: str = "mpsum"):
    """All-reduce-sum over the ``model`` axis (feature-axis partials: the
    d-contraction of a feature-sharded matvec, partial squared norms).

    On any mesh whose model axis is absent or size 1 this is an IDENTITY —
    no psum, no ledger entry — which is the zero-collective ``model=1``
    path the ledger pins check: degenerate meshes record exactly nothing
    under the ``model`` axis. Otherwise records ``(m-1)*B`` per data
    coordinate (``B`` = the per-device operand at this call site) under the
    ``model`` ledger axis and reduces. Must be called inside a
    ``shard_map`` that binds the axis (when m > 1)."""
    if n_model_shards(mesh) <= 1:
        return x
    record_axis_collective(op, mesh, MODEL_AXIS, _local_nbytes(x))
    return lax.psum(x, MODEL_AXIS)


def mpgather(x, mesh: Mesh, *, op: str = "mpgather", axis: int = 0):
    """All-gather of per-model-shard slices (coef slices, per-column stats)
    into the full axis, tiled along ``axis``. Identity (no collective, no
    ledger entry) when the model axis is absent or size 1. Records
    ``(m-1)*B_shard`` per data coordinate — the (s-1) shard-sized messages
    each participant's ring stage forwards."""
    if n_model_shards(mesh) <= 1:
        return x
    record_axis_collective(op, mesh, MODEL_AXIS, _local_nbytes(x))
    return lax.all_gather(x, MODEL_AXIS, axis=axis, tiled=True)


def mpsum_scatter(x, mesh: Mesh, *, op: str = "mpsum_scatter"):
    """Reduce-scatter over the ``model`` axis: each model shard keeps its
    ``1/m`` slice of the full sum (axis 0 tiled) — the gradient shape:
    every shard computes a full-width partial, each keeps its own coef
    slice. Identity when the model axis is absent or size 1; same ledger
    model as :func:`mpsum` (the combining bytes are identical; scatter
    changes the LOWERING, not the logical count)."""
    if n_model_shards(mesh) <= 1:
        return x
    record_axis_collective(op, mesh, MODEL_AXIS, _local_nbytes(x))
    return lax.psum_scatter(x, MODEL_AXIS, tiled=True)


# ---------------------------------------------------------------------------
# model-axis metering scope for GSPMD-implicit feature collectives
# ---------------------------------------------------------------------------

_model_scope = threading.local()


@contextlib.contextmanager
def model_metered(mesh: Optional[Mesh]):
    """Meter the GSPMD-implicit feature-axis collectives of plain-jit
    programs traced in this dynamic scope.

    The shard_map solvers call :func:`mpsum`/:func:`mpgather` explicitly,
    so their model-axis traffic records at the call site. The jit-compiled
    solvers (newton/lbfgs/…, the PCA fit program) never name mesh axes —
    GSPMD inserts the d-axis collectives from the input shardings — so
    their contraction seams (``_data_matvec``/``_data_pullback``/
    ``_weighted_gram``, the PCA/tsqr column gathers) instead call
    :func:`record_model_collective`, which records the ANALYTIC bytes of
    the collective GSPMD must insert, but only inside this scope and only
    when ``mesh`` actually has a model axis of size > 1. Recording happens
    at trace time like every other ledger site: cache hits record nothing,
    preserving zero-steady-state-compiles ⟺ zero-ledger-growth."""
    active = mesh if (mesh is not None and n_model_shards(mesh) > 1) else None
    prev = getattr(_model_scope, "mesh", None)
    _model_scope.mesh = active
    try:
        yield
    finally:
        _model_scope.mesh = prev


def model_metered_mesh() -> Optional[Mesh]:
    """The mesh of the innermost active :func:`model_metered` scope (None
    outside any scope, or when the scope's mesh has no model axis)."""
    return getattr(_model_scope, "mesh", None)


def record_model_collective(op: str, shape, dtype) -> None:
    """Record one feature-axis collective of a GLOBAL ``(shape, dtype)``
    operand under the active :func:`model_metered` scope: ``(m-1)*B`` total
    on the ``model`` ledger axis (the per-group slices of a model-sharded
    result, summed over the data groups, telescope back to the full operand
    bytes). No-op outside a scope — direct core-solver calls and every
    data-parallel fit record nothing, so existing ledger pins see no new
    entries."""
    mesh = model_metered_mesh()
    if mesh is None:
        return
    m = n_model_shards(mesh)
    nbytes = int(np.prod(shape, dtype=np.int64)) \
        * int(jax.numpy.dtype(dtype).itemsize)
    _ledger.record(op, MODEL_AXIS, (m - 1) * nbytes)


__all__ += ["model_metered_mesh"]

# re-exported for consumers that already import hierarchy
__all__ += ["data_axes", "data_pspec", "is_hierarchical", "n_data_shards",
            "n_model_shards"]
