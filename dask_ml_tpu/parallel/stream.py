"""Double-buffered host→device block streaming for the larger-than-HBM tier.

The streamed solvers (``models/glm.py::admm_streamed``,
``decomposition/streaming.py::streamed_moments``) historically took only a
TRACED ``block_fn`` and ``lax.scan``-ed it inside the compiled program —
perfect for device-regenerated synthetic blocks, but structurally unable to
overlap a real host→device transfer with compute: block production is
serialized against the inner Newton solve / Gram matmul inside the scan
body. This module is the host-resident counterpart:

- :class:`HostBlockSource` owns the host arrays (or a per-block loader
  callable) and issues **asynchronous** ``jax.device_put`` transfers, so
  block ``b+1`` can be in flight while block ``b``'s compute runs.
- :func:`prefetched_scan` is the host-driven analogue of
  ``lax.scan(step, carry, blocks)``: it keeps ``prefetch`` transfers ahead
  of the consuming jitted step (depth 2 = classic double buffering) and
  drops to a strict serial transfer→compute→transfer schedule at depth 0
  (the overlap-off baseline the benches compare against).

Why a host-driven outer loop instead of ``io_callback``-fed buffers: an
``io_callback`` inside the scan body is *ordered* with respect to the
surrounding computation — XLA gives it no lookahead, so the callback's
host work serializes exactly like the traced ``block_fn`` does, and the
alternative (effectful unordered callbacks + double-buffer index juggling
inside the trace) reimplements what the JAX runtime already provides for
free: dispatch is asynchronous, so a host loop that issues ``device_put``
(b+1) before dispatching compute(b) gets transfer/compute overlap from the
transfer engine with no in-trace machinery. Measured (``bench.py
--host-stream`` reports both schedules side by side as
``overlap_speedup``): even on the 8-device CPU mesh, where transfers are
nearly free and only dispatch overlap remains, the prefetched loop beats
the serialized schedule — 1.16× on the streamed-ADMM config (256 MB
re-streamed over 3 outer epochs, 2.65 s vs 3.07 s) and ~1.0–1.04× on the
one-pass PCA config; on a bandwidth-starved link (the bench host's
~10 MB/s tunnel) the win approaches the full transfer time of
all-but-one block, since compute hides entirely behind the stream. The
host loop also reproduces the traced-scan trajectory because both modes
run the same per-block implementation (bit-identical on the CPU test
mesh; within float tolerance where a backend compiles the scan-inlined
and standalone per-block programs differently — see
``models/glm.py::_streamed_block_newton``).

The trajectory contract: a ``HostBlockSource`` with B blocks fed to
``admm_streamed``/``pca_fit_blocks`` produces the SAME result as a traced
``block_fn`` yielding identical block contents — the consuming solvers
share one per-block compute implementation across both modes.
"""

from __future__ import annotations

import copy
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from dask_ml_tpu.parallel import telemetry
from dask_ml_tpu.parallel.faults import BlockFetchError, Preempted

__all__ = ["HostBlockSource", "prefetched_scan"]


def _is_scipy_sparse(a) -> bool:
    try:
        import scipy.sparse

        return scipy.sparse.issparse(a)
    except ImportError:  # pragma: no cover
        return False


def _logical_nbytes(a) -> int:
    """What this block element would weigh DENSE and uncast — the
    baseline both wire wins (precision cast AND sparse encoding) are
    measured against. A sparse element's logical bytes are its dense
    n*d*itemsize equivalent; for dense arrays this is plain ``.nbytes``
    (the pre-sparse behavior, unchanged)."""
    from dask_ml_tpu.ops.sparse import SparseRows

    if isinstance(a, SparseRows):
        n, d = a.shape
        return int(n) * int(d) * int(np.dtype(a.values.dtype).itemsize)
    return int(a.nbytes)


class _Compose:
    """Composition of two block transforms with stable hash/eq, so the
    consuming jitted step (which takes the transform as a static argument)
    keeps hitting its compile cache across source copies."""

    def __init__(self, outer: Callable, inner: Callable):
        self.outer = outer
        self.inner = inner

    def __call__(self, blk):
        return self.outer(self.inner(blk))

    def __hash__(self):
        return hash((self.outer, self.inner))

    def __eq__(self, other):
        return (isinstance(other, _Compose)
                and (self.outer, self.inner) == (other.outer, other.inner))


def _sync(tree) -> None:
    """Completion barrier: ``block_until_ready`` plus a one-element value
    fetch per array leaf — on tunneled backends ``block_until_ready`` is
    advisory (it returns before the device is actually done; see bench.py's
    methodology notes), so the fetches are what guarantee the strict
    serial schedule in the overlap-off path. Every leaf is fetched because
    a block tuple arrives as INDEPENDENT transfers (one ``device_put``
    each), not outputs of one program completing together."""
    jax.block_until_ready(tree)
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            np.asarray(leaf.ravel()[:1])


class HostBlockSource:
    """A host-resident row-block source for the streamed >HBM solvers.

    Two construction modes:

    - ``HostBlockSource((X, y, w), n_blocks=40)`` — a tuple of host arrays
      sharing axis 0 (any count: ``(X, w)`` for PCA, ``(X, y, w)`` for
      GLMs), split into ``n_blocks`` equal row blocks. Arrays are made
      contiguous up front so every block transfer is one flat DMA — the
      practical host-side analogue of pinning.
    - ``HostBlockSource(loader=f, n_blocks=40)`` — ``f(b)`` returns block
      ``b`` as a tuple of host arrays (shapes/dtypes identical across
      blocks, or the consuming step recompiles per shape). This is the
      out-of-core path: ``f`` can read from disk/object storage.

    ``transform`` is an optional device-side function applied to the block
    tuple INSIDE the consumer's jitted step (e.g. appending the intercept
    column) — it costs nothing extra because it fuses into the block's
    compute program. ``prefetch`` is the pipeline depth consumers default
    to: 2 = double buffering (one block computing, one in flight); 0 =
    strict serial transfer→compute alternation (the overlap-off baseline).

    Ragged tails pad automatically: when the row count does not split into
    ``n_blocks`` equal blocks (arrays mode), or the loader's LAST block
    comes back short (the out-of-core tail), the block is zero-padded up
    to the common block shape (``shapes.pad_tail``) — equal shapes are
    what keep the per-block program compiled ONCE per epoch. Zero rows are
    weight-0 rows for every consumer here (the block tuple's per-row
    weight array is zero on them), so a padded tail produces bit-identical
    results to a manually pre-padded source. ``pad_tail=None`` (default)
    auto-pads only when the block tuple's last array is 1-D — the weight
    vector every streamed consumer here carries ((X, w), (X, y, w)). That
    is a HEURISTIC for the weight contract, not proof: a weightless
    ``(X, y)`` tuple with 1-D labels matches it too (no in-repo consumer
    takes that shape, but a custom step might) — pass ``pad_tail=False``
    whenever the trailing 1-D array is not a per-row weight, because zero
    rows would enter an unweighted consumer as real data. A tuple whose
    last array is NOT 1-D keeps the old loud unequal-blocks
    ``ValueError``. ``pad_tail=True`` forces padding (caller vouches for
    the weight contract); ``pad_tail=False`` forbids it.

    ``retry_policy`` (a :class:`~dask_ml_tpu.parallel.faults.RetryPolicy`)
    makes block reads and ``device_put`` transfers survive transient
    failures — flaky object storage in loader mode, backend transfer
    hiccups — with exponential backoff; without one, the first failure
    propagates as before. ``fault_injector`` (a
    :class:`~dask_ml_tpu.parallel.faults.FaultInjector`) deterministically
    injects those failures for tests and the ``bench.py --faults`` drill.

    ``storage_dtype`` is the WIRE dtype (docs/precision.md): each block's
    floating 2-D+ arrays are cast host-side BEFORE ``device_put``, so a
    bf16 policy halves the bytes every transfer moves — the host→device
    link is this tier's measured bottleneck (PR 1), making the wire cast
    the highest-leverage place low precision can act. 1-D per-row vectors
    (labels, weights) stay exact. The default ``"policy"`` resolves the
    active :mod:`~dask_ml_tpu.parallel.precision` policy's storage dtype
    at construction ("auto" = bf16 on TPU, no cast elsewhere); ``None``
    disables casting; an explicit dtype forces it.

    The source tracks ``bytes_streamed``/``blocks_started`` for effective-
    bandwidth accounting (``reset_stats()`` between timed runs), plus
    ``logical_bytes_streamed`` — what the same blocks would have weighed
    uncast — so the bench can report wire vs logical effective GB/s side
    by side (their ratio IS the policy's wire win). The counters increment
    only when a transfer is successfully issued — a failed-then-retried
    ``device_put`` counts once — and ``discard_inflight()`` rolls
    issued-but-never-consumed transfers back out, so the stats always
    equal the blocks compute actually consumed.
    """

    def __init__(self, arrays: Optional[Sequence[np.ndarray]] = None,
                 n_blocks: Optional[int] = None, *,
                 loader: Optional[Callable[[int], tuple]] = None,
                 transform: Optional[Callable] = None,
                 prefetch: int = 2, device=None,
                 retry_policy=None, fault_injector=None,
                 pad_tail: Optional[bool] = None,
                 storage_dtype="policy", host_rank: Optional[int] = None):
        if (arrays is None) == (loader is None):
            raise ValueError(
                "pass exactly one of `arrays` (host array tuple) or "
                "`loader` (per-block callable)")
        if n_blocks is None or int(n_blocks) < 1:
            raise ValueError("n_blocks must be a positive integer")
        self.n_blocks = int(n_blocks)
        self.prefetch = int(prefetch)
        self.transform = transform
        self.pad_tail = pad_tail if pad_tail is None else bool(pad_tail)
        self._device = device
        self._loader = loader
        self._arrays: Optional[tuple] = None
        # common per-block row count; loader mode learns it from block 0
        self._rows = None
        # per-position ELL slot buckets for sparse block elements: fixed
        # ONCE (arrays mode: from the whole matrix; loader mode: from the
        # first block seen), so every block shares one (rows, k) shape and
        # the consuming per-block program compiles once per epoch
        # (docs/sparse.md)
        self._ell_k: dict = {}
        if arrays is not None:
            from dask_ml_tpu.ops.sparse import SparseRows
            from dask_ml_tpu.parallel import shapes as shapes_lib

            prepped = []
            for i, a in enumerate(arrays):
                if _is_scipy_sparse(a):
                    a = a.tocsr()
                    row_nnz = np.diff(a.indptr)
                    self._ell_k[i] = shapes_lib.bucket_nnz(
                        int(row_nnz.max()) if a.shape[0] else 0)
                elif isinstance(a, SparseRows):
                    a = SparseRows(np.ascontiguousarray(a.values),
                                   np.ascontiguousarray(a.cols), a.d)
                else:
                    a = np.ascontiguousarray(a)
                prepped.append(a)
            arrays = tuple(prepped)
            n = arrays[0].shape[0]
            for a in arrays[1:]:
                if a.shape[0] != n:
                    raise ValueError(
                        f"all arrays must share axis 0: got lengths "
                        f"{[a.shape[0] for a in arrays]}")
            if n % self.n_blocks and not self._may_pad(arrays):
                raise ValueError(
                    f"{n} rows do not split into {self.n_blocks} equal "
                    "blocks; auto-padding needs a trailing 1-D per-row "
                    "weight array in the block tuple (zero rows are inert "
                    "only under weights) or an explicit pad_tail=True — "
                    "otherwise pad the tail rows (weight 0) yourself: "
                    "equal block shapes are what keep the per-block "
                    "program compiled once")
            self._arrays = arrays
            self._rows = -(-n // self.n_blocks)  # ceil: tail block pads
        from dask_ml_tpu.parallel import precision as precision_lib

        if storage_dtype == "policy":
            storage_dtype = precision_lib.resolve().storage_dtype()
        self.storage_dtype = storage_dtype
        self.retry_policy = retry_policy
        self.fault_injector = fault_injector
        # which host (elastic process rank) this source streams for; when
        # set, transfer bytes additionally mirror into the per-host
        # `stream.bytes{host=}` registry counter (docs/observability.md) so
        # a multi-host fit's bandwidth breaks down by process
        self.host_rank = None if host_rank is None else int(host_rank)
        self._inflight: dict = {}
        self._inflight_bytes: dict = {}
        self.bytes_streamed = 0
        self.logical_bytes_streamed = 0
        self.blocks_started = 0

    def _may_pad(self, blk) -> bool:
        """Whether the ragged tail may be auto-padded: explicit pad_tail
        wins; the default (None) requires the block tuple's LAST array to
        be 1-D — the per-row weight vector every streamed consumer here
        carries, which is what makes zero padding inert."""
        if self.pad_tail is not None:
            return self.pad_tail
        return len(blk) >= 2 and np.asarray(blk[-1]).ndim == 1

    # -- host side ---------------------------------------------------------

    def host_block(self, b: int) -> tuple:
        """Block ``b`` as host arrays (views into the owned arrays, or the
        loader's output coerced to ndarrays). Under a ``retry_policy``,
        transient read failures (loader ``OSError``/timeouts) back off and
        retry before propagating."""
        if not 0 <= b < self.n_blocks:
            raise IndexError(f"block {b} out of range [0, {self.n_blocks})")

        def read():
            from dask_ml_tpu.ops.sparse import SparseRows, ell_from_csr
            from dask_ml_tpu.parallel import shapes as shapes_lib

            def coerce(i, a):
                if isinstance(a, SparseRows):
                    return a
                if _is_scipy_sparse(a):
                    # loader-emitted sparse block: ELL-encode at a slot
                    # bucket learned from the FIRST block seen for this
                    # tuple position (all blocks must share it — a later
                    # block with a wider row raises ell_from_csr's loud
                    # "widen k" instead of silently recompiling per block)
                    a = a.tocsr()
                    key = ("loader", i)
                    k = self._ell_k.get(key)
                    if k is None:
                        row_nnz = np.diff(a.indptr)
                        k = shapes_lib.bucket_nnz(
                            int(row_nnz.max()) if a.shape[0] else 0)
                        self._ell_k[key] = k
                    return ell_from_csr(a, k=k)
                return np.asarray(a)

            if self.fault_injector is not None:
                self.fault_injector.on_load(b)
            if self._arrays is not None:
                s = b * self._rows
                blk = []
                for i, a in enumerate(self._arrays):
                    part = a[s:s + self._rows]
                    if _is_scipy_sparse(part):
                        # the block's wire encoding: the CSR slice as ELL
                        # indices+values at the SOURCE-WIDE slot bucket
                        part = ell_from_csr(part, k=self._ell_k[i])
                    blk.append(part)
                blk = tuple(blk)
            else:
                blk = tuple(coerce(i, a)
                            for i, a in enumerate(self._loader(b)))
            return self._pad_block(b, blk)

        if self.retry_policy is None:
            return read()
        return self.retry_policy.run(read, kind="block-load",
                                     detail=f"block {b}")

    def _pad_block(self, b: int, blk: tuple) -> tuple:
        """Zero-pad a short ragged TAIL block up to the common per-block
        row count, so every block presents the SAME shape to the consuming
        jitted step — one compiled per-block program per epoch. Zero rows
        carry zero weight (the block tuple's weight array pads to 0), so
        the padding is inert in the weighted solvers; see the class
        docstring for the ``pad_tail`` modes. A short NON-tail block is an
        error either way (a truncated shard read must surface, not be
        masked as weight-0 rows)."""
        if self.pad_tail is False or not self._may_pad(blk):
            return blk
        rows = int(blk[0].shape[0])
        if self._rows is None:
            # loader mode learns the common shape lazily: any block but the
            # last is full-shaped by the ragged-tail contract. If the FIRST
            # read is the tail (a resume landing there), peek block 0.
            if b < self.n_blocks - 1 or self.n_blocks == 1:
                self._rows = rows
                return blk
            if self.fault_injector is not None:
                # the peek is a real block-0 load: keep the deterministic
                # drill's load schedule honest
                self.fault_injector.on_load(0)
            first = self._loader(0)[0]
            self._rows = int(first.shape[0] if hasattr(first, "shape")
                             else np.asarray(first).shape[0])
        if rows == self._rows:
            return blk
        if rows > self._rows:
            raise ValueError(
                f"block {b} has {rows} rows, more than the common block "
                f"shape of {self._rows}; only the ragged TAIL may be "
                "short")
        if b != self.n_blocks - 1:
            raise ValueError(
                f"block {b} has {rows} rows but the common block shape is "
                f"{self._rows}; only the ragged TAIL (block "
                f"{self.n_blocks - 1}) may be short — a short interior "
                "block means truncated input, which padding would "
                "silently mask")
        from dask_ml_tpu.parallel.shapes import pad_tail

        return pad_tail(blk, self._rows)

    @property
    def out_struct(self) -> tuple:
        """ShapeDtypeStructs of one block AS THE CONSUMER SEES IT (i.e.
        after ``transform``). Cached: in loader mode the first computation
        reads a real block (potentially an expensive out-of-core fetch),
        and repeating that per call would double block 0's I/O."""
        cached = getattr(self, "_out_struct", None)
        if cached is not None:
            return cached
        structs = tuple(
            jax.tree_util.tree_map(
                lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype), a)
            for a in self._cast_wire(self.host_block(0)))
        if self.transform is not None:
            structs = jax.eval_shape(self.transform, structs)
        self._out_struct = tuple(structs)
        return self._out_struct

    # -- async transfer pipeline ------------------------------------------

    def start(self, b: int) -> None:
        """Issue the (asynchronous) host→device transfer of block ``b``.
        Idempotent while the block is in flight. Under a ``retry_policy``,
        a transient ``device_put`` failure backs off and re-issues; the
        stats increment only once the transfer is successfully issued, so
        retried transfers never double-count bytes (the effective-GB/s
        numbers in ``bench.py`` stay honest across retries)."""
        if b in self._inflight:
            return
        with telemetry.span("stream.transfer", block=b):
            blk = self.host_block(b)
            # logical = dense-and-uncast equivalent bytes: for sparse
            # elements the dense n*d*itemsize the same block would have
            # weighed, so logical/wire IS the combined sparse+precision
            # wire win the bench gates on (docs/sparse.md)
            logical = sum(_logical_nbytes(a) for a in blk)
            # the wire cast happens HERE, after the (exact) host read and
            # before the transfer: wire bytes are what actually cross the
            # link
            blk = self._cast_wire(blk)

            def put():
                if self.fault_injector is not None:
                    self.fault_injector.on_transfer(b)
                return tuple(jax.device_put(a, self._device) for a in blk)

            if self.retry_policy is None:
                dev = put()
            else:
                dev = self.retry_policy.run(put, kind="device-put",
                                            detail=f"block {b}")
            nbytes = sum(int(a.nbytes) for a in blk)
        self._inflight[b] = dev
        self._inflight_bytes[b] = (nbytes, logical)
        self.bytes_streamed += nbytes
        self.logical_bytes_streamed += logical
        self.blocks_started += 1
        if telemetry.enabled():
            # registry mirrors of the per-source counters just above —
            # same increment site, so within an enabled scope the two can
            # only agree (pinned by tests/test_telemetry.py)
            reg = telemetry.metrics()
            reg.counter("stream.bytes_streamed").inc(nbytes)
            reg.counter("stream.logical_bytes_streamed").inc(logical)
            reg.counter("stream.blocks_started").inc(1)
            if self.host_rank is not None:
                # per-host wire bytes for the elastic data plane: one
                # labeled counter per process rank, so a multi-host fit's
                # bandwidth breaks down by host (docs/observability.md)
                reg.counter("stream.bytes",
                            host=str(self.host_rank)).inc(nbytes)

    def _cast_wire(self, blk: tuple) -> tuple:
        from dask_ml_tpu.parallel import precision as precision_lib

        return precision_lib.cast_wire(blk, self.storage_dtype)

    def take(self, b: int) -> tuple:
        """Device arrays for block ``b`` — already in flight when the
        pipeline prefetched it, started on demand otherwise. The slot is
        released so the block can be re-streamed on a later epoch.

        If a prior ``start(b)`` died mid-pipeline (its transfer failed and
        left no in-flight slot), the fetch is re-issued here under the
        retry policy; a terminal failure raises
        :class:`~dask_ml_tpu.parallel.faults.BlockFetchError` naming the
        block index instead of a bare ``KeyError``."""
        dev = self._inflight.pop(b, None)
        if dev is None:
            try:
                self.start(b)
            except (IndexError, BlockFetchError):
                raise
            except Exception as e:
                raise BlockFetchError(
                    f"block {b}/{self.n_blocks}: fetch failed terminally "
                    f"after retries ({type(e).__name__}: {e})") from e
            dev = self._inflight.pop(b, None)
            if dev is None:  # pragma: no cover - start() postcondition
                raise BlockFetchError(
                    f"block {b}/{self.n_blocks}: start() completed without "
                    "an in-flight transfer")
        self._inflight_bytes.pop(b, None)
        # the prefetch queue-depth gauge, sampled at every take(): how many
        # transfers remain in flight ahead of compute right now — always in
        # [0, prefetch], and the direct precursor to a serving queue-depth
        # gauge (ROADMAP item 1)
        telemetry.gauge("stream.queue_depth").set(len(self._inflight))
        return dev

    def discard_inflight(self) -> None:
        """Drop queued transfers (end of run / early convergence exit) and
        roll them back out of the stats: a discarded transfer was issued
        but never consumed by compute, and counting it would inflate this
        run's effective GB/s — and leak wrapped-around lookahead into the
        next timed run's accounting. Transfers issued before a
        ``reset_stats()`` boundary (rollback entry ``None``) were never
        part of the current counters and are dropped without subtracting."""
        mirror = telemetry.metrics() if telemetry.enabled() else None
        for b in list(self._inflight):
            entry = self._inflight_bytes.pop(b, None)
            if entry is not None:
                wire, logical = entry
                self.bytes_streamed -= wire
                self.logical_bytes_streamed -= logical
                self.blocks_started -= 1
                if mirror is not None:
                    # keep the registry mirrors tracking the legacy
                    # counters through the rollback too
                    mirror.counter("stream.bytes_streamed").inc(-wire)
                    mirror.counter(
                        "stream.logical_bytes_streamed").inc(-logical)
                    mirror.counter("stream.blocks_started").inc(-1)
                    if self.host_rank is not None:
                        mirror.counter(
                            "stream.bytes",
                            host=str(self.host_rank)).inc(-wire)
            del self._inflight[b]

    def reset_stats(self) -> None:
        """Zero the transfer counters (between timed runs). Transfers
        still in flight were issued against the OLD counters, so their
        rollback entries are neutralized — a later ``discard_inflight()``
        must not subtract pre-reset bytes from the fresh zeros. The retry
        policy's counters are its own (``retry_policy.reset_stats()``) —
        they double as the deadline budget, which a new timed run does not
        automatically refill."""
        self.bytes_streamed = 0
        self.logical_bytes_streamed = 0
        self.blocks_started = 0
        self._inflight_bytes = {b: None for b in self._inflight}

    def with_transform(self, fn: Callable) -> "HostBlockSource":
        """A copy of this source whose blocks pass through ``fn`` (applied
        after any existing transform) inside the consumer's jitted step.
        Pass a module-level function: the consumer keys its compile cache
        on the transform's identity."""
        src = copy.copy(self)
        src.transform = fn if self.transform is None else _Compose(
            fn, self.transform)
        src._inflight = {}
        src._inflight_bytes = {}
        src._out_struct = None  # the copy's transform changes the shapes
        src.reset_stats()
        # retry_policy/fault_injector are shared by reference: counters and
        # injection plans stay visible on the caller's objects
        return src


def prefetched_scan(step, carry, source: HostBlockSource, *,
                    prefetch: Optional[int] = None, wrap: bool = False,
                    checkpoint=None, epoch: int = 0, start_block: int = 0,
                    outs: Optional[list] = None,
                    blocks: Optional[Sequence[int]] = None):
    """Host-driven ``lax.scan`` over a :class:`HostBlockSource`.

    ``step(carry, b, block) -> (carry, out)`` must dispatch jitted work and
    return without forcing values (the usual JAX async contract). Returns
    ``(carry, outs)`` with ``outs`` the per-block list.

    ``prefetch`` (default: the source's depth) is the number of block
    transfers kept in flight ahead of compute; depth 2 is double buffering
    — while block ``b`` computes, block ``b+1``'s DMA runs and block
    ``b+2``'s host slice is being issued. ``wrap=True`` lets the lookahead
    wrap past the last block back to block 0, priming the next epoch of an
    outer loop that rescans the same source (streamed ADMM's outer
    iterations).

    Depth 0 is the measured overlap-off baseline: each transfer is forced
    to COMPLETE (value-fetch barrier — see :func:`_sync`) before its
    compute is dispatched, and the compute is forced to complete before the
    next transfer is issued, i.e. the exact schedule the traced-scan mode
    imposes on block production.

    Preemption safety (``checkpoint``: a
    :class:`~dask_ml_tpu.parallel.faults.ScanCheckpoint`): after every
    completed block the scan (a) snapshots ``(carry, outs, next_block,
    epoch)`` when the interval says so, and (b) polls the checkpoint's
    :class:`~dask_ml_tpu.parallel.faults.GracefulDrain` flag and the
    source's fault injector — a requested drain (SIGTERM/SIGINT, or a
    simulated preemption) finishes the in-flight block, discards queued
    transfers, snapshots, and raises
    :class:`~dask_ml_tpu.parallel.faults.Preempted`. ``start_block`` /
    ``outs`` / ``epoch`` are the resume coordinates a loaded snapshot
    provides: the scan replays from the first incomplete block with a
    bit-identical trajectory (the per-block programs are deterministic
    functions of the carry and block contents).

    ``blocks`` makes the scan SHARD-AWARE (the elastic data plane,
    ``parallel/elastic.py``): an explicit sequence of block ids to consume
    — this host's slice of a seeded epoch permutation — instead of the
    default ``range(n_blocks)``. ``start_block`` is then a POSITION in
    that sequence (the two coincide for the default scan), snapshots store
    the sequence itself under ``meta['blocks']`` so a resume replays the
    SAME permutation slice even if the roster has since changed, and
    ``step`` still receives the GLOBAL block id. ``wrap`` is rejected with
    an explicit sequence: the next epoch draws its own permutation, so
    there is no "block after the last" to prime.
    """
    n = source.n_blocks
    depth = source.prefetch if prefetch is None else int(prefetch)
    outs = [] if outs is None else list(outs)
    start_block = int(start_block)
    injector = getattr(source, "fault_injector", None)
    if blocks is None:
        seq = range(n)
        saved_seq = None
    else:
        if wrap:
            raise ValueError(
                "wrap=True cannot combine with an explicit blocks= "
                "sequence: the lookahead would need the NEXT epoch's "
                "permutation, which only the elastic driver knows — prime "
                "it there instead")
        seq = [int(b) for b in blocks]
        saved_seq = seq
    n_seq = len(seq)

    def after_block(pos, b, carry):
        """Post-block bookkeeping: may snapshot; raises Preempted on a
        drain request or an injected preemption. ``pos`` is the position
        in the scanned sequence (= the resume coordinate), ``b`` the
        global block id (= the injection-plan key)."""
        preempt = injector is not None and injector.should_preempt(b, epoch)
        if checkpoint is None:
            if preempt:
                source.discard_inflight()
                raise Preempted(
                    f"preempted after block {b} of epoch {epoch} with no "
                    "checkpoint configured; progress was lost")
            return
        drain = checkpoint.drain
        if preempt or (drain is not None and drain.requested):
            source.discard_inflight()
            checkpoint.save(carry, outs, pos + 1, epoch, reason="preempt",
                            blocks=saved_seq)
            raise Preempted(
                f"graceful drain: snapshot at block {pos + 1}/{n_seq} of "
                f"epoch {epoch} saved to {checkpoint.path}; re-run with "
                "the same checkpoint path to resume", path=checkpoint.path)
        checkpoint.tick(carry, outs, pos + 1, epoch, blocks=saved_seq)

    if depth <= 0:
        for pos in range(start_block, n_seq):
            b = seq[pos]
            with telemetry.span("stream.block", block=b, epoch=epoch):
                with telemetry.span("stream.take", block=b):
                    blk = source.take(b)
                    _sync(blk)
                with telemetry.span("stream.compute", block=b) as sc:
                    carry, out = step(carry, b, blk)
                    sc.sync(out if out is not None else carry)
                    _sync(out if out is not None else carry)
            outs.append(out)
            after_block(pos, b, carry)
        return carry, outs
    for j in range(min(depth, n_seq - start_block)):
        source.start(seq[start_block + j])
    for pos in range(start_block, n_seq):
        b = seq[pos]
        with telemetry.span("stream.block", block=b, epoch=epoch):
            with telemetry.span("stream.take", block=b):
                blk = source.take(b)
            nxt = pos + depth
            if nxt < n_seq:
                source.start(seq[nxt])
            elif wrap and nxt - n_seq < n_seq:
                source.start(seq[nxt - n_seq])
            # dispatch-only under the async pipeline: the span measures
            # host-side step dispatch, not device completion (which the
            # NEXT block's take() overlaps with by design)
            with telemetry.span("stream.compute", block=b):
                carry, out = step(carry, b, blk)
        outs.append(out)
        after_block(pos, b, carry)
    return carry, outs
