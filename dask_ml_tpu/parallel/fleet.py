"""Fault-tolerant serving fleet: replica sharding, SLO routing, hot-swap.

PR 9's :class:`~dask_ml_tpu.parallel.serving.ServingLoop` made online
inference continuously-batched and compile-once — on ONE loop over ONE
mesh, which is a single point of failure and a single queue. This module
is the production tier above it (ROADMAP north-star item 2: keep
answering when a replica dies, a model is swapped mid-flight, or traffic
exceeds capacity):

- :class:`ServingFleet` runs N ``ServingLoop`` replicas over DISJOINT
  device subsets (each replica gets its own 1-D data mesh over its slice
  of ``jax.devices()``; with fewer devices than replicas they share)
  behind a host-side router. Routing balances on the queue-depth and
  batch-latency signals each loop already exports through the PR-7
  telemetry layer (read via :meth:`ServingLoop.queue_depth` /
  :meth:`ServingLoop.latency_s`, the loop-side mirrors of the
  ``serving.queue_depth`` gauge and ``serving.batch_seconds`` surface, so
  balancing also works with telemetry off).
- **Health**: every replica's dispatch thread heartbeats each collect
  iteration; a monitor thread declares a replica dead when the heartbeat
  stalls past ``heartbeat_timeout_s`` or the thread is gone, and a
  consecutive-failure circuit breaker takes an erroring replica out of
  rotation for ``breaker_cooldown_s`` (half-open probe after cooldown).
- **Re-route + replay**: when a replica dies or drains, its in-flight
  requests are replayed on a survivor from the fleet's own host-side
  copy. Completion is idempotent BY REQUEST ID — the first resolution of
  a fleet future wins, so a false-positive death costs duplicate
  compute, never a dropped or double-resolved future.
- **SLO-aware admission**: ``submit(priority=, deadline=)`` flows into
  the loops' earliest-deadline-first coalescer; past-deadline requests
  are shed with :class:`~dask_ml_tpu.parallel.serving.DeadlineExceeded`
  instead of queueing to death, and a replica's
  :class:`~dask_ml_tpu.parallel.serving.ServingQueueFull` triggers
  router SPILLOVER to a sibling before backpressure ever reaches the
  caller.
- **Zero-downtime hot-swap**: :meth:`ServingFleet.swap` builds the new
  :class:`~dask_ml_tpu.parallel.serving.ServedModel`, pre-compiles its
  programs on every replica through the exact serving staging path
  (``warmup_model``), THEN atomically installs it with a bumped
  monotonic version — in-flight batches finish on the old program
  (dispatch resolves the registry entry per batch), new batches take the
  new one, and no request is lost or served a half-updated model.
- **Wire protocol**: :class:`FleetServer` accepts out-of-process clients
  over a socket speaking the shared length-prefixed magic+length+sha256
  frame codec (:mod:`dask_ml_tpu.parallel.framing` — the same frame
  layout PR 8's checkpoints use). Frame payloads are the TYPED codec
  (:func:`~dask_ml_tpu.parallel.framing.encode_payload`: a JSON control
  envelope + dtype/shape-tagged numpy buffers, strict decode caps, no
  object deserialization anywhere), so the socket surface is safe for
  untrusted clients. One frame = one request; responses return out of
  order tagged by id, and a request that fails validation fails ITS
  caller's frame only — never a batch another client shares.
  :class:`FleetClient` adds per-request deadlines (a wedged server
  surfaces as a typed :class:`FleetTimeoutError`, never an eternal
  block) and reconnects ONCE when the server closed the previous
  connection cleanly between frames. The process-isolated tier above
  this (``parallel/procfleet.py``) runs each replica as its own OS
  process behind exactly this wire.

Telemetry (all at their increment sites, mirror discipline of
docs/observability.md): ``fleet.reroutes``, ``fleet.spillover``,
``fleet.shed``, ``fleet.swaps``, ``fleet.replica_deaths`` counters and
the ``fleet.replica_up`` gauge. ``bench.py --serving --fleet`` drills the
whole tier — mixed-priority traffic, a mid-run hot-swap, a replica kill,
zero dropped requests, bit-identity to the direct path — and commits the
gates as FLEET_r01.json (docs/serving.md, "The serving fleet").
"""

from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time
import uuid
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Optional

import numpy as np

from dask_ml_tpu.parallel import framing
from dask_ml_tpu.parallel.serving import (
    DeadlineExceeded,
    ModelRegistry,
    ServedModel,
    ServingClosed,
    ServingError,
    ServingLoop,
    ServingQueueFull,
    ServingStopped,
    _fail_future,
)

__all__ = [
    "ServingFleet",
    "FleetServer",
    "FleetClient",
    "FleetTimeoutError",
    "RetryBudget",
]


class FleetTimeoutError(ServingError):
    """A wire request (or ping) exceeded its deadline: the server socket
    is wedged, the replica process is gone without closing the
    connection, or the request simply outlived its budget. Typed so
    callers (and the process-fleet router, which treats it as a re-route
    signal) can distinguish "no answer in time" from a served error —
    and so nothing ever blocks forever on a dead peer."""


def _set_future(fut: Future, result) -> bool:
    """Idempotent result delivery: claims and resolves ``fut`` unless a
    racing path (duplicate completion after a false-positive death) got
    there first. First resolution wins; returns True when it was this
    one."""
    if fut.done():
        return False  # the other completion won (duplicate compute only)
    try:
        if not fut.set_running_or_notify_cancel():
            return False  # client cancelled
    except RuntimeError:
        pass  # already claimed (e.g. by a replay in flight)
    try:
        fut.set_result(result)
        return True
    except Exception:
        return False  # already resolved — duplicate compute, not an error


@dataclasses.dataclass(eq=False)
class _Replica:
    name: str
    loop: ServingLoop
    mesh: object
    consecutive_failures: int = 0
    breaker_open_until: float = 0.0  # monotonic instant
    dead: bool = False
    #: rolling window of fleet-observed request latencies — the hedge
    #: threshold's quantile source (same shape as the process fleet's)
    lat: deque = dataclasses.field(
        default_factory=lambda: deque(maxlen=128))

    def breaker_open(self) -> bool:
        return time.monotonic() < self.breaker_open_until


@dataclasses.dataclass(eq=False)
class _FleetRequest:
    """The fleet's own host-side copy of one request — everything needed
    to replay it on a survivor when its replica dies."""

    rid: str
    model: str
    method: str
    X: np.ndarray
    priority: int
    deadline_abs: Optional[float]  # absolute perf_counter instant
    future: Future
    attempts: int = 0
    replica: Optional[str] = None
    hedges: int = 0
    #: replica name -> dispatch perf_counter instant for every attempt
    #: still awaiting completion (the hedge scan reads waits off this;
    #: each completion callback pops its own entry)
    outstanding: dict = dataclasses.field(default_factory=dict)

    def remaining(self) -> Optional[float]:
        if self.deadline_abs is None:
            return None
        return self.deadline_abs - time.perf_counter()


class ServingFleet:
    """N serving replicas behind a health-checked, SLO-aware router
    (module docstring has the architecture).

    Parameters
    ----------
    registry : ModelRegistry, optional
        ONE registry shared by every replica (hot-swap publishes once);
        a private one is created by default.
    n_replicas : int
        Replica count. Each replica gets ``len(jax.devices())//n`` devices
        (disjoint, in device order); when devices are scarcer than
        replicas they round-robin single devices.
    meshes : sequence of Mesh, optional
        Explicit per-replica meshes (overrides ``n_replicas`` slicing).
    policy, max_batch_rows, max_queue, coalesce_window_s, retry_policy
        Forwarded to every :class:`ServingLoop`.
    heartbeat_interval_s, heartbeat_timeout_s
        Monitor cadence and the heartbeat stall past which a replica is
        declared dead (its in-flight requests replay on survivors).
    max_consecutive_failures, breaker_cooldown_s
        Circuit breaker: after this many consecutive request failures a
        replica leaves rotation for the cooldown, then gets a half-open
        probe.
    max_replays : int, optional
        Re-route budget per request (default: replica count) — a request
        is failed with its last cause rather than bouncing forever.
    hedge : bool
        Adaptive request hedging (default OFF for the in-process tier):
        a request waiting past ``hedge_factor`` × the
        ``hedge_quantile``-th quantile of its replica's recent observed
        latencies (loop EWMA while the window fills, ``hedge_cold_s``
        before any sample, floored at ``hedge_min_s``) is speculatively
        re-submitted to the next-best replica — first resolution wins
        under the same idempotent completion the replay path already
        uses, so the duplicate work is deliberate and counted
        (``serving.hedged`` / ``serving.hedge_wins``).
    hedge_quantile, hedge_factor, hedge_min_s, hedge_cold_s
        The hedge threshold's shape (same contract as the process
        fleet's).
    drain : GracefulDrain, optional
        Shared drain scope: on SIGTERM (or ``drain.request()``) every
        replica stops accepting, flushes its queue, and resolves every
        future; the fleet stops admitting (new submits raise
        :class:`ServingStopped`).
    fault_injector : FaultInjector, optional
        Forwarded to every replica — ``kill_replica``/``slow_replica``/
        ``delay_dispatch`` plans address replicas by name
        (``"{name}-r{i}"``).
    """

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 n_replicas: int = 2,
                 meshes=None,
                 policy=None,
                 max_batch_rows: int = 2048,
                 max_queue: int = 4096,
                 coalesce_window_s="adaptive",
                 heartbeat_interval_s: float = 0.05,
                 heartbeat_timeout_s: float = 2.0,
                 max_consecutive_failures: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 max_replays: Optional[int] = None,
                 hedge: bool = False,
                 hedge_quantile: float = 0.5,
                 hedge_factor: float = 3.0,
                 hedge_min_s: float = 0.05,
                 hedge_cold_s: float = 0.5,
                 drain=None,
                 retry_policy=None,
                 fault_injector=None,
                 name: str = "fleet"):
        self.registry = registry if registry is not None else ModelRegistry()
        self.n_replicas = int(n_replicas)
        self._meshes = list(meshes) if meshes is not None else None
        self.policy = policy
        self.max_batch_rows = int(max_batch_rows)
        self.max_queue = int(max_queue)
        self.coalesce_window_s = (
            coalesce_window_s if isinstance(coalesce_window_s, str)
            else float(coalesce_window_s))
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.max_replays = max_replays
        self.hedge = bool(hedge)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_factor = float(hedge_factor)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_cold_s = float(hedge_cold_s)
        self.name = str(name)
        self._drain = drain
        self._retry_policy = retry_policy
        self._fault_injector = fault_injector

        self._lock = threading.Lock()
        self._replicas: list = []
        self._inflight: dict = {}  # rid -> _FleetRequest
        self._closing = False
        self._started = False
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._rr = 0  # round-robin tiebreak cursor
        # operational counters (telemetry mirrors at the increment sites)
        self.n_reroutes = 0
        self.n_spillovers = 0
        self.n_shed = 0
        self.n_swaps = 0
        self.n_replica_deaths = 0
        self.n_hedged = 0
        self.n_hedge_wins = 0

    # -- lifecycle ---------------------------------------------------------

    def _build_meshes(self) -> list:
        import jax

        from dask_ml_tpu.parallel import mesh as mesh_lib

        if self._meshes is not None:
            if len(self._meshes) < 1:
                raise ValueError("meshes must name at least one mesh")
            return self._meshes
        devs = list(jax.devices())
        n = self.n_replicas
        if n < 1:
            raise ValueError("n_replicas must be >= 1")
        if len(devs) >= n:
            per = len(devs) // n
            groups = [devs[i * per:(i + 1) * per] for i in range(n)]
        else:
            # scarcer devices than replicas: round-robin single devices
            # (replicas share hardware but keep independent queues/meshes
            # — still the right shape for failover/drain logic off-TPU)
            groups = [[devs[i % len(devs)]] for i in range(n)]
        return [mesh_lib.make_mesh(devices=g) for g in groups]

    def start(self) -> "ServingFleet":
        if self._started:
            return self
        meshes = self._build_meshes()
        self._replicas = []
        for i, mesh in enumerate(meshes):
            rname = f"{self.name}-r{i}"
            loop = ServingLoop(
                self.registry, policy=self.policy,
                max_batch_rows=self.max_batch_rows,
                max_queue=self.max_queue,
                coalesce_window_s=self.coalesce_window_s,
                mesh=mesh, drain=self._drain,
                retry_policy=self._retry_policy,
                fault_injector=self._fault_injector,
                name=rname)
            loop.start()
            self._replicas.append(_Replica(name=rname, loop=loop, mesh=mesh))
        self._closing = False
        self._started = True
        from dask_ml_tpu.parallel import telemetry

        # like ServingLoop.start: the monitor thread inherits an ENABLED
        # telemetry scope so its increment sites (replica_up gauge,
        # replica_deaths/reroutes on death) mirror under
        # config_context(telemetry=True) around start()
        self._telemetry_inherit = telemetry.enabled()
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name=f"{self.name}-monitor",
            daemon=True)
        self._monitor.start()
        self._set_replica_up()
        return self

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Stop the fleet: stop admitting, stop every replica
        (``drain=True`` flushes their queues and resolves every future),
        then fail whatever replay bookkeeping remains so nothing is ever
        left pending."""
        with self._lock:
            self._closing = True
        self._monitor_stop.set()
        m = self._monitor
        if m is not None and m.is_alive() \
                and m is not threading.current_thread():
            m.join(timeout)
        for rep in self._replicas:
            rep.loop.stop(drain=drain, timeout=timeout)
        # anything still inflight lost its completion callback's replay
        # path (closing → no re-route); fail it rather than leak it
        with self._lock:
            leftovers = list(self._inflight.values())
            self._inflight.clear()
        for freq in leftovers:
            _fail_future(freq.future, ServingStopped(
                f"fleet {self.name!r} stopped"))

    def warmup(self, buckets=None, models=None) -> dict:
        """Pre-compile every (replica, model, method, bucket) program;
        aggregated counts."""
        out = {"n_programs": 0, "n_compiles": 0, "compile_seconds": 0.0}
        for rep in self._replicas:
            w = rep.loop.warmup(buckets=buckets, models=models)
            out["n_programs"] += w["n_programs"]
            out["n_compiles"] += w["n_compiles"]
            out["compile_seconds"] = round(
                out["compile_seconds"] + w["compile_seconds"], 3)
        return out

    # -- registry ----------------------------------------------------------

    def register(self, name: str, estimator, *, methods=None) -> ServedModel:
        return self.registry.register(name, estimator, methods=methods)

    def swap(self, name: str, estimator, *, methods=None,
             warmup: bool = True) -> int:
        """Zero-downtime hot-swap: build the new ServedModel, pre-compile
        its programs on every live replica (so the new version's first
        batch pays no compile), then atomically install it with a bumped
        version. In-flight batches finish on the old program; returns the
        new version number."""
        from dask_ml_tpu.parallel import telemetry

        model = self.registry.build(name, estimator, methods=methods)
        if warmup:
            for rep in self._replicas:
                if not rep.dead and rep.loop.alive():
                    rep.loop.warmup_model(model)
        self.registry.install(model)
        with self._lock:
            self.n_swaps += 1
        if telemetry.enabled():
            telemetry.metrics().counter("fleet.swaps", model=name).inc()
        return model.version

    # -- routing -----------------------------------------------------------

    @property
    def max_request_rows(self) -> int:
        """Per-request row cap (the replica loops' batch budget) —
        present so ``ParallelPostFit(serving=fleet)`` chunks exactly as
        it would against a single loop."""
        return self.max_batch_rows

    def replicas_up(self) -> int:
        return sum(1 for rep in self._replicas
                   if not rep.dead and rep.loop.alive())

    def _set_replica_up(self) -> None:
        from dask_ml_tpu.parallel import telemetry

        if telemetry.enabled():
            telemetry.metrics().gauge("fleet.replica_up").set(
                self.replicas_up())

    def _eligible(self, exclude) -> list:
        return [rep for rep in self._replicas
                if rep.name not in exclude and not rep.dead
                and rep.loop.alive()]

    #: latency quantum for routing (seconds): EWMA differences below this
    #: are noise (two healthy replicas jitter at the ms level), so the
    #: round-robin tiebreak spreads load across them; a genuine straggler
    #: (an injected slow_replica penalty, a contended device) exceeds a
    #: bucket and is routed around.
    LATENCY_QUANTUM_S = 0.1

    def _pick(self, exclude) -> Optional[_Replica]:
        """Least-loaded routing on (queue depth, quantized latency EWMA)
        — the loop-side mirrors of the ``serving.queue_depth`` gauge and
        ``serving.batch_seconds`` surface the telemetry layer exports —
        with round-robin spread among equals. Breaker-open replicas are
        skipped unless nothing else is live (half-open probe of the
        soonest-expiring breaker)."""
        live = self._eligible(exclude)
        if not live:
            return None
        closed = [rep for rep in live if not rep.breaker_open()]
        if not closed:
            return min(live, key=lambda rep: rep.breaker_open_until)
        with self._lock:
            self._rr += 1
            rr = self._rr
        return min(
            closed,
            key=lambda rep: (rep.loop.queue_depth()
                             + (1 if rep.loop.busy else 0),
                             int(rep.loop.latency_s()
                                 / self.LATENCY_QUANTUM_S),
                             (self._replicas.index(rep) + rr)
                             % max(len(self._replicas), 1)))

    def _note_failure(self, rep: _Replica) -> None:
        rep.consecutive_failures += 1
        if rep.consecutive_failures >= self.max_consecutive_failures \
                and not rep.breaker_open():
            rep.breaker_open_until = (time.monotonic()
                                      + self.breaker_cooldown_s)

    def _note_success(self, rep: _Replica) -> None:
        rep.consecutive_failures = 0
        rep.breaker_open_until = 0.0

    def submit(self, model: str, X, method: str = "predict", *,
               priority: int = 0, deadline: Optional[float] = None,
               request_id: Optional[str] = None) -> Future:
        """Route one request to the least-loaded live replica; returns a
        fleet-level Future that survives replica death (re-route +
        replay, idempotent by ``request_id``). Validation failures, an
        already-expired ``deadline``, and fleet-wide backpressure
        (:class:`ServingQueueFull` from EVERY live replica — spillover
        exhausted) raise synchronously to THIS caller. Submitting an id
        that is already in flight returns the existing future (client
        retry = same request)."""
        if self._drain is not None and self._drain.requested:
            self._closing = True
        if self._closing or not self._started:
            raise ServingStopped(
                f"fleet {self.name!r} is not accepting requests")
        rid = str(request_id) if request_id is not None else uuid.uuid4().hex
        with self._lock:
            existing = self._inflight.get(rid)
            if existing is not None:
                return existing.future
        now = time.perf_counter()
        if deadline is not None and float(deadline) <= 0.0:
            self._count_shed(model)
            raise DeadlineExceeded(
                f"request deadline {float(deadline):.3f}s is already past "
                "at fleet admission")
        freq = _FleetRequest(
            rid=rid, model=str(model), method=str(method), X=X,
            priority=int(priority),
            deadline_abs=None if deadline is None else now + float(deadline),
            future=Future())
        self._route(freq, sync=True)
        return freq.future

    def call(self, model: str, X, method: str = "predict", *,
             priority: int = 0, deadline: Optional[float] = None,
             timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience wrapped in a ``fleet.request`` span."""
        from dask_ml_tpu.parallel import telemetry

        with telemetry.span("fleet.request", model=str(model),
                            method=str(method)):
            return self.submit(model, X, method=method, priority=priority,
                               deadline=deadline).result(timeout)

    def _count_shed(self, model: str) -> None:
        from dask_ml_tpu.parallel import telemetry

        with self._lock:
            self.n_shed += 1
        if telemetry.enabled():
            telemetry.metrics().counter("fleet.shed", model=model).inc()

    def _route(self, freq: _FleetRequest, *, sync: bool,
               exclude: Optional[set] = None) -> None:
        """Place ``freq`` on a replica. ``sync=True`` (first admission)
        propagates terminal errors to the caller; ``sync=False`` (replay)
        sets them on the fleet future. Spillover: a queue-full replica is
        excluded and the next one tried before backpressure surfaces."""
        from dask_ml_tpu.parallel import telemetry

        exclude = set() if exclude is None else set(exclude)
        queue_full_seen = False
        while True:
            if self._closing:
                self._terminal(freq, ServingStopped(
                    f"fleet {self.name!r} is stopping"), sync)
                return
            rep = self._pick(exclude)
            if rep is None:
                if queue_full_seen:
                    exc: ServingError = ServingQueueFull(
                        "every live replica's queue is at capacity "
                        f"({self.max_queue} requests each)")
                else:
                    exc = ServingStopped(
                        f"fleet {self.name!r} has no live replica")
                self._terminal(freq, exc, sync)
                return
            remaining = freq.remaining()
            if remaining is not None and remaining <= 0.0:
                self._count_shed(freq.model)
                self._terminal(freq, DeadlineExceeded(
                    f"request {freq.rid} deadline passed during routing"),
                    sync)
                return
            t0 = time.perf_counter()
            try:
                rfut = rep.loop.submit(
                    freq.model, freq.X, method=freq.method,
                    priority=freq.priority, deadline=remaining)
            except ServingQueueFull:
                queue_full_seen = True
                exclude.add(rep.name)
                with self._lock:
                    self.n_spillovers += 1
                if telemetry.enabled():
                    telemetry.metrics().counter(
                        "fleet.spillover", replica=rep.name).inc()
                continue
            except ServingClosed:
                # draining/stopped replica: take it out of this route and
                # let the health monitor decide its fate
                exclude.add(rep.name)
                continue
            except DeadlineExceeded as e:
                self._count_shed(freq.model)
                self._terminal(freq, e, sync)
                return
            except Exception as e:  # noqa: BLE001 — validation errors etc.
                self._terminal(freq, e, sync)
                return
            freq.attempts += 1
            freq.replica = rep.name
            with self._lock:
                freq.outstanding[rep.name] = t0
                self._inflight[freq.rid] = freq
            rfut.add_done_callback(
                lambda f, freq=freq, rep=rep, t0=t0:
                self._on_done(freq, rep, t0, False, f))
            return

    def _terminal(self, freq: _FleetRequest, exc: BaseException,
                  sync: bool) -> None:
        with self._lock:
            self._inflight.pop(freq.rid, None)
        if sync:
            raise exc
        _fail_future(freq.future, exc)

    def _replay_budget(self) -> int:
        return (self.max_replays if self.max_replays is not None
                else max(len(self._replicas), 1))

    def _on_done(self, freq: _FleetRequest, rep: _Replica, t0: float,
                 hedge: bool, rfut) -> None:
        """Replica-future completion, on the replica's dispatch thread
        (or the failing path's). Success and model errors resolve the
        fleet future; replica-death errors re-route + replay.

        With hedging, a request may have SEVERAL attempts outstanding:
        each completion pops only its own ``outstanding`` entry, the
        first successful resolution wins (``_set_future`` is
        idempotent), and a losing attempt's failure never terminates a
        request a sibling attempt can still answer."""
        from dask_ml_tpu.parallel import telemetry
        from dask_ml_tpu.parallel.faults import SimulatedReplicaDeath

        with self._lock:
            owned = freq.outstanding.get(rep.name) == t0
            if owned:
                freq.outstanding.pop(rep.name, None)
        try:
            result = rfut.result()
        except (ServingStopped, ServingClosed, SimulatedReplicaDeath) as e:
            # the REPLICA went away, not the request: re-route + replay
            self._note_failure(rep)
            if freq.future.done() or not owned:
                return  # a sibling attempt already resolved (or will)
            with self._lock:
                still_out = bool(freq.outstanding)
            if freq.attempts > self._replay_budget():
                if still_out:
                    # another attempt (a hedge on a live replica) may
                    # still resolve this request; if it fails too, ITS
                    # callback lands here with nothing outstanding
                    return
                self._terminal(freq, e, sync=False)
                return
            with self._lock:
                self.n_reroutes += 1
            if telemetry.enabled():
                telemetry.metrics().counter(
                    "fleet.reroutes", replica=rep.name).inc()
            self._route(freq, sync=False, exclude={rep.name})
        except DeadlineExceeded as e:
            if freq.future.done():
                return
            self._count_shed(freq.model)
            self._terminal(freq, e, sync=False)
        except BaseException as e:  # noqa: BLE001 — the request's own error
            self._note_failure(rep)
            if freq.future.done():
                return
            self._terminal(freq, e, sync=False)
        else:
            self._note_success(rep)
            dt = time.perf_counter() - t0
            with self._lock:
                rep.lat.append(dt)
                self._inflight.pop(freq.rid, None)
            if _set_future(freq.future, result) and hedge:
                with self._lock:
                    self.n_hedge_wins += 1
                if telemetry.enabled():
                    telemetry.metrics().counter(
                        "serving.hedge_wins", replica=rep.name).inc()

    # -- hedging -----------------------------------------------------------

    def _hedge_threshold(self, rep: _Replica) -> float:
        """``hedge_factor`` × the ``hedge_quantile`` of ``rep``'s recent
        fleet-observed latencies (loop EWMA while the window is short,
        ``hedge_cold_s`` before any), floored at ``hedge_min_s`` — the
        same adaptive shape as the process fleet: a uniformly-slow
        replica raises its own bar, hedging targets the TAIL."""
        with self._lock:
            samples = list(rep.lat)
        if len(samples) >= 8:
            base = float(np.quantile(samples, self.hedge_quantile))
        else:
            base = float(rep.loop.latency_s())
            if base <= 0.0:
                return self.hedge_cold_s
        return max(self.hedge_min_s, self.hedge_factor * base)

    def _hedge_scan(self) -> None:
        """One monitor-tick pass over in-flight requests: any attempt
        waiting past its replica's adaptive threshold gets ONE
        speculative re-submission on the next-best replica."""
        from dask_ml_tpu.parallel import telemetry

        now = time.perf_counter()
        with self._lock:
            candidates = [freq for freq in self._inflight.values()
                          if not freq.future.done() and freq.hedges < 1
                          and freq.outstanding]
        by_name = {rep.name: rep for rep in self._replicas}
        thresholds: dict = {}
        for freq in candidates:
            with self._lock:
                waits = list(freq.outstanding.items())
            for rep_name, t0 in waits:
                rep = by_name.get(rep_name)
                if rep is None:
                    continue
                thr = thresholds.get(rep_name)
                if thr is None:
                    thr = thresholds[rep_name] = \
                        self._hedge_threshold(rep)
                if now - t0 <= thr:
                    continue
                target = self._pick(
                    exclude={n for n, _ in waits} | {rep_name})
                if target is None:
                    break
                remaining = freq.remaining()
                if remaining is not None and remaining <= 0.0:
                    break
                ht0 = time.perf_counter()
                try:
                    rfut = target.loop.submit(
                        freq.model, freq.X, method=freq.method,
                        priority=freq.priority, deadline=remaining)
                except Exception:  # noqa: BLE001 — target refused; later
                    break  # scan may retry with the budget unconsumed
                freq.hedges += 1
                with self._lock:
                    freq.attempts += 1
                    freq.outstanding[target.name] = ht0
                    self.n_hedged += 1
                if telemetry.enabled():
                    telemetry.metrics().counter(
                        "serving.hedged", replica=target.name).inc()
                rfut.add_done_callback(
                    lambda f, freq=freq, rep=target, t0=ht0:
                    self._on_done(freq, rep, t0, True, f))
                break

    # -- health monitoring -------------------------------------------------

    def _monitor_loop(self) -> None:
        import contextlib

        from dask_ml_tpu import config as config_lib

        ctx = (config_lib.config_context(telemetry=True)
               if getattr(self, "_telemetry_inherit", False)
               else contextlib.nullcontext())
        interval = self.heartbeat_interval_s
        with ctx:
            while not self._monitor_stop.wait(interval):
                if self._drain is not None and self._drain.requested:
                    with self._lock:
                        self._closing = True
                if self.hedge and not self._closing:
                    try:
                        self._hedge_scan()
                    except Exception:  # noqa: BLE001 — monitor survives
                        import logging

                        logging.getLogger(__name__).exception(
                            "fleet %r: hedge scan failed (continuing)",
                            self.name)
                for rep in self._replicas:
                    loop = rep.loop
                    if rep.dead:
                        # resurrection: a FALSE-positive death (slow
                        # batch stalled the heartbeat, loop actually
                        # fine) heals once the beat returns — the replay
                        # already made it safe, this makes it temporary.
                        # A crashed/stopped loop is terminal.
                        if loop.alive() and loop.heartbeat_age() \
                                <= self.heartbeat_timeout_s:
                            rep.dead = False
                            rep.consecutive_failures = 0
                            rep.breaker_open_until = 0.0
                            self._set_replica_up()
                        continue
                    if not loop.alive():
                        # thread gone or crashed: immediate death
                        if loop.fatal is not None or loop.stopped:
                            self._declare_dead(rep)
                        continue
                    if loop.heartbeat_age() > self.heartbeat_timeout_s:
                        self._declare_dead(rep)

    def _declare_dead(self, rep: _Replica) -> None:
        """Terminal for the replica: take it out of rotation and replay
        its in-flight requests on survivors. Idempotent resolution makes
        a FALSE-positive declaration (stalled heartbeat, loop actually
        alive) safe: both completions race to the same fleet future and
        the first one wins — duplicate compute, never a double resolve."""
        from dask_ml_tpu.parallel import telemetry

        if rep.dead:
            return
        rep.dead = True
        self._set_replica_up()
        if self._closing:
            # fleet-wide drain/stop: replicas stopping cleanly are not
            # deaths — no counter, no replay (stop() fails leftovers)
            return
        with self._lock:
            self.n_replica_deaths += 1
            victims = [freq for freq in self._inflight.values()
                       if freq.replica == rep.name
                       or rep.name in freq.outstanding]
        if telemetry.enabled():
            telemetry.metrics().counter(
                "fleet.replica_deaths", replica=rep.name).inc()
        cause = ServingStopped(
            f"replica {rep.name!r} declared dead "
            f"(heartbeat {rep.loop.heartbeat_age():.2f}s"
            + (f", fatal {rep.loop.fatal!r}" if rep.loop.fatal is not None
               else "") + ")")
        for freq in victims:
            from dask_ml_tpu.parallel import telemetry as _t

            if freq.attempts > self._replay_budget():
                self._terminal(freq, cause, sync=False)
                continue
            with self._lock:
                self.n_reroutes += 1
            if _t.enabled():
                _t.metrics().counter(
                    "fleet.reroutes", replica=rep.name).inc()
            self._route(freq, sync=False, exclude={rep.name})

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            counters = {
                "reroutes": self.n_reroutes,
                "spillovers": self.n_spillovers,
                "shed": self.n_shed,
                "swaps": self.n_swaps,
                "replica_deaths": self.n_replica_deaths,
                "hedged": self.n_hedged,
                "hedge_wins": self.n_hedge_wins,
                "inflight": len(self._inflight),
            }
        return {
            "name": self.name,
            "replicas_up": self.replicas_up(),
            "replicas": {rep.name: {
                "alive": rep.loop.alive(),
                "dead": rep.dead,
                "breaker_open": rep.breaker_open(),
                "queue_depth": rep.loop.queue_depth(),
                "latency_ewma_s": round(rep.loop.latency_s(), 6),
                **{k: v for k, v in rep.loop.stats().items()
                   if k in ("submitted", "completed", "errors", "batches",
                            "rows_served", "shed")},
            } for rep in self._replicas},
            **counters,
        }


# ---------------------------------------------------------------------------
# wire protocol: out-of-process clients over a socket
# ---------------------------------------------------------------------------

#: errors the wire protocol maps by name so a remote caller can catch the
#: same classes a local one would
_WIRE_ERRORS = {
    "DeadlineExceeded": DeadlineExceeded,
    "ServingQueueFull": ServingQueueFull,
    "ServingStopped": ServingStopped,
    "ServingClosed": ServingClosed,
    "FleetTimeoutError": FleetTimeoutError,
    "PayloadError": framing.PayloadError,
    "ValueError": ValueError,
    "KeyError": KeyError,
}


class _ShmSwitch:
    """Writer-queue marker: every response enqueued after it leaves over
    the negotiated shm ring instead of the TCP frame wire (FIFO order
    guarantees the hello ACK, enqueued just before, went out on TCP)."""

    __slots__ = ("ep",)

    def __init__(self, ep):
        self.ep = ep


#: one byte on the retained TCP socket after every shm ring write: the
#: peer blocks in the kernel (cheap, instant wakeup) instead of polling
#: the ring, and the byte stream doubles as the liveness/EOF channel
_DOORBELL = b"\x01"


class FleetServer:
    """Socket front-end for a :class:`ServingFleet` (or a single
    :class:`ServingLoop`): out-of-process clients submit inference
    requests as frames of the shared codec
    (:data:`~dask_ml_tpu.parallel.framing.WIRE_MAGIC`).

    One frame carries one TYPED request payload
    (:func:`~dask_ml_tpu.parallel.framing.encode_payload`: a JSON
    control envelope — ``op="submit"``, id, model, method, priority,
    deadline — plus the row array as one dtype/shape-tagged buffer);
    responses are frames tagged with the request id and return OUT OF
    ORDER as futures resolve, so one slow request never convoys a
    connection. ``op="ping"`` answers with the serving pid;
    ``op="stats"`` returns the routing-signal snapshot (queue depth,
    latency EWMA, batch count — plus whatever ``extra_stats`` adds; the
    process-fleet replicas report their steady-state compile count
    through it). A request that fails validation (or sheds on its
    deadline) gets an error response naming the exception class — that
    caller only, never a shared batch
    (validation-fails-the-caller-not-the-batch, docs/serving.md). A
    payload that fails its typed decode fails ITS frame only (the frame
    boundary was intact); a frame that fails its checksum gets an error
    response and the connection is closed (the stream's byte alignment
    can no longer be trusted).

    Nothing received on this socket is ever deserialized as an object —
    control is JSON under a size cap, buffers are (dtype, shape, bytes)
    against an allowlist — so the surface is safe for untrusted clients
    (the remaining exposure is load, which ``max_payload`` and the
    serving layer's admission control bound).
    """

    def __init__(self, fleet, host: str = "127.0.0.1", port: int = 0, *,
                 max_payload: int = 256 * 1024 * 1024,
                 extra_stats=None, shm: bool = True):
        self.fleet = fleet
        self.max_payload = int(max_payload)
        self._extra_stats = extra_stats
        self.shm = bool(shm)
        self.n_shm_conns = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: list = []
        self.n_requests = 0
        self.n_frame_errors = 0

    def start(self) -> "FleetServer":
        if self._accept_thread is not None:
            return self
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-server-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed
            self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="fleet-server-conn", daemon=True).start()

    #: response-queue depth per connection; a client that stops READING
    #: while responses pile up is broken — the connection is closed
    #: rather than buffering unboundedly
    MAX_PENDING_RESPONSES = 1024

    def _send(self, conn, out_q, control: dict, arrays=(),
              release=None) -> None:
        """Enqueue one response for the connection's writer thread. The
        write itself happens OFF the caller's thread: responses are
        delivered from future callbacks that run on replica dispatch
        threads, and a blocking ``sendall`` to a stalled client there
        would freeze the replica's dispatch loop (and read as a death to
        the health monitor). ``release`` (the shm record hold of the
        request this response answers) runs after the response bytes
        left — the response may alias the request's in-ring buffers, so
        the record cannot be recycled a moment earlier."""
        import queue as queue_mod

        try:
            out_q.put_nowait((control, tuple(arrays), release))
        except queue_mod.Full:
            if release is not None:
                release()
            try:
                conn.close()  # reader+writer unwind on the closed socket
            except OSError:
                pass

    def _write_loop(self, conn, out_q) -> None:
        from dask_ml_tpu.parallel import telemetry

        shm_ep = None
        while True:
            msg = out_q.get()
            if msg is None:
                return
            if msg.__class__ is _ShmSwitch:
                # everything enqueued after this marker leaves over the
                # negotiated shm ring; the hello ACK sits BEFORE it in
                # this FIFO, so the client reads the ACK on TCP and only
                # then arms its ring reader
                shm_ep = msg.ep
                continue
            control, arrays, release = msg
            try:
                try:
                    if shm_ep is not None:
                        shm_ep.send(control, arrays,
                                    timeout=self.SEND_TIMEOUT_S)
                        conn.sendall(_DOORBELL)
                    else:
                        # encode ONCE and write from the retained
                        # buffers: a single digest pass over the parts,
                        # no payload concatenation copy per response
                        n = framing.write_frame_parts(
                            conn,
                            framing.encode_payload_parts(control, arrays),
                            magic=framing.WIRE_MAGIC,
                            checksum=framing.WIRE_CHECKSUM)
                        if telemetry.enabled():
                            telemetry.metrics().counter(
                                "wire.bytes", transport="tcp").inc(n)
                except framing.PayloadError as e:
                    # an un-encodable RESPONSE (e.g. a host-fallback
                    # model returning string labels — a dtype the typed
                    # wire refuses) fails ITS caller with an error
                    # frame; the writer must survive, or every later
                    # response on this connection silently wedges
                    err = {"id": control.get("id"), "ok": False,
                           "error": "PayloadError",
                           "message": f"response not wire-encodable: "
                                      f"{str(e)[:512]}"}
                    if shm_ep is not None:
                        shm_ep.send(err, timeout=self.SEND_TIMEOUT_S)
                        conn.sendall(_DOORBELL)
                    else:
                        framing.write_frame(
                            conn, framing.encode_payload(err),
                            magic=framing.WIRE_MAGIC,
                            checksum=framing.WIRE_CHECKSUM)
            except OSError:
                return  # peer went away; nothing to deliver to
            finally:
                if release is not None:
                    release()

    #: writer-side bound on one shm ring send — a client that stopped
    #: draining its response ring for this long is treated as gone
    SEND_TIMEOUT_S = 30.0

    def _serve_conn(self, conn) -> None:
        import queue as queue_mod

        out_q: "queue_mod.Queue" = queue_mod.Queue(
            maxsize=self.MAX_PENDING_RESPONSES)
        state: dict = {"shm": None}
        writer = threading.Thread(target=self._write_loop,
                                  args=(conn, out_q),
                                  name="fleet-server-writer", daemon=True)
        writer.start()
        try:
            while not self._stop.is_set():
                ep = state["shm"]
                if ep is not None:
                    if not self._serve_shm_step(conn, out_q, ep):
                        return
                    continue
                try:
                    payload = framing.read_frame(
                        conn, magic=framing.WIRE_MAGIC,
                        max_payload=self.max_payload,
                        checksum=framing.WIRE_CHECKSUM)
                except framing.FrameError as e:
                    # a torn/corrupt frame fails ITS caller and ends the
                    # stream: byte alignment is gone, so nothing later on
                    # this connection can be attributed safely
                    self.n_frame_errors += 1
                    self._send(conn, out_q, {
                        "id": None, "ok": False,
                        "error": type(e).__name__, "message": str(e)})
                    return
                if payload is None:
                    return  # clean EOF
                self._handle(conn, out_q, payload, state)
        finally:
            # let queued responses flush, then stop the writer; closing
            # the socket afterwards unblocks a writer stalled mid-send
            try:
                out_q.put_nowait(None)
            except queue_mod.Full:
                pass
            writer.join(5.0)
            ep = state.get("shm")
            if ep is not None:
                ep.close()
            try:
                conn.close()
            except OSError:
                pass
            if conn in self._conns:
                self._conns.remove(conn)

    def _serve_shm_step(self, conn, out_q, ep) -> bool:
        """One step of a negotiated shm connection: drain the request
        ring, then BLOCK on the TCP socket for the client's doorbell
        byte — a kernel wakeup instead of a poll loop, so an idle (or
        GIL-contended) link costs nothing. Every ring record is paired
        with one doorbell byte sent after its READY publish, so a
        drain-to-empty after every wakeup can never strand a record;
        stale coalesced bytes just buy a benign extra drain pass. False
        ends the connection. A ``kill -9``'d client surfaces here
        exactly the way it does on the framed wire: as EOF/reset on the
        socket."""
        import select

        try:
            rec = ep.recv(timeout=0.0)
        except framing.PayloadError as e:
            # typed decode failed but the record frame was intact: fails
            # its request only (record already released), the ring and
            # the connection survive — same contract as the TCP wire
            self._send(conn, out_q, {
                "id": None, "ok": False,
                "error": type(e).__name__, "message": str(e)})
            return True
        except (framing.FrameError, ConnectionError) as e:
            self.n_frame_errors += 1
            self._send(conn, out_q, {
                "id": None, "ok": False,
                "error": type(e).__name__, "message": str(e)})
            return False
        if rec is None:
            try:
                ready, _, _ = select.select([conn], [], [], 0.25)
                if not ready:
                    return True  # idle: loop to re-check server stop
                b = conn.recv(4096)
                if b == b"":
                    return False  # client closed cleanly
            except (OSError, ValueError):
                return False  # reset/abort/closed-fd: client died
            return True
        msg, arrays, token = rec

        def release(t=token):
            ep.release(t)

        self._handle_msg(conn, out_q, msg, arrays, release)
        return True

    def _handle_hello(self, conn, out_q, msg: dict, state: dict) -> None:
        """``op="shm_hello"``: the client created a shared-memory
        segment and asks this server to attach. Attach can only succeed
        when both ends share a kernel — that IS the same-machine test —
        so any failure just answers ``shm: false`` and the connection
        stays on the framed TCP wire, byte-identical semantics."""
        rid = msg.get("id") if isinstance(msg.get("id"), str) else None
        if not self.shm or state.get("shm") is not None:
            self._send(conn, out_q, {
                "id": rid, "ok": True, "shm": False,
                "reason": ("shm disabled on this server" if not self.shm
                           else "shm already negotiated")})
            return
        try:
            from dask_ml_tpu.parallel import shm as shm_lib

            ep = shm_lib.ShmServer(
                str(msg.get("segment")),
                ring_bytes=msg.get("ring_bytes"),
                checksum=msg.get("checksum"))
        except Exception as e:  # noqa: BLE001 — any attach/validate
            # failure means "this link stays on TCP", never an error
            self._send(conn, out_q, {
                "id": rid, "ok": True, "shm": False,
                "reason": f"{type(e).__name__}: {str(e)[:256]}"})
            return
        import queue as queue_mod

        self._send(conn, out_q, {"id": rid, "ok": True, "shm": True})
        try:
            out_q.put_nowait(_ShmSwitch(ep))
        except queue_mod.Full:
            ep.close()
            try:
                conn.close()
            except OSError:
                pass
            return
        state["shm"] = ep
        self.n_shm_conns += 1

    def _stats_snapshot(self) -> dict:
        """The routing-signal summary ``op="stats"`` answers with —
        loop-side queue depth + latency EWMA (the same surfaces the
        in-process router balances on) plus the serving pid, so a
        process-fleet router can label its telemetry per replica
        process."""
        target = self.fleet
        out = {"pid": os.getpid(), "n_requests": self.n_requests}
        qd = getattr(target, "queue_depth", None)
        if callable(qd):
            out["queue_depth"] = int(qd())
        lat = getattr(target, "latency_s", None)
        if callable(lat):
            out["latency_ewma_s"] = float(lat())
        out["batches"] = int(getattr(target, "n_batches", 0))
        if self._extra_stats is not None:
            out.update(self._extra_stats())
        return out

    def _handle(self, conn, out_q, payload, state=None) -> None:
        """One framed TCP request: typed decode, then either the
        shm negotiation op or the shared dispatch."""
        try:
            msg, arrays = framing.decode_payload(payload)
        except Exception as e:  # noqa: BLE001 — per-frame error delivery
            self._send(conn, out_q, {
                "id": None, "ok": False,
                "error": type(e).__name__, "message": str(e)})
            return
        if msg.get("op") == "shm_hello" and state is not None:
            self._handle_hello(conn, out_q, msg, state)
            return
        self._handle_msg(conn, out_q, msg, arrays, None)

    def _handle_msg(self, conn, out_q, msg: dict, arrays,
                    release) -> None:
        """Dispatch one decoded request, transport-agnostic. ``release``
        (shm only) is handed to exactly one ``_send`` — the writer runs
        it after the response leaves, which is when the request's
        in-ring buffers (possibly aliased by the response) are last
        read."""
        rid = None
        try:
            op = msg.get("op")
            rid = msg.get("id")
            if rid is not None and not isinstance(rid, str):
                raise framing.PayloadError(
                    f"request id must be a string, got "
                    f"{type(rid).__name__}")
            if op == "ping":
                self._send(conn, out_q, {"id": rid, "ok": True,
                                         "pong": True,
                                         "pid": os.getpid()},
                           release=release)
                return
            if op == "stats":
                self._send(conn, out_q, {"id": rid, "ok": True,
                                         "stats": self._stats_snapshot()},
                           release=release)
                return
            if op != "submit":
                raise ValueError(f"unknown wire op {op!r}")
            if len(arrays) != 1:
                raise framing.PayloadError(
                    f"submit expects exactly one array buffer, got "
                    f"{len(arrays)}")
            X = arrays[0]
            deadline = msg.get("deadline")
            if deadline is not None and not isinstance(
                    deadline, (int, float)):
                raise framing.PayloadError(
                    "deadline must be a number or null")
            self.n_requests += 1
            kwargs = {}
            if rid is not None and isinstance(self.fleet, ServingFleet):
                kwargs["request_id"] = rid  # client retry = same request
            fut = self.fleet.submit(
                str(msg.get("model")), X,
                method=str(msg.get("method", "predict")),
                priority=int(msg.get("priority", 0)),
                deadline=deadline, **kwargs)
        except Exception as e:  # noqa: BLE001 — per-frame error delivery
            self._send(conn, out_q, {
                "id": rid, "ok": False,
                "error": type(e).__name__, "message": str(e)},
                release=release)
            return

        def deliver(f, rid=rid):
            try:
                out = f.result()
            except Exception as e:  # noqa: BLE001
                self._send(conn, out_q, {
                    "id": rid, "ok": False,
                    "error": type(e).__name__, "message": str(e)},
                    release=release)
            else:
                self._send(conn, out_q, {"id": rid, "ok": True},
                           arrays=(np.asarray(out),), release=release)

        fut.add_done_callback(deliver)


class RetryBudget:
    """Client-side load-aware retry budget: a token bucket that couples
    the RIGHT to retry to observed success. Every success deposits
    ``ratio`` tokens (capped at ``cap``); every retry spends one whole
    token. Healthy service → budget stays full and transient blips
    retry freely; degraded service → successes dry up, the bucket
    drains, and retries STOP instead of multiplying the load that is
    causing the failures (the retry-storm amplification a fixed
    retry count cannot prevent). Share one instance across the clients
    of a service so the bound is per-service, not per-caller."""

    def __init__(self, ratio: float = 0.1, *, initial: float = 10.0,
                 cap: float = 100.0):
        if float(ratio) < 0.0:
            raise ValueError("ratio must be >= 0")
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._lock = threading.Lock()
        self._tokens = min(float(initial), self.cap)
        self.n_spent = 0
        self.n_denied = 0

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.ratio, self.cap)

    def try_spend(self) -> bool:
        """Claim one retry token; False (and counted) when the budget
        is exhausted — the caller must surface the original failure."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.n_spent += 1
                return True
            self.n_denied += 1
            return False


class FleetClient:
    """Out-of-process client of a :class:`FleetServer`: frames typed
    requests over one socket, demultiplexes out-of-order responses by id
    on a reader thread. ``submit`` returns a Future; ``call`` blocks.
    Error responses re-raise as the same exception classes a local
    caller would see (:data:`_WIRE_ERRORS`; anything unmapped surfaces
    as ``RuntimeError`` naming the remote class).

    Retries: ``call`` re-attempts :class:`FleetTimeoutError` /
    :class:`ServingStopped` failures up to ``retries`` times, but only
    while the :class:`RetryBudget` has tokens — retries are earned by
    successes (deposit ``ratio``) and spent one token each, so a
    degraded server sees the retry load FALL with its success rate
    instead of multiplying (mirrored as ``fleet.retries`` and
    ``fleet.retry_budget_exhausted`` at the increment sites).

    Deadlines: ``request_timeout`` (and the per-call ``timeout=`` on
    ``submit``) arms a reaper that fails the future with the typed
    :class:`FleetTimeoutError` when no response arrived in time — a
    wedged or silently-dead server can never block a caller forever
    (``ping`` has the same contract). Timeouts mirror to the
    ``fleet.timeouts`` counter at the increment site.

    Reconnect: when the server closed the previous connection CLEANLY
    between frames (EOF, not an error), the next ``submit``/``ping``
    transparently reconnects once. In-flight requests of the closed
    connection were already failed with ``ServingStopped`` — reconnect
    never resurrects them; a torn connection (reset, frame corruption)
    stays down so the failure is visible.
    """

    def __init__(self, address, *, timeout: Optional[float] = None,
                 request_timeout: Optional[float] = None,
                 send_timeout: Optional[float] = 30.0,
                 retries: int = 0,
                 retry_budget: Optional[RetryBudget] = None,
                 shm: bool = True,
                 shm_ring_bytes: Optional[int] = None):
        self.address = (address[0], int(address[1]))
        self._connect_timeout = timeout
        self.request_timeout = request_timeout
        self.send_timeout = send_timeout
        self.retries = int(retries)
        self._shm_enabled = bool(shm)
        self._shm_ring_bytes = shm_ring_bytes
        self._shm = None  # negotiated ShmClient endpoint, else None
        # (rid, endpoint) of an in-flight shm offer: the READ LOOP arms
        # the ring when the matching ACK arrives, so no framed read can
        # race the server's first doorbell byte
        self._shm_pending = None
        self.n_shm_connects = 0
        # retries without a budget would be exactly the retry-storm
        # amplifier the budget exists to prevent: default one in
        self.retry_budget = (retry_budget if retry_budget is not None
                             else (RetryBudget() if self.retries > 0
                                   else None))
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: dict = {}  # id -> Future
        self._deadlines: dict = {}  # id -> absolute monotonic instant
        # globally-unique id prefix: rids reach the FLEET's dedup table,
        # where two clients colliding (id() reuse across processes or
        # after GC) would silently hand one client the other's result
        self._rid_prefix = uuid.uuid4().hex[:16]
        self._seq = 0
        self._closed = False
        self._clean_eof = False
        self._reconnected = False
        self._reaper: Optional[threading.Thread] = None
        self.n_timeouts = 0
        self.n_reconnects = 0
        self.n_retries = 0
        self.n_budget_exhausted = 0
        from dask_ml_tpu.parallel import telemetry

        self._telemetry_inherit = telemetry.enabled()
        self._sock = self._connect()
        self._negotiate_shm()

    def _connect(self):
        import struct as struct_mod

        sock = socket.create_connection(self.address,
                                        timeout=self._connect_timeout)
        # the connect timeout must not leak into the reader's blocking
        # recv (an idle connection would look like a dead one)
        sock.settimeout(None)
        if self.send_timeout is not None:
            # kernel-level SEND timeout only (SO_SNDTIMEO): a wedged
            # server whose recv buffer filled must fail the sender's
            # sendall instead of blocking it forever under the write
            # lock — socket.settimeout would also arm recv and kill the
            # reader on every idle connection
            try:
                t = float(self.send_timeout)
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                    struct_mod.pack("ll", int(t),
                                    int((t - int(t)) * 1e6)))
            except (OSError, AttributeError):
                pass  # platform without SO_SNDTIMEO: keep blocking sends
        threading.Thread(target=self._read_loop, args=(sock,),
                         name="fleet-client-reader", daemon=True).start()
        return sock

    def close(self) -> None:
        self._closed = True
        ep, self._shm = self._shm, None
        if ep is not None:
            ep.close()
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _negotiate_shm(self) -> None:
        """Offer the server a shared-memory ring for this connection
        (``op="shm_hello"`` over the just-established TCP wire). Create
        the segment, name it, wait for the attach verdict: yes → both
        directions move to the ring and the socket stays open as the
        doorbell + liveness/EOF channel; no (cross-machine, disabled,
        old server) → unlink the segment and keep the framed wire.
        Never raises — TCP is always the safe landing."""
        if not self._shm_enabled or self._closed:
            return
        try:
            from dask_ml_tpu.parallel import shm as shm_lib

            kwargs = ({} if self._shm_ring_bytes is None
                      else {"ring_bytes": int(self._shm_ring_bytes)})
            ep = shm_lib.ShmClient(**kwargs)
        except Exception:  # noqa: BLE001 — no shm on this platform
            return
        with self._lock:
            self._seq += 1
            rid = f"{self._rid_prefix}-{self._seq}"
            fut: Future = Future()
            self._pending[rid] = fut
        ok = False
        try:
            hello = dict(ep.hello())
            hello["id"] = rid
            # the READ LOOP arms the ring the moment it decodes the ACK
            # (before delivering this future): the very next byte the
            # server sends after a yes is a doorbell, not a frame, so
            # the switch cannot be left to this thread — the reader
            # would already be blocked inside read_frame misparsing it
            self._shm_pending = (rid, ep)
            # written directly (the caller already holds _wlock on the
            # reconnect path; _send_msg would deadlock on it)
            framing.write_frame_parts(
                self._sock, framing.encode_payload_parts(hello),
                magic=framing.WIRE_MAGIC,
                checksum=framing.WIRE_CHECKSUM)
            msg = fut.result(10.0)
            ok = isinstance(msg, dict) and msg.get("shm") is True
        except Exception:  # noqa: BLE001 — any failure → TCP fallback
            ok = False
        finally:
            self._shm_pending = None
            with self._lock:
                self._pending.pop(rid, None)
                self._deadlines.pop(rid, None)
        if not ok:
            with self._lock:
                armed = self._shm is ep
                if armed:  # ACK raced the 10s verdict timeout: keep it
                    ok = True
            if not ok:
                ep.close(unlink=True)

    def _drain_shm(self, ep) -> None:
        """Deliver every response currently in the ring. Responses are
        small (one result array): copy out and release the record
        immediately — the hold-until-done discipline matters on the
        server's request side, not here."""
        while True:
            try:
                rec = ep.recv(timeout=0.0)
            except framing.PayloadError:
                continue  # malformed response fails its frame only
            if rec is None:
                return
            msg, arrays, token = rec
            try:
                copies = [np.array(a) for a in arrays]
            finally:
                ep.release(token)
            self._dispatch_msg(msg, copies)

    def _shm_doorbell_loop(self, ep, sock) -> bool:
        """The read loop's shm mode: block on the TCP socket for the
        server's doorbell byte, then drain the response ring. Every ring
        record is paired with one byte sent after its READY publish, so
        drain-to-empty per wakeup never strands a response. Returns True
        on clean server EOF (mirrors ``read_frame`` returning None);
        ring corruption raises FrameError, socket death OSError — both
        unwind through the read loop's one pending-failure path."""
        while not self._closed and not ep.closed:
            self._drain_shm(ep)
            b = sock.recv(4096)
            if b == b"":
                return True
        return False

    def _dispatch_msg(self, msg: dict, arrays) -> None:
        """Demultiplex one response (either transport) to its future."""
        rid = msg.get("id")
        with self._lock:
            fut = self._pending.pop(rid, None)
            self._deadlines.pop(rid, None)
        if fut is None:
            return  # response to a caller that went away
        if msg.get("ok"):
            _set_future(fut, arrays[0] if arrays else msg)
        else:
            cls = _WIRE_ERRORS.get(msg.get("error"), RuntimeError)
            _fail_future(fut, cls(
                f"[remote {msg.get('error')}] {msg.get('message')}"))

    def _read_loop(self, sock) -> None:
        exc: BaseException = ServingStopped("wire connection closed")
        clean = False
        try:
            while True:
                payload = framing.read_frame(
                    sock, magic=framing.WIRE_MAGIC,
                    checksum=framing.WIRE_CHECKSUM)
                if payload is None:
                    clean = True
                    break
                msg, arrays = framing.decode_payload(payload)
                pend = self._shm_pending
                if (pend is not None and msg.get("id") == pend[0]
                        and msg.get("ok") and msg.get("shm") is True):
                    # the server attached: arm the ring BEFORE waking
                    # the negotiator, then leave framed mode for good —
                    # everything after this frame is doorbell bytes
                    with self._lock:
                        self._shm = pend[1]
                    self.n_shm_connects += 1
                    self._dispatch_msg(msg, arrays)
                    clean = self._shm_doorbell_loop(pend[1], sock)
                    break
                self._dispatch_msg(msg, arrays)
        except (OSError, framing.FrameError) as e:
            exc = e
        finally:
            with self._lock:
                if sock is self._sock:
                    # a cleanly-closed connection arms the one-shot
                    # reconnect; a torn one stays down
                    self._clean_eof = clean and not self._closed
                    ep, self._shm = self._shm, None
                else:
                    ep = None
                pending = list(self._pending.values())
                self._pending.clear()
                self._deadlines.clear()
            if ep is not None:
                ep.close()  # unlink: this connection's segment dies here
            cause = (ServingStopped("wire connection closed by server")
                     if clean else ServingStopped(
                         f"wire connection lost: {exc!r}"))
            for fut in pending:
                _fail_future(fut, cause)

    def _ensure_connected(self) -> None:
        """Reconnect ONCE after a clean server-side close (under the
        write lock's caller)."""
        with self._lock:
            if not self._clean_eof or self._closed:
                return
            if self._reconnected:
                raise ServingStopped(
                    "wire connection closed by server (already "
                    "reconnected once)")
            self._clean_eof = False
            self._reconnected = True
            self.n_reconnects += 1
        ep, self._shm = self._shm, None
        if ep is not None:
            ep.close()  # a fresh connection negotiates a fresh segment
        try:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = self._connect()
        except OSError as e:
            raise ServingStopped(f"wire reconnect failed: {e!r}")
        self._negotiate_shm()

    def _count_timeout(self) -> None:
        from dask_ml_tpu.parallel import telemetry

        with self._lock:
            self.n_timeouts += 1
        if telemetry.enabled():
            telemetry.metrics().counter("fleet.timeouts").inc()

    def _reap_loop(self) -> None:
        import contextlib

        from dask_ml_tpu import config as config_lib

        ctx = (config_lib.config_context(telemetry=True)
               if self._telemetry_inherit else contextlib.nullcontext())
        with ctx:
            while not self._closed:
                now = time.monotonic()
                expired = []
                with self._lock:
                    for rid, t in list(self._deadlines.items()):
                        if t <= now:
                            self._deadlines.pop(rid, None)
                            fut = self._pending.pop(rid, None)
                            if fut is not None:
                                expired.append((rid, fut))
                    if not self._deadlines and not expired:
                        # nothing armed: exit instead of idle-polling;
                        # _arm_deadline restarts the thread (same lock)
                        self._reaper = None
                        return
                for rid, fut in expired:
                    if _fail_future(fut, FleetTimeoutError(
                            f"request {rid} got no wire response within "
                            "its deadline")):
                        self._count_timeout()
                time.sleep(0.02)

    def _arm_deadline(self, rid: str, timeout: Optional[float]) -> None:
        if timeout is None:
            return
        with self._lock:
            self._deadlines[rid] = time.monotonic() + float(timeout)
            if self._reaper is None or not self._reaper.is_alive():
                self._reaper = threading.Thread(
                    target=self._reap_loop, name="fleet-client-reaper",
                    daemon=True)
                self._reaper.start()

    def _send_msg(self, control: dict, arrays=()) -> None:
        from dask_ml_tpu.parallel import telemetry

        with self._wlock:
            self._ensure_connected()
            ep = self._shm
            if ep is not None:
                # negotiated ring: one encode pass straight into shared
                # memory (its own wire.bytes{transport="shm"} mirror),
                # then the doorbell byte that wakes the server's
                # kernel-blocked reader
                ep.send(control, arrays, timeout=self.send_timeout)
                self._sock.sendall(_DOORBELL)
                return
            parts = framing.encode_payload_parts(control, arrays)
            try:
                n = framing.write_frame_parts(
                    self._sock, parts, magic=framing.WIRE_MAGIC,
                    checksum=framing.WIRE_CHECKSUM)
            except OSError:
                # the close may have raced the write; one clean-EOF
                # reconnect attempt, then give up loudly
                self._ensure_connected()
                ep = self._shm
                if ep is not None:
                    ep.send(control, arrays, timeout=self.send_timeout)
                    self._sock.sendall(_DOORBELL)
                    return
                n = framing.write_frame_parts(
                    self._sock, parts, magic=framing.WIRE_MAGIC,
                    checksum=framing.WIRE_CHECKSUM)
            if telemetry.enabled():
                telemetry.metrics().counter(
                    "wire.bytes", transport="tcp").inc(n)

    def _new_request(self) -> tuple:
        with self._lock:
            if self._closed:
                raise ServingStopped("client is closed")
            self._seq += 1
            rid = f"{self._rid_prefix}-{self._seq}"
            fut: Future = Future()
            self._pending[rid] = fut
        return rid, fut

    def _send_or_unregister(self, rid: str, fut: Future,
                            control: dict, arrays=()) -> None:
        """``_send_msg`` that never leaks: a failed send pops the
        pending entry and fails the future before re-raising (a polling
        caller — ping() on a downed server — must not grow
        ``_pending`` by one dead future per attempt)."""
        try:
            self._send_msg(control, arrays)
        except BaseException as e:
            with self._lock:
                self._pending.pop(rid, None)
                self._deadlines.pop(rid, None)
            _fail_future(fut, e if isinstance(e, ServingError)
                         else ServingStopped(f"wire send failed: {e!r}"))
            raise

    def submit(self, model: str, X, method: str = "predict", *,
               priority: int = 0,
               deadline: Optional[float] = None,
               timeout: Optional[float] = None) -> Future:
        """Send one request; the Future resolves to the result array,
        a remote error, or — when ``timeout`` (default: the client's
        ``request_timeout``) passes with no response —
        :class:`FleetTimeoutError`."""
        rid, fut = self._new_request()
        self._send_or_unregister(
            rid, fut,
            {"op": "submit", "id": rid, "model": str(model),
             "method": str(method), "priority": int(priority),
             "deadline": deadline}, arrays=(np.asarray(X),))
        self._arm_deadline(
            rid, timeout if timeout is not None else self.request_timeout)
        return fut

    def _call_once(self, model: str, X, method: str = "predict", *,
                   priority: int = 0, deadline: Optional[float] = None,
                   timeout: Optional[float] = None) -> np.ndarray:
        fut = self.submit(model, X, method=method, priority=priority,
                          deadline=deadline, timeout=timeout)
        try:
            return fut.result(timeout if timeout is not None
                              else self.request_timeout)
        except _FutureTimeout:
            # the reaper holds the same deadline and is the ONE counting
            # site (it fails the still-pending future moments after this
            # raise) — counting here too would double fleet.timeouts
            raise FleetTimeoutError(
                f"no wire response for {model!r}.{method} within "
                f"{timeout if timeout is not None else self.request_timeout}"
                "s")

    def call(self, model: str, X, method: str = "predict", *,
             priority: int = 0, deadline: Optional[float] = None,
             timeout: Optional[float] = None) -> np.ndarray:
        """One blocking request, retried (transient failures only: wire
        timeout, server gone) up to ``retries`` times UNDER the retry
        budget — when the budget is dry, the original failure surfaces
        immediately (class docstring has the policy)."""
        from dask_ml_tpu.parallel import telemetry

        attempts = 0
        while True:
            try:
                out = self._call_once(
                    model, X, method=method, priority=priority,
                    deadline=deadline, timeout=timeout)
            except (FleetTimeoutError, ServingStopped):
                if attempts >= self.retries or self._closed:
                    raise
                if self.retry_budget is not None \
                        and not self.retry_budget.try_spend():
                    with self._lock:
                        self.n_budget_exhausted += 1
                    if telemetry.enabled():
                        telemetry.metrics().counter(
                            "fleet.retry_budget_exhausted").inc()
                    raise
                attempts += 1
                with self._lock:
                    self.n_retries += 1
                if telemetry.enabled():
                    telemetry.metrics().counter("fleet.retries").inc()
                continue
            if self.retry_budget is not None:
                self.retry_budget.on_success()
            return out

    def stats(self, timeout: float = 10.0) -> dict:
        """The server's ``op="stats"`` snapshot (queue depth, latency
        EWMA, pid, …) — :class:`FleetTimeoutError` past ``timeout``."""
        rid, fut = self._new_request()
        self._send_or_unregister(rid, fut, {"op": "stats", "id": rid})
        self._arm_deadline(rid, timeout)
        try:
            return dict(fut.result(timeout).get("stats") or {})
        except _FutureTimeout:
            raise FleetTimeoutError(  # the reaper counts (see call())
                f"no stats response within {timeout}s")

    def ping(self, timeout: float = 10.0) -> bool:
        """Round-trip liveness probe with a hard deadline: True on pong,
        :class:`FleetTimeoutError` when the server never answers —
        never an eternal block on a wedged socket."""
        rid, fut = self._new_request()
        self._send_or_unregister(rid, fut, {"op": "ping", "id": rid})
        self._arm_deadline(rid, timeout)
        try:
            return bool(fut.result(timeout).get("pong"))
        except _FutureTimeout:
            raise FleetTimeoutError(  # the reaper counts (see call())
                f"no pong within {timeout}s")
